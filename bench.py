"""Benchmark: MNIST-FC training throughput (BASELINE.json config[0]).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Protocol (BASELINE.md): steady-state samples/sec/chip after a warm-up epoch
(jit compile excluded), averaged over >=3 epochs.  ``vs_baseline`` is the
speedup over the reference's numpy backend FLOOR measured in-process (the
reference itself is unrecoverable — SURVEY §0/§6 — so its numpy backend is
reproduced here faithfully: per-minibatch python loop, numpy GEMMs, same
topology/update rule, which is exactly what `veles ... --backend numpy` ran).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy


def build_workflow(n_train, n_valid, mb):
    from veles_tpu import prng
    from veles_tpu.config import root
    prng.reset()
    prng.seed_all(1)
    root.mnist.update({
        "loader": {"minibatch_size": mb, "n_train": n_train,
                   "n_valid": n_valid},
        "decision": {"max_epochs": 1000, "fail_iterations": 1000},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 100,
             "learning_rate": 0.03, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.03, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    wf = mnist.build(fused=True)
    wf.initialize()
    return wf


def epoch_plan_arrays(loader):
    """Train-portion (idx, mask) matrices for the epoch-scan fast path."""
    from veles_tpu.loader.base import TRAIN
    loader._plan_epoch()
    idx, mask = [], []
    for cls, chunk, actual in loader._order:
        if cls != TRAIN:
            continue
        idx.append(chunk)
        m = numpy.zeros(len(chunk), numpy.float32)
        m[:actual] = 1.0
        mask.append(m)
    return numpy.stack(idx), numpy.stack(mask)


def bench_tpu(wf, epochs=3):
    import jax
    runner = wf._fused_runner
    train_epoch, _ = runner.epoch_fns()
    loader = wf.loader
    data = loader.original_data.devmem
    labels = loader.original_labels.devmem
    idx, mask = epoch_plan_arrays(loader)
    n_samples = int(mask.sum())
    steps_per_epoch = idx.shape[0]
    # warm-up epoch (compile); step0 threads the global step so lr policies
    # (when configured) decay across epochs instead of restarting
    state, totals = train_epoch(runner.state, data, labels, idx, mask,
                                step0=0)
    jax.block_until_ready(totals)
    begin = time.perf_counter()
    for epoch in range(epochs):
        state, totals = train_epoch(state, data, labels, idx, mask,
                                    step0=(epoch + 1) * steps_per_epoch)
    jax.block_until_ready(totals)
    elapsed = time.perf_counter() - begin
    runner.state = state
    return epochs * n_samples / elapsed


def bench_numpy_floor(wf, min_seconds=3.0):
    """The reference's numpy backend, reproduced: python minibatch loop with
    numpy GEMMs, same 784->100(tanh)->10(softmax) + momentum SGD."""
    loader = wf.loader
    data = numpy.asarray(loader.original_data.mem)
    labels = numpy.asarray(loader.original_labels.mem)
    idx, mask = epoch_plan_arrays(loader)
    rng = numpy.random.RandomState(1)
    w1 = rng.uniform(-0.1, 0.1, (784, 100)).astype(numpy.float32)
    b1 = numpy.zeros(100, numpy.float32)
    w2 = rng.uniform(-0.1, 0.1, (100, 10)).astype(numpy.float32)
    b2 = numpy.zeros(10, numpy.float32)
    vw1 = numpy.zeros_like(w1); vb1 = numpy.zeros_like(b1)
    vw2 = numpy.zeros_like(w2); vb2 = numpy.zeros_like(b2)
    lr, mom = 0.03, 0.9
    a, bconst = 1.7159, 0.6666

    done_samples = 0
    begin = time.perf_counter()
    while time.perf_counter() - begin < min_seconds:
        for mb_idx, mb_mask in zip(idx, mask):
            x = data[mb_idx]
            lab = labels[mb_idx]
            n = int(mb_mask.sum())
            y1 = a * numpy.tanh(bconst * (x @ w1 + b1))
            z2 = y1 @ w2 + b2
            e = numpy.exp(z2 - z2.max(axis=1, keepdims=True))
            probs = e / e.sum(axis=1, keepdims=True)
            onehot = numpy.eye(10, dtype=numpy.float32)[lab]
            err2 = (probs - onehot) * mb_mask[:, None]
            gw2 = y1.T @ err2 / n
            gb2 = err2.sum(0) / n
            err1 = (err2 @ w2.T) * (bconst * (a - y1 * y1 / a))
            gw1 = x.T @ err1 / n
            gb1 = err1.sum(0) / n
            vw2 = mom * vw2 - lr * gw2; w2 += vw2
            vb2 = mom * vb2 - lr * gb2; b2 += vb2
            vw1 = mom * vw1 - lr * gw1; w1 += vw1
            vb1 = mom * vb1 - lr * gb1; b1 += vb1
            done_samples += n
    return done_samples / (time.perf_counter() - begin)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true",
                        help="tiny sizes on CPU for CI validation")
    parser.add_argument("--epochs", type=int, default=3)
    args = parser.parse_args()

    if args.smoke:
        import jax
        jax.config.update("jax_platforms", "cpu")
        n_train, n_valid, mb = 2000, 500, 100
        floor_seconds = 0.5
    else:
        n_train, n_valid, mb = 60000, 10000, 100
        floor_seconds = 3.0

    wf = build_workflow(n_train, n_valid, mb)
    tpu_sps = bench_tpu(wf, epochs=args.epochs)
    floor_sps = bench_numpy_floor(wf, min_seconds=floor_seconds)
    print(json.dumps({
        "metric": "mnist_fc_train_samples_per_sec_per_chip",
        "value": round(tpu_sps, 1),
        "unit": "samples/sec",
        "vs_baseline": round(tpu_sps / floor_sps, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
