"""Benchmarks: MNIST-FC, CIFAR-10-conv, AlexNet (BASELINE configs 0-2).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "configs"}.
The headline metric stays MNIST-FC samples/sec/chip (config[0]); the
``configs`` field carries the full per-config methodology record — step
time, analytic model FLOPs, achieved TFLOP/s, and MFU — for every bench.

Measurement protocol (BASELINE.md):
- steady-state samples/sec/chip after a warm-up epoch (compile excluded),
  timed over enough epochs to dominate host<->device latency;
- SYNCHRONIZATION: on this image the TPU is reached through a tunnel whose
  ``block_until_ready`` does NOT wait for execution (dispatch returns
  immediately; a 4096^3 matmul "finished" at 7000 TFLOP/s on a 197-TFLOP
  chip).  Every timing window therefore ends with a VALUE FETCH of one
  metric leaf, which cannot complete before the computation does.  The
  fetch round-trip (~70 ms) is amortized by sizing windows >= seconds.
- MFU = achieved TFLOP/s / bf16 peak of the chip.  Matmul precision is
  fp32 HIGHEST (convergence parity — SURVEY §7); measured rooflines on
  TPU v5e: ~28 TF/s fp32-HIGHEST, ~116 TF/s fp32-DEFAULT (bf16 passes),
  ~124 TF/s pure bf16 at 4096^3.  A bf16 variant of the AlexNet bench is
  also recorded (the TPU-idiomatic fast path).
- ``vs_baseline`` is the speedup over the reference's numpy backend FLOOR
  measured in-process (the reference itself is unrecoverable — SURVEY
  §0/§6): per-minibatch python loop, numpy GEMMs, same topology.

FLOPs convention: analytic per-sample model FLOPs — dense fwd = 2*in*out,
conv fwd = 2*ky*kx*cin*cout*oh*ow; training = 3x fwd per parameterized
layer, minus the dX term of the first parameterized layer (its err_input
is never formed).  Activations/pools/LRN/softmax are excluded (memory-
bound, <2% of conv/dense FLOPs at these shapes).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy

# bf16 peak TFLOP/s per chip, by device_kind prefix
PEAK_BF16_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,   # v5e
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v6": 918.0,
    "TPU v7": 2300.0,
}


def enable_compile_cache():
    """Arm jax's persistent compilation cache (best-effort).

    Through the TPU tunnel a conv-program compile is a 20-40 s RPC and
    the relay has wedged DURING such an RPC in 3/3 hardware sessions —
    a warm cache removes the recompile (and with it most of the wedge
    exposure) for every worker subprocess after the first, and across
    bench/convergence sessions entirely.  Wrapped: if the axon PJRT
    plugin cannot serialize executables, jax logs and skips caching —
    never an error.  VELES_JAX_CACHE_DIR overrides the location;
    VELES_JAX_CACHE=0 disables."""
    if os.environ.get("VELES_JAX_CACHE", "1") in ("", "0"):
        return
    path = os.environ.get(
        "VELES_JAX_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    try:
        import jax
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception as exc:                      # pragma: no cover
        print("[bench] compile cache unavailable: %r" % (exc,),
              file=sys.stderr)


def _peak_tflops():
    import jax
    kind = jax.devices()[0].device_kind
    for prefix, peak in PEAK_BF16_TFLOPS.items():
        if kind.startswith(prefix):
            return kind, peak
    return kind, None


def _sync(tree):
    """Force execution by FETCHING one leaf (see module docstring: the
    tunnel's block_until_ready does not block)."""
    import jax
    return numpy.asarray(jax.tree.leaves(tree)[0]).ravel()[0]


# --------------------------------------------------------------- workflows
def build_mnist(n_train, n_valid, mb, seed=1):
    from veles_tpu import prng
    from veles_tpu.config import root
    prng.reset()
    prng.seed_all(seed)
    root.mnist.update({
        "loader": {"minibatch_size": mb, "n_train": n_train,
                   "n_valid": n_valid},
        "decision": {"max_epochs": 1000, "fail_iterations": 1000},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 100,
             "learning_rate": 0.03, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.03, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    wf = mnist.build(fused=True)
    wf.initialize()
    return wf


# round-1 name of the MNIST builder, kept as an alias
build_workflow = build_mnist


def build_cifar(n_train, n_valid, mb, seed=1):
    from veles_tpu import prng
    from veles_tpu.config import root
    prng.reset()
    prng.seed_all(seed)
    root.__dict__.pop("cifar", None)
    root.cifar.update({
        "loader": {"minibatch_size": mb, "n_train": n_train,
                   "n_valid": n_valid},
        "decision": {"max_epochs": 1000, "fail_iterations": 1000},
    })
    from veles_tpu.samples import cifar
    wf = cifar.build(fused=True)   # default small-conv topology (config[1])
    wf.initialize()
    return wf


def build_alexnet(n_train, n_valid, mb, image_hw=(256, 256), n_classes=1000,
                  crop=(227, 227)):
    """Full-size AlexNet (BASELINE config[2]) on random 256x256 images with
    the real random-crop+flip augmentation and dropout FC trunk."""
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.samples.imagenet import ImagenetWorkflow, alexnet_layers
    prng.reset()
    prng.seed_all(1)

    class _RandomImages(FullBatchLoader):
        def load_data(self):
            rng = numpy.random.RandomState(12345)
            h, w = image_hw
            total = n_train + n_valid
            self.original_data.reset(
                rng.uniform(-1.0, 1.0, (total, h, w, 3))
                .astype(numpy.float32))
            self.original_labels.reset(
                rng.randint(0, n_classes, total).astype(numpy.int32))
            self.class_lengths = [0, n_valid, n_train]

    wf = ImagenetWorkflow(
        None, name="alexnet_bench", loader_factory=_RandomImages,
        loader_config={"minibatch_size": mb},
        layers=alexnet_layers(n_classes=n_classes, crop=crop),
        decision_config={"max_epochs": 1000, "fail_iterations": 1000},
        loss_function="softmax", fused=True)
    wf.initialize()
    return wf


# ------------------------------------------------------------------- flops
def model_train_flops_per_sample(runner):
    """Analytic training FLOPs per sample (convention in module docstring)."""
    total = 0.0
    first = True
    for fwd in runner.forwards:
        if not getattr(fwd, "has_params", False) or fwd.weights.is_empty:
            continue
        w_shape = tuple(fwd.weights.shape)
        if len(w_shape) == 4:         # conv (ky, kx, cin, cout)
            oh, ow = fwd.output_sample_shape[:2]
            f = 2.0 * numpy.prod(w_shape) * oh * ow
        else:                         # dense (n_in, n_out)
            f = 2.0 * numpy.prod(w_shape)
        total += 3.0 * f - (f if first else 0.0)
        first = False
    return float(total)


# ------------------------------------------------------------------ timing
def epoch_plan_arrays(loader, wanted_cls=None):
    """(idx, mask) matrices of one set for the epoch-scan fast path,
    from a FRESH plan (train by default; pass loader.base.VALID for the
    validation set).  Extraction lives on the Loader (plan_arrays)."""
    loader._plan_epoch()
    return loader.plan_arrays(wanted_cls)


def best_time(fn, reps=3):
    """Best-of-``reps`` wall time of ``fn()``, each run ended by a value
    FETCH (see _sync) — the shared core of every K-vs-1 microbench."""
    best = float("inf")
    for _ in range(reps):
        begin = time.perf_counter()
        out = fn()
        _sync(out)
        best = min(best, time.perf_counter() - begin)
    return best


def timed_window(dispatch, target_seconds, initial=1):
    """Grow the work window until it dominates the fetch round-trip:
    ``dispatch(n, start)`` issues ``n`` work units beginning at offset
    ``start`` and must END IN A VALUE FETCH (module docstring:
    block_until_ready does not block through the tunnel).  Returns
    (n_in_final_window, elapsed_seconds)."""
    n, done = initial, 0
    while True:
        begin = time.perf_counter()
        dispatch(n, done)
        elapsed = time.perf_counter() - begin
        done += n
        if elapsed >= target_seconds:
            return n, elapsed
        n = max(n * 2, int(n * 1.3 * target_seconds / max(elapsed, 1e-3)))


#: epochs folded into ONE device program by the timing path — through a
#: tunnel each jit call is a synchronous execute RPC (~0.1-1 s observed),
#: so per-epoch dispatch would dominate small models' timings; the chunk
#: pays that RPC once per CHUNK_EPOCHS epochs (compiled.epoch_chunk_fn).
#: On CPU (--smoke) dispatch is ~free and fp32-HIGHEST convs are slow, so
#: chunking would only multiply the warm-up cost — use 1 there.
CHUNK_EPOCHS = 8


def _chunk_epochs():
    import jax
    return 1 if jax.default_backend() == "cpu" else CHUNK_EPOCHS


def bench_epoch_scan(wf, target_seconds=4.0):
    """Steady-state samples/sec via the epoch-scan path, dispatched in
    chunks of epochs so the per-execute round-trip amortizes.

    Returns (samples_per_sec, steps_per_epoch, step_time_us)."""
    runner = wf._fused_runner
    chunk_epochs = _chunk_epochs()
    chunk = runner.epoch_chunk_fn(chunk_epochs)
    loader = wf.loader
    data = loader.original_data.devmem
    labels = loader.original_labels.devmem
    idx, mask = epoch_plan_arrays(loader)
    n_samples = int(mask.sum())
    steps_per_epoch = idx.shape[0]
    from veles_tpu import prng
    rng = prng.get("dropout").key() if runner._has_stochastic else None

    def run_chunks(state, n, step0):
        for c in range(n):
            state, totals = chunk(state, data, labels, idx, mask, rng=rng,
                                  step0=step0 + c * chunk_epochs
                                  * steps_per_epoch)
        return state, totals

    # warm-up chunk (compile) — must also end in a fetch
    holder = {"state": runner.state}
    state, totals = run_chunks(holder["state"], 1, 0)
    _sync(totals)
    holder["state"] = state

    def dispatch(n, done):
        state, totals = run_chunks(holder["state"], n,
                                   (done + 1) * chunk_epochs
                                   * steps_per_epoch)
        _sync(totals)
        holder["state"] = state

    chunks, elapsed = timed_window(dispatch, target_seconds)
    runner.state = holder["state"]
    epochs = chunks * chunk_epochs
    sps = epochs * n_samples / elapsed
    step_us = elapsed / (epochs * steps_per_epoch) * 1e6
    return sps, steps_per_epoch, step_us


def bench_config(name, wf, target_seconds, device_kind, peak_tflops,
                 precision):
    sps, steps, step_us = bench_epoch_scan(wf, target_seconds)
    flops = model_train_flops_per_sample(wf._fused_runner)
    achieved = sps * flops / 1e12
    rec = {
        "samples_per_sec": round(sps, 1),
        "minibatch": int(wf.loader.max_minibatch_size),
        "steps_per_epoch": int(steps),
        "step_time_us": round(step_us, 2),
        "model_train_mflops_per_sample": round(flops / 1e6, 3),
        "achieved_tflops": round(achieved, 2),
        "mfu_pct_of_bf16_peak": (round(100.0 * achieved / peak_tflops, 2)
                                 if peak_tflops else None),
        "precision": precision,
        "device": device_kind,
    }
    print("%-16s %12.0f samples/s  %8.1f us/step  %7.2f TF/s  MFU %s%%"
          % (name, sps, step_us, achieved,
             rec["mfu_pct_of_bf16_peak"]), file=sys.stderr)
    return rec


# ------------------------------------------------ alexnet from records
def bench_alexnet_records(wf, target_seconds=4.0, smoke=False):
    """End-to-end AlexNet training throughput fed from a RECORDS FILE:
    per minibatch, the native C++ gather+convert reads uint8 images from
    the memory-mapped record file and the jitted train step consumes
    them — the real input path a disk-resident ImageNet epoch uses
    (VERDICT r3 Weak #7: the HBM-resident bench excluded input cost).

    Dispatches pipeline: the tunnel returns immediately on dispatch, so
    host-side gather of batch i+1 overlaps device compute of batch i;
    the timing window ends in one metric fetch.  emit_summary adds
    ``pipeline_ratio_vs_hbm`` = this number / the HBM-resident
    samples/sec — 1.0 means the input path is fully hidden.
    """
    import tempfile
    import jax
    import jax.numpy as jnp
    from veles_tpu import native, prng

    runner = wf._fused_runner
    mb = int(wf.loader.max_minibatch_size)
    shape = tuple(wf.loader.original_data.shape[1:])      # (H, W, 3)
    n_classes = int(numpy.prod(wf.forwards[-1].output_sample_shape))
    n = 256 if smoke else 1024
    rs = numpy.random.RandomState(7)
    data = rs.randint(0, 256, (n,) + shape, numpy.uint8)
    labels = (numpy.arange(n) % n_classes).astype(numpy.int32)
    mask = numpy.ones(mb, numpy.float32)

    with tempfile.TemporaryDirectory() as tmp:
        src, lab = records_fixture(tmp, data, labels, mb)
        rng0 = (prng.get("dropout").key()
                if runner._has_stochastic else None)
        state = runner.state

        def dispatch(state, step):
            idx = ((numpy.arange(mb) + step * mb) % n).astype(numpy.int32)
            x = native.gather_convert(src, idx, scale=1.0 / 127.5,
                                      offset=-1.0)
            y = native.gather_labels(lab, idx)
            r = (jax.random.fold_in(rng0, step)
                 if rng0 is not None else None)
            return runner._train(state, x, y, mask,
                                 jnp.asarray(mb, jnp.int32), r,
                                 jnp.asarray(step, jnp.int32))

        holder = {"state": state}
        _, metrics = dispatch(holder["state"], 0)
        _sync(metrics)          # per-minibatch train-step compile + warm

        def window(n, done):
            st = holder["state"]
            for i in range(n):
                st, metrics = dispatch(st, 1 + done + i)
            _sync(metrics)
            holder["state"] = st

        steps, elapsed = timed_window(window, target_seconds, initial=8)
    sps = steps * mb / elapsed
    rec = {
        "samples_per_sec": round(sps, 1),
        "step_time_ms": round(elapsed / steps * 1e3, 3),
        "minibatch": mb,
        "images_in_file": n,
        "native_gather": native.available(),
    }
    return rec


# ------------------------------------------------------------- convergence
def bench_convergence(build_fn, max_epochs=15, patience=5):
    """Train to the stopping criterion (no val improvement for ``patience``
    epochs) via the epoch-scan path and record the final val metric — the
    convergence half of the BASELINE acceptance (val-acc at throughput),
    which throughput-only benches never measured (VERDICT r3 Missing #2).
    The metric follows the workflow's evaluator: classification records
    n_err, MSE/autoencoder workflows record the mean per-sample squared
    reconstruction error (BASELINE config[3]) — one source of truth, the
    same flag that routes the scan's target.

    Runs the SAME pure step functions the Decision-driven graph runs
    (compiled.py composes one set of fns for both), with a fresh shuffle
    per epoch, seed pinned by build_fn.
    """
    import jax
    from veles_tpu import prng
    from veles_tpu.loader.base import VALID

    wf = build_fn()
    runner = wf._fused_runner
    metric = "mse" if runner._is_mse else "n_err"
    loader = wf.loader
    data = loader.original_data.devmem
    # MSE/AE workflows reconstruct the input: the scan's target is the
    # data itself (labels=None), matching the evaluator's target aliasing
    labels = (None if runner._is_mse
              else loader.original_labels.devmem)
    vidx, vmask = epoch_plan_arrays(loader, wanted_cls=VALID)
    n_valid = int(vmask.sum())
    rng = prng.get("dropout").key() if runner._has_stochastic else None

    # train-k-epochs + per-epoch eval in ONE program: through the tunnel
    # each execute costs ~0.4 s, so the per-epoch (2 RPC/epoch) loop pays
    # 2k RPCs where this pays 1 per chunk; per-epoch val metrics come
    # back stacked so the early-stop decisions are IDENTICAL, just
    # evaluated in k-epoch batches (at most k-1 extra epochs trained
    # past the stopping point, never a different best)
    k = _chunk_epochs()
    chunk_eval = runner.epoch_chunk_eval_fn(k)

    state = runner.state
    best, best_epoch, since = None, 0, 0
    begin = time.perf_counter()
    epoch = 0
    stop = False
    while not stop and epoch < max_epochs:
        plans = [epoch_plan_arrays(loader) for _ in range(k)]  # fresh
        idx = numpy.stack([p[0] for p in plans])   # shuffle per epoch
        mask = numpy.stack([p[1] for p in plans])
        steps_per_epoch = idx.shape[-2]
        # base key: _epoch_chunk_eval folds per epoch by global step
        state, _, val_stack, _ = chunk_eval(
            state, data, labels, idx, mask, vidx, vmask, rng=rng,
            step0=epoch * steps_per_epoch)
        if metric == "n_err":
            vals = numpy.asarray(val_stack["n_err"])        # sync point
        else:
            vals = (numpy.asarray(val_stack["mse_sum"])
                    / max(n_valid, 1))
        for row in range(k):
            epoch += 1
            val = (int(vals[row]) if metric == "n_err"
                   else float(vals[row]))
            if best is None or val < best:
                best, best_epoch, since = val, epoch, 0
            else:
                since += 1
            if since >= patience or epoch >= max_epochs:
                stop = True
                break
    wall = time.perf_counter() - begin
    runner.state = state
    rec = {
        "val_count": n_valid,
        "best_epoch": best_epoch,
        "epochs_run": epoch,
        "wall_s": round(wall, 1),
    }
    if metric == "n_err":
        rec["best_val_err"] = best
        rec["best_val_err_pct"] = round(100.0 * best / max(n_valid, 1), 2)
    else:
        rec["best_val_mse"] = round(best, 6)
    return rec


# -------------------------------------------------------- transformer LM
def bench_lm(smoke=False, iters=None, publish=None):
    """Char-LM transformer training throughput (the beyond-parity
    long-context family): tokens/sec of THE product train step
    (transformer.make_adam_train_step — the same function
    TransformerTrainer jits), measured by in-jit K-vs-1 repetition
    (lax.scan) so the tunnel's per-dispatch latency cancels.  TFLOP/s
    uses the standard 6·N·T convention (N = param count, T = tokens;
    attention term excluded) — approximate but comparable across rounds.

    ``publish`` (optional) is called with the partial record after each
    sub-leg (train / remat / flash / decode) so the orchestrator keeps
    completed legs if a later leg's compile hangs the worker.
    """
    import jax
    import jax.numpy as jnp
    from veles_tpu import prng
    from veles_tpu.ops.transformer import (init_transformer_params,
                                           lm_loss, make_adam_train_step)

    if smoke:
        vocab, d, heads, layers, seq, mb = 64, 32, 2, 2, 64, 8
        iters = 2 if iters is None else iters
    else:
        vocab, d, heads, layers, seq, mb = 256, 512, 8, 8, 512, 32
        iters = 6 if iters is None else iters
    host = init_transformer_params(prng.get("init"), vocab, d, heads,
                                   layers, max_len=seq + 1)
    params = jax.tree.map(jnp.asarray, host)
    n_params = sum(int(numpy.prod(a.shape))
                   for a in jax.tree.leaves(params))
    opt = (jax.tree.map(jnp.zeros_like, params),
           jax.tree.map(jnp.zeros_like, params))
    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (mb, seq + 1), 0, vocab, jnp.int32)
    mask = jnp.ones((mb,), jnp.float32)
    def measure(remat):
        train_step = make_adam_train_step(
            lambda p, toks, msk: lm_loss(p, toks, msk, heads,
                                         remat=remat), 1e-3)

        def step(carry, _):
            p, opt_state, t = carry
            p, opt_state, metrics = train_step(p, opt_state, tokens,
                                               mask, t)
            return (p, opt_state, t + 1), metrics["loss_sum"]

        def chain(k):
            def fn(p, opt):
                carry, losses = jax.lax.scan(
                    step, (p, opt, jnp.asarray(0, jnp.int32)), None,
                    length=k)
                return losses[-1]
            return jax.jit(fn)

        f1, fk = chain(1), chain(1 + iters)
        _sync(f1(params, opt)); _sync(fk(params, opt))    # compile
        return (best_time(lambda: fk(params, opt))
                - best_time(lambda: f1(params, opt))) / iters

    step_s = measure(remat=False)
    toks = mb * seq
    rec = {
        "tokens_per_sec": round(toks / step_s, 1),
        "step_time_ms": round(step_s * 1e3, 3),
        "seq_len": seq, "minibatch": mb, "d_model": d,
        "n_layers": layers, "n_params": n_params,
        "approx_tflops": round(6.0 * n_params * toks / step_s / 1e12, 2),
        "flops_convention": "6*N*T, attention excluded",
    }
    if publish:
        publish(rec)
    # the HBM-for-FLOPs trade, priced: same step with per-block
    # jax.checkpoint (recompute ~1 extra fwd in the bwd pass)
    remat_s = measure(remat=True)
    rec["tokens_per_sec_remat"] = round(toks / remat_s, 1)
    rec["remat_overhead_pct"] = round(100.0 * (remat_s / step_s - 1.0), 1)
    if publish:
        publish(rec)

    # attention-backend comparison: the bundled TPU Pallas flash kernel
    # vs XLA's fused attention on the SAME train step (TPU only — the
    # kernel has no CPU lowering); the winner would keep the default
    from veles_tpu.ops.pallas_kernels import on_tpu
    if not on_tpu():
        pass                                  # kernel has no CPU lowering
    elif seq % 128:
        # the bundled kernel's default blocks are 128-wide; a short
        # smoke sequence is "not applicable", not "kernel broke"
        rec["flash_pallas_skipped"] = "seq %d not divisible by 128" % seq
    else:
        from veles_tpu.ops import attention as A
        A.set_attention_backend("flash_pallas")
        try:
            flash_s = measure(remat=False)
            rec["tokens_per_sec_flash_pallas"] = round(toks / flash_s, 1)
            rec["flash_vs_xla_speedup"] = round(step_s / flash_s, 2)
        except Exception as exc:   # noqa: BLE001 — recorded, not fatal
            rec["flash_pallas_error"] = repr(exc)[-300:]
        finally:
            A.set_attention_backend("xla")
    if publish:
        publish(rec)

    # serving side: KV-cached greedy decode throughput.  generate() is
    # one jit call (prefill + scan); both timings PIN the same max_len
    # (cache shape) so the n_long-vs-n_short subtraction isolates step
    # count alone — prefill, dispatch, and cache size all cancel
    from veles_tpu.ops.transformer import generate
    key = jax.random.PRNGKey(3)
    n_short, n_long = (2, 10) if smoke else (8, 64)
    dec_mb = 1 if smoke else 8
    dprompt = jax.random.randint(key, (dec_mb, 8), 0, vocab, jnp.int32)
    cache_len = 8 + n_long

    def decode_time(n):
        run = lambda: generate(params, dprompt, n, heads, temperature=0,
                               max_len=cache_len)
        _sync(run())   # compile
        return best_time(run)

    per_tok = (decode_time(n_long) - decode_time(n_short)) \
        / (n_long - n_short)
    rec["decode_tokens_per_sec"] = round(dec_mb / per_tok, 1)
    rec["decode_ms_per_token"] = round(per_tok * 1e3, 3)
    rec["decode_batch"] = dec_mb
    if publish:
        publish(rec)

    # GQA serving lever: same model shape with 1 kv head — the decode
    # delta vs the record above is what grouped-query attention buys
    # (smaller cache reads per token) on this hardware
    gqa_host = init_transformer_params(prng.get("init"), vocab, d, heads,
                                       layers, max_len=seq + 1,
                                       n_kv_heads=1, rope=True)
    gqa_params = jax.tree.map(jnp.asarray, gqa_host)

    def gqa_decode_time(n):
        run = lambda: generate(gqa_params, dprompt, n, heads,
                               temperature=0, max_len=cache_len,
                               rope=True)
        _sync(run())   # compile
        return best_time(run)

    gqa_per_tok = (gqa_decode_time(n_long) - gqa_decode_time(n_short)) \
        / (n_long - n_short)
    rec["decode_tokens_per_sec_gqa1_rope"] = round(dec_mb / gqa_per_tok,
                                                   1)
    rec["gqa_decode_speedup"] = round(per_tok / gqa_per_tok, 2)
    return rec


# ------------------------------------------------------------ DP scaling
def bench_scaling(smoke=False, seconds=2.0):
    """DP scaling-efficiency hook (BASELINE config[4]): MNIST-FC
    epoch-scan samples/sec on ONE device vs ALL local devices via
    ShardedTrainer.  Recorded as skipped on single-device hosts (this
    container's TPU is one chip); the measurement runs unchanged the
    round the driver offers a multi-chip mesh.
    """
    import jax
    from veles_tpu.parallel import make_mesh, ShardedTrainer

    n = len(jax.devices())
    if n < 2:
        return {"skipped": "single device — scaling unmeasurable here"}
    sizes = (4000, 800, 200) if smoke else (60000, 10000, 512)

    def measure(n_dev):
        wf = build_mnist(*sizes)
        trainer = ShardedTrainer(wf._fused_runner, make_mesh(n_dev))
        loader = wf.loader
        trainer.place_dataset(numpy.asarray(loader.original_data.mem),
                              numpy.asarray(loader.original_labels.mem))
        idx, mask = epoch_plan_arrays(loader)
        n_samples = int(mask.sum())
        _sync(trainer.train_epoch(idx, mask))          # compile + warm
        epochs, elapsed = 1, 0.0
        while elapsed < seconds:
            begin = time.perf_counter()
            for _ in range(epochs):
                totals = trainer.train_epoch(idx, mask)
            _sync(totals)
            elapsed = time.perf_counter() - begin
            if elapsed < seconds:
                epochs *= 2
        return epochs * n_samples / elapsed

    sps_1, sps_n = measure(1), measure(n)
    return {
        "devices": n,
        "samples_per_sec_1dev": round(sps_1, 1),
        "samples_per_sec_ndev": round(sps_n, 1),
        "scaling_efficiency": round(sps_n / (n * sps_1), 3),
    }


# ------------------------------------------------- sgd backend (XLA/Pallas)
def bench_sgd_backends(n=4 * 1024 * 1024, iters=20, smoke=False,
                       publish=None):
    """XLA-vs-Pallas fused-SGD-update comparison (SURVEY §2.4 custom-kernel
    row): per-update device time on an AlexNet-FC-sized fp32 tensor,
    measured by in-jit repetition (K-vs-1 difference — dispatch overhead
    cancels).  The winner keeps the default (functional._SGD_BACKEND).
    ``publish`` streams the partial record after each backend so a hang
    in the pallas leg cannot discard the measured xla number."""
    import jax
    import jax.numpy as jnp
    from veles_tpu.ops import functional as F

    if smoke:
        n, iters = 64 * 1024, 4   # interpret-mode pallas is slow off-TPU
    key = jax.random.PRNGKey(0)
    p0 = jax.random.normal(key, (n,), jnp.float32)
    v0 = jnp.zeros((n,), jnp.float32)
    g0 = jax.random.normal(jax.random.fold_in(key, 1), (n,), jnp.float32)
    bs = jnp.asarray(128, jnp.int32)
    record = {"elements": n}
    for backend in ("xla", "pallas"):
        F.set_sgd_backend(backend)
        try:
            def chain(p, v, g, k):
                def body(i, pv):
                    return F.sgd_update(pv[0], pv[1], g, bs, 0.01, 0.9,
                                        0.0005, 0.0, None)
                return jax.lax.fori_loop(0, k, body, (p, v))

            f1 = jax.jit(lambda p, v, g: chain(p, v, g, 1))
            fk = jax.jit(lambda p, v, g: chain(p, v, g, 1 + iters))
            _sync(f1(p0, v0, g0)); _sync(fk(p0, v0, g0))  # compile
            record[backend + "_us"] = round(
                (best_time(lambda: fk(p0, v0, g0))
                 - best_time(lambda: f1(p0, v0, g0))) / iters * 1e6, 2)
            if publish:
                publish(record)
        finally:
            F.set_sgd_backend("xla")
    if "xla_us" in record and "pallas_us" in record:
        record["winner"] = ("pallas" if record["pallas_us"] <
                            record["xla_us"] else "xla")
    return record


# ------------------------------------------------ native PJRT runner (C++)
def bench_native_runner(smoke=False):
    """End-to-end proof of the standalone C++ PJRT runner (libVeles
    parity): train tiny MNIST on CPU, export a native bundle, run
    native/artifact_runner against a PJRT plugin .so, and parity-check
    its output against the in-framework forward.  The worker's own jax
    is cpu-pinned by orchestrate(), so on hardware the C++ binary is the
    tunnel's only client; off-hardware (or tunnel down) the record still
    proves build+selfcheck and reports the execute error."""
    import subprocess
    import tempfile

    import jax
    jax.config.update("jax_platforms", "cpu")

    nat_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "veles_tpu", "native")
    record = {}
    try:
        subprocess.run(["make", "artifact_runner"], cwd=nat_dir,
                       check=True, stdout=subprocess.PIPE,
                       stderr=subprocess.STDOUT, timeout=180)
    except Exception as e:   # noqa: BLE001 — recorded
        return {"error": "build failed: %r" % (e,)}
    runner_bin = os.path.join(nat_dir, "artifact_runner")

    from veles_tpu.native import find_pjrt_plugin
    plugin = find_pjrt_plugin()
    if plugin is None:
        return {"error": "no PJRT plugin .so found"}
    record["plugin"] = plugin

    out = subprocess.run([runner_bin, "--selfcheck", plugin],
                         stdout=subprocess.PIPE, timeout=120)
    record["selfcheck"] = ("ok" if b"SELFCHECK OK" in out.stdout
                           else "failed rc=%d" % out.returncode)
    if os.environ.get("VELES_BENCH_TUNNEL_DEAD"):
        # selfcheck only dlopens (no client); the execute leg would hang
        # on the wedged relay until its timeout — skip it explicitly
        record["execute"] = "skipped (tunnel dead — execute would hang)"
        return record

    from veles_tpu import export, prng
    from veles_tpu.config import root
    prng.reset()
    prng.seed_all(1)
    root.__dict__.pop("mnist", None)
    root.mnist.update({
        "loader": {"minibatch_size": 50, "n_train": 500, "n_valid": 100},
        "decision": {"max_epochs": 1, "fail_iterations": 5},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 64,
             "learning_rate": 0.03, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.03, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    wf = mnist.train()
    if smoke:
        # CI contract run: no device behind either plugin here, and a
        # client-create against a dead tunnel can hang — prove build +
        # selfcheck + export only (the TPU-marked test and the real
        # bench run cover execute)
        import tempfile as _tf
        export.export_native_bundle(
            wf, os.path.join(_tf.mkdtemp(prefix="native_smoke_"), "nb"),
            batch=8)
        record["execute"] = "skipped (smoke: no device)"
        record["bundle_export"] = "ok"
        return record
    tmp = tempfile.mkdtemp(prefix="native_bench_")
    bundle = export.export_native_bundle(wf, os.path.join(tmp, "nb"),
                                         batch=8)
    x = numpy.random.RandomState(0).uniform(
        -1, 1, (8, 784)).astype(numpy.float32)
    in_bin = os.path.join(tmp, "in.bin")
    out_bin = os.path.join(tmp, "out.bin")
    with open(in_bin, "wb") as f:
        f.write(x.tobytes())
    begin = time.perf_counter()
    proc = subprocess.run([runner_bin, bundle, plugin, in_bin, out_bin],
                          stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, timeout=600)
    wall = time.perf_counter() - begin
    tail = proc.stdout.decode(errors="replace")[-400:]
    if proc.returncode != 0 or b"EXECUTE OK" not in proc.stdout:
        record["execute"] = "failed: %s" % tail.strip()
        return record
    got = numpy.fromfile(out_bin, numpy.float32).reshape(8, -1)
    want = numpy.asarray(wf._fused_runner.eval_forward()(
        wf._fused_runner.state, x))
    record.update({
        "execute": "ok",
        "compile_plus_infer_wall_s": round(wall, 2),
        "max_abs_diff_vs_framework": float(numpy.abs(got - want).max()),
        "parity": bool(numpy.allclose(got, want, rtol=1e-3, atol=1e-3)),
    })
    return record


# --------------------------------------------------- lrn backend (XLA/Pallas)
def bench_lrn_backends(iters=8, smoke=False, publish=None):
    """XLA-vs-Pallas LRN comparison at the AlexNet-LRN1 train shape
    (fwd+bwd — the top memory-bound item of the post-bf16 step,
    docs/PERF.md round-5 analysis): per-application device time by
    in-jit K-vs-1 repetition.  The winner keeps the default
    (functional._LRN_BACKEND).  ``publish`` streams the partial record
    after each backend (see bench_sgd_backends)."""
    import jax
    import jax.numpy as jnp
    from veles_tpu.ops import functional as F

    shape = (8, 28, 28, 32) if smoke else (128, 55, 55, 96)
    if smoke:
        iters = 2                 # interpret-mode pallas is slow off-TPU
    key = jax.random.PRNGKey(0)
    x0 = jax.random.normal(key, shape, jnp.float32)
    dy0 = jax.random.normal(jax.random.fold_in(key, 1), shape,
                            jnp.float32)
    record = {"shape": list(shape)}
    for backend in ("xla", "pallas"):
        F.set_lrn_backend(backend)
        try:
            def fwd_bwd(x, dy, k):
                def body(i, acc):
                    y, vjp = jax.vjp(F.lrn_forward, acc)
                    (dx,) = vjp(dy)
                    return dx
                return jax.lax.fori_loop(0, k, body, x)

            f1 = jax.jit(lambda x, dy: fwd_bwd(x, dy, 1))
            fk = jax.jit(lambda x, dy: fwd_bwd(x, dy, 1 + iters))
            _sync(f1(x0, dy0)); _sync(fk(x0, dy0))       # compile
            record[backend + "_us"] = round(
                (best_time(lambda: fk(x0, dy0))
                 - best_time(lambda: f1(x0, dy0))) / iters * 1e6, 2)
            if publish:
                publish(record)
        finally:
            F.set_lrn_backend("xla")
    if "xla_us" in record and "pallas_us" in record:
        record["winner"] = ("pallas" if record["pallas_us"] <
                            record["xla_us"] else "xla")
    return record


# --------------------------------------------------- records input pipeline
def records_fixture(tmpdir, data, labels, mb):
    """Write a record file and open it through RecordsLoader — the shared
    fixture for the records-path benches.  Returns (memmap_src, labels)."""
    from veles_tpu.loader.records import write_records, RecordsLoader
    path = write_records(tmpdir + "/bench.rec", data, labels,
                         [0, 0, len(data)])
    loader = RecordsLoader(None, path=path, minibatch_size=mb,
                           name="recloader")
    loader.initialize()
    return loader._data, numpy.asarray(loader._labels)


def bench_records(smoke=False, seconds=2.0):
    """Throughput of the record-file input pipeline (VERDICT r3 Weak #7:
    the streaming path a real ImageNet epoch needs, never benched):
    memmap gather + uint8→[-1,1] float32 convert per minibatch, native
    C++ (loader hot path) vs the numpy fallback.  Host-side — the number
    is platform-independent and bounds the achievable samples/s of any
    records-fed training run."""
    import tempfile
    from veles_tpu import native

    n, hw, mb = (256, 32, 32) if smoke else (2048, 128, 128)
    rng = numpy.random.RandomState(0)
    data = rng.randint(0, 256, (n, hw, hw, 3), numpy.uint8)
    labels = (numpy.arange(n) % 100).astype(numpy.int32)
    record = {"images": n, "hw": hw, "minibatch": mb,
              "native_available": native.available()}
    with tempfile.TemporaryDirectory() as tmp:
        src, lab = records_fixture(tmp, data, labels, mb)

        def timed(gather):
            idx = rng.randint(0, n, mb).astype(numpy.int32)
            gather(idx)  # warm (page in the mmap, build the .so)
            done, begin = 0, time.perf_counter()
            while time.perf_counter() - begin < seconds:
                idx = rng.randint(0, n, mb).astype(numpy.int32)
                gather(idx)
                done += mb
            return done / (time.perf_counter() - begin)

        sps_native = timed(lambda idx: (
            native.gather_convert(src, idx, scale=1.0 / 127.5, offset=-1.0),
            native.gather_labels(numpy.asarray(lab), idx)))
        out = numpy.empty((mb,) + src.shape[1:], numpy.float32)
        sps_numpy = timed(lambda idx: native._numpy_gather(
            src, idx, 1.0 / 127.5, -1.0, out))
        sample_mb = data[0].nbytes / 1e6
        record["samples_per_sec"] = round(sps_native, 1)
        record["numpy_fallback_samples_per_sec"] = round(sps_numpy, 1)
        record["read_mb_per_sec"] = round(sps_native * sample_mb, 1)
    return record


# ------------------------------------------------------------- numpy floor
def bench_numpy_floor(wf, min_seconds=3.0):
    """The reference's numpy backend, reproduced: python minibatch loop with
    numpy GEMMs, same 784->100(tanh)->10(softmax) + momentum SGD."""
    loader = wf.loader
    data = numpy.asarray(loader.original_data.mem)
    labels = numpy.asarray(loader.original_labels.mem)
    idx, mask = epoch_plan_arrays(loader)
    rng = numpy.random.RandomState(1)
    w1 = rng.uniform(-0.1, 0.1, (784, 100)).astype(numpy.float32)
    b1 = numpy.zeros(100, numpy.float32)
    w2 = rng.uniform(-0.1, 0.1, (100, 10)).astype(numpy.float32)
    b2 = numpy.zeros(10, numpy.float32)
    vw1 = numpy.zeros_like(w1); vb1 = numpy.zeros_like(b1)
    vw2 = numpy.zeros_like(w2); vb2 = numpy.zeros_like(b2)
    lr, mom = 0.03, 0.9
    a, bconst = 1.7159, 0.6666

    done_samples = 0
    begin = time.perf_counter()
    while time.perf_counter() - begin < min_seconds:
        for mb_idx, mb_mask in zip(idx, mask):
            x = data[mb_idx]
            lab = labels[mb_idx]
            n = int(mb_mask.sum())
            y1 = a * numpy.tanh(bconst * (x @ w1 + b1))
            z2 = y1 @ w2 + b2
            e = numpy.exp(z2 - z2.max(axis=1, keepdims=True))
            probs = e / e.sum(axis=1, keepdims=True)
            onehot = numpy.eye(10, dtype=numpy.float32)[lab]
            err2 = (probs - onehot) * mb_mask[:, None]
            gw2 = y1.T @ err2 / n
            gb2 = err2.sum(0) / n
            err1 = (err2 @ w2.T) * (bconst * (a - y1 * y1 / a))
            gw1 = x.T @ err1 / n
            gb1 = err1.sum(0) / n
            vw2 = mom * vw2 - lr * gw2; w2 += vw2
            vb2 = mom * vb2 - lr * gb2; b2 += vb2
            vw1 = mom * vw1 - lr * gw1; w1 += vw1
            vb1 = mom * vb1 - lr * gb1; b1 += vb1
            done_samples += n
    return done_samples / (time.perf_counter() - begin)


KNOWN_CONFIGS = ("mnist", "cifar", "alexnet", "alexnet_records", "sgd",
                 "lrn", "records", "convergence", "lm", "scaling",
                 "native")
#: record name -> the worker config that produces it (the config whose
#: ``<name>_error`` explains the record's absence); tools/bench_report.py
#: renders failures from this vocabulary, so keep it next to the configs
RECORD_WORKERS = {"mnist_fc": "mnist", "cifar_conv": "cifar",
                  "cifar_conv_bf16": "cifar", "alexnet": "alexnet",
                  "alexnet_bf16": "alexnet", "alexnet_fast": "alexnet",
                  "alexnet_records": "alexnet_records",
                  "char_lm": "lm", "sgd_update": "sgd",
                  "lrn_fwd_bwd": "lrn", "records_pipeline": "records",
                  "dp_scaling": "scaling", "native_runner": "native"}
#: "convergence" expands to one watchdog worker per sub-bench, so a hang
#: in one (e.g. a tunnel death mid-compile) cannot discard the others
CONVERGENCE_SUBS = ("kohonen", "mnist_fc", "cifar_conv",
                    "cifar_conv_bf16", "mnist_ae")


def expand_configs(wanted):
    out = []
    for c in wanted:
        if c == "convergence":
            out.extend("convergence:" + s for s in CONVERGENCE_SUBS)
        else:
            out.append(c)
    return out


def probe_device(timeout_s=None):
    """Tiny compile+fetch under a hard deadline.  A wedged TPU-tunnel relay
    makes any dispatch hang FOREVER (observed for hours in round 4), so
    the probe runs on a daemon thread and the caller gives up on it."""
    import threading
    probe_ok = []

    def _probe():
        import jax
        probe_ok.append(_sync(jax.jit(lambda a: a + 1)(numpy.ones(2))))

    probe = threading.Thread(target=_probe, daemon=True)
    probe.start()
    probe.join(timeout=timeout_s if timeout_s is not None
               else float(os.environ.get("VELES_BENCH_PROBE_S", 300)))
    return bool(probe_ok)


class _StreamingResults(dict):
    """Worker-side results dict that (when VELES_BENCH_STREAM=1, set by
    the orchestrator) emits each completed record to stdout the moment it
    lands, as a ``{"partial": {...}}`` JSON line.  Round-5 lesson: the
    cifar worker measured cifar_conv, then hung on the bf16 leg, and the
    watchdog kill discarded the good record with the bad — partials let
    the orchestrator keep everything measured before a hang."""

    def _stream(self, payload):
        if os.environ.get("VELES_BENCH_STREAM") == "1":
            print(json.dumps({"partial": payload}), flush=True)

    def __setitem__(self, key, value):
        super().__setitem__(key, value)
        self._stream({key: value})

    def stream_all(self):
        self._stream(dict(self))


def run_configs(wanted, args):
    """Run the wanted bench configs in THIS process; returns the results
    dict (per-config records and/or ``<name>_error`` entries)."""
    if args.smoke:
        import jax
        jax.config.update("jax_platforms", "cpu")
        sizes = {"mnist": (2000, 500, 100), "cifar": (500, 100, 50),
                 "alexnet": (64, 16, 16)}
        alex_kwargs = dict(image_hw=(64, 64), n_classes=10, crop=(56, 56))
        target, floor_seconds = args.seconds or 0.5, 0.5
    else:
        sizes = {"mnist": (60000, 10000, 100), "cifar": (50000, 10000, 100),
                 "alexnet": (1024, 128, 128)}
        alex_kwargs = {}
        target, floor_seconds = args.seconds or 4.0, 3.0

    # VELES_BENCH_SIMULATE_DEAD_TUNNEL=1 makes DEVICE workers (not
    # --smoke, not orchestrate's cpu-pinned host_only workers) behave as
    # if the tunnel were wedged — tests the degraded-record path;
    # probe_device itself stays honest (the __probe__ worker and the
    # recovery watcher must never be fooled)
    simulated_dead = (
        os.environ.get("VELES_BENCH_SIMULATE_DEAD_TUNNEL", "0")
        not in ("", "0")
        and not args.smoke
        and os.environ.get("JAX_PLATFORMS") != "cpu")
    enable_compile_cache()
    if simulated_dead or not probe_device():
        return {"error": "device probe did not complete — "
                         "TPU tunnel unreachable"}

    device_kind, peak = _peak_tflops()
    results = _StreamingResults()

    def guarded(section, fn):
        """One config blowing up must not zero the whole bench record."""
        import traceback
        try:
            fn()
            # re-stream the whole dict: records grow in place after their
            # first assignment (floors, parity sub-records), and the
            # orchestrator's partial collection must see the final shape
            results.stream_all()
        except Exception:
            traceback.print_exc()
            results[section + "_error"] = traceback.format_exc()[-800:]

    def _bench_mnist():
        wf = build_mnist(*sizes["mnist"])
        results["mnist_fc"] = bench_config(
            "mnist_fc", wf, target, device_kind, peak, "fp32_highest")
        floor = bench_numpy_floor(wf, min_seconds=floor_seconds)
        results["mnist_fc"]["numpy_floor_samples_per_sec"] = round(floor, 1)
        results["mnist_fc"]["vs_numpy_floor"] = round(
            results["mnist_fc"]["samples_per_sec"] / floor, 2)
        # int8-artifact predict parity ON THIS DEVICE (VERDICT r4 task 8:
        # the CPU-side test exists; this puts the TPU number in the
        # bench record): quantized vs fp32 artifact outputs
        import tempfile
        from veles_tpu import export
        d = tempfile.mkdtemp()
        fp = export.export_model(wf, os.path.join(d, "m.veles"))
        qp = export.export_model(wf, os.path.join(d, "m8.veles"),
                                 quantize="int8")
        ref, qm = export.load_model(fp), export.load_model(qp)
        x = numpy.random.RandomState(0).uniform(
            -1, 1, (256, 784)).astype(numpy.float32)
        a, b = ref.predict(x), qm.predict(x)
        results["mnist_fc"]["artifact_int8_parity"] = {
            "argmax_agreement": float(
                (a.argmax(1) == b.argmax(1)).mean()),
            "max_abs_diff": float(numpy.abs(a - b).max()),
        }

    if "mnist" in wanted:
        guarded("mnist", _bench_mnist)

    def bench_bf16_variant(name, build_fn):
        """The TPU-idiomatic fast path: bf16 operand casts inside the
        step, then restore parity precision."""
        from veles_tpu.ops import functional as F
        with F.matmul_precision("bfloat16"):
            results[name] = bench_config(
                name, build_fn(), target, device_kind, peak, "bf16_cast")

    def _bench_cifar():
        wf = build_cifar(*sizes["cifar"])
        results["cifar_conv"] = bench_config(
            "cifar_conv", wf, target, device_kind, peak, "fp32_highest")
        bench_bf16_variant("cifar_conv_bf16",
                           lambda: build_cifar(*sizes["cifar"]))

    if "cifar" in wanted:
        guarded("cifar", _bench_cifar)

    def _bench_alexnet():
        wf = build_alexnet(*sizes["alexnet"], **alex_kwargs)
        results["alexnet"] = bench_config(
            "alexnet", wf, target, device_kind, peak, "fp32_highest")
        bench_bf16_variant(
            "alexnet_bf16",
            lambda: build_alexnet(*sizes["alexnet"], **alex_kwargs))
        # the full fast path: bf16 convs + the fused Pallas LRN — shown
        # NEXT TO alexnet_bf16 so the LRN kernel's end-to-end effect is
        # a diff between two records, win or lose (docs/PERF.md r5)
        from veles_tpu.ops import functional as F
        F.set_lrn_backend("pallas")
        try:
            bench_bf16_variant(
                "alexnet_fast",
                lambda: build_alexnet(*sizes["alexnet"], **alex_kwargs))
        finally:
            F.set_lrn_backend("xla")

    if "alexnet" in wanted:
        guarded("alexnet", _bench_alexnet)

    def _bench_alexnet_records():
        # end-to-end: the training step fed from a real records file
        # through the native gather path (VERDICT r3 Weak #7: the
        # HBM-resident bench never included input-pipeline cost).  Own
        # worker: the per-minibatch step is a FRESH compile, and a hang
        # here must not discard the HBM numbers
        wf = build_alexnet(*sizes["alexnet"], **alex_kwargs)
        results["alexnet_records"] = bench_alexnet_records(
            wf, target_seconds=target, smoke=args.smoke)
        print("alexnet_records: %s" % results["alexnet_records"],
              file=sys.stderr)

    if "alexnet_records" in wanted:
        guarded("alexnet_records", _bench_alexnet_records)

    conv_sel = set()
    for c in wanted:
        if c == "convergence":
            conv_sel.update(CONVERGENCE_SUBS)
        elif c.startswith("convergence:"):
            conv_sel.add(c.split(":", 1)[1])
    if conv_sel:
        # small-but-real convergence runs (val-acc is the OTHER half of the
        # BASELINE acceptance); sizes keep the wall time in minutes on TPU
        # (and seconds in --smoke: fp32-HIGHEST convs on CPU are SLOW)
        if args.smoke:
            conv_sizes = {"mnist": (2000, 500, 100),
                          "cifar": (200, 100, 50),
                          "ae": (500, 200, 50)}
            conv_epochs = {"mnist": (8, 4), "cifar": (4, 2), "ae": (4, 2)}
        else:
            conv_sizes = {"mnist": (60000, 10000, 100),
                          "cifar": (10000, 2000, 100),
                          "ae": (10000, 2000, 100)}
            conv_epochs = {"mnist": (15, 5), "cifar": (15, 5),
                           "ae": (10, 4)}

        def build_ae():
            """MNIST conv autoencoder (BASELINE config[3]) at bench sizes;
            metric = mean per-sample squared reconstruction error."""
            from veles_tpu import prng
            from veles_tpu.config import root
            prng.reset()
            prng.seed_all(1)
            n_train, n_valid, mb = conv_sizes["ae"]
            root.__dict__.pop("mnist_ae", None)
            root.mnist_ae.update({
                "loader": {"minibatch_size": mb, "n_train": n_train,
                           "n_valid": n_valid},
                "decision": {"max_epochs": 1000, "fail_iterations": 1000},
            })
            from veles_tpu.samples import mnist_ae
            wf = mnist_ae.build(fused=True)
            wf.initialize()
            return wf

        def _bench_kohonen():
            """SOM quantization error to Decision-complete (row 3's
            unsupervised half).  Non-SGD graph path — the trainer
            dispatches per minibatch, so sizes stay small."""
            from veles_tpu import prng
            from veles_tpu.config import root
            prng.reset()
            prng.seed_all(1)
            root.__dict__.pop("kohonen", None)
            from veles_tpu.samples import kohonen
            kohonen.default_config()
            root.kohonen.update({
                "loader": {"minibatch_size": 100,
                           "n_train": 500 if args.smoke else 2000},
                "decision": {"max_epochs": 4 if args.smoke else 10,
                             "fail_iterations": 20},
            })
            begin = time.perf_counter()
            wf = kohonen.train()
            qerrs = [m["train"]["qerr"]
                     for m in wf.decision.epoch_metrics]
            results["convergence_kohonen"] = {
                "first_epoch_qerr": round(qerrs[0], 4),
                "best_qerr": round(min(qerrs), 4),
                "epochs_run": len(qerrs),
                "wall_s": round(time.perf_counter() - begin, 1),
            }
            print("convergence kohonen: %s"
                  % results["convergence_kohonen"], file=sys.stderr)

        if "kohonen" in conv_sel:
            guarded("convergence_kohonen", _bench_kohonen)

        for name, build_fn in (
                ("mnist_fc", lambda: build_mnist(*conv_sizes["mnist"])),
                ("cifar_conv", lambda: build_cifar(*conv_sizes["cifar"])),
                # bf16 operand casts on the SAME topology/seed/data: the
                # val-err delta vs cifar_conv is the convergence-parity
                # evidence the bf16 conv-net default rests on (PERF.md)
                ("cifar_conv_bf16",
                 lambda: build_cifar(*conv_sizes["cifar"])),
                ("mnist_ae", build_ae)):
            if name not in conv_sel:
                continue
            def _bench_conv(name=name, build_fn=build_fn):
                key = {"mnist_fc": "mnist", "cifar_conv": "cifar",
                       "cifar_conv_bf16": "cifar", "mnist_ae": "ae"}[name]
                epochs, patience = conv_epochs[key]
                from veles_tpu.ops import functional as F
                with F.matmul_precision("bfloat16" if name.endswith("_bf16")
                                        else "float32"):
                    results["convergence_" + name] = bench_convergence(
                        build_fn, max_epochs=epochs, patience=patience)
                print("convergence %s: %s"
                      % (name, results["convergence_" + name]),
                      file=sys.stderr)
            guarded("convergence_" + name, _bench_conv)

    def _publisher(key):
        """Stream a copy of a growing record under ``key`` (partials
        survive a later-leg hang; copies keep streamed snapshots
        immune to in-place mutation)."""
        return lambda r: results.__setitem__(key, dict(r))

    def _bench_lm():
        results["char_lm"] = bench_lm(
            smoke=args.smoke, publish=_publisher("char_lm"))
        print("char_lm: %s" % results["char_lm"], file=sys.stderr)

    if "lm" in wanted:
        guarded("lm", _bench_lm)

    def _bench_scaling():
        results["dp_scaling"] = bench_scaling(smoke=args.smoke)
        print("dp_scaling: %s" % results["dp_scaling"], file=sys.stderr)

    if "scaling" in wanted:
        guarded("scaling", _bench_scaling)

    def _bench_sgd():
        results["sgd_update"] = bench_sgd_backends(
            smoke=args.smoke, publish=_publisher("sgd_update"))
        print("sgd_update: %s" % results["sgd_update"], file=sys.stderr)

    if "sgd" in wanted:
        guarded("sgd", _bench_sgd)

    def _bench_lrn():
        results["lrn_fwd_bwd"] = bench_lrn_backends(
            smoke=args.smoke, publish=_publisher("lrn_fwd_bwd"))
        print("lrn_fwd_bwd: %s" % results["lrn_fwd_bwd"],
              file=sys.stderr)

    if "lrn" in wanted:
        guarded("lrn", _bench_lrn)

    def _bench_native():
        if args.in_process and not args.smoke:
            # bench_native_runner pins THIS process's jax to cpu (the
            # tunnel must belong to the C++ client alone) — under
            # --in-process that would poison sibling configs' device
            # numbers or contend for the tunnel; the watchdog-worker
            # path is the supported one
            results["native_runner"] = {
                "skipped": "needs its own worker process — run without "
                           "--in-process"}
            return
        results["native_runner"] = bench_native_runner(smoke=args.smoke)
        print("native_runner: %s" % results["native_runner"],
              file=sys.stderr)

    if "native" in wanted:
        guarded("native", _bench_native)

    def _bench_recs():
        results["records_pipeline"] = bench_records(
            smoke=args.smoke, seconds=min(target, 4.0))
        print("records_pipeline: %s" % results["records_pipeline"],
              file=sys.stderr)

    if "records" in wanted:
        guarded("records", _bench_recs)

    return results


def summary_record(results):
    """Build (record, exit_code) for the driver's summary JSON line —
    the metric-selection priority lives HERE so the final emit and the
    per-leg partial stream (``orchestrate``) can never disagree on
    shape."""
    hbm = results.get("alexnet", {})
    rec = results.get("alexnet_records", {})
    if isinstance(rec, dict) and rec.get("samples_per_sec") and \
            isinstance(hbm, dict) and hbm.get("samples_per_sec"):
        # 1.0 = the records input path is fully hidden behind compute
        rec["pipeline_ratio_vs_hbm"] = round(
            rec["samples_per_sec"] / hbm["samples_per_sec"], 3)
    model_results = [k for k in results
                     if isinstance(results[k], dict)
                     and "samples_per_sec" in results[k]
                     and k != "records_pipeline"]  # host-side, not a model
    if model_results:
        headline_name = ("mnist_fc" if "mnist_fc" in results
                         else model_results[0])
        headline = results[headline_name]
        return {
            "metric": "%s_train_samples_per_sec_per_chip" % headline_name,
            "value": headline["samples_per_sec"],
            "unit": "samples/sec",
            "vs_baseline": headline.get("vs_numpy_floor"),
            "configs": results,
        }, 0
    if "sgd_update" in results:   # aux-only invocation
        return {
            "metric": "sgd_update_device_us",
            "value": results["sgd_update"].get("xla_us"),
            "unit": "us",
            "vs_baseline": None,
            "configs": results,
        }, 0
    if "lrn_fwd_bwd" in results:
        return {
            "metric": "lrn_fwd_bwd_device_us",
            "value": results["lrn_fwd_bwd"].get("xla_us"),
            "unit": "us",
            "vs_baseline": None,
            "configs": results,
        }, 0
    if "records_pipeline" in results:
        # preferred over native_runner: always carries a real value
        # (the native record may be selfcheck-only on a dead tunnel)
        return {
            "metric": "records_pipeline_samples_per_sec",
            "value": results["records_pipeline"]["samples_per_sec"],
            "unit": "samples/sec",
            "vs_baseline": None,
            "configs": results,
        }, 0
    if "native_runner" in results:
        return {
            "metric": "native_runner_compile_plus_infer_wall_s",
            "value": results["native_runner"].get(
                "compile_plus_infer_wall_s"),
            "unit": "s",
            "vs_baseline": None,
            "configs": results,
        }, 0
    if "char_lm" in results:
        return {
            "metric": "char_lm_train_tokens_per_sec",
            "value": results["char_lm"]["tokens_per_sec"],
            "unit": "tokens/sec",
            "vs_baseline": None,
            "configs": results,
        }, 0
    if results.get("dp_scaling", {}).get("scaling_efficiency") \
            is not None:
        return {
            "metric": "dp_scaling_efficiency",
            "value": results["dp_scaling"].get("scaling_efficiency"),
            "unit": "fraction",
            "vs_baseline": None,
            "configs": results,
        }, 0
    if "skipped" in results.get("dp_scaling", {}):
        # a skipped scaling probe on a single-device host is a SUCCESS
        # (the record documents why), not a bench failure
        return {
            "metric": "dp_scaling_skipped",
            "value": None,
            "unit": "",
            "vs_baseline": None,
            "configs": results,
        }, 0
    if any(k.startswith("convergence_") and isinstance(results[k], dict)
           for k in results):   # convergence-only invocation
        keys = [k for k in ("convergence_mnist_fc", "convergence_cifar_conv",
                            "convergence_mnist_ae", "convergence_kohonen")
                if isinstance(results.get(k), dict)]
        keys += [k for k in results if k.startswith("convergence_")
                 and isinstance(results[k], dict) and k not in keys]
        units = {"best_val_err_pct": "percent", "best_val_mse": "mse",
                 "best_qerr": "qe"}
        key, suffix, value, unit = None, None, None, ""
        for k in keys:
            hit = next((sfx for sfx in units if sfx in results[k]), None)
            if hit is not None:
                key, suffix = k, hit
                value, unit = results[k][hit], units[hit]
                break
        if key is None:   # convergence dicts with no known metric key
            key, suffix = keys[0], "record"
            value = None
        return {
            "metric": "%s_%s" % (key, suffix),
            "value": value,
            "unit": unit,
            "vs_baseline": None,
            "configs": results,
        }, 0
    # everything failed: still emit the one JSON line with errors
    return {
        "metric": "bench_failed",
        "value": None,
        "unit": "",
        "vs_baseline": None,
        "configs": results,
    }, 1


def emit_summary(results):
    """Print the FINAL summary JSON line the driver records (the last
    parseable line of stdout wins); returns the exit code."""
    rec, code = summary_record(results)
    print(json.dumps(rec), flush=True)
    return code


def collect_worker_output(stdout_bytes):
    """Merge every parseable worker stdout line: ``partial`` lines stream
    in as records complete (kept even when the worker is later killed);
    the final ``results`` line, when present, wins.  Returns
    (records_dict, saw_final_line)."""
    got = {}
    final = None
    for raw in (stdout_bytes or b"").decode(errors="replace").splitlines():
        raw = raw.strip()
        if not raw.startswith("{"):
            continue
        try:
            obj = json.loads(raw)
        except ValueError:
            continue
        if "partial" in obj:
            got.update(obj["partial"])
        elif "results" in obj:
            final = obj["results"]
    if final is not None:
        got.update(final)
    return got, final is not None


class OuterTimeout(BaseException):
    """Raised by the SIGTERM handler: the DRIVER's outer watchdog fired
    (round-5 lesson, BENCH_r05.json: a wedged relay burned the whole
    outer `timeout` budget in probe retries and the bench died with
    rc 124 and NO JSON line — every already-measured record lost).
    BaseException so no blanket per-config `except Exception` can eat
    it on the way out."""


def total_deadline():
    """Monotonic deadline for the WHOLE bench run (VELES_BENCH_TOTAL_S,
    0 disables): finishing — with partials — BEFORE the driver's outer
    timeout is the only way to exit 0 with the record intact, because
    GNU timeout reports 124 regardless of the child's own exit code."""
    total = float(os.environ.get("VELES_BENCH_TOTAL_S", 1680))
    return (time.monotonic() + total) if total > 0 else None


def orchestrate(wanted, args, argv, results=None, deadline=None):
    """Run each config in its own subprocess under a hard deadline.

    Round-4 lesson: a tunnel that dies MID-RUN leaves the next XLA compile
    hanging forever inside a C++ call no in-process guard can interrupt —
    the whole bench then gets killed from outside without ever printing
    its JSON line.  Per-config worker processes bound the damage: a hung
    config is killed and recorded as an error, the rest still run, and the
    one-line contract always holds.  Workers run STRICTLY sequentially
    (the TPU tunnel admits one client at a time) and the parent never
    imports jax (an idle client could hold the tunnel claim).

    ``results`` (when given) is mutated IN PLACE so the caller's SIGTERM
    handler can emit whatever was measured if the outer watchdog fires
    mid-config; ``deadline`` (time.monotonic()) bounds the whole run —
    configs that would start too close to it are recorded as skipped so
    the summary line still gets out in time.
    """
    import subprocess
    per_config = float(os.environ.get(
        "VELES_BENCH_CONFIG_TIMEOUT_S", 300 if args.smoke else 1500))
    # total seconds the run may spend WAITING for a wedged relay to
    # release its claim (a killed-mid-claim client wedges it until the
    # grant timeout) before remaining device configs are skipped
    recover_budget = float(os.environ.get("VELES_BENCH_RECOVER_S", 1800))
    # configs that never touch the device (host pipeline; the native
    # runner pins its worker to cpu): they still run — and still produce
    # records — when the tunnel is dead, so a dead-tunnel bench degrades
    # to a valid host-side record instead of round-4's empty bench_failed
    host_only = {"records", "native"}
    if results is None:
        results = {}

    def stream_summary():
        """One full summary line after EVERY completed leg — not only on
        SIGTERM.  BENCH_r04/r05 lesson: `timeout -k` follows TERM with
        KILL, and a KILLed process runs no handler — rc 124 landed with
        "parsed": null even though legs had finished.  The driver takes
        the LAST parseable stdout line, so streaming the running record
        here means any kill, however rude, still leaves every completed
        leg in the output JSON."""
        rec, _ = summary_record(results)
        print(json.dumps(rec), flush=True)

    def time_left():
        return (float("inf") if deadline is None
                else deadline - time.monotonic())
    tunnel_dead = False

    def probe_ok():
        """Probe in a subprocess (the parent never imports jax).  The
        probe worker's deadline is pinned via the env var so the parent's
        subprocess timeout is always the longer one, and any probe
        failure mode just means 'treat the tunnel as dead'."""
        try:
            env = dict(os.environ, VELES_BENCH_PROBE_S="120")
            probe = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--worker", "__probe__"] + argv,
                stdout=subprocess.PIPE, timeout=180, env=env, check=False)
            out = (probe.stdout.decode(errors="replace")
                   .strip().splitlines())
            return bool(out and json.loads(out[-1]).get("ok"))
        except Exception:
            return False

    for name in wanted:
        if time_left() < 60:
            # too close to the driver's outer watchdog to start another
            # config: record the skip and keep going (cheap) so the
            # summary emits while we still own the process
            results[name + "_error"] = (
                "skipped: total bench deadline reached "
                "(VELES_BENCH_TOTAL_S) — partial results emitted")
            stream_summary()
            continue
        if tunnel_dead and name not in host_only:
            # wait out the relay grant timeout while budget remains —
            # round-5 lesson: one hung config used to forfeit every
            # remaining device record even though the relay recovers.
            # The probe-retry loop is ALSO deadline-bounded: r05 died
            # burning the outer timeout right here, losing the record
            while recover_budget > 0 and time_left() > 180:
                begin = time.time()
                if probe_ok():
                    recover_budget -= time.time() - begin
                    tunnel_dead = False
                    break
                recover_budget -= time.time() - begin
                pause = min(120.0, recover_budget, max(time_left() - 180,
                                                       0))
                if pause <= 0:
                    break
                print("[bench] relay wedged; retrying probe in %.0fs "
                      "(%.0fs recovery budget left)" % (pause,
                                                        recover_budget),
                      file=sys.stderr)
                time.sleep(pause)
                recover_budget -= pause
        if tunnel_dead and name not in host_only:
            results[name + "_error"] = ("skipped: device unreachable "
                                        "after an earlier config hung")
            stream_summary()
            continue
        cmd = [sys.executable, os.path.abspath(__file__),
               "--worker", name] + argv
        env = dict(os.environ, VELES_BENCH_STREAM="1")
        if name in host_only:
            # cpu-pinned worker: the host-side config must not claim (or
            # hang on) the one-client-at-a-time tunnel — for 'native'
            # specifically, the C++ runner must be the only claimant
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
            if tunnel_dead:
                # the native EXECUTE leg is itself a tunnel client; a
                # wedged relay would burn its full timeouts — tell the
                # worker to stop after build+selfcheck+export
                env["VELES_BENCH_TUNNEL_DEAD"] = "1"
        # a worker may not outlive the total deadline either — cap its
        # watchdog so ITS kill (and partial collection) happens while
        # the parent can still print the summary line
        worker_timeout = min(per_config, max(time_left() - 60, 30))
        try:
            proc = subprocess.run(cmd, stdout=subprocess.PIPE,
                                  timeout=worker_timeout, env=env)
            got, complete = collect_worker_output(proc.stdout)
            if not got and not complete:
                got = {name + "_error":
                       "worker produced no output (rc=%s)"
                       % proc.returncode}
            if "error" in got:   # in-worker probe never came back
                got = {name + "_error": got.pop("error"), **got}
                tunnel_dead = True
            results.update(got)
        except subprocess.TimeoutExpired as exc:
            got, _ = collect_worker_output(exc.stdout)  # keep pre-hang records
            results.update(got)
            results[name + "_error"] = ("killed after %.0fs (hung device "
                                        "dispatch/compile)"
                                        % worker_timeout)
            tunnel_dead = True
        except Exception as exc:   # worker crash / bad output
            results[name + "_error"] = "worker failed: %r" % (exc,)
        stream_summary()
    return results


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true",
                        help="tiny sizes on CPU for CI validation")
    parser.add_argument("--configs",
                        # most-valuable-first: the relay has wedged
                        # during a conv-program compile in 3/3 hardware
                        # sessions, and a wedge forfeits every config
                        # behind it — so the headline alexnet records
                        # run before cifar, and the cheap sgd/lrn/lm
                        # kernels before the long convergence legs.
                        # The order applies to the orchestrated
                        # (watchdog-subprocess) path; run_configs
                        # (--in-process / --smoke) keeps its fixed
                        # source order, which only matters off the
                        # wedge-prone tunnel anyway
                        default="mnist,alexnet,cifar,sgd,lrn,lm,"
                                "convergence,alexnet_records,records,"
                                "scaling,native",
                        help="comma list: " + ",".join(KNOWN_CONFIGS))
    parser.add_argument("--seconds", type=float, default=None,
                        help="target seconds per timing window")
    parser.add_argument("--in-process", action="store_true",
                        help="run all configs in this process (no "
                             "per-config watchdog subprocesses)")
    parser.add_argument("--worker", default=None, metavar="CONFIG",
                        help=argparse.SUPPRESS)   # internal: one config
    args = parser.parse_args()

    if args.worker == "__probe__":
        print(json.dumps({"ok": probe_device(
            float(os.environ.get("VELES_BENCH_PROBE_S", 120)))}))
        return 0
    if args.worker is not None:
        results = run_configs([args.worker], args)
        print(json.dumps({"worker": args.worker, "results": results}))
        return 0

    wanted = [c.strip() for c in args.configs.split(",") if c.strip()]
    known = set(KNOWN_CONFIGS) | {
        "convergence:" + s for s in CONVERGENCE_SUBS}
    unknown = [c for c in wanted if c not in known]
    if unknown or not wanted:
        parser.error("unknown configs %r (choose from %s)"
                     % (unknown, ", ".join(sorted(known))))

    # The driver runs the bench under an outer `timeout`: if the relay
    # wedge eats the whole budget, TERM arrives here — emit whatever was
    # measured (the one-line contract) and exit 0 instead of dying
    # silently with "parsed": null (BENCH_r05.json's failure mode)
    import signal
    partial = {}

    def _on_term(signum, frame):
        raise OuterTimeout()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:       # non-main thread (embedded use): skip
        pass

    try:
        # --smoke forces CPU, where a wedged-tunnel hang cannot occur —
        # run in process, skip one python+jax cold start per config
        if args.in_process or args.smoke:
            results = run_configs(wanted, args)
        else:
            argv = (["--seconds", str(args.seconds)]
                    if args.seconds else [])
            results = orchestrate(expand_configs(wanted), args, argv,
                                  results=partial,
                                  deadline=total_deadline())
    except OuterTimeout:
        partial["bench_error"] = (
            "terminated by the outer watchdog (SIGTERM) mid-run — "
            "partial results emitted, exit 0")
        emit_summary(partial)
        return 0
    rc = emit_summary(results)
    if rc and results and all(
            isinstance(v, str) and "total bench deadline" in v
            for v in results.values()):
        # nothing measured because the deadline landed before ANY config
        # could start: the wedged-relay partial case, not a bench
        # failure.  A genuine config failure alongside deadline skips
        # keeps the nonzero rc
        return 0
    return rc


if __name__ == "__main__":
    sys.exit(main())
