"""Docs stay truthful: the coverage map's citations must resolve."""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))


def test_coverage_map_citations_resolve():
    import check_coverage_map
    text = (check_coverage_map.REPO / "docs" / "COVERAGE.md").read_text()
    assert check_coverage_map.check(text) == []
