"""Tier-3 CIFAR conv-stack functional tests (BASELINE config[1] shape)."""

import numpy
import pytest

from veles_tpu import prng
from veles_tpu.config import root


def _configure(n_train=600, n_valid=200, max_epochs=8):
    root.cifar.update({
        "loader": {"minibatch_size": 50, "n_train": n_train,
                   "n_valid": n_valid},
        "decision": {"max_epochs": max_epochs, "fail_iterations": 50},
        "layers": [
            {"type": "conv_str", "n_kernels": 16, "kx": 5, "ky": 5,
             "padding": "SAME", "learning_rate": 0.01,
             "weights_stddev": 0.05},
            {"type": "max_pooling", "kx": 4, "ky": 4},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.01, "weights_stddev": 0.05},
        ],
    })


def test_cifar_conv_converges():
    prng.reset(); prng.seed_all(42)
    _configure()
    from veles_tpu.samples import cifar
    wf = cifar.train(fused=True)
    metrics = wf.decision.epoch_metrics
    first = metrics[0]["validation"]["err_pct"]
    final = metrics[-1]["validation"]["err_pct"]
    assert final < 25.0, (first, final)
    assert final < first


def test_cifar_default_topology_converges():
    """The SAMPLE DEFAULT layer stack must be trainable out of the box
    (round 4 found the previous smooth-relu/glorot default stalled at
    chance — convergence of defaults is part of the product contract)."""
    prng.reset(); prng.seed_all(42)
    root.__dict__.pop("cifar", None)
    root.cifar.update({
        "loader": {"minibatch_size": 50, "n_train": 600, "n_valid": 200},
        "decision": {"max_epochs": 8, "fail_iterations": 50},
    })
    from veles_tpu.samples import cifar
    wf = cifar.train(fused=True)
    errs = [m["validation"]["err_pct"] for m in wf.decision.epoch_metrics
            if "validation" in m]
    assert errs[-1] < 10.0, errs


@pytest.mark.slow
# ~28 s: repeats the fp32 convergence run above under bf16 casts; the
# bf16 numerics themselves are unit-pinned and the convergence parity
# is recorded in docs/PERF.md — heavy re-verification rides in the
# slow suite (tier-1 runs within ~2% of its outer watchdog)
def test_cifar_default_topology_converges_bf16():
    """Convergence PARITY under bf16 operand casts (the TPU fast path):
    the same sample-default conv stack, seed and data must reach the
    same <10% val-err bar that the fp32-HIGHEST run does — the CPU half
    of the evidence the bf16 conv-net recommendation rests on (the
    hardware half is bench.py convergence:cifar_conv_bf16)."""
    from veles_tpu.ops import functional as F
    prng.reset(); prng.seed_all(42)
    root.__dict__.pop("cifar", None)
    root.cifar.update({
        "loader": {"minibatch_size": 50, "n_train": 600, "n_valid": 200},
        "decision": {"max_epochs": 8, "fail_iterations": 50},
    })
    from veles_tpu.samples import cifar
    with F.matmul_precision("bfloat16"):
        wf = cifar.train(fused=True)
    errs = [m["validation"]["err_pct"] for m in wf.decision.epoch_metrics
            if "validation" in m]
    assert errs[-1] < 10.0, errs


def test_cifar_fused_and_unit_mode_identical():
    from veles_tpu.samples import cifar
    finals, weights = [], []
    for fused in (True, False):
        prng.reset(); prng.seed_all(42)
        _configure(n_train=200, n_valid=100, max_epochs=1)
        wf = cifar.train(fused=fused)
        finals.append(wf.decision.epoch_metrics[-1]["validation"])
        wf.snapshot_state()
        weights.append([numpy.array(f.weights.mem) for f in wf.forwards
                        if f.has_params])
    assert finals[0]["n_err"] == finals[1]["n_err"]
    for wa, wb in zip(weights[0], weights[1]):
        numpy.testing.assert_allclose(wa, wb, rtol=1e-5, atol=1e-6)


def test_conv_with_dropout_and_lrn_trains():
    """Dropout (stochastic) + LRN layers inside the standard graph."""
    prng.reset(); prng.seed_all(42)
    root.cifar.update({
        "loader": {"minibatch_size": 25, "n_train": 100, "n_valid": 50},
        "decision": {"max_epochs": 2, "fail_iterations": 50},
        "layers": [
            {"type": "conv_str", "n_kernels": 8, "kx": 3, "ky": 3,
             "padding": "SAME", "learning_rate": 0.02},
            {"type": "norm"},
            {"type": "max_pooling", "kx": 4, "ky": 4},
            {"type": "dropout", "dropout_ratio": 0.3},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.02},
        ],
    })
    from veles_tpu.samples import cifar
    wf = cifar.train(fused=True)
    losses = [m["train"]["loss"] for m in wf.decision.epoch_metrics]
    assert losses[-1] < losses[0]
    # eval path (validation) must be deterministic despite dropout:
    val0 = wf.decision.epoch_metrics[0]["validation"]["loss"]
    prng.reset(); prng.seed_all(42)
    root.cifar.update({"decision": {"max_epochs": 1}})
    wf2 = cifar.train(fused=True)
    assert abs(wf2.decision.epoch_metrics[0]["validation"]["loss"] -
               val0) < 1e-6


def test_unit_mode_dropout_off_at_eval():
    """Unit-mode eval minibatches must not apply dropout (fused parity)."""
    prng.reset(); prng.seed_all(42)
    root.cifar.update({
        "loader": {"minibatch_size": 25, "n_train": 50, "n_valid": 50},
        "decision": {"max_epochs": 1, "fail_iterations": 10},
        "layers": [
            {"type": "dropout", "dropout_ratio": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.0},
        ],
    })
    from veles_tpu.samples import cifar
    wf = cifar.build(fused=False)
    wf.initialize()
    wf.loader.run()                      # first VALID minibatch
    assert wf.loader.minibatch_class == 1
    wf.forwards[0].run()
    # eval: identity, no mask applied even at ratio 0.9
    numpy.testing.assert_array_equal(
        numpy.asarray(wf.forwards[0].output.mem),
        numpy.asarray(wf.loader.minibatch_data.mem))


def test_epoch_scan_requires_and_accepts_rng_with_dropout():
    import jax
    prng.reset(); prng.seed_all(42)
    root.cifar.update({
        "loader": {"minibatch_size": 25, "n_train": 50, "n_valid": 25},
        "decision": {"max_epochs": 1, "fail_iterations": 10},
        "layers": [
            {"type": "dropout", "dropout_ratio": 0.5},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.01},
        ],
    })
    from veles_tpu.samples import cifar
    wf = cifar.build(fused=True)
    wf.initialize()
    runner = wf._fused_runner
    train_epoch, _ = runner.epoch_fns()
    loader = wf.loader
    loader._plan_epoch()
    idx = numpy.stack([c for cls, c, a in loader._order if cls == 2])
    mask = numpy.stack([(numpy.arange(len(c)) < a).astype(numpy.float32)
                        for cls, c, a in loader._order if cls == 2])
    data = loader.original_data.devmem
    labels = loader.original_labels.devmem
    try:
        train_epoch(runner.state, data, labels, idx, mask)
        raise AssertionError("expected ValueError without rng")
    except ValueError as e:
        assert "stochastic" in str(e)
    state, totals = train_epoch(runner.state, data, labels, idx, mask,
                                jax.random.PRNGKey(0))
    assert int(totals["n_err"]) >= 0
