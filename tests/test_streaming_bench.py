"""tools/stream_bench.py — the streaming windowed epoch-scan evidence
harness (ISSUE 3 acceptance: overlap is real and measured).

The sustained run is slow-marked (tier-1 skips it); the CLI contract
test runs the tiny shape so the tool itself stays covered.
"""

import json
import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.mark.slow
def test_stream_bench_overlap_and_dispatch_reduction():
    """The acceptance numbers, measured: dispatches per epoch drop from
    ~minibatches to ~windows, and the staging-stall fraction stays under
    50% with stage-ahead 1."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from stream_bench import run_stream_bench
    finally:
        sys.path.pop(0)
    record = run_stream_bench(samples=4096, minibatch=64, window=8,
                              stage_ahead=1, epochs=3)
    mbs = record["train_minibatches_per_epoch"]
    graph_d = record["graph_loop"]["dispatches_per_epoch"]
    stream_d = record["streaming"]["dispatches_per_epoch"]
    windows = record["streaming"]["windows_per_epoch"]
    # graph mode: ~one dispatch per minibatch (train + eval sets)
    assert graph_d >= mbs
    # streaming: ~one dispatch per window (+ per-epoch eval + replay)
    assert stream_d < graph_d / 2
    assert windows <= stream_d <= windows + 3
    assert record["dispatch_reduction"] > 2
    assert record["streaming"]["staging_stall_pct"] < 50.0
    assert record["parity"]["epochs_equal"]


def test_stream_bench_cli_one_json_line():
    """Standalone contract: one parseable JSON line on stdout."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "stream_bench.py"),
         "--samples", "256", "--minibatch", "16", "--window", "3",
         "--epochs", "2"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        cwd=REPO, timeout=300)
    assert proc.returncode == 0
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1
    record = json.loads(lines[0])
    assert record["streaming"]["windows_per_epoch"] > 0
    assert record["graph_loop"]["samples_per_sec"] > 0
