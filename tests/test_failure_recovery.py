"""Failure recovery: SIGKILL mid-training + ``--snapshot auto`` resume.

SURVEY §5.3: the reference detected dead slaves and reissued their jobs
(veles/server.py::drop_slave [H]); on the SPMD substrate that elasticity is
deliberately downgraded to kill-and-resume — a killed run restarts from the
last atomically-published snapshot and must reach the IDENTICAL final state
an unkilled run reaches.  This test proves that contract end to end with a
real SIGKILL against a real training subprocess.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import time


WORKER = os.path.join(os.path.dirname(__file__), "resume_worker.py")


def _run_worker(out_dir, mode, epoch_sleep=0.0, wait=True):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)  # skip the TPU-tunnel plugin
    proc = subprocess.Popen(
        [sys.executable, WORKER, str(out_dir), mode, str(epoch_sleep)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    if not wait:
        return proc
    out, _ = proc.communicate(timeout=240)
    assert proc.returncode == 0, out.decode(errors="replace")[-2000:]
    return proc


def test_sigkill_resume_reaches_identical_state(tmp_path):
    control_dir = tmp_path / "control"
    victim_dir = tmp_path / "victim"
    control_dir.mkdir()
    victim_dir.mkdir()

    # ---- control: straight 6-epoch run
    _run_worker(control_dir, "control")
    with open(control_dir / "control.json", encoding="utf-8") as f:
        control = json.load(f)
    assert control["epochs"] == 6

    # ---- victim: slowed run, SIGKILLed once >=2 snapshots are published
    proc = _run_worker(victim_dir, "victim", epoch_sleep=0.5, wait=False)
    snap_glob = str(victim_dir / "snaps" / "mnist_[0-9]*.pickle")
    deadline = time.time() + 180
    try:
        while time.time() < deadline:
            if len(glob.glob(snap_glob)) >= 2:
                break
            if proc.poll() is not None:
                out = proc.stdout.read().decode(errors="replace")
                raise AssertionError("victim exited before it could be "
                                     "killed:\n" + out[-2000:])
            time.sleep(0.05)
        else:
            raise AssertionError("victim produced no snapshots in time")
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == -signal.SIGKILL
    assert not (victim_dir / "victim.json").exists(), \
        "victim finished cleanly — the kill came too late to prove anything"

    # ---- resume: --snapshot auto picks up the victim's latest snapshot
    _run_worker(victim_dir, "resume")
    with open(victim_dir / "resume.json", encoding="utf-8") as f:
        resumed = json.load(f)

    # identical FINAL state: bit-exact weights, same metric history
    assert resumed["weights_sha"] == control["weights_sha"]
    assert resumed["best_metric"] == control["best_metric"]
    assert resumed["best_epoch"] == control["best_epoch"]
    assert resumed["epochs"] == 6


def test_find_current_ignores_tmp_staging_files(tmp_path):
    """A crash can leave '*_current.pickle.gz.tmp' behind; the auto-resume
    resolver must never pick it (it is raw staged bytes, not a snapshot)."""
    from veles_tpu import snapshotter
    good = tmp_path / "wf_current.pickle.gz"
    good.write_bytes(b"x")
    stale = tmp_path / "wf_current.pickle.gz.tmp"
    stale.write_bytes(b"y")
    os.utime(good, (1000, 1000))  # tmp file is NEWER
    assert snapshotter.find_current(str(tmp_path)) == str(good)
    assert snapshotter.find_current(str(tmp_path), "wf") == str(good)
    assert snapshotter.find_current(str(tmp_path), "other") is None


def test_restore_keeps_runtime_shard_identity(tmp_path):
    """Restoring a process-0 snapshot on a differently-sharded process must
    keep the RUNTIME shard and re-plan, not adopt process 0's shard."""
    from veles_tpu import prng
    from veles_tpu.config import root
    prng.reset()
    prng.seed_all(1)
    root.mnist.update({
        "loader": {"minibatch_size": 10, "n_train": 40, "n_valid": 20},
        "decision": {"max_epochs": 1, "fail_iterations": 5},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 8,
             "learning_rate": 0.05, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.05, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    wf = mnist.build(fused=True)
    wf.initialize()
    state = wf.loader.state_dict()  # written as (0, 1) — the writer process

    # same topology: restored verbatim (bit-exact resume path)
    wf.loader.load_state_dict(state)
    assert wf.loader._shard == (0, 1)
    assert wf.loader._order is not None

    # different topology: runtime identity wins, plan is rebuilt
    wf.loader.shard(1, 2)
    wf.loader.load_state_dict(state)
    assert wf.loader._shard == (1, 2)
    assert wf.loader._order is None and wf.loader._position == 0
    wf.loader.run()  # re-plans for shard (1, 2) without error
    # both classes start at even offsets, so shard (1, 2) sees odd indices
    assert all(int(i) % 2 == 1 for i in wf.loader.minibatch_indices.mem), \
        "re-planned minibatch must come from THIS process's stride"


def test_snapshot_auto_fresh_run(tmp_path):
    """--snapshot auto with an empty snapshot dir is a fresh run."""
    _run_worker(tmp_path, "resume")
    with open(tmp_path / "resume.json", encoding="utf-8") as f:
        result = json.load(f)
    assert result["epochs"] == 6
