"""Seeded recompile-hazard violations (ISSUE 17).

Every marked line must be flagged at exactly that line: traced-body
closure over ``self``, shape-dependent Python branching, Python
concretization of a traced argument, a jit site with no program-family
census entry, and a census entry that lies about its family.  The
census cross-check findings land in ``jitguard_fixture.py`` (the
stand-in jit-guard file), marked there.
"""


class FakeEngine:
    def _jit(self, fn):
        return fn

    def _build(self):
        def step(x, pos):
            if x.shape[0] > 4:                 # EXPECT-LINT recompile-hazard
                x = x + 1
            k = int(pos)                       # EXPECT-LINT recompile-hazard
            return x * self.scale + k          # EXPECT-LINT recompile-hazard

        self._step_jit = self._jit(step)       # EXPECT-LINT recompile-hazard
        # programs: twin
        self._decode_jit = self._jit(step)     # EXPECT-LINT recompile-hazard
        # programs: verify
        self._verify_jit = self._jit(step)     # EXPECT-LINT recompile-hazard

    def _build_while(self):
        import jax

        def cond(c):
            return c < 4

        def body(c):
            return c + 1

        # An uncensused resident loop program (ISSUE 19): a while
        # twin with no census family entry at all, then one whose
        # named family no jit site installs.
        loop = jax.lax.while_loop(cond, body, 0)   # EXPECT-LINT recompile-hazard
        # programs: phantom
        twin = jax.lax.while_loop(cond, body, 0)   # EXPECT-LINT recompile-hazard
        return loop, twin
