"""Lint fixture: a NAMED, reasoned suppression — the access is real
but excepted, so the pass reports nothing and lists the suppression."""

import threading


class Peeker:
    _guarded_by = {"_flag": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._flag = False

    def set(self):
        with self._lock:
            self._flag = True

    def peek(self):
        # lint: allow(lock-discipline): benign racy peek for a fast-path shortcut
        return self._flag
