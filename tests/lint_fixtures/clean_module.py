"""Lint fixture: a module the ISSUE 15 passes must find NOTHING in —
correct lock discipline, a pure scanned body, no suppressions."""

import threading

from jax import lax


class Counter:
    _guarded_by = {"_n": "_lock", "_peak": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._peak = 0

    def bump(self):
        with self._lock:
            self._n += 1
            self._peak = max(self._peak, self._n)

    def read(self):
        with self._lock:
            return self._n

    def _drop(self):
        # caller-holds: _lock
        self._n -= 1

    def drop(self):
        with self._lock:
            self._drop()


def scan_body(carry, x):
    rows = []
    rows.append(x)          # local container: not a closure mutation
    return carry + x, rows[0]


def run(xs):
    return lax.scan(scan_body, 0.0, xs)
