"""Seeded resource-lifecycle violations (ISSUE 17).

Escape-analysis seeds: a Future created and dropped on the floor, a
tracer span and a page allocation resolved only in straight-line code
after raisable calls (the PR 12 hedge-loser-span and PR 6 COW-leak
classes).  The clean shapes — resolution owned by a finally/except,
ownership handed off by storing/returning — must NOT be flagged.
"""

from concurrent.futures import Future


def leak_future(work):
    fut = Future()                   # EXPECT-LINT resource-lifecycle
    work.do()
    return None


def exception_path_span(tracer, engine):
    span = tracer.begin("decode.tick")   # EXPECT-LINT resource-lifecycle
    engine.dispatch()
    tracer.end(span)


def exception_path_pages(pool, table):
    pages = pool.alloc(4)            # EXPECT-LINT resource-lifecycle
    table.install(7)
    pool.release(pages)


def clean_resolved_future(work):
    fut = Future()
    try:
        fut.set_result(work.do())
    except Exception as e:   # noqa: BLE001 — fixture
        fut.set_exception(e)
    return None


def clean_span_finally(tracer, engine):
    span = tracer.begin("decode.tick")
    try:
        engine.dispatch()
    finally:
        tracer.end(span)


def clean_escape_by_handoff(tracer, lane):
    span = tracer.begin("prefill.chunk")
    lane.spans.append(span)


def clean_escape_by_return(pool):
    pages = pool.alloc(2)
    return pages
