"""Lint fixture: a suppression with NO reason string — itself a
finding, and the access it failed to suppress is flagged too."""

import threading


class Sloppy:
    _guarded_by = {"_x": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._x = 0

    def bump(self):
        # lint: allow(lock-discipline):
        self._x += 1
