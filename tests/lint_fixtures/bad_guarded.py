"""Lint fixture: unlocked reads/writes of guarded attributes —
``# EXPECT-LINT <check>`` marks each line the pass must flag."""

import threading


class Queue:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []   # guarded-by: _lock
        self._depth = 0    # guarded-by: _lock

    def push(self, x):
        with self._lock:
            self._items.append(x)
            self._depth += 1

    def steal(self):
        item = self._items.pop()   # EXPECT-LINT lock-discipline
        self._depth -= 1           # EXPECT-LINT lock-discipline
        return item
