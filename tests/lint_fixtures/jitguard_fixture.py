"""Stand-in jit-guard fixture for the census cross-check (ISSUE 17).

Asserts compile-count bounds for families ``step`` and ``orphan`` —
neither of which ``bad_recompile.py``'s census declares — so both
directions of the census↔fixture agreement check fire at the marked
lines.
"""


def check_programs(engine):
    assert engine._step_jit._cache_size() <= 2     # EXPECT-LINT recompile-hazard
    assert engine._orphan_jit._cache_size() <= 1   # EXPECT-LINT recompile-hazard
