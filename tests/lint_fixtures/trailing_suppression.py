"""Lint fixture: a TRAILING suppression covers only its own line —
the unrelated violation directly below it must still be flagged (a
suppression must never swallow a second finding)."""

import threading


class Sneaky:
    _guarded_by = {"_x": "_lock", "_y": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._x = 0
        self._y = 0

    def peek_and_poke(self):
        x = self._x  # lint: allow(lock-discipline): reasoned racy peek
        self._y = x + 1            # EXPECT-LINT lock-discipline
        return x
