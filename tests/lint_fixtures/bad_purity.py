"""Lint fixture: host-side impurity inside traced/scanned bodies —
each flagged construct would be baked in as a trace-time constant (or
a silent host mutation) on a real TPU compile."""

import time

import jax
import numpy as np
from jax import lax

TRACE_LOG = []


def scan_body(carry, x):
    t = time.time()                # EXPECT-LINT traced-purity
    noise = np.random.rand()       # EXPECT-LINT traced-purity
    print("step", t)               # EXPECT-LINT traced-purity
    TRACE_LOG.append(x)            # EXPECT-LINT traced-purity
    return carry + x + noise, x


def run(xs):
    return lax.scan(scan_body, 0.0, xs)


def clean_fn(x):
    return x * 2


fast = jax.jit(clean_fn)
