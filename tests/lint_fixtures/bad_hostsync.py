"""Seeded host-sync violations (ISSUE 17).

Hot-path methods (trailing ``# hot-path`` marker) committing every
implicit-sync sin the pass knows: host coercion of a dispatched
value, ``.item()`` readback, ``jnp`` staging of a dispatch argument,
an un-fenced timing read, and a dispatch issued under a held lock.
The sanctioned shapes (``xfer.to_host`` / ``xfer.to_device``) appear
too and must NOT be flagged.
"""

import time

import numpy

from veles_tpu.serving import xfer


class FakeEngine:
    def _step(self, active):   # hot-path
        toks = self._step_jit(active)
        n = int(toks)                          # EXPECT-LINT host-sync
        arr = numpy.asarray(toks)              # EXPECT-LINT host-sync
        v = toks.item()                        # EXPECT-LINT host-sync
        return n, arr, v

    def _tick(self):   # hot-path
        t0 = time.monotonic()
        out = self._decode_jit(t0)
        self.ewma = time.monotonic() - t0      # EXPECT-LINT host-sync
        return out

    def _stage(self, xs):   # hot-path
        import jax.numpy as jnp
        return self._step_jit(jnp.asarray(xs))   # EXPECT-LINT host-sync

    def _locked(self, x):   # hot-path
        with self._lock:
            return self._step_jit(x)           # EXPECT-LINT host-sync

    def _sanctioned(self, active):   # hot-path
        toks = self._step_jit(xfer.to_device(active))
        host = xfer.to_host(toks)
        n = int(host)
        self.metrics.observe(time.monotonic())
        return n
