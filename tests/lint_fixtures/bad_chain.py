"""Lint fixture: a broken caller-holds chain — the helper declares
its caller holds the lock, and one caller does not."""

import threading


class Pool:
    _guarded_by = {"_slots": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._slots = []

    def _push(self, x):
        # caller-holds: _lock
        self._slots.append(x)

    def put_locked(self, x):
        with self._lock:
            self._push(x)

    def put_unlocked(self, x):
        self._push(x)              # EXPECT-LINT lock-discipline
