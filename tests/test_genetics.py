"""Genetics (Tune + GA) and ensemble tests (SURVEY §2.1, §3.5)."""

import numpy
import pytest

from veles_tpu.config import Config, Tune, root
from veles_tpu.genetics import find_tunes, optimize, set_leaf


class TestTuneDiscovery:
    def test_find_and_set(self):
        cfg = Config("root")
        cfg.model.lr = Tune(0.01, 0.001, 0.1)
        cfg.model.momentum = 0.9
        cfg.loader.size = Tune(100, 10, 1000)
        tunes = find_tunes(cfg, "root")
        assert [p for p, _ in tunes] == ["root.loader.size", "root.model.lr"]
        set_leaf("root.model.lr", 0.05, cfg)
        assert cfg.model.lr == 0.05


class TestGA:
    def test_converges_on_quadratic(self):
        from veles_tpu import prng
        prng.reset()
        prng.seed_all(7)
        genes = [("root.ga_test.x", Tune(5.0, -10.0, 10.0)),
                 ("root.ga_test.y", Tune(-5.0, -10.0, 10.0))]

        def evaluate(individual):
            x, y = individual
            return (x - 2.0) ** 2 + (y + 3.0) ** 2

        best_fit, best_genes, pop = optimize(evaluate, generations=12,
                                             population=12, genes=genes)
        assert best_fit < 0.5, (best_fit, best_genes)
        assert abs(best_genes["root.ga_test.x"] - 2.0) < 1.0
        assert abs(best_genes["root.ga_test.y"] + 3.0) < 1.0
        # fitness history is monotone non-increasing at the elite
        fits = [h[0] for h in pop.history]
        assert fits[-1] <= fits[0]

    def test_bounds_respected(self):
        from veles_tpu import prng
        prng.reset()
        prng.seed_all(3)
        genes = [("root.ga_b.x", Tune(0.5, 0.0, 1.0))]
        seen = []

        def evaluate(ind):
            seen.append(ind[0])
            return ind[0]

        optimize(evaluate, generations=4, population=6, genes=genes)
        assert all(0.0 <= v <= 1.0 for v in seen)


class TestWorkflowOptimize:
    def test_optimizes_mnist_lr(self):
        """Tiny end-to-end GA over the MNIST sample's learning rate."""
        from veles_tpu import prng
        from veles_tpu.genetics import optimize_workflow
        prng.reset()
        prng.seed_all(1)
        root.mnist.update({
            "loader": {"minibatch_size": 50, "n_train": 200, "n_valid": 100},
            "decision": {"max_epochs": 2, "fail_iterations": 5},
            "layers": [
                {"type": "all2all_tanh", "output_sample_shape": 16,
                 "learning_rate": Tune(0.001, 0.0005, 0.1), "momentum": 0.9},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.03, "momentum": 0.9},
            ],
        })
        from veles_tpu.samples import mnist
        best_fit, best_genes, _ = optimize_workflow(
            mnist, generations=2, population=3, seed=1)
        assert numpy.isfinite(best_fit)
        (path, value), = best_genes.items()
        assert "learning_rate" in path
        assert 0.0005 <= value <= 0.1


class TestPopulationParallel:
    def test_parallel_matches_sequential(self):
        """Individuals screened across worker subprocesses must give the
        IDENTICAL GA trajectory as the sequential in-process path (each
        evaluation is deterministic in (config, genes, seed) — ref:
        SURVEY §3.5 fork-per-individual population parallelism)."""
        from veles_tpu import prng
        from veles_tpu.genetics import optimize_workflow
        from veles_tpu.samples import mnist

        def configure():
            prng.reset()
            prng.seed_all(1)
            root.__dict__.pop("mnist", None)
            root.mnist.update({
                "loader": {"minibatch_size": 50, "n_train": 200,
                           "n_valid": 100},
                "decision": {"max_epochs": 2, "fail_iterations": 5},
                "layers": [
                    {"type": "all2all_tanh", "output_sample_shape": 16,
                     "learning_rate": Tune(0.001, 0.0005, 0.1),
                     "momentum": 0.9},
                    {"type": "softmax", "output_sample_shape": 10,
                     "learning_rate": 0.03, "momentum": 0.9},
                ],
            })

        configure()
        seq_fit, seq_genes, _ = optimize_workflow(
            mnist, generations=2, population=3, seed=1, workers=0)
        configure()
        par_fit, par_genes, _ = optimize_workflow(
            mnist, generations=2, population=3, seed=1, workers=3)
        assert par_fit == seq_fit
        assert par_genes == seq_genes


    def test_worker_failure_raises_with_stderr(self):
        """A crashing worker surfaces its stderr; siblings are cleaned up."""
        import pytest
        from veles_tpu.config import Tune
        from veles_tpu.genetics import evaluate_population
        genes = [("root.ga_fail.x", Tune(0.5, 0.0, 1.0))]
        with pytest.raises(RuntimeError, match="worker"):
            evaluate_population("veles_tpu.samples.no_such_module", genes,
                                [[0.5], [0.6]], seed=1, workers=2)


class TestOptimizeCLI:
    def test_cli_optimize_with_workers(self, tmp_path):
        """`--optimize g:p:w` end to end through the real CLI: config file
        with a Tune leaf, GA across worker subprocesses, winner printed."""
        import os
        import subprocess
        import sys
        cfg = tmp_path / "tunes.py"
        cfg.write_text(
            "root.mnist.update({\n"
            "    'loader': {'minibatch_size': 50, 'n_train': 150,\n"
            "               'n_valid': 50},\n"
            "    'decision': {'max_epochs': 1, 'fail_iterations': 5},\n"
            "    'layers': [\n"
            "        {'type': 'all2all_tanh', 'output_sample_shape': 8,\n"
            "         'learning_rate': Tune(0.001, 0.0005, 0.1),\n"
            "         'momentum': 0.9},\n"
            "        {'type': 'softmax', 'output_sample_shape': 10,\n"
            "         'learning_rate': 0.03, 'momentum': 0.9},\n"
            "    ],\n"
            "})\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.run(
            [sys.executable, "-m", "veles_tpu", "veles_tpu.samples.mnist",
             str(cfg), "-d", "cpu", "--random-seed", "1",
             "--optimize", "1:2:2"],
            capture_output=True, text=True, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            timeout=420)
        assert proc.returncode == 0, proc.stderr[-3000:]
        assert "best fitness:" in proc.stdout
        assert "learning_rate" in proc.stdout


class TestEnsemble:
    def test_members_and_combination(self):
        from veles_tpu import prng
        from veles_tpu.ensemble import train_ensemble
        prng.reset()
        prng.seed_all(1)
        root.mnist.update({
            "loader": {"minibatch_size": 50, "n_train": 300, "n_valid": 100},
            "decision": {"max_epochs": 2, "fail_iterations": 5},
            "layers": [
                {"type": "all2all_tanh", "output_sample_shape": 16,
                 "learning_rate": 0.03, "momentum": 0.9},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.03, "momentum": 0.9},
            ],
        })
        from veles_tpu.samples import mnist
        trainer, combined = train_ensemble(mnist, size=3, base_seed=5)
        assert len(trainer.members) == 3
        assert combined["count"] == 100
        assert len(combined["members"]) == 3
        # the ensemble should not be (much) worse than its best member
        assert combined["ensemble_n_err"] <= min(combined["members"]) + 5
        # different seeds really produced different members (weights differ)
        w0 = numpy.asarray(
            trainer.members[0][1].forwards[0].weights.mem)
        w1 = numpy.asarray(
            trainer.members[1][1].forwards[0].weights.mem)
        assert not numpy.allclose(w0, w1)
        # ...but every member trained on the SAME dataset (pinned data
        # streams): evaluating members 1..N on member 0's validation set is
        # only meaningful if the data matches
        d0 = numpy.asarray(trainer.members[0][1].loader.original_data.mem)
        for _, wf, _ in trainer.members[1:]:
            numpy.testing.assert_array_equal(
                d0, numpy.asarray(wf.loader.original_data.mem))
        # and no member predicts at chance on the shared validation set
        assert max(combined["members"]) < 50

    @pytest.mark.slow
    def test_parallel_members_match_sequential(self):
        """Members trained in worker subprocesses and restored from their
        snapshots must equal in-process members exactly (same platform) —
        the reference's members-across-slaves parallelism (SURVEY §3.5).
        Slow-marked for tier-1 runtime headroom: the in-process
        ensemble leg (test_members_and_combination) and the GA
        population-parallel parity leg stay tier-1."""
        from veles_tpu import prng
        from veles_tpu.ensemble import train_ensemble
        from veles_tpu.samples import mnist

        def configure():
            prng.reset()
            prng.seed_all(1)
            root.__dict__.pop("mnist", None)
            root.mnist.update({
                "loader": {"minibatch_size": 50, "n_train": 200,
                           "n_valid": 100},
                "decision": {"max_epochs": 2, "fail_iterations": 5},
                "layers": [
                    {"type": "all2all_tanh", "output_sample_shape": 16,
                     "learning_rate": 0.03, "momentum": 0.9},
                    {"type": "softmax", "output_sample_shape": 10,
                     "learning_rate": 0.03, "momentum": 0.9},
                ],
            })

        configure()
        seq_trainer, seq_combined = train_ensemble(mnist, size=2,
                                                   base_seed=5)
        configure()
        par_trainer, par_combined = train_ensemble(mnist, size=2,
                                                   base_seed=5, workers=2)
        assert par_combined == seq_combined
        for (_, seq_wf, seq_sum), (_, par_wf, par_sum) in zip(
                seq_trainer.members, par_trainer.members):
            assert par_sum == seq_sum
            numpy.testing.assert_array_equal(
                numpy.asarray(seq_wf.forwards[0].weights.mem),
                numpy.asarray(par_wf.forwards[0].weights.mem))


@pytest.mark.slow
def test_optimizes_char_lm_learning_rate():
    """The GA generalizes to the transformer family: Tune over the
    char-LM trainer's learning rate, fitness = validation loss from
    TransformerDecision.best_metric (lower is better).  Slow-marked
    (tier-1 runtime headroom, same discipline as the PR-3 trim):
    tier-1 keeps the GA parity (TestPopulationParallel) and CLI
    (TestOptimizeCLI) representatives; this full GA-over-a-trained-LM
    convergence leg rides the slow suite."""
    from veles_tpu import prng
    from veles_tpu.genetics import optimize_workflow
    prng.reset()
    prng.seed_all(1)
    root.__dict__.pop("char_lm", None)
    root.char_lm.update({
        "loader": {"minibatch_size": 32, "n_train": 128, "n_valid": 64,
                   "seq_len": 32, "vocab": 16},
        "trainer": {"vocab": 16, "d_model": 32, "n_heads": 2,
                    "n_layers": 1, "max_len": 32,
                    "learning_rate": Tune(1e-3, 1e-4, 1e-2),
                    "n_experts": 0, "pipeline_stages": 0,
                    "remat": False},
        "decision": {"max_epochs": 2, "fail_iterations": 10},
    })
    from veles_tpu.samples import char_lm
    best_fit, best_genes, _ = optimize_workflow(
        char_lm, generations=2, population=3, seed=1)
    assert numpy.isfinite(best_fit)
    (path, value), = best_genes.items()
    assert "learning_rate" in path
    assert 1e-4 <= value <= 1e-2
