"""Worker process for the multi-host SPMD tests (tests/test_multihost.py).

Each of N processes owns 4 virtual CPU devices; together they form one
8-device global mesh — the process-level analogue of the reference's
loopback master/slave tests (SURVEY §4 test_client_server.py).  Every
process builds the identical workflow (same seed, pinned data stream),
shards the loader by its mesh-derived data block, feeds its LOCAL batch
rows, and runs lock-step SPMD train steps whose gradient averaging is
the GSPMD all-reduce.  Per-step metrics are printed as JSON for the
parent test to compare across processes and against a single-process
reference run.

Modes (argv[4], default "dp"):
- ``dp``  — blocked mesh (data, 1): pure data parallelism by process.
- ``tp``  — interleaved mesh (4, 2) whose MODEL axis spans the two
  processes (megatron-style cross-host TP): layer 0 is output-sharded,
  every process loads the full batch (spmd_loader_shard returns one
  block), and parameter shards are cut per-device from the local copy.
- ``diverge`` — NEGATIVE test of the init-state digest guard: process 1
  perturbs one weight before constructing ShardedTrainer, which must
  refuse to assemble shards from divergent local copies (ADVICE r4).
"""

import json
import os
import sys


def build_mesh(mode, n_procs):
    import jax
    import numpy
    from jax.sharding import Mesh
    devices = jax.devices()
    if mode == "dp":
        from veles_tpu.parallel import make_mesh
        return make_mesh(len(devices), devices=devices)
    # tp: model axis across processes — column c of every row lives on
    # process c (devices are enumerated process-major)
    per = len(devices) // n_procs
    grid = numpy.array([[devices[p * per + r] for p in range(n_procs)]
                        for r in range(per)])
    return Mesh(grid, ("data", "model"))


def main(coordinator, num_processes, process_id, mode="dp", steps=3):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    assert jax.process_count() == num_processes
    assert len(jax.devices()) == 4 * num_processes

    import numpy
    from veles_tpu import prng
    from veles_tpu.config import root
    from veles_tpu.parallel import ShardedTrainer, spmd_loader_shard

    prng.reset()
    prng.seed_all(1)
    root.mnist.update({
        "loader": {"minibatch_size": 32, "n_train": 128, "n_valid": 32},
        "decision": {"max_epochs": 1, "fail_iterations": 5},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.05, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    wf = mnist.build(fused=True)
    mesh = build_mesh(mode, num_processes)
    # SPMD loader sharding from the mesh layout — every process plans the
    # same global minibatch sequence and yields the rows its
    # data-coordinates cover (SURVEY §5.8: the reference's index
    # shipping, collapsed into deterministic sharding).  Under "tp" the
    # model axis spans processes, so there is ONE data block and every
    # process loads the full batch.
    shard_idx, shard_cnt = spmd_loader_shard(mesh)
    wf.loader.shard_spmd(shard_idx, shard_cnt)
    wf.initialize()
    loader = wf.loader
    assert loader.local_minibatch_size == 32 // shard_cnt
    if mode == "tp":
        assert shard_cnt == 1    # full batch everywhere

    if mode == "diverge":
        if process_id == 1:
            entry = wf._fused_runner.state[0]
            entry["w"] = numpy.asarray(entry["w"]) + 1e-3
        try:
            ShardedTrainer(wf._fused_runner, mesh)
        except Exception as exc:   # noqa: BLE001 — the guard must fire
            assert "initial runner state differs" in str(exc), exc
            print("DIVERGE-CAUGHT")
            return
        raise AssertionError("divergent init was NOT detected")

    trainer = ShardedTrainer(
        wf._fused_runner, mesh,
        model_shard_layers=[0] if mode == "tp" else ())
    assert trainer.multiprocess
    if mode == "tp":
        # layer 0's weights really are split over the cross-process axis
        w = trainer.state[0]["w"]
        assert not w.is_fully_addressable
        assert w.addressable_data(0).shape[-1] == 16 // num_processes

    from veles_tpu.loader.base import TRAIN
    out = []
    done = 0
    while done < steps:
        loader.run()    # fills the LOCAL minibatch Vectors via the plan
        if loader.minibatch_class != TRAIN:
            continue
        x = numpy.asarray(loader.minibatch_data.mem)
        y = numpy.asarray(loader.minibatch_labels.mem)
        mask = numpy.asarray(loader.minibatch_mask.mem)
        metrics = trainer.train_step(x, y, mask, loader.minibatch_size,
                                     step=done)
        host = ShardedTrainer.fetch(metrics)
        out.append({k: float(numpy.ravel(v)[0]) for k, v in host.items()})
        done += 1
    print("METRICS " + json.dumps(out))


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
         sys.argv[4] if len(sys.argv) > 4 else "dp")
