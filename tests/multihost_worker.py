"""Worker process for the multi-host SPMD test (tests/test_multihost.py).

Each of N processes owns 4 virtual CPU devices; together they form one
8-device global mesh — the process-level analogue of the reference's
loopback master/slave tests (SURVEY §4 test_client_server.py).  Every
process builds the identical workflow (same seed, pinned data stream),
shards the loader by its process index, feeds its LOCAL batch rows, and
runs lock-step SPMD train steps whose gradient averaging is the GSPMD
all-reduce.  Per-step metrics are printed as JSON for the parent test to
compare across processes and against a single-process reference run.
"""

import json
import os
import sys


def main(coordinator, num_processes, process_id, steps=3):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    assert jax.process_count() == num_processes
    assert len(jax.devices()) == 4 * num_processes

    import numpy
    from veles_tpu import prng
    from veles_tpu.config import root
    from veles_tpu.parallel import make_mesh, ShardedTrainer

    prng.reset()
    prng.seed_all(1)
    root.mnist.update({
        "loader": {"minibatch_size": 32, "n_train": 128, "n_valid": 32},
        "decision": {"max_epochs": 1, "fail_iterations": 5},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.05, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    wf = mnist.build(fused=True)
    # SPMD loader sharding — every process plans the same global minibatch
    # sequence and yields its contiguous local rows (SURVEY §5.8: the
    # reference's index shipping, collapsed into deterministic sharding)
    wf.loader.shard_spmd(jax.process_index(), jax.process_count())
    wf.initialize()
    loader = wf.loader
    assert loader.local_minibatch_size == 32 // num_processes

    mesh = make_mesh(4 * num_processes, devices=jax.devices())
    trainer = ShardedTrainer(wf._fused_runner, mesh)
    assert trainer.multiprocess

    from veles_tpu.loader.base import TRAIN
    out = []
    done = 0
    while done < steps:
        loader.run()    # fills the LOCAL minibatch Vectors via the plan
        if loader.minibatch_class != TRAIN:
            continue
        x = numpy.asarray(loader.minibatch_data.mem)
        y = numpy.asarray(loader.minibatch_labels.mem)
        mask = numpy.asarray(loader.minibatch_mask.mem)
        metrics = trainer.train_step(x, y, mask, loader.minibatch_size,
                                     step=done)
        host = ShardedTrainer.fetch(metrics)
        out.append({k: float(numpy.ravel(v)[0]) for k, v in host.items()})
        done += 1
    print("METRICS " + json.dumps(out))


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
