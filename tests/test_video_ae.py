"""VideoAE sample (synthetic footage autoencoder, SURVEY §2.3 samples)."""

import numpy

from veles_tpu import prng
from veles_tpu.config import root


def test_synth_video_temporal_structure():
    from veles_tpu.samples.video_ae import synth_video
    stream = prng.get("t_video", pinned=True)
    frames = synth_video(stream, n_sequences=4, frames_per_seq=6, hw=20)
    assert frames.shape == (24, 20, 20, 1)
    assert frames.dtype == numpy.float32
    # adjacent frames of one sequence are closer than frames of
    # different sequences (the blob moves smoothly within a sequence)
    seq = frames[:6, :, :, 0]
    adjacent = numpy.abs(seq[1:] - seq[:-1]).mean()
    across = numpy.abs(frames[0, :, :, 0] - frames[6, :, :, 0]).mean()
    assert adjacent < across


def test_video_ae_reconstruction_improves():
    prng.reset(); prng.seed_all(9)
    root.__dict__.pop("video_ae", None)
    from veles_tpu.samples import video_ae
    video_ae.default_config()
    root.video_ae.update({
        "loader": {"minibatch_size": 50, "n_train": 400, "n_valid": 96},
        "decision": {"max_epochs": 4, "fail_iterations": 20},
    })
    wf = video_ae.train(fused=True)
    losses = [m["validation"]["loss"]
              for m in wf.decision.epoch_metrics]
    assert losses[-1] < losses[0]          # reconstruction MSE decreases
    assert numpy.isfinite(losses).all()
