"""Launcher: profiler trace capture + device step-time reporting.

Ref: veles/launcher.py [H] + SURVEY §5.1 (tracing/profiling rebuild note):
the reference exposed per-unit timing; the TPU rebuild adds a jax.profiler
trace of the whole run (``--profile DIR``) and a measured fused-step device
time in print_stats.
"""

import glob
import os


def _build_tiny_mnist():
    from veles_tpu import prng
    from veles_tpu.config import root
    prng.reset()
    prng.seed_all(1)
    root.mnist.update({
        "loader": {"minibatch_size": 50, "n_train": 200, "n_valid": 100},
        "decision": {"max_epochs": 2, "fail_iterations": 5},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.03, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.03, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    return mnist.build(fused=True)


class TestLauncherProfile:
    def test_profile_writes_trace(self, tmp_path):
        from veles_tpu.launcher import Launcher
        wf = _build_tiny_mnist()
        trace_dir = str(tmp_path / "trace")
        launcher = Launcher(wf, stats=False, profile=trace_dir)
        launcher.boot()
        assert wf.decision.complete
        found = glob.glob(os.path.join(
            trace_dir, "plugins", "profile", "*", "*.xplane.pb"))
        assert found, "no xplane trace written under %s" % trace_dir

    def test_device_step_time_measured(self, tmp_path):
        from veles_tpu.launcher import Launcher
        wf = _build_tiny_mnist()
        Launcher(wf, stats=False).boot()
        step_time = wf._fused_runner.measure_device_step_time(iters=3)
        assert step_time is not None and 0.0 < step_time < 60.0
        wf.print_stats()  # must not raise with the device-time line
