"""Launcher: profiler trace capture + device step-time reporting.

Ref: veles/launcher.py [H] + SURVEY §5.1 (tracing/profiling rebuild note):
the reference exposed per-unit timing; the TPU rebuild adds a jax.profiler
trace of the whole run (``--profile DIR``) and a measured fused-step device
time in print_stats.
"""

import glob
import os

import numpy
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_tiny_mnist(seed=1, max_epochs=2):
    from veles_tpu import prng
    from veles_tpu.config import root
    prng.reset()
    prng.seed_all(seed)
    root.__dict__.pop("mnist", None)   # fresh subtree per test
    root.mnist.update({
        "loader": {"minibatch_size": 50, "n_train": 200, "n_valid": 100},
        "decision": {"max_epochs": max_epochs, "fail_iterations": 5},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.03, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.03, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    return mnist.build(fused=True)


class TestLauncherProfile:
    @pytest.mark.slow
    # ~22 s of jax-profiler trace capture for an auxiliary diagnostic
    # flag — rides in the slow suite (tier-1 runs within ~2% of its
    # outer watchdog)
    def test_profile_writes_trace(self, tmp_path):
        from veles_tpu.launcher import Launcher
        wf = _build_tiny_mnist()
        trace_dir = str(tmp_path / "trace")
        launcher = Launcher(wf, stats=False, profile=trace_dir)
        launcher.boot()
        assert wf.decision.complete
        found = glob.glob(os.path.join(
            trace_dir, "plugins", "profile", "*", "*.xplane.pb"))
        assert found, "no xplane trace written under %s" % trace_dir

    def test_device_step_time_measured(self, tmp_path):
        from veles_tpu.launcher import Launcher
        wf = _build_tiny_mnist()
        Launcher(wf, stats=False).boot()
        step_time = wf._fused_runner.measure_device_step_time(iters=3)
        assert step_time is not None and 0.0 < step_time < 60.0
        wf.print_stats()  # must not raise with the device-time line

    def test_stats_measurement_never_moves_weights(self, tmp_path):
        """measure_device_step_time re-dispatches real train steps for
        timing but must DISCARD their updates: the final weights after
        the last epoch's metrics are recorded may not change because
        stats were printed (VERDICT r4 weak #5 regression guard)."""
        import jax
        from veles_tpu.launcher import Launcher
        wf = _build_tiny_mnist()
        Launcher(wf, stats=False).boot()
        runner = wf._fused_runner
        before = jax.tree.map(numpy.array, runner.state)
        runner.measure_device_step_time(iters=3)
        wf.print_stats()
        after = jax.tree.map(numpy.array, runner.state)
        for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
            numpy.testing.assert_array_equal(a, b)


class TestEpochScanDriver:
    def test_chunk1_matches_graph_loop_exactly(self):
        """--epoch-scan with chunk=1 on a deterministic (no-dropout)
        model: per-epoch decision metrics AND final weights equal the
        per-minibatch graph loop's bit-for-bit semantics (same plans,
        same set ordering: validation before each epoch's training)."""
        from veles_tpu.launcher import Launcher

        wf_a = _build_tiny_mnist(seed=7, max_epochs=3)
        Launcher(wf_a, stats=False).boot()

        wf_b = _build_tiny_mnist(seed=7, max_epochs=3)
        Launcher(wf_b, stats=False, epoch_scan=1).boot()

        assert wf_b.is_finished and bool(wf_b.decision.complete)
        assert len(wf_a.decision.epoch_metrics) == \
            len(wf_b.decision.epoch_metrics)
        for ma, mb in zip(wf_a.decision.epoch_metrics,
                          wf_b.decision.epoch_metrics):
            assert set(ma) == set(mb)
            for set_name in ma:
                for key in ("n_err", "count", "loss"):
                    if key in ma[set_name]:
                        va, vb = ma[set_name][key], mb[set_name][key]
                        numpy.testing.assert_allclose(va, vb, rtol=1e-5)
        assert wf_a.decision.best_metric == wf_b.decision.best_metric
        assert wf_a.decision.best_epoch == wf_b.decision.best_epoch
        for fa, fb in zip(wf_a.forwards, wf_b.forwards):
            if fa.has_params:
                numpy.testing.assert_allclose(
                    numpy.asarray(fa.weights.mem),
                    numpy.asarray(fb.weights.mem), rtol=2e-5, atol=2e-6)

    def test_spmd_driver_step_count_matches_graph_loop(self):
        """Mid-chunk completion under --distributed: the replay trains
        the stopping epoch truncated to steps-1, but graph mode
        DISPATCHES (and counts in train_steps) the discarded last
        minibatch too — the driver must leave trainer.step_count at the
        graph-loop value so a resumed lr policy starts at the same step
        (round-5 review finding)."""
        import jax
        from veles_tpu.launcher import Launcher
        from veles_tpu.epoch_driver import EpochScanDriver
        from veles_tpu.parallel import make_mesh, ShardedTrainer

        wf_a = _build_tiny_mnist(seed=11, max_epochs=3)
        Launcher(wf_a, stats=False).boot()
        graph_steps = wf_a.fused_step.train_steps
        assert graph_steps > 0

        wf_b = _build_tiny_mnist(seed=11, max_epochs=3)
        wf_b.initialize()
        # 2 devices: the helper's minibatch of 50 must divide the data axis
        mesh = make_mesh(2, devices=jax.devices("cpu")[:2])
        trainer = ShardedTrainer(wf_b._fused_runner, mesh)
        wf_b._sharded_trainer = trainer
        EpochScanDriver(wf_b, chunk=1).run()
        assert bool(wf_b.decision.complete)
        assert trainer.step_count == graph_steps
        # and the replayed weights still match the graph loop exactly
        trainer.sync_to_runner()
        wf_b._fused_runner.sync_to_units()
        for fa, fb in zip(wf_a.forwards, wf_b.forwards):
            if fa.has_params:
                numpy.testing.assert_allclose(
                    numpy.asarray(fa.weights.mem),
                    numpy.asarray(fb.weights.mem), rtol=2e-5, atol=2e-6)

    def test_chunked_matches_chunk1(self):
        """chunk=2 trains the same trajectory as chunk=1 (decisions at
        coarser readback granularity, identical best tracking here
        because no early stop triggers mid-chunk)."""
        from veles_tpu.launcher import Launcher
        wf_a = _build_tiny_mnist(seed=9, max_epochs=4)
        Launcher(wf_a, stats=False, epoch_scan=1).boot()
        wf_b = _build_tiny_mnist(seed=9, max_epochs=4)
        Launcher(wf_b, stats=False, epoch_scan=2).boot()
        assert len(wf_a.decision.epoch_metrics) == \
            len(wf_b.decision.epoch_metrics)
        assert wf_a.decision.best_metric == wf_b.decision.best_metric
        for fa, fb in zip(wf_a.forwards, wf_b.forwards):
            if fa.has_params:
                numpy.testing.assert_allclose(
                    numpy.asarray(fa.weights.mem),
                    numpy.asarray(fb.weights.mem), rtol=2e-5, atol=2e-6)

    def test_snapshots_written_and_resumable(self, tmp_path):
        """The driver fires the snapshotter through its normal gates and
        the snapshot restores through the normal path."""
        from veles_tpu.launcher import Launcher
        from veles_tpu.config import root
        from veles_tpu import prng, snapshotter as snap_mod
        prng.reset(); prng.seed_all(3)
        root.__dict__.pop("mnist", None)
        root.mnist.update({
            "loader": {"minibatch_size": 50, "n_train": 200,
                       "n_valid": 100},
            "decision": {"max_epochs": 2, "fail_iterations": 5},
            "snapshotter": {"directory": str(tmp_path), "interval": 1},
            "layers": [
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.05}],
        })
        from veles_tpu.samples import mnist
        wf = mnist.build(fused=True)
        Launcher(wf, stats=False, epoch_scan=1).boot()
        latest = snap_mod.find_current(str(tmp_path), wf.snapshotter.prefix)
        assert latest is not None
        prng.reset(); prng.seed_all(3)
        wf2 = mnist.build(fused=True)
        wf2.initialize()
        payload = snap_mod.restore(wf2, latest)
        assert payload["epoch"] == 2

    def test_test_set_metrics_match_graph_loop(self):
        """Loaders with a TEST split: the driver evaluates it per epoch
        (before valid, like the plan orders it) and the decision records
        the same per-set metrics as the graph loop."""
        from veles_tpu.launcher import Launcher
        from veles_tpu.standard_workflow import StandardWorkflow
        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu import prng

        class ThreeSetLoader(FullBatchLoader):
            def load_data(self):
                r = numpy.random.RandomState(4)
                protos = r.uniform(-1, 1, (10, 20)).astype(numpy.float32)
                labels = (numpy.arange(260) % 10).astype(numpy.int32)
                data = (protos[labels]
                        + r.normal(0, .5, (260, 20)).astype(numpy.float32))
                self.original_data.reset(data)
                self.original_labels.reset(labels)
                self.class_lengths = [60, 80, 120]   # test|valid|train

        def build():
            prng.reset(); prng.seed_all(11)
            return StandardWorkflow(
                None, name="threeset", loader_factory=ThreeSetLoader,
                loader_config={"minibatch_size": 20},
                decision_config={"max_epochs": 2, "fail_iterations": 5},
                layers=[{"type": "softmax", "output_sample_shape": 10,
                         "learning_rate": 0.05}])

        wf_a = build()
        Launcher(wf_a, stats=False).boot()
        wf_b = build()
        Launcher(wf_b, stats=False, epoch_scan=1).boot()
        assert len(wf_a.decision.epoch_metrics) == \
            len(wf_b.decision.epoch_metrics)
        for ma, mb in zip(wf_a.decision.epoch_metrics,
                          wf_b.decision.epoch_metrics):
            assert set(ma) == set(mb) == {"test", "validation", "train"}
            for set_name in ma:
                for key in ("n_err", "count", "loss"):
                    if key in ma[set_name]:
                        numpy.testing.assert_allclose(
                            ma[set_name][key], mb[set_name][key],
                            rtol=1e-5)

    def test_dropout_network_trains_and_improves(self):
        """Stochastic layers go through the driver's rng path (scan-key
        folding — the documented epoch-scan semantics) and the model
        still learns."""
        from veles_tpu.launcher import Launcher
        from veles_tpu.config import root
        from veles_tpu import prng
        prng.reset(); prng.seed_all(5)
        root.__dict__.pop("mnist", None)
        root.mnist.update({
            "loader": {"minibatch_size": 50, "n_train": 200,
                       "n_valid": 100},
            "decision": {"max_epochs": 4, "fail_iterations": 10},
            "layers": [
                {"type": "all2all_tanh", "output_sample_shape": 32,
                 "learning_rate": 0.03, "momentum": 0.9},
                {"type": "dropout", "dropout_ratio": 0.2},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.03, "momentum": 0.9}],
        })
        from veles_tpu.samples import mnist
        wf = mnist.build(fused=True)
        Launcher(wf, stats=False, epoch_scan=2).boot()
        hist = [m["validation"]["n_err"]
                for m in wf.decision.epoch_metrics if "validation" in m]
        assert len(hist) >= 2 and hist[-1] < hist[0]

    def test_resume_from_mid_run_snapshot_matches_uninterrupted(
            self, tmp_path):
        """Driver kill-and-resume parity: restoring the epoch-2 snapshot
        and continuing reaches the same final weights as the
        uninterrupted run (loader plan/_position and PRNG streams round-
        trip, so the resumed run replans exactly like the original)."""
        import glob
        from veles_tpu.launcher import Launcher
        from veles_tpu.config import root
        from veles_tpu import prng

        def build():
            prng.reset(); prng.seed_all(21)
            root.__dict__.pop("mnist", None)
            root.mnist.update({
                "loader": {"minibatch_size": 50, "n_train": 200,
                           "n_valid": 100},
                "decision": {"max_epochs": 4, "fail_iterations": 10},
                "snapshotter": {"directory": str(tmp_path),
                                "interval": 1},
                "layers": [
                    {"type": "all2all_tanh", "output_sample_shape": 16,
                     "learning_rate": 0.03, "momentum": 0.9},
                    {"type": "softmax", "output_sample_shape": 10,
                     "learning_rate": 0.03, "momentum": 0.9}],
            })
            from veles_tpu.samples import mnist
            return mnist.build(fused=True)

        wf_full = build()
        Launcher(wf_full, stats=False, epoch_scan=1).boot()
        full_w = [numpy.asarray(f.weights.mem) for f in wf_full.forwards
                  if f.has_params]
        mid = glob.glob(str(tmp_path / "mnist_2_*.pickle*"))
        assert mid, "no epoch-2 snapshot written"

        wf_res = build()
        Launcher(wf_res, stats=False, epoch_scan=1,
                 snapshot=mid[0]).boot()
        assert int(wf_res.loader.epoch_number) == 4
        res_w = [numpy.asarray(f.weights.mem) for f in wf_res.forwards
                 if f.has_params]
        for a, b in zip(full_w, res_w):
            numpy.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)

    def test_rejects_unfused_workflows(self):
        from veles_tpu.epoch_driver import EpochScanDriver
        import pytest
        from veles_tpu import prng
        from veles_tpu.config import root
        prng.reset(); prng.seed_all(1)
        root.__dict__.pop("mnist", None)
        root.mnist.update({
            "loader": {"minibatch_size": 50, "n_train": 100,
                       "n_valid": 50},
            "decision": {"max_epochs": 1, "fail_iterations": 5},
            "layers": [{"type": "softmax", "output_sample_shape": 10,
                        "learning_rate": 0.05}],
        })
        from veles_tpu.samples import mnist
        wf = mnist.build(fused=False)
        with pytest.raises(ValueError, match="fused"):
            EpochScanDriver(wf)


def test_cli_serve_after_training(tmp_path):
    """--serve PORT: train, then serve the trained workflow over HTTP
    until interrupted (the reference's snapshot-to-serving ergonomics
    in one command)."""
    import json
    import signal
    import subprocess
    import sys
    import time
    import urllib.request

    import numpy

    env = dict(os.environ)
    env["XLA_FLAGS"] = ""
    proc = subprocess.Popen(
        [sys.executable, "-m", "veles_tpu", "veles_tpu.samples.mnist",
         "-d", "cpu", "--random-seed", "7", "--no-stats", "--serve", "0",
         "root.mnist.loader.n_train=128", "root.mnist.loader.n_valid=64",
         "root.mnist.loader.minibatch_size=64",
         "root.mnist.decision.max_epochs=1"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=REPO)
    try:
        import queue
        import threading
        lines = queue.Queue()
        reader = threading.Thread(
            target=lambda: [lines.put(ln) for ln in proc.stdout],
            daemon=True)
        reader.start()
        port, deadline = None, time.time() + 300
        while time.time() < deadline:
            try:
                line = lines.get(timeout=5)
            except queue.Empty:
                assert proc.poll() is None, "CLI exited before serving"
                continue
            if line.startswith("SERVING "):
                port = int(line.split(":")[2].split("/")[0])
                break
        assert port, "server never announced itself within the deadline"
        x = numpy.zeros((2, 784), numpy.float32).tolist()
        req = urllib.request.Request(
            "http://127.0.0.1:%d/predict" % port,
            data=json.dumps({"input": x}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        assert len(out["output"]) == 2 and len(out["output"][0]) == 10
    finally:
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
    assert proc.returncode in (0, -signal.SIGINT)


def test_cli_evaluate_only(tmp_path):
    """--evaluate --snapshot: one scoring pass, weights untouched
    (SURVEY §3.3 resume/EVALUATE from snapshot)."""
    import json
    import subprocess
    import sys

    env = dict(os.environ)
    env["XLA_FLAGS"] = ""
    common = [sys.executable, "-m", "veles_tpu", "veles_tpu.samples.mnist",
              "-d", "cpu", "--random-seed", "7", "--no-stats"]
    overrides = ["root.mnist.loader.n_train=128",
                 "root.mnist.loader.n_valid=64",
                 "root.mnist.loader.minibatch_size=64",
                 "root.mnist.decision.max_epochs=1"]
    # train 1 epoch, snapshot
    proc = subprocess.run(
        common + ["--snapshot-dir", str(tmp_path),
                  "--result-file", str(tmp_path / "train.json")] + overrides,
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    train = json.loads((tmp_path / "train.json").read_text())
    snap = train["snapshot"]

    # evaluate-only from the snapshot: same val metrics, no training
    proc = subprocess.run(
        common + ["--snapshot", snap, "--evaluate",
                  "--result-file", str(tmp_path / "eval.json")] + overrides,
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    ev = json.loads((tmp_path / "eval.json").read_text())
    # the epoch plan scores validation BEFORE the epoch's training
    # updates, so scoring the FINAL snapshot must do at least as well
    # as the training run's last validation pass
    assert (ev["last_epoch_metrics"]["validation"]["n_err"]
            <= train["last_epoch_metrics"]["validation"]["n_err"])

    # evaluation is pure: a second scoring pass reproduces it exactly
    proc = subprocess.run(
        common + ["--snapshot", snap, "--evaluate",
                  "--result-file", str(tmp_path / "eval2.json")] + overrides,
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    ev2 = json.loads((tmp_path / "eval2.json").read_text())
    assert (ev2["last_epoch_metrics"]["validation"]
            == ev["last_epoch_metrics"]["validation"])
    # scoring never rewrites the training run's best-* bookkeeping
    assert ev["best_metric"] == train["best_metric"]
    assert ev["best_epoch"] == train["best_epoch"]
    # and never writes snapshots (no lineage pollution)
    assert "snapshot" not in ev or ev["snapshot"] == train["snapshot"]


def test_launcher_evaluate_leaves_weights_untouched(tmp_path):
    """In-process check of the --evaluate contract on a fused GD
    workflow: parameters identical before/after the scoring pass."""
    import numpy
    from veles_tpu.launcher import Launcher
    wf = _build_tiny_mnist(seed=3, max_epochs=1)
    launcher = Launcher(wf, stats=False, evaluate=True)
    launcher.boot()
    wf._fused_runner.sync_to_units()     # device state -> unit Vectors
    after = [numpy.array(f.weights.mem) for f in wf.forwards]
    # a fresh identically-seeded init equals the "trained" weights:
    # nothing moved during the evaluation pass
    wf2 = _build_tiny_mnist(seed=3, max_epochs=1)
    wf2.initialize()
    for a, f in zip(after, wf2.forwards):
        numpy.testing.assert_array_equal(a, numpy.array(f.weights.mem))
    # and the scoring pass produced metrics
    assert launcher.result_summary()["last_epoch_metrics"]["validation"]


def test_cli_serve_unservable_fails_before_training():
    """--serve on a workflow with no forward chain / LM trainer must
    error out BEFORE launcher.boot(), not after the training run
    completes (ADVICE r4): a misconfiguration knowable up front must
    not discard the session."""
    import subprocess
    import sys
    import time

    env = dict(os.environ)
    env["XLA_FLAGS"] = ""
    start = time.time()
    proc = subprocess.run(
        [sys.executable, "-m", "veles_tpu", "veles_tpu.samples.kohonen",
         "-d", "cpu", "--random-seed", "7", "--no-stats", "--serve", "0",
         # LARGE epoch budget: if the check ran post-training this would
         # take minutes — the early error must ignore it entirely
         "root.kohonen.decision.max_epochs=100000"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=REPO, timeout=120)
    assert proc.returncode == 2, proc.stderr
    assert "--serve" in proc.stderr and "no forward chain" in proc.stderr
    assert time.time() - start < 120
