"""Attention + ring sequence parallelism tests.

Oracle chain: numpy softmax attention → jax dense → blockwise (flash) →
ring over an 8-device CPU mesh — each stage must match the previous one.
"""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.ops import attention as A


def numpy_attention(q, k, v, causal=False):
    dh = q.shape[-1]
    s = q @ numpy.swapaxes(k, -1, -2) / numpy.sqrt(dh)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = numpy.tril(numpy.ones((sq, sk), bool), sk - sq)
        s = numpy.where(mask, s, -1e30)
    e = numpy.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    return p @ v


def qkv(batch=2, heads=2, seq=32, dh=8, seed=0):
    r = numpy.random.RandomState(seed)
    shape = (batch, heads, seq, dh)
    return (r.randn(*shape).astype(numpy.float32),
            r.randn(*shape).astype(numpy.float32),
            r.randn(*shape).astype(numpy.float32))


class TestDenseAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_numpy(self, causal):
        q, k, v = qkv()
        out = A.attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          causal=causal)
        numpy.testing.assert_allclose(numpy.asarray(out),
                                      numpy_attention(q, k, v, causal),
                                      rtol=1e-4, atol=1e-5)


class TestBlockwiseAttention:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("block", [8, 16, 32])
    def test_matches_dense(self, causal, block):
        q, k, v = qkv(seq=32)
        dense = A.attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                            causal=causal)
        blocked = A.blockwise_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            block_size=block, causal=causal)
        numpy.testing.assert_allclose(numpy.asarray(blocked),
                                      numpy.asarray(dense),
                                      rtol=1e-4, atol=1e-5)

    def test_indivisible_block_raises(self):
        q, k, v = qkv(seq=32)
        with pytest.raises(ValueError):
            A.blockwise_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), block_size=7)


class TestMHA:
    def test_shapes_and_grad(self):
        from veles_tpu import prng
        prng.reset()
        prng.seed_all(1)
        params = A.init_mha_params(prng.get("init"), d_model=16, n_heads=4)
        x = jnp.asarray(numpy.random.RandomState(0)
                        .randn(2, 8, 16).astype(numpy.float32))
        out = A.mha_forward(params, x, n_heads=4)
        assert out.shape == (2, 8, 16)
        grads = jax.grad(lambda p: (A.mha_forward(p, x, 4) ** 2).sum())(
            jax.tree.map(jnp.asarray, params))
        for leaf in jax.tree.leaves(grads):
            assert numpy.isfinite(numpy.asarray(leaf)).all()

    def test_blockwise_path_matches(self):
        from veles_tpu import prng
        prng.reset()
        prng.seed_all(1)
        params = jax.tree.map(
            jnp.asarray,
            A.init_mha_params(prng.get("init"), d_model=16, n_heads=2))
        x = jnp.asarray(numpy.random.RandomState(0)
                        .randn(2, 32, 16).astype(numpy.float32))
        dense = A.mha_forward(params, x, 2, causal=True)
        blocked = A.mha_forward(params, x, 2, causal=True, block_size=8)
        numpy.testing.assert_allclose(numpy.asarray(blocked),
                                      numpy.asarray(dense),
                                      rtol=1e-4, atol=1e-5)


class TestRingAttention:
    @pytest.fixture
    def mesh(self):
        devices = jax.devices("cpu")
        if len(devices) < 8:
            pytest.skip("needs 8 virtual devices")
        from veles_tpu.parallel.ring import make_seq_mesh
        return make_seq_mesh(8, data_parallel=2, devices=devices[:8])

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, mesh, causal):
        from veles_tpu.parallel.ring import ring_attention
        q, k, v = qkv(batch=2, heads=2, seq=32, dh=8)
        dense = A.attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                            causal=causal)
        ring = ring_attention(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), mesh, causal=causal)
        numpy.testing.assert_allclose(numpy.asarray(ring),
                                      numpy.asarray(dense),
                                      rtol=1e-4, atol=1e-5)

    def test_output_is_seq_sharded(self, mesh):
        from veles_tpu.parallel.ring import ring_attention
        from jax.sharding import NamedSharding, PartitionSpec as P
        q, k, v = qkv(batch=2, heads=2, seq=32, dh=8)
        out = ring_attention(jnp.asarray(q), jnp.asarray(k),
                             jnp.asarray(v), mesh)
        expect = NamedSharding(mesh, P("data", None, "seq", None))
        assert out.sharding.is_equivalent_to(expect, out.ndim)

    def test_grad_flows_through_ring(self, mesh):
        from veles_tpu.parallel.ring import ring_attention
        q, k, v = qkv(batch=2, heads=2, seq=32, dh=8)

        def loss(q_):
            return (ring_attention(q_, jnp.asarray(k), jnp.asarray(v),
                                   mesh) ** 2).sum()

        g = jax.grad(loss)(jnp.asarray(q))
        assert numpy.isfinite(numpy.asarray(g)).all()
        # compare with dense-attention gradient
        g_dense = jax.grad(lambda q_: (A.attention(
            q_, jnp.asarray(k), jnp.asarray(v), causal=True) ** 2).sum())(
                jnp.asarray(q))
        numpy.testing.assert_allclose(numpy.asarray(g),
                                      numpy.asarray(g_dense),
                                      rtol=1e-3, atol=1e-4)


class TestFlashPallasBackend:
    """The bundled TPU Pallas flash-attention kernel as an opt-in
    backend (attention.set_attention_backend)."""

    def test_backend_flag_validates(self):
        from veles_tpu.ops import attention as A
        with pytest.raises(ValueError):
            A.set_attention_backend("nope")
        A.set_attention_backend("xla")   # restore-is-default no-op

    def test_off_tpu_is_a_loud_error(self):
        """No silent fallback: off-TPU the kernel must refuse, not
        quietly compute something else."""
        from veles_tpu.ops import attention as A
        from veles_tpu.ops.pallas_kernels import on_tpu
        if on_tpu():
            pytest.skip("on-TPU: covered by the parity test")
        q = jnp.zeros((1, 2, 128, 64), jnp.float32)
        with pytest.raises(RuntimeError, match="TPU"):
            A.flash_attention_tpu(q, q, q)

    @pytest.mark.skipif(
        not __import__("veles_tpu.ops.pallas_kernels",
                       fromlist=["on_tpu"]).on_tpu(),
        reason="the bundled kernel has no CPU lowering")
    def test_matches_xla_attention_on_tpu(self):
        from veles_tpu.ops import attention as A
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (2, 4, 256, 64), jnp.float32)
        k = jax.random.normal(jax.random.fold_in(key, 1), q.shape)
        v = jax.random.normal(jax.random.fold_in(key, 2), q.shape)
        ref = A.attention(q, k, v, causal=True)
        got = A.flash_attention_tpu(q, k, v, causal=True)
        numpy.testing.assert_allclose(numpy.asarray(got),
                                      numpy.asarray(ref),
                                      rtol=2e-3, atol=2e-3)


class TestWindowedRingAttention:
    """Sliding window composes with sequence-parallel ring attention:
    positions are global, so the band crosses shard borders exactly."""

    @pytest.mark.parametrize("window", [1, 5, 12, 999])
    def test_matches_dense_windowed(self, window):
        from veles_tpu.ops.attention import attention
        from veles_tpu.parallel.ring import make_seq_mesh, ring_attention
        mesh = make_seq_mesh(4, devices=jax.devices("cpu")[:4])
        key = jax.random.PRNGKey(0)
        # s_local = 8 => window=5 stays in-shard for some queries and
        # crosses the border for others; 12 always crosses; 999 ≡ causal
        q = jax.random.normal(key, (2, 2, 32, 8), jnp.float32)
        k = jax.random.normal(jax.random.fold_in(key, 1), q.shape)
        v = jax.random.normal(jax.random.fold_in(key, 2), q.shape)
        ref = attention(q, k, v, causal=True, window=window)
        got = ring_attention(q, k, v, mesh, causal=True, window=window)
        numpy.testing.assert_allclose(numpy.asarray(got),
                                      numpy.asarray(ref),
                                      rtol=1e-4, atol=1e-5)

    def test_window_requires_causal(self):
        from veles_tpu.parallel.ring import make_seq_mesh, ring_attention
        mesh = make_seq_mesh(2, devices=jax.devices("cpu")[:2])
        q = jnp.zeros((1, 1, 8, 4), jnp.float32)
        with pytest.raises(ValueError, match="causal"):
            ring_attention(q, q, q, mesh, causal=False, window=2)


@pytest.mark.parametrize("window", [1, 3, 10, 999])
def test_blockwise_windowed_matches_dense(window):
    """Flash-style blockwise + sliding window ≡ dense windowed (incl.
    fully-masked EARLY blocks, whose transient terms the online rescale
    must zero — the finite-NEG_INF subtlety)."""
    from veles_tpu.ops.attention import attention, blockwise_attention
    key = jax.random.PRNGKey(5)
    q = jax.random.normal(key, (2, 2, 32, 8), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), q.shape)
    v = jax.random.normal(jax.random.fold_in(key, 2), q.shape)
    ref = attention(q, k, v, causal=True, window=window)
    got = blockwise_attention(q, k, v, block_size=8, causal=True,
                              window=window)
    numpy.testing.assert_allclose(numpy.asarray(got), numpy.asarray(ref),
                                  rtol=1e-4, atol=1e-5)


class TestAttentionSinks:
    """sinks=K keeps the first K positions attendable under a window
    (StreamingLLM form) — identical across all three decompositions."""

    def _qkv(self, seq=32):
        key = jax.random.PRNGKey(7)
        q = jax.random.normal(key, (2, 2, seq, 8), jnp.float32)
        k = jax.random.normal(jax.random.fold_in(key, 1), q.shape)
        v = jax.random.normal(jax.random.fold_in(key, 2), q.shape)
        return q, k, v

    def test_sinks_widen_the_window_exactly(self):
        """Manual oracle: with window=4, sinks=2, position p attends to
        {0, 1} ∪ (p-4, p] and nothing else."""
        from veles_tpu.ops.attention import attention
        q, k, v = self._qkv(16)
        got = attention(q, k, v, causal=True, window=4, sinks=2)
        # oracle via explicit bias on plain causal attention
        p = numpy.arange(16)
        allowed = (p[None, :] <= p[:, None]) & (
            (p[:, None] - p[None, :] < 4) | (p[None, :] < 2))
        bias = jnp.where(jnp.asarray(allowed), 0.0, -1e30)
        ref = attention(q, k, v, causal=False, bias=bias)
        numpy.testing.assert_allclose(numpy.asarray(got),
                                      numpy.asarray(ref),
                                      rtol=1e-5, atol=1e-6)

    def test_blockwise_and_ring_match_dense(self):
        from veles_tpu.ops.attention import attention, blockwise_attention
        from veles_tpu.parallel.ring import make_seq_mesh, ring_attention
        q, k, v = self._qkv(32)
        ref = attention(q, k, v, causal=True, window=5, sinks=3)
        blk = blockwise_attention(q, k, v, block_size=8, causal=True,
                                  window=5, sinks=3)
        numpy.testing.assert_allclose(numpy.asarray(blk),
                                      numpy.asarray(ref),
                                      rtol=1e-4, atol=1e-5)
        mesh = make_seq_mesh(4, devices=jax.devices("cpu")[:4])
        ring = ring_attention(q, k, v, mesh, causal=True, window=5,
                              sinks=3)
        numpy.testing.assert_allclose(numpy.asarray(ring),
                                      numpy.asarray(ref),
                                      rtol=1e-4, atol=1e-5)

    def test_ring_early_exit_keeps_sink_blocks_live(self):
        """The ring's liveness test must not skip the block holding the
        sinks even when it is far outside the window (the exact bug a
        naive interval test would have)."""
        from veles_tpu.ops.attention import attention
        from veles_tpu.parallel.ring import make_seq_mesh, ring_attention
        q, k, v = self._qkv(32)           # s_local=8, 4 shards
        # window=2 puts shard 0 far outside every later query's band
        ref = attention(q, k, v, causal=True, window=2, sinks=1)
        ring = ring_attention(q, k, v, mesh=make_seq_mesh(
            4, devices=jax.devices("cpu")[:4]), causal=True, window=2,
            sinks=1)
        numpy.testing.assert_allclose(numpy.asarray(ring),
                                      numpy.asarray(ref),
                                      rtol=1e-4, atol=1e-5)
