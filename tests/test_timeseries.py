"""Continuous telemetry + SLO burn-rate alerting (ISSUE 14): the
time-series store over the serving metrics, runtime/device gauges, the
tracer's incremental cost ledger, the SLO state machine and its
health-checker hook, and the new HTTP endpoints."""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))


def _tiny_params(max_len=48, vocab=16, n_heads=2, n_layers=2):
    import jax
    import jax.numpy as jnp
    from veles_tpu import prng
    from veles_tpu.ops.transformer import init_transformer_params
    host = init_transformer_params(prng.get("init"), vocab, d_model=32,
                                   n_heads=n_heads, n_layers=n_layers,
                                   max_len=max_len)
    return jax.tree.map(jnp.asarray, host)


def _get_json(port, path):
    with urllib.request.urlopen(
            "http://127.0.0.1:%d%s" % (port, path), timeout=10) as r:
        return json.loads(r.read())


class TestTimeSeriesStore:
    def test_counter_windows_and_restart_clamp(self):
        """Counters become restart-tolerant windowed rates: positive
        deltas accumulate, a counter that went BACKWARDS (an engine
        restart replacing its row) contributes zero — the rate is
        never negative."""
        from veles_tpu.serving import ServingMetrics, TimeSeriesStore
        m = ServingMetrics("ts_ctr")
        store = TimeSeriesStore(interval_s=0.05, capacity=64)
        store.add_source(m, key="src")
        for n in (5, 9, 2, 6):       # 9 -> 2 is the restart
            m2 = ServingMetrics("ts_ctr")
            for _ in range(n):
                m2.record_enqueue()
            # swap the sampled instance's counter value directly
            m.requests = m2.requests
            store.sample_once()
        w = store.window("src.counter.requests", 60)
        assert w["kind"] == "counter"
        assert w["last"] == 6
        # deltas: +4 (5->9), clamp(9->2)=0, +4 (2->6)
        assert w["delta"] == 8
        assert w["rate_per_s"] >= 0

    def test_gauge_and_histogram_windows(self):
        from veles_tpu.serving import ServingMetrics, TimeSeriesStore
        m = ServingMetrics("ts_h")
        store = TimeSeriesStore(interval_s=0.05, capacity=64)
        store.add_source(m, key="src")
        store.sample_once()          # baseline point (zero deltas)
        for i, (depth, ttft) in enumerate(
                ((3, 0.004), (7, 0.004), (5, 0.2))):
            m.set_gauge("queue_depth", depth)
            m.record_ttft(ttft)
            store.sample_once()
        g = store.window("src.gauge.queue_depth", 60)
        assert g["last"] == 5 and g["min"] == 3 and g["max"] == 7
        h = store.window("src.hist.ttft", 60)
        assert h["count_delta"] == 3
        # two fast observations, one slow: p50 resolves to the fast
        # bucket bound, p95 to the slow one
        assert h["p50"] <= 0.005
        assert h["p95"] >= 0.2
        assert h["bounds"]          # consumers can interpret buckets
        # the windowed good/total helper the SLO layer uses
        good, total = store.count_in_window("src.hist.ttft", 60, 0.005)
        assert (good, total) == (2, 3)

    def test_capacity_bounds_every_ring(self):
        from veles_tpu.serving import ServingMetrics, TimeSeriesStore
        m = ServingMetrics("ts_cap")
        store = TimeSeriesStore(interval_s=0.01, capacity=8)
        store.add_source(m, key="src")
        for _ in range(40):
            m.record_enqueue()
            store.sample_once()
        assert store.samples == 40
        w = store.window("src.counter.requests", 1e9)
        assert w["points"] == 8          # ring, not unbounded history

    def test_snapshot_strict_json_with_shared_sampled_at(self):
        """/timeseries.json shape: strict JSON (no NaN), the shared
        monotonic sampled_at stamp, per-kind windowed stats plus raw
        points inside the window — and the /metrics.json snapshot
        carries the SAME clock's stamp (the ISSUE 14 small fix), so
        rate math across two scrapes is arithmetic."""
        from veles_tpu.serving import ServingMetrics, TimeSeriesStore
        from veles_tpu.serving.metrics import monotonic_offset
        m = ServingMetrics("ts_snap")
        store = TimeSeriesStore(interval_s=0.05, capacity=16)
        store.add_source(m, key="src")
        for _ in range(3):
            m.record_enqueue()
            m.record_response(0.01)
            m.record_decode_step(float("nan"))   # hostile input
            store.sample_once()
        snap = store.snapshot(window_s=60)
        text = json.dumps(snap, allow_nan=False)   # raises on NaN
        snap2 = json.loads(text)
        assert snap2["samples"] == 3
        assert 0 < snap2["sampled_at"] <= monotonic_offset()
        ctr = snap2["series"]["src.counter.requests"]
        assert ctr["kind"] == "counter" and ctr["last"] == 3
        assert len(ctr["series"]) == 3           # raw ring points
        msnap = m.snapshot()
        assert 0 < msnap["sampled_at"] <= monotonic_offset()
        before = m.snapshot()["sampled_at"]
        time.sleep(0.01)
        assert m.snapshot()["sampled_at"] > before

    def test_concurrent_writers_sampler_and_reads(self):
        """The ISSUE 14 concurrency contract: writer threads hammer
        the metrics, the sampler thread ticks, and concurrent
        window()/snapshot() reads never see a torn window — no
        exceptions, counter 'last' monotone across reads, deltas and
        rates never negative, snapshots strict-JSON throughout."""
        from veles_tpu.serving import ServingMetrics, TimeSeriesStore
        m = ServingMetrics("ts_conc")
        store = TimeSeriesStore(interval_s=0.005, capacity=256)
        store.add_source(m, key="src")
        errors = []
        stop = threading.Event()

        def hammer():
            try:
                i = 0
                while not stop.is_set():
                    m.record_enqueue()
                    m.record_response(0.001 * (i % 5 + 1))
                    m.record_ttft(0.002)
                    m.inc("tokens_out", 3)
                    m.set_gauge("queue_depth", i % 11)
                    i += 1
            except Exception as e:   # noqa: BLE001 — the assertion
                errors.append(e)

        writers = [threading.Thread(target=hammer) for _ in range(3)]
        for t in writers:
            t.start()
        store.start()
        try:
            last_seen = -1
            deadline = time.monotonic() + 0.8
            while time.monotonic() < deadline:
                w = store.window("src.counter.requests", 60)
                if w is not None:
                    assert w["delta"] >= 0
                    assert w["rate_per_s"] >= 0
                    assert w["last"] >= last_seen
                    last_seen = w["last"]
                h = store.window("src.hist.ttft", 60)
                if h is not None:
                    assert h["count_delta"] >= 0
                snap = store.snapshot(window_s=5)
                json.dumps(snap, allow_nan=False)
        finally:
            stop.set()
            for t in writers:
                t.join(timeout=10)
            store.stop()
        assert not errors, errors
        assert store.samples > 10
        # the final ring state agrees with the final counter value
        final = store.window("src.counter.requests", 1e9)
        assert final["last"] <= m.snapshot()["requests"]


class TestRuntimeGauges:
    def test_engine_runtime_probe(self):
        """The ISSUE 14 runtime gauges on a live engine: the jit
        program-cache size as a compile_programs gauge (the invariant
        the jit-guard tests pin, live) with a monotone compiles_total
        counter, process RSS, tokens/s and live MFU from the FLOPs
        model, all written into the engine's own metrics row."""
        from veles_tpu.serving import LMEngine, ServingMetrics
        from veles_tpu.serving.timeseries import (
            engine_flops_per_token, engine_program_cache_size,
            runtime_probe)
        params = _tiny_params()
        engine = LMEngine(params, n_heads=2, max_len=48, slots=2,
                          name="rp_t",
                          metrics=ServingMetrics("rp_t")).start()
        try:
            probe = runtime_probe(engine)
            probe()                      # before any traffic
            snap0 = engine.metrics.snapshot()
            assert snap0["gauges"]["process_rss_bytes"] > 0
            engine.generate(numpy.asarray([[1, 2, 3]] * 2), 6)
            probe()
            time.sleep(0.02)
            probe()
            snap = engine.metrics.snapshot()
            g = snap["gauges"]
            # traffic compiled programs: the gauge reads the live jit
            # caches and the counter accumulated the observed growth
            assert g["compile_programs"] > 0
            assert g["compile_programs"] \
                == engine_program_cache_size(engine)
            assert snap["counters"]["compiles_total"] \
                == g["compile_programs"]
            assert "tokens_per_s" in g
            assert "mfu_live" in g and g["mfu_live"] >= 0
            assert engine_flops_per_token(engine) > 0
        finally:
            engine.stop()

    def test_megastep_waste_gauge(self):
        """The fused-decode early-exit tail as a live gauge: the probe
        derives megastep_waste_frac from the counter deltas between
        its ticks."""
        from veles_tpu.serving import ServingMetrics
        from veles_tpu.serving.timeseries import runtime_probe

        class _Eng:        # metrics-only stand-in; no device needed
            params = None
            n_heads = 2
            max_len = 32
            _mesh = None
            _device = None
            metrics = ServingMetrics("ms_t")

        eng = _Eng()
        probe = runtime_probe(eng, flops_per_token=None)
        probe()
        eng.metrics.record_megastep(k=8, lanes=2, tokens=12,
                                    wasted_iterations=4)
        probe()
        frac = eng.metrics.snapshot()["gauges"]["megastep_waste_frac"]
        assert frac == pytest.approx(4 / 16)


class TestLiveLedger:
    def test_live_ledger_equals_ring_and_trace_report(self, tmp_path):
        """The acceptance criterion: the tracer's incrementally-
        maintained ledger is EXACTLY the ring-aggregated cost_ledger
        on the same traced run (same rows, same dedup-by-did counts,
        same rounded quantiles), and matches tools/trace_report.py's
        rebuild from the Chrome export (counts exact; durations to
        the export's 0.1 us rounding)."""
        from veles_tpu.serving import (LMEngine, ServingMetrics,
                                       SpanTracer)
        import trace_report
        params = _tiny_params()
        tracer = SpanTracer(mode="all", last=64)
        engine = LMEngine(params, n_heads=2, max_len=48, slots=2,
                          prefill_chunk=8, spec_k=2, name="led_t",
                          metrics=ServingMetrics("led_t"),
                          tracer=tracer).start()
        try:
            prompts = [[1, 2, 3], [2, 4, 6, 8], [5, 1, 5, 1, 5],
                       [7, 7]]
            futures = [engine.submit(p, 6) for p in prompts]
            for f in futures:
                f.result(timeout=60)
        finally:
            engine.stop()
        ring = tracer.ledger()
        live = tracer.live_ledger()
        assert ring and live
        assert ring == live          # bit-exact, full-row equality
        # the export→trace_report round trip agrees row for row
        path = tmp_path / "trace.json"
        path.write_text(json.dumps(tracer.export_chrome()))
        rebuilt = trace_report.rebuild_requests(
            trace_report.load_trace(str(path)))
        from veles_tpu.serving import cost_ledger
        reported = cost_ledger(rebuilt)
        key = lambda r: (r["op"], r["bucket"], r["backend"])  # noqa
        assert {key(r) for r in reported} == {key(r) for r in live}
        by_key = {key(r): r for r in reported}
        for row in live:
            rep = by_key[key(row)]
            assert rep["dispatches"] == row["dispatches"]
            assert rep["lanes"] == row["lanes"]
            for q in ("p50_ms", "p95_ms", "mean_ms"):
                assert rep[q] == pytest.approx(row[q], abs=2e-3)

    def test_errors_mode_ledger_survives_ring_discard(self):
        """'errors' retention discards successful records from the
        ring — the live ledger still counts their dispatches (it is
        the aggregate view, not the post-mortem one)."""
        from veles_tpu.serving import SpanTracer
        tr = SpanTracer(mode="errors", last=8)
        ctx = tr.start_request(name="r1")
        tr.add(ctx, "decode.step", "decode", 0.0, 0.001,
               attrs={"bucket": 2, "backend": "xla"})
        tr.finish_request(ctx)           # success: ring discards it
        assert tr.requests() == []
        assert tr.ledger() == []         # ring view: empty
        live = tr.live_ledger()
        assert len(live) == 1 and live[0]["dispatches"] == 1


class TestSLOMonitor:
    @staticmethod
    def _store(metrics, key="src"):
        from veles_tpu.serving import TimeSeriesStore
        store = TimeSeriesStore(interval_s=0.05, capacity=256)
        store.add_source(metrics, key=key)
        return store

    def test_objective_validation(self):
        from veles_tpu.serving import Objective
        with pytest.raises(ValueError, match="kind"):
            Objective("x", "throughput", 0.9)
        with pytest.raises(ValueError, match="target"):
            Objective("x", "availability", 1.0)
        with pytest.raises(ValueError, match="threshold_s"):
            Objective("x", "latency", 0.9, series="ttft")
        with pytest.raises(ValueError, match="series"):
            Objective("x", "latency", 0.9, series="nope",
                      threshold_s=0.1)

    def test_state_machine_transitions(self):
        """ok → warn → page → ok, driven deterministically by
        synthetic counters and synchronous sample_once(): warn at a
        short-window burn >= 1, page only when EVERY window burns >=
        page_burn, recovery when the short window's burn drops."""
        from veles_tpu.serving import (Objective, ServingMetrics,
                                       SLOMonitor)
        m = ServingMetrics("slo_sm")
        store = self._store(m)
        mon = SLOMonitor(
            store, [Objective("avail", "availability", 0.9)],
            windows_s=(0.4, 300.0), min_events=1)
        store.add_listener(mon.sample_once)
        store.sample_once()                  # baseline
        for _ in range(100):
            m.record_response(0.001)
        store.sample_once()
        assert mon.state("src", "avail") == 0          # OK
        for _ in range(15):                  # ratio 15/115 -> burn 1.3
            m.record_error()
        store.sample_once()
        assert mon.state("src", "avail") == 1          # WARN
        for _ in range(85):                  # ratio 0.5 -> burn 5.0
            m.record_error()
        store.sample_once()
        assert mon.state("src", "avail") == 2          # PAGE
        assert mon.metrics.counter("slo_pages_total") == 1
        # recovery: let the short window age out the bad deltas, then
        # feed clean traffic
        time.sleep(0.5)
        for _ in range(50):
            m.record_response(0.001)
        store.sample_once()
        for _ in range(50):
            m.record_response(0.001)
        store.sample_once()
        assert mon.state("src", "avail") == 0          # recovered
        assert mon.metrics.counter("slo_recoveries_total") == 1
        snap = mon.snapshot()
        json.dumps(snap, allow_nan=False)
        assert snap["pages_total"] == 1

    def test_latency_objective_bucket_resolution(self):
        from veles_tpu.serving import (Objective, ServingMetrics,
                                       SLOMonitor)
        m = ServingMetrics("slo_lat")
        store = self._store(m)
        mon = SLOMonitor(
            store,
            [Objective("ttft", "latency", 0.9, series="ttft",
                       threshold_s=0.05)],
            windows_s=(60.0, 300.0), min_events=1, page_burn=2.0)
        store.sample_once()
        for _ in range(20):
            m.record_ttft(0.004)             # good
        store.sample_once()
        mon.sample_once()
        assert mon.state("src", "ttft") == 0
        for _ in range(20):
            m.record_ttft(0.4)               # bad: ratio 0.5, burn 5
        store.sample_once()
        mon.sample_once()
        assert mon.state("src", "ttft") == 2

    def test_min_events_holds_state(self):
        """One failed request on an idle fleet is not a page."""
        from veles_tpu.serving import (Objective, ServingMetrics,
                                       SLOMonitor)
        m = ServingMetrics("slo_idle")
        store = self._store(m)
        mon = SLOMonitor(
            store, [Objective("avail", "availability", 0.999)],
            windows_s=(60.0, 300.0), min_events=5)
        store.sample_once()
        m.record_error()                     # ratio 1.0 but 1 event
        store.sample_once()
        rows = mon.sample_once()
        assert mon.state("src", "avail") == 0
        assert rows[0]["held"] is True       # gate, not a verdict

    def test_latency_threshold_between_bounds_rounds_down(self):
        """The conservative cut (review-hardened): a threshold
        BETWEEN bucket bounds rounds DOWN, so traffic violating the
        threshold but under the next bound up still burns — bucket
        resolution can over-alert, never hide a violation."""
        from veles_tpu.serving import (Objective, ServingMetrics,
                                       SLOMonitor)
        m = ServingMetrics("slo_cut")
        store = self._store(m)
        # threshold 0.3 sits between the 0.25 and 0.5 bounds
        mon = SLOMonitor(
            store,
            [Objective("ttft", "latency", 0.9, series="ttft",
                       threshold_s=0.3)],
            windows_s=(60.0, 300.0), min_events=1)
        store.sample_once()
        for _ in range(20):
            m.record_ttft(0.45)          # violates 0.3, under 0.5
        store.sample_once()
        mon.sample_once()
        assert mon.state("src", "ttft") == 2       # PAGE, not OK
        good, total = store.count_in_window("src.hist.ttft", 60, 0.3)
        assert (good, total) == (0, 20)

    def test_held_page_never_refeeds_checker(self):
        """Review-hardened: a PAGE carried by the min_events gate
        (a quarantined replica serves no traffic, so its window never
        refills) must not keep signaling the checker — otherwise a
        recovered replica is re-quarantined forever on one stale
        burst."""
        from veles_tpu.serving import (Objective, ServingMetrics,
                                       SLOMonitor, TimeSeriesStore)

        class StubChecker:
            def __init__(self):
                self.pages, self.oks = [], []

            def note_slo_page(self, i, reason=""):
                self.pages.append(i)

            def note_slo_ok(self, i):
                self.oks.append(i)

        m0 = ServingMetrics("slo_held0")
        m1 = ServingMetrics("slo_held1")
        store = TimeSeriesStore(interval_s=0.02, capacity=64)
        store.add_source(m0, key="r0")
        store.add_source(m1, key="r1")
        checker = StubChecker()
        mon = SLOMonitor(
            store, [Objective("avail", "availability", 0.9)],
            windows_s=(0.4, 300.0), min_events=5, checker=checker,
            source_replicas={"r0": 0, "r1": 1})
        store.add_listener(mon.sample_once)
        store.sample_once()
        for _ in range(20):                  # fresh burn on r0 only
            m0.record_error()
            m1.record_response(0.001)
        store.sample_once()
        assert mon.state("r0", "avail") == 2
        assert checker.pages == [0]
        # traffic stops; the short window drains below min_events —
        # the held PAGE must signal nothing (neither page nor ok)
        time.sleep(0.5)
        pages_before = list(checker.pages)
        store.sample_once()
        store.sample_once()
        rows = {(r["source"], r["objective"]): r
                for r in mon.sample_once()}
        assert rows[("r0", "avail")]["state"] == 2
        assert rows[("r0", "avail")]["held"] is True
        assert checker.pages == pages_before

    def test_from_spec_file_and_shed_objective(self, tmp_path):
        from veles_tpu.serving import ServingMetrics, SLOMonitor
        spec = {"windows_s": [0.5, 120], "warn_burn": 1.0,
                "page_burn": 3.0, "min_events": 2,
                "objectives": [
                    {"name": "shed", "kind": "shed_rate",
                     "target": 0.9}]}
        path = tmp_path / "slo.json"
        path.write_text(json.dumps(spec))
        m = ServingMetrics("slo_file")
        store = self._store(m)
        mon = SLOMonitor.from_spec(str(path), store)
        assert mon.windows_s == (0.5, 120.0)
        assert mon.page_burn == 3.0
        store.sample_once()
        for _ in range(10):
            m.record_response(0.001)
        for _ in range(10):                  # 10 shed / 20 -> burn 5
            m.record_shed()
        store.sample_once()
        mon.sample_once()
        assert mon.state("src", "shed") == 2
        assert SLOMonitor.from_spec(None, store) is None
        with pytest.raises(ValueError, match="objectives"):
            SLOMonitor.from_spec({"nope": 1}, store)

    def test_page_feeds_health_checker_not_fleet_wide(self):
        """The router hook: a paging REPLICA source counts as health
        failures toward quarantine; a fleet-wide burn (every source
        paging) is never fed — and a solo engine is never quarantined
        by its own burn."""
        from veles_tpu.serving import (HealthChecker, LMEngine,
                                       Objective, Router,
                                       ServingMetrics, SLOMonitor,
                                       TimeSeriesStore)
        params = _tiny_params()
        replicas = [LMEngine(params, n_heads=2, max_len=48, slots=1,
                             name="slo_hc%d" % i,
                             metrics=ServingMetrics(
                                 "slo_hc",
                                 labels={"replica": str(i)}))
                    for i in range(2)]
        router = Router(replicas).start()
        checker = HealthChecker(router, fail_threshold=2,
                                cooldown_s=600.0)
        try:
            store = TimeSeriesStore(interval_s=0.05, capacity=64)
            keys = []
            for i, e in enumerate(replicas):
                store.add_source(e.metrics, key="r%d" % i)
                keys.append("r%d" % i)
            mon = SLOMonitor(
                store, [Objective("avail", "availability", 0.9)],
                windows_s=(60.0, 300.0), min_events=1,
                checker=checker,
                source_replicas={k: i for i, k in enumerate(keys)})
            store.add_listener(mon.sample_once)
            store.sample_once()
            # fleet-wide burn: BOTH replicas error — no quarantine
            for e in replicas:
                for _ in range(10):
                    e.metrics.record_error()
            store.sample_once()
            assert mon.state("r0", "avail") == 2
            assert mon.state("r1", "avail") == 2
            assert router._live == [True, True]
            # replica-scoped burn: only r0 keeps erroring while r1
            # recovers; two paging scans quarantine r0
            time.sleep(0.05)
            for _ in range(200):
                replicas[1].metrics.record_response(0.001)
            for _ in range(20):
                replicas[0].metrics.record_error()
            store.sample_once()
            assert mon.state("r1", "avail") in (0, 1)
            store.sample_once()
            assert router._live[0] is False
            assert checker.states()[0] == checker.OPEN
            assert router._live[1] is True
        finally:
            checker.stop()
            router.stop()

    def test_page_streak_survives_successful_probes(self):
        """A slow-but-RESPONSIVE replica keeps answering the health
        checker's synthetic probes; those successes reset the probe
        fail count but must NOT clear the SLO page streak — and
        note_slo_ok (the burn actually stopping) must."""
        from veles_tpu.serving import (HealthChecker, LMEngine,
                                       Router, ServingMetrics)
        params = _tiny_params()
        replicas = [LMEngine(params, n_heads=2, max_len=48, slots=1,
                             name="slo_pr%d" % i,
                             metrics=ServingMetrics("slo_pr%d" % i))
                    for i in range(2)]
        router = Router(replicas).start()
        checker = HealthChecker(router, fail_threshold=2,
                                cooldown_s=600.0)
        try:
            checker.warm_probes()
            checker.note_slo_page(0, reason="burning")
            # a full probe scan succeeds in between (the production
            # cadence): the page streak must survive it
            checker.step()
            assert checker.states()[0] == checker.HEALTHY
            checker.note_slo_page(0, reason="still burning")
            assert checker.states()[0] == checker.OPEN
            assert router._live[0] is False
            # ...and a cleared burn resets the streak: one page, then
            # ok, then one page again never sums to a quarantine
            checker.note_slo_page(1, reason="blip")
            checker.note_slo_ok(1)
            checker.note_slo_page(1, reason="later blip")
            assert checker.states()[1] == checker.HEALTHY
            # an OPERATOR drain is not the checker's to manage: page
            # signals against replica 1 after a manual unregister are
            # ignored (same fixture — replica 0 is already quarantined
            # by the checker above, which is the other no-op branch)
            router.unregister(1, reason="operator")
            checker.note_slo_page(1, reason="test")
            assert checker.states()[1] == checker.HEALTHY
            checker.note_slo_page(0, reason="already open")  # no-op
            assert checker.states()[0] == checker.OPEN
            with pytest.raises(ValueError):
                checker.note_slo_page(7)
        finally:
            checker.stop()
            router.stop()


class TestTelemetryEndpoints:
    def _serve(self):
        """A tiny server with every ISSUE 14 surface armed: metrics,
        a sampled store, an SLO monitor, and a tracer with ledger
        rows — no engine needed (the endpoints read components)."""
        from veles_tpu.restful_api import RESTfulAPI
        from veles_tpu.serving import (Objective, ServingMetrics,
                                       SLOMonitor, SpanTracer,
                                       TimeSeriesStore)
        m = ServingMetrics("ep_t")
        store = TimeSeriesStore(interval_s=0.05, capacity=32)
        store.add_source(m, key="ep")
        mon = SLOMonitor(
            store, [Objective("avail", "availability", 0.99)],
            windows_s=(60.0, 300.0), min_events=1)
        tracer = SpanTracer(mode="all", last=8)
        ctx = tracer.start_request(name="seed")
        tracer.add(ctx, "decode.step", "decode", 0.0, 0.002,
                   attrs={"bucket": 2, "backend": "xla"})
        tracer.finish_request(ctx)
        for i in range(3):
            m.record_enqueue()
            m.record_response(0.01)
            m.record_ttft(0.01)
            store.sample_once()
        mon.sample_once()
        api = RESTfulAPI(None, handler=lambda p: {"ok": True},
                         metrics=m, tracer=tracer, telemetry=store,
                         slo=mon)
        return api.start(port=0)

    def test_endpoints_strict_json_and_status_panel(self):
        api = self._serve()
        try:
            ts = _get_json(api.port, "/timeseries.json?window=30")
            assert ts["window_s"] == 30.0
            assert ts["samples"] == 3
            assert "ep.counter.responses" in ts["series"]
            assert ts["sampled_at"] > 0
            slo = _get_json(api.port, "/slo.json")
            assert slo["worst_state_name"] == "ok"
            assert slo["objectives"][0]["objective"] == "avail"
            assert slo["sampled_at"] > 0
            led = _get_json(api.port, "/ledger.json")
            assert led["dispatches_total"] == 1
            assert led["rows"][0]["op"] == "decode.step"
            assert led["sampled_at"] > 0
            ms = _get_json(api.port, "/metrics.json")
            assert ms["sampled_at"] > 0       # the small fix
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/status" % api.port,
                    timeout=10) as r:
                assert r.headers["Content-Type"].startswith(
                    "text/plain")
                text = r.read().decode()
            assert "veles_tpu serving status" in text
            assert "[slo" in text and "[telemetry" in text
            assert "[cost ledger" in text
            # schema guard: the live payloads conform to the shapes
            # tools/check_stream_records.py enforces tier-1
            import check_stream_records as csr
            assert csr.check_timeseries_payload(ts) == []
            assert csr.check_slo_payload(slo) == []
        finally:
            api.stop()

    def test_bad_window_param_is_400(self):
        api = self._serve()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _get_json(api.port, "/timeseries.json?window=banana")
            assert err.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as err:
                _get_json(api.port, "/timeseries.json?window=-5")
            assert err.value.code == 400
        finally:
            api.stop()

    def test_endpoints_absent_without_components(self):
        """A server without telemetry/slo keeps 404 semantics for the
        new paths (but /status always answers)."""
        from veles_tpu.restful_api import RESTfulAPI
        api = RESTfulAPI(None, handler=lambda p: {"ok": True})
        api.start(port=0)
        try:
            for path in ("/timeseries.json", "/slo.json",
                         "/ledger.json"):
                with pytest.raises(urllib.error.HTTPError) as err:
                    _get_json(api.port, path)
                assert err.value.code == 404
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/status" % api.port,
                    timeout=10) as r:
                assert b"serving status" in r.read()
        finally:
            api.stop()


class TestWebStatusTimeseries:
    def test_dashboard_serves_default_store(self):
        """web_status.py exposes the process's default telemetry
        store at /timeseries.json — dashboard and serving port share
        one set of rings; 404 when none is published."""
        from veles_tpu.serving import ServingMetrics, TimeSeriesStore
        from veles_tpu.serving import timeseries as ts_mod
        from veles_tpu.web_status import WebStatus
        old = ts_mod.get_default()
        status = WebStatus().start(port=0)
        try:
            ts_mod.set_default(None)
            with pytest.raises(urllib.error.HTTPError) as err:
                _get_json(status.port, "/timeseries.json")
            assert err.value.code == 404
            m = ServingMetrics("ws_ts")
            store = TimeSeriesStore(interval_s=0.05, capacity=16)
            store.add_source(m, key="ws")
            m.record_enqueue()
            store.sample_once()
            ts_mod.set_default(store)
            snap = _get_json(status.port, "/timeseries.json")
            assert "ws.counter.requests" in snap["series"]
        finally:
            ts_mod.set_default(old)
            status.stop()


class TestServeLMTelemetry:
    def test_serve_lm_wires_store_slo_and_endpoints(self):
        """End to end through serve_lm(telemetry=, slo=True): the
        store samples the engine on its cadence, the SLO monitor
        rides the tick, every new endpoint answers on the serving
        port, and stop() tears the sampler down before the engine."""
        from veles_tpu import prng
        from veles_tpu.config import root
        from veles_tpu.restful_api import serve_lm
        from veles_tpu.serving import timeseries as ts_mod
        prng.reset()
        prng.seed_all(5)
        root.__dict__.pop("char_lm", None)
        root.char_lm.update({
            "loader": {"minibatch_size": 32, "n_train": 64,
                       "n_valid": 32, "seq_len": 16, "vocab": 16},
            "trainer": {"vocab": 16, "d_model": 32, "n_heads": 2,
                        "n_layers": 1, "max_len": 32,
                        "learning_rate": 3e-3, "n_experts": 0,
                        "pipeline_stages": 0, "remat": False},
            "decision": {"max_epochs": 1, "fail_iterations": 10},
        })
        from veles_tpu.samples import char_lm
        wf = char_lm.train()
        api = serve_lm(wf, port=0, max_new=8, slots=2,
                       telemetry=0.05, slo=True)
        try:
            assert api.telemetry is not None
            assert api.slo is not None
            assert ts_mod.get_default() is api.telemetry
            payload = {"input": [[3, 4, 5]], "n_new": 4}
            req = urllib.request.Request(
                "http://127.0.0.1:%d/predict" % api.port,
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                out = json.loads(resp.read())
            assert len(out["tokens"][0]) == 7
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline \
                    and api.telemetry.samples < 3:
                time.sleep(0.05)
            assert api.telemetry.samples >= 3
            ts = _get_json(api.port, "/timeseries.json")
            resp_series = [n for n in ts["series"]
                           if n.endswith("counter.responses")]
            assert resp_series
            slo = _get_json(api.port, "/slo.json")
            assert slo["objectives"]       # evaluated on the cadence
            assert slo["worst_state_name"] in ("ok", "warn", "page")
            # the runtime probe ran: compile_programs is live
            ms = _get_json(api.port, "/metrics.json")
            assert ms["gauges"]["compile_programs"] > 0
        finally:
            api.stop()
        assert api.telemetry._thread is None      # sampler stopped


class TestChaosSLOSmoke:
    @pytest.mark.slow
    def test_slo_burn_alert_scenario_smoke(self):
        """The full chaos scenario at smoke size (slow suite — the
        tier-1 representative of the burn→page→quarantine path is
        TestSLOMonitor::test_page_feeds_health_checker_not_fleet_wide,
        and the scenario itself is asserted by every
        tools/chaos_bench.py run; the PR 3/8 watchdog-headroom
        discipline)."""
        from chaos_bench import (build_params, expected_rows,
                                 mixed_length_prompts,
                                 scenario_slo_burn_alert)
        vocab, max_len, n_heads, n_new = 16, 48, 2, 6
        params = build_params(vocab=vocab, d_model=32, n_heads=2,
                              n_layers=2, max_len=max_len, seed=7)
        prompts = mixed_length_prompts(4, vocab, 3,
                                       max_len - n_new - 4, seed=5)
        expect = expected_rows(params, prompts, n_new, n_heads,
                               max_len)
        record = scenario_slo_burn_alert(
            params, n_heads, max_len, prompts, n_new, expect,
            spike_s=0.05)
        assert record["replica0_quarantined"] is True
        assert record["sampling_windows_to_quarantine"] <= 2
        assert record["completed_exactly_once"] == 8
