"""Tier-4 distributed tests on the virtual 8-device CPU mesh (SURVEY §4).

The analogue of the reference's loopback master/slave tests
(test_client_server.py style): same-machine, real collective semantics.
Key assertion: SPMD data-parallel training is numerically equivalent to
single-device training — the all-reduce IS the reference's gradient
averaging.
"""

import numpy
import pytest

import jax

from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.parallel import make_mesh, ShardedTrainer


def _build(mb=64):
    root.mnist.update({
        "loader": {"minibatch_size": mb, "n_train": 256, "n_valid": 64},
        "decision": {"max_epochs": 1, "fail_iterations": 10},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.05, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    wf = mnist.build(fused=True)
    wf.initialize()
    return wf


def _batch(mb, seed=3):
    rng = numpy.random.RandomState(seed)
    x = rng.randn(mb, 784).astype(numpy.float32)
    labels = rng.randint(0, 10, mb).astype(numpy.int32)
    mask = numpy.ones(mb, numpy.float32)
    return x, labels, mask


def test_dp_matches_single_device():
    prng.reset(); prng.seed_all(11)
    wf = _build()
    runner = wf._fused_runner
    import jax.numpy as jnp
    x, labels, mask = _batch(64)
    # single-device reference trajectory
    ref_state = jax.tree.map(lambda a: a, runner.state)
    for step in range(3):
        ref_state, ref_metrics = jax.jit(runner._train_step)(
            ref_state, x, labels, mask, jnp.asarray(64, jnp.int32))
    # sharded trajectory from the same init
    prng.reset(); prng.seed_all(11)
    wf2 = _build()
    runner2 = wf2._fused_runner
    mesh = make_mesh(8)
    trainer = ShardedTrainer(runner2, mesh)
    for step in range(3):
        metrics = trainer.train_step(x, labels, mask, 64)
    for ref_entry, entry in zip(ref_state, trainer.state):
        for key in ref_entry:
            numpy.testing.assert_allclose(
                numpy.asarray(ref_entry[key]), numpy.asarray(entry[key]),
                rtol=2e-5, atol=2e-6)
    assert int(metrics["n_err"]) == int(ref_metrics["n_err"])


def test_tp_model_sharding_matches():
    """Tensor-parallel first layer must give the same numbers too."""
    prng.reset(); prng.seed_all(11)
    wf = _build()
    runner = wf._fused_runner
    import jax.numpy as jnp
    x, labels, mask = _batch(64)
    ref_state, _ = jax.jit(runner._train_step)(
        runner.state, x, labels, mask, jnp.asarray(64, jnp.int32))

    prng.reset(); prng.seed_all(11)
    wf2 = _build()
    runner2 = wf2._fused_runner
    mesh = make_mesh(8, model_parallel=2)
    trainer = ShardedTrainer(runner2, mesh, model_shard_layers=(0,))
    trainer.train_step(x, labels, mask, 64)
    for ref_entry, entry in zip(ref_state, trainer.state):
        for key in ref_entry:
            numpy.testing.assert_allclose(
                numpy.asarray(ref_entry[key]), numpy.asarray(entry[key]),
                rtol=2e-5, atol=2e-6)
    # the plan's sharding really is in force (weights split over 'model')
    w0 = trainer.state[0]["w"]
    assert not w0.sharding.is_fully_replicated


def test_tp_alexnet_fc_trunk_matches():
    """TP at the scale it exists for: the AlexNet 4096-wide FC trunk
    sharded over 'model', asserted numerically equivalent to the
    replicated run (VERDICT r3 Weak #6: no 16-unit toys)."""
    import jax.numpy as jnp
    from veles_tpu.parallel import model_shard_candidates
    from veles_tpu.samples.imagenet import ImagenetWorkflow, alexnet_layers
    from veles_tpu.loader.fullbatch import FullBatchLoader

    mb = 16

    class _SmallImages(FullBatchLoader):
        def load_data(self):
            rng = numpy.random.RandomState(7)
            self.original_data.reset(
                rng.uniform(-1, 1, (mb * 2, 64, 64, 3))
                .astype(numpy.float32))
            self.original_labels.reset(
                rng.randint(0, 16, mb * 2).astype(numpy.int32))
            self.class_lengths = [0, mb, mb]

    def build():
        prng.reset(); prng.seed_all(21)
        wf = ImagenetWorkflow(
            None, name="tp_alexnet", loader_factory=_SmallImages,
            loader_config={"minibatch_size": mb},
            layers=alexnet_layers(n_classes=16, crop=(56, 56)),
            decision_config={"max_epochs": 1, "fail_iterations": 5},
            loss_function="softmax", fused=True)
        wf.initialize()
        return wf

    x, labels, mask = (numpy.random.RandomState(9)
                       .uniform(-1, 1, (mb, 64, 64, 3))
                       .astype(numpy.float32),
                       numpy.arange(mb, dtype=numpy.int32) % 16,
                       numpy.ones(mb, numpy.float32))
    rng = jax.random.PRNGKey(4)

    # replicated reference trajectory (single device)
    wf = build()
    runner = wf._fused_runner
    ref_state, ref_metrics = jax.jit(runner._train_step)(
        runner.state, x, labels, mask, jnp.asarray(mb, jnp.int32), rng,
        jnp.asarray(0, jnp.int32))

    # TP trajectory: both 4096-wide FC layers sharded over 'model'
    wf2 = build()
    runner2 = wf2._fused_runner
    fc = model_shard_candidates(runner2, min_width=4096)
    assert len(fc) == 2, fc  # exactly the two 4096-wide trunk layers
    assert all(runner2.state[i]["w"].shape[-1] == 4096 for i in fc)
    mesh = make_mesh(8, model_parallel=2)
    trainer = ShardedTrainer(runner2, mesh, model_shard_layers=fc)
    metrics = trainer.train_step(x, labels, mask, mb, rng=rng, step=0)

    # the trunk really is split over 'model' (not replicated)
    for i in fc:
        assert not trainer.state[i]["w"].sharding.is_fully_replicated
        assert trainer.state[i]["w"].sharding.shard_shape(
            trainer.state[i]["w"].shape)[-1] == 2048
    numpy.testing.assert_allclose(
        float(trainer.fetch(metrics)["loss_sum"]),
        float(ref_metrics["loss_sum"]), rtol=1e-4)
    assert int(trainer.fetch(metrics)["n_err"]) == int(ref_metrics["n_err"])
    for i, (ref_entry, entry) in enumerate(zip(ref_state, trainer.state)):
        for key in ref_entry:
            numpy.testing.assert_allclose(
                numpy.asarray(ref_entry[key]), numpy.asarray(entry[key]),
                rtol=2e-4, atol=2e-5,
                err_msg="layer %d %s diverged under TP" % (i, key))


def test_epoch_scan_matches_per_step_loop():
    """The one-dispatch-per-epoch scan path equals the per-minibatch path."""
    prng.reset(); prng.seed_all(13)
    wf = _build(mb=64)
    runner = wf._fused_runner
    import jax.numpy as jnp
    loader = wf.loader
    data = loader.original_data.devmem
    labels = loader.original_labels.devmem
    from veles_tpu.loader.base import TRAIN
    loader._plan_epoch()
    idx = numpy.stack([c for cls, c, a in loader._order if cls == TRAIN])
    mask = numpy.stack([
        (numpy.arange(len(c)) < a).astype(numpy.float32)
        for cls, c, a in loader._order if cls == TRAIN])

    # per-step loop
    state_a = jax.tree.map(lambda a: a, runner.state)
    step = jax.jit(runner._train_step)
    for i in range(idx.shape[0]):
        x = numpy.asarray(jax.numpy.take(data, idx[i], axis=0))
        lab = numpy.asarray(jax.numpy.take(labels, idx[i], axis=0))
        state_a, _ = step(state_a, x, lab, mask[i],
                          jnp.asarray(int(mask[i].sum()), jnp.int32))
    # scan path
    train_epoch, _ = runner.epoch_fns()
    state_b, totals = train_epoch(runner.state, data, labels, idx, mask)
    for ea, eb in zip(state_a, state_b):
        for key in ea:
            numpy.testing.assert_allclose(
                numpy.asarray(ea[key]), numpy.asarray(eb[key]),
                rtol=2e-5, atol=2e-6)


def test_epoch_chunk_matches_sequential_epochs():
    """epoch_chunk_fn(k) — k epochs in ONE device program (the dispatch
    amortization the bench times through the tunnel) — must equal k
    sequential train_epoch calls, including the per-epoch key folding by
    global step offset."""
    prng.reset(); prng.seed_all(13)
    wf = _build(mb=64)
    runner = wf._fused_runner
    loader = wf.loader
    data = loader.original_data.devmem
    labels = loader.original_labels.devmem
    from veles_tpu.loader.base import TRAIN
    loader._plan_epoch()
    idx = numpy.stack([c for cls, c, a in loader._order if cls == TRAIN])
    mask = numpy.stack([
        (numpy.arange(len(c)) < a).astype(numpy.float32)
        for cls, c, a in loader._order if cls == TRAIN])
    steps = idx.shape[0]
    base = jax.random.PRNGKey(7)

    # sequential: two train_epoch calls, base key folded by global offset
    # (real copy: train_epoch donates, and the chunk leg needs the
    # original buffers afterwards)
    train_epoch, _ = runner.epoch_fns()
    state_a = jax.tree.map(jax.numpy.array, runner.state)
    for e in range(2):
        off = e * steps
        state_a, totals_a = train_epoch(
            state_a, data, labels, idx, mask,
            rng=jax.random.fold_in(base, off), step0=off)

    # chunked: one dispatch, k=2
    chunk = runner.epoch_chunk_fn(2)
    state_b, stacked = chunk(runner.state, data, labels, idx, mask,
                             rng=base, step0=0)
    for ea, eb in zip(state_a, state_b):
        for key in ea:
            numpy.testing.assert_allclose(
                numpy.asarray(ea[key]), numpy.asarray(eb[key]),
                rtol=2e-5, atol=2e-6)
    # stacked metrics: one row per epoch; row 1 equals the sequential
    # second epoch's totals
    for key in totals_a:
        assert numpy.asarray(stacked[key]).shape[0] == 2
        numpy.testing.assert_allclose(
            numpy.asarray(stacked[key][1]), numpy.asarray(totals_a[key]),
            rtol=2e-5, atol=2e-6)


def test_epoch_chunk_eval_matches_sequential_rounds():
    """epoch_chunk_eval_fn(k) — k (train epoch -> val eval) rounds in one
    program — returns exactly the per-epoch val totals the sequential
    train_epoch/eval_epoch loop fetches, and the same final state."""
    prng.reset(); prng.seed_all(29)
    wf = _build(mb=64)
    runner = wf._fused_runner
    loader = wf.loader
    data = loader.original_data.devmem
    labels = loader.original_labels.devmem
    from veles_tpu.loader.base import TRAIN, VALID
    loader._plan_epoch()

    def order(cls):
        idx = numpy.stack([c for k_, c, a in loader._order if k_ == cls])
        mask = numpy.stack([
            (numpy.arange(len(c)) < a).astype(numpy.float32)
            for k_, c, a in loader._order if k_ == cls])
        return idx, mask

    idx, mask = order(TRAIN)
    vidx, vmask = order(VALID)
    steps = idx.shape[0]
    base = jax.random.PRNGKey(11)

    # sequential reference (on a copy: the chunk leg donates)
    train_epoch, eval_epoch = runner.epoch_fns()
    state_a = jax.tree.map(jax.numpy.array, runner.state)
    seq_vals = []
    for e in range(2):
        off = e * steps
        state_a, _ = train_epoch(state_a, data, labels, idx, mask,
                                 rng=jax.random.fold_in(base, off),
                                 step0=off)
        seq_vals.append(eval_epoch(state_a, data, labels, vidx, vmask))

    chunk = runner.epoch_chunk_eval_fn(2)
    state_b, _, val_stack, test_stack = chunk(
        runner.state, data, labels, idx, mask, vidx, vmask, rng=base,
        step0=0)
    assert test_stack is None   # no test plan given
    for ea, eb in zip(state_a, state_b):
        for key in ea:
            numpy.testing.assert_allclose(
                numpy.asarray(ea[key]), numpy.asarray(eb[key]),
                rtol=2e-5, atol=2e-6)
    for e in range(2):
        for key in seq_vals[e]:
            numpy.testing.assert_allclose(
                numpy.asarray(val_stack[key][e]),
                numpy.asarray(seq_vals[e][key]), rtol=1e-5)


def test_loader_host_sharding_composes_with_mesh():
    """Multi-host story: each process takes a strided shard; union of shards
    covers the dataset exactly once (replaces index shipping)."""
    prng.reset(); prng.seed_all(5)
    root.mnist.update({
        "loader": {"minibatch_size": 32, "n_train": 128, "n_valid": 32},
        "decision": {"max_epochs": 1, "fail_iterations": 10},
        "layers": [{"type": "softmax", "output_sample_shape": 10,
                    "learning_rate": 0.05}],
    })
    from veles_tpu.samples import mnist
    seen = set()
    for proc in range(2):
        prng.reset(); prng.seed_all(5)
        wf = mnist.build(fused=True)
        wf.loader.shard(proc, 2)
        wf.initialize()
        for cls, chunk, actual in wf.loader._order:
            seen.update(chunk[:actual].tolist())
    assert seen == set(range(160))


def test_sharded_epoch_scan_matches_per_step_spmd():
    """ShardedTrainer.train_epoch (one dispatch per epoch, plan matrices
    sharded over the data axis) equals the per-minibatch SPMD path and
    works with a TP layer in the same plan."""
    from veles_tpu.loader.base import TRAIN

    def plan(loader):
        loader._plan_epoch()
        idx = numpy.stack([c for cls, c, a in loader._order
                           if cls == TRAIN])
        mask = numpy.stack([
            (numpy.arange(len(c)) < a).astype(numpy.float32)
            for cls, c, a in loader._order if cls == TRAIN])
        return idx, mask

    # per-minibatch SPMD trajectory
    prng.reset(); prng.seed_all(17)
    wf_a = _build(mb=64)
    runner_a = wf_a._fused_runner
    mesh = make_mesh(8, model_parallel=2)
    trainer_a = ShardedTrainer(runner_a, mesh, model_shard_layers=(0,))
    data = numpy.asarray(wf_a.loader.original_data.mem)
    labels = numpy.asarray(wf_a.loader.original_labels.mem)
    idx, mask = plan(wf_a.loader)
    for i in range(idx.shape[0]):
        trainer_a.train_step(data[idx[i]], labels[idx[i]], mask[i],
                             int(mask[i].sum()), step=i)

    # epoch-scan SPMD trajectory from the same init and plan
    prng.reset(); prng.seed_all(17)
    wf_b = _build(mb=64)
    runner_b = wf_b._fused_runner
    trainer_b = ShardedTrainer(runner_b, mesh, model_shard_layers=(0,))
    idx_b, mask_b = plan(wf_b.loader)
    numpy.testing.assert_array_equal(idx, idx_b)   # same PRNG -> same plan
    trainer_b.place_dataset(data, labels)
    totals = trainer_b.train_epoch(idx_b, mask_b, step0=0)
    assert trainer_b.step_count == idx.shape[0]

    for ea, eb in zip(trainer_a.state, trainer_b.state):
        for key in ea:
            numpy.testing.assert_allclose(
                numpy.asarray(ea[key]), numpy.asarray(eb[key]),
                rtol=2e-5, atol=2e-6)
    # TP layer stayed sharded through the scan (out_shardings pinned)
    assert not trainer_b.state[0]["w"].sharding.is_fully_replicated

    # eval_epoch totals match summing per-step eval metrics
    totals_eval = trainer_b.eval_epoch(idx_b, mask_b)
    per = None
    for i in range(idx.shape[0]):
        m = trainer_b.eval_step(data[idx[i]], labels[idx[i]], mask[i])
        host = ShardedTrainer.fetch(m)
        per = (host if per is None else
               {k: per[k] + host[k] for k in per})
    host_tot = ShardedTrainer.fetch(totals_eval)
    for k in host_tot:
        numpy.testing.assert_allclose(numpy.ravel(host_tot[k]),
                                      numpy.ravel(per[k]), rtol=1e-5)


def test_sharded_train_epochs_chunk_matches_sequential():
    """ShardedTrainer.train_epochs — k epochs with per-epoch shuffled
    plans in ONE dispatch under the mesh (incl. a TP layer) — equals k
    sequential train_epoch calls on the same plans."""
    from veles_tpu.loader.base import TRAIN

    def plan(loader):
        loader._plan_epoch()
        idx = numpy.stack([c for cls, c, a in loader._order
                           if cls == TRAIN])
        mask = numpy.stack([
            (numpy.arange(len(c)) < a).astype(numpy.float32)
            for cls, c, a in loader._order if cls == TRAIN])
        return idx, mask

    mesh = make_mesh(8, model_parallel=2)

    def two_plans(loader):
        i0, m0 = plan(loader)
        i1, m1 = plan(loader)   # re-plan => independently shuffled epoch
        assert not numpy.array_equal(i0, i1)
        return (numpy.stack([i0, i1]), numpy.stack([m0, m1]))

    # sequential: two train_epoch dispatches
    prng.reset(); prng.seed_all(23)
    wf_a = _build(mb=64)
    trainer_a = ShardedTrainer(wf_a._fused_runner, mesh,
                               model_shard_layers=(0,))
    data = numpy.asarray(wf_a.loader.original_data.mem)
    labels = numpy.asarray(wf_a.loader.original_labels.mem)
    idx3, mask3 = two_plans(wf_a.loader)
    steps = idx3.shape[1]
    trainer_a.place_dataset(data, labels)
    for e in range(2):
        totals_a = trainer_a.train_epoch(idx3[e], mask3[e],
                                         step0=e * steps)

    # chunked: one dispatch with the same two plans
    prng.reset(); prng.seed_all(23)
    wf_b = _build(mb=64)
    trainer_b = ShardedTrainer(wf_b._fused_runner, mesh,
                               model_shard_layers=(0,))
    idx3_b, mask3_b = two_plans(wf_b.loader)
    numpy.testing.assert_array_equal(idx3, idx3_b)
    trainer_b.place_dataset(data, labels)
    stacked = trainer_b.train_epochs(idx3_b, mask3_b, step0=0)
    assert trainer_b.step_count == 2 * steps

    for ea, eb in zip(trainer_a.state, trainer_b.state):
        for key in ea:
            numpy.testing.assert_allclose(
                numpy.asarray(ea[key]), numpy.asarray(eb[key]),
                rtol=2e-5, atol=2e-6)
    assert not trainer_b.state[0]["w"].sharding.is_fully_replicated
    # stacked row 1 == the sequential second epoch's totals
    host = ShardedTrainer.fetch(stacked)
    host_a = ShardedTrainer.fetch(totals_a)
    for k in host:
        assert numpy.asarray(host[k]).shape[0] == 2
        numpy.testing.assert_allclose(numpy.ravel(host[k][1]),
                                      numpy.ravel(host_a[k]), rtol=1e-5)


def test_sharded_train_epochs_eval_matches_sequential():
    """ShardedTrainer.train_epochs_eval == per-epoch train_epoch +
    eval_epoch under the same mesh (per-epoch val totals, final state)."""
    from veles_tpu.loader.base import TRAIN, VALID

    def order(loader, cls):
        return loader.plan_arrays(cls)

    mesh = make_mesh(8, model_parallel=2)

    prng.reset(); prng.seed_all(31)
    wf_a = _build(mb=64)
    trainer_a = ShardedTrainer(wf_a._fused_runner, mesh,
                               model_shard_layers=(0,))
    data = numpy.asarray(wf_a.loader.original_data.mem)
    labels = numpy.asarray(wf_a.loader.original_labels.mem)
    wf_a.loader._plan_epoch()
    i0, m0 = order(wf_a.loader, TRAIN)
    vidx, vmask = order(wf_a.loader, VALID)
    wf_a.loader._plan_epoch()
    i1, m1 = order(wf_a.loader, TRAIN)
    steps = i0.shape[0]
    trainer_a.place_dataset(data, labels)
    seq_vals = []
    for e, (ei, em) in enumerate([(i0, m0), (i1, m1)]):
        trainer_a.train_epoch(ei, em, step0=e * steps)
        seq_vals.append(ShardedTrainer.fetch(
            trainer_a.eval_epoch(vidx, vmask)))

    prng.reset(); prng.seed_all(31)
    wf_b = _build(mb=64)
    trainer_b = ShardedTrainer(wf_b._fused_runner, mesh,
                               model_shard_layers=(0,))
    wf_b.loader._plan_epoch()
    i0b, m0b = order(wf_b.loader, TRAIN)
    wf_b.loader._plan_epoch()
    i1b, m1b = order(wf_b.loader, TRAIN)
    numpy.testing.assert_array_equal(i0, i0b)
    numpy.testing.assert_array_equal(i1, i1b)
    trainer_b.place_dataset(data, labels)
    _, val_stack = trainer_b.train_epochs_eval(
        numpy.stack([i0b, i1b]), numpy.stack([m0b, m1b]), vidx, vmask,
        step0=0)
    host = ShardedTrainer.fetch(val_stack)
    for e in range(2):
        for key in seq_vals[e]:
            numpy.testing.assert_allclose(
                numpy.ravel(host[key][e]),
                numpy.ravel(seq_vals[e][key]), rtol=1e-5)
    for ea, eb in zip(trainer_a.state, trainer_b.state):
        for key in ea:
            numpy.testing.assert_allclose(
                numpy.asarray(ea[key]), numpy.asarray(eb[key]),
                rtol=2e-5, atol=2e-6)


def test_epoch_scan_requires_divisible_minibatch():
    prng.reset(); prng.seed_all(17)
    wf = _build(mb=64)
    trainer = ShardedTrainer(wf._fused_runner, make_mesh(8))
    trainer.place_dataset(numpy.asarray(wf.loader.original_data.mem),
                          numpy.asarray(wf.loader.original_labels.mem))
    bad_idx = numpy.zeros((2, 13), numpy.int32)   # 13 % 8 != 0
    bad_mask = numpy.ones((2, 13), numpy.float32)
    with pytest.raises(ValueError):
        trainer.train_epoch(bad_idx, bad_mask)
