"""Plotting stack tests: spec rendering, plotter units in a live training
run, and the ZMQ graphics server→client transport (SURVEY §2.1/§5.5)."""

import os

import numpy
import pytest

from veles_tpu.plotter import render_spec


class TestRenderSpec:
    def test_curve(self, tmp_path):
        path = render_spec({"kind": "curve",
                            "series": {"train": [3, 2, 1],
                                       "validation": [4, 3, 2]},
                            "title": "err"}, str(tmp_path / "c.png"))
        assert os.path.getsize(path) > 0

    def test_matrix(self, tmp_path):
        path = render_spec({"kind": "matrix",
                            "matrix": numpy.eye(4)}, str(tmp_path / "m.png"))
        assert os.path.getsize(path) > 0

    def test_hist(self, tmp_path):
        path = render_spec({"kind": "hist",
                            "values": numpy.random.RandomState(0).randn(100)},
                           str(tmp_path / "h.png"))
        assert os.path.getsize(path) > 0

    def test_image_grid(self, tmp_path):
        imgs = numpy.random.RandomState(0).rand(6, 8, 8)
        path = render_spec({"kind": "image_grid", "images": imgs},
                           str(tmp_path / "g.png"))
        assert os.path.getsize(path) > 0

    def test_unknown_kind(self, tmp_path):
        with pytest.raises(ValueError):
            render_spec({"kind": "nope"}, str(tmp_path / "x.png"))


class TestStopDedup:
    def test_stop_skips_identical_final_spec(self, tmp_path):
        """stop() must not duplicate the last plot when nothing changed,
        but must emit new state accumulated after the last unit fire."""
        from veles_tpu.plotter import Plotter
        from veles_tpu.workflow import Workflow

        class FixedPlotter(Plotter):
            payload = [1, 2, 3]

            def plot_spec(self):
                return {"kind": "curve",
                        "series": {"y": list(self.payload)}}

        wf = Workflow(None, name="wf")
        p = FixedPlotter(wf, output_dir=str(tmp_path), name="p")
        p.redraw()
        assert len(p.specs) == 1
        p.stop()                       # unchanged state → no duplicate
        assert len(p.specs) == 1
        p.payload.append(4)            # state advanced without a fire
        p.stop()
        assert len(p.specs) == 2
        assert p.specs[-1]["series"]["y"] == [1, 2, 3, 4]


class TestPlottersInTraining:
    def test_standard_plotters_produce_files(self, tmp_path):
        from veles_tpu import prng
        from veles_tpu.config import root
        prng.reset()
        prng.seed_all(1)
        root.mnist.update({
            "loader": {"minibatch_size": 50, "n_train": 200, "n_valid": 100},
            "decision": {"max_epochs": 2, "fail_iterations": 10},
            "layers": [
                {"type": "all2all_tanh", "output_sample_shape": 16,
                 "learning_rate": 0.03, "momentum": 0.9},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.03, "momentum": 0.9},
            ],
        })
        from veles_tpu.samples import mnist
        wf = mnist.build(fused=True)
        plot_dir = str(tmp_path / "plots")
        wf.link_plotters(output_dir=plot_dir)
        wf.initialize()
        wf.run()
        files = sorted(os.listdir(plot_dir))
        kinds = {f.rsplit("_", 1)[0] for f in files}
        assert kinds == {"plot_curve", "plot_confusion", "plot_weights"}
        # one redraw per epoch boundary (x 2 epochs, x3 sets finishing —
        # at least 2 curve files)
        assert sum(f.startswith("plot_curve") for f in files) >= 2
        # specs carry the data for tests/publishing
        curve = wf.plotters[0].specs[-1]
        assert "validation" in curve["series"]

    def test_weights2d_conv_kernels(self):
        from veles_tpu import prng
        from veles_tpu.config import root
        prng.reset()
        prng.seed_all(1)
        root.cifar.update({
            "loader": {"minibatch_size": 25, "n_train": 50, "n_valid": 25},
            "decision": {"max_epochs": 1, "fail_iterations": 5},
            # explicit layers: root is a process-global tree, other tests
            # may have installed a different topology under root.cifar
            "layers": [
                {"type": "conv_relu", "n_kernels": 8, "kx": 3, "ky": 3,
                 "padding": "SAME", "learning_rate": 0.01, "momentum": 0.9},
                {"type": "max_pooling", "kx": 2, "ky": 2},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.01, "momentum": 0.9},
            ],
        })
        from veles_tpu.samples import cifar
        wf = cifar.build(fused=False)
        wf.initialize()
        from veles_tpu.nn_plotting_units import Weights2D
        w2d = Weights2D(wf, name="w2d")
        w2d.input = wf.forwards[0]
        w2d._initialized = True
        spec = w2d.plot_spec()
        assert spec["kind"] == "image_grid"
        assert len(spec["images"]) == wf.forwards[0].n_kernels


class TestGraphicsTransport:
    def test_pub_sub_roundtrip(self, tmp_path):
        from veles_tpu.graphics_server import GraphicsServer
        from veles_tpu.graphics_client import GraphicsClient
        import time
        server = GraphicsServer("tcp://127.0.0.1:0")
        client = GraphicsClient(server.endpoint,
                                out_dir=str(tmp_path / "out"))
        time.sleep(0.2)        # PUB/SUB slow-joiner
        server.send({"kind": "curve", "series": {"a": [1, 2]},
                     "name": "roundtrip"})
        assert client.poll_once(5000)
        files = os.listdir(tmp_path / "out")
        assert files and files[0].startswith("roundtrip")
        server.close()
        assert not client.poll_once(2000)   # end-of-stream marker
        client.close()
