"""Kohonen SOM tests: winner search vs numpy oracle, update rule pull,
convergence of the demo sample (SURVEY §4 tiers 2-3)."""

import numpy

import jax.numpy as jnp

from veles_tpu.ops import functional as F
from veles_tpu.ops.kohonen import grid_coords


def rng(seed=0):
    return numpy.random.RandomState(seed)


class TestKohonenFunctional:
    def test_winners_match_numpy(self):
        r = rng(1)
        x = r.randn(16, 4).astype(numpy.float32)
        w = r.randn(9, 4).astype(numpy.float32)
        winners, dmin = F.kohonen_winners(jnp.asarray(x), jnp.asarray(w))
        d = ((x[:, None, :] - w[None, :, :]) ** 2).sum(-1)
        numpy.testing.assert_array_equal(numpy.asarray(winners),
                                         d.argmin(1))
        numpy.testing.assert_allclose(numpy.asarray(dmin), d.min(1),
                                      rtol=1e-4, atol=1e-4)

    def test_update_pulls_winner_toward_sample(self):
        w = numpy.zeros((4, 2), numpy.float32)
        w[3] = [0.9, 0.9]
        x = numpy.array([[1.0, 1.0]], numpy.float32)
        mask = numpy.ones(1, numpy.float32)
        grid = jnp.asarray(grid_coords(2, 2))
        new_w, metrics = F.kohonen_update(
            jnp.asarray(w), jnp.asarray(x), jnp.asarray(mask), grid,
            jnp.asarray(0.5, jnp.float32), jnp.asarray(0.5, jnp.float32))
        new_w = numpy.asarray(new_w)
        # winner (neuron 3) moved halfway toward the sample
        numpy.testing.assert_allclose(new_w[3], [0.95, 0.95], atol=1e-5)
        # distant neurons moved much less than the winner
        assert abs(new_w[0]).max() < 0.05
        assert float(metrics["qe_sum"]) > 0

    def test_masked_samples_do_not_update(self):
        r = rng(2)
        w = r.randn(4, 2).astype(numpy.float32)
        x = r.randn(3, 2).astype(numpy.float32)
        grid = jnp.asarray(grid_coords(2, 2))
        dead = jnp.asarray(numpy.zeros(3, numpy.float32))
        new_w, metrics = F.kohonen_update(
            jnp.asarray(w), jnp.asarray(x), dead, grid,
            jnp.asarray(0.5, jnp.float32), jnp.asarray(1.0, jnp.float32))
        numpy.testing.assert_allclose(numpy.asarray(new_w), w, atol=1e-6)
        assert float(metrics["qe_sum"]) == 0.0


class TestKohonenSample:
    def test_converges_and_spreads(self):
        from veles_tpu.config import root
        root.kohonen.update({
            "loader": {"minibatch_size": 50, "n_train": 500},
            "trainer": {"shape": (6, 6), "learning_rate": 0.3,
                        "decay_steps": 100},
            "decision": {"max_epochs": 5, "fail_iterations": 20},
        })
        from veles_tpu.samples import kohonen
        wf = kohonen.train()
        qerrs = [m["train"]["qerr"] for m in wf.decision.epoch_metrics]
        assert len(qerrs) == 5
        assert qerrs[-1] < qerrs[0], qerrs
        # forward ran at completion and distributed wins over many neurons
        assert wf.forward.hits.sum() > 0
        assert (wf.forward.hits > 0).sum() >= 4


def test_eval_only_freezes_codebook():
    """wf.eval_only (Launcher --evaluate) must stop the SOM trainer from
    updating weights even on TRAIN minibatches — the shared
    Unit.is_train_minibatch gate covers gradient-free trainers too."""
    from veles_tpu import prng
    from veles_tpu.config import root
    prng.reset(); prng.seed_all(3)
    root.__dict__.pop("kohonen", None)
    from veles_tpu.samples import kohonen as sample
    sample.default_config()
    root.kohonen.update({
        "loader": {"minibatch_size": 50, "n_train": 100},
        "decision": {"max_epochs": 1, "fail_iterations": 5},
    })
    wf = sample.build()
    wf.initialize()
    wf.eval_only = True
    w_before = numpy.array(wf.trainer.weights.mem)
    wf.loader.run()                     # a TRAIN minibatch (train-only set)
    assert wf.loader.minibatch_class == 2
    wf.trainer.run()
    numpy.testing.assert_array_equal(w_before,
                                     numpy.array(wf.trainer.weights.mem))
