"""Tier-2 op tests: jax ops vs a hand-written numpy oracle.

This mirrors the reference's per-op test pattern (numpy backend vs device
backends, allclose within dtype tolerance — SURVEY §4 tier 2); the numpy
oracle here is written independently of the jax code.
"""

import numpy
import pytest

from veles_tpu.ops import functional as F

# fp32 tolerance: XLA's transcendental approximations (tanh, exp) differ from
# numpy's at the ~1e-5 relative level, same class of tolerance the reference
# used between its numpy and device backends
RTOL = 5e-4
ATOL = 1e-4


def _np_activate(z, kind):
    if kind == "linear":
        return z
    if kind == "tanh":
        return 1.7159 * numpy.tanh(0.6666 * z)
    if kind == "relu":
        return numpy.log1p(numpy.exp(z))
    if kind == "strict_relu":
        return numpy.maximum(z, 0.0)
    if kind == "sigmoid":
        return 1.0 / (1.0 + numpy.exp(-z))
    if kind == "softmax":
        e = numpy.exp(z - z.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)
    raise AssertionError(kind)


ACTIVATIONS = ["linear", "tanh", "relu", "strict_relu", "sigmoid", "softmax"]


@pytest.mark.parametrize("activation", ACTIVATIONS)
def test_dense_forward_matches_numpy(activation):
    rng = numpy.random.RandomState(5)
    x = rng.randn(7, 13).astype(numpy.float32)
    w = rng.randn(13, 9).astype(numpy.float32) * 0.3
    b = rng.randn(9).astype(numpy.float32) * 0.1
    got = numpy.asarray(F.dense_forward(x, w, b, activation))
    want = _np_activate(x @ w + b, activation)
    numpy.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)


def test_dense_forward_flattens_nd_input():
    rng = numpy.random.RandomState(0)
    x = rng.randn(4, 2, 3, 5).astype(numpy.float32)
    w = rng.randn(30, 6).astype(numpy.float32)
    got = numpy.asarray(F.dense_forward(x, w, None, "linear"))
    want = x.reshape(4, 30) @ w
    numpy.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)


@pytest.mark.parametrize("activation",
                         ["linear", "tanh", "relu", "strict_relu", "sigmoid"])
def test_dense_backward_matches_finite_differences(activation):
    """Gradient check: the backward pass vs numeric dL/dW, dL/db, dL/dx for
    L = sum(y * r) with fixed random r (covers arbitrary err_output)."""
    rng = numpy.random.RandomState(7)
    x = rng.randn(5, 8).astype(numpy.float64)
    w = rng.randn(8, 6).astype(numpy.float64) * 0.4
    b = rng.randn(6).astype(numpy.float64) * 0.1
    r = rng.randn(5, 6).astype(numpy.float64)

    def loss(x_, w_, b_):
        return float((_np_activate(x_ @ w_ + b_, activation) * r).sum())

    y = _np_activate(x @ w + b, activation)
    err_input, grad_w, grad_b = F.dense_backward(x, y, r, w, activation)
    eps = 1e-6

    def numgrad(arr, f):
        g = numpy.zeros_like(arr)
        flat = arr.reshape(-1)
        gf = g.reshape(-1)
        for i in range(flat.size):
            old = flat[i]
            flat[i] = old + eps
            up = f()
            flat[i] = old - eps
            down = f()
            flat[i] = old
            gf[i] = (up - down) / (2 * eps)
        return g

    gw = numgrad(w, lambda: loss(x, w, b))
    gb = numgrad(b, lambda: loss(x, w, b))
    gx = numgrad(x, lambda: loss(x, w, b))
    numpy.testing.assert_allclose(numpy.asarray(grad_w), gw, rtol=1e-3,
                                  atol=1e-4)
    numpy.testing.assert_allclose(numpy.asarray(grad_b), gb, rtol=1e-3,
                                  atol=1e-4)
    numpy.testing.assert_allclose(numpy.asarray(err_input), gx, rtol=1e-3,
                                  atol=1e-4)


def test_softmax_loss_oracle():
    rng = numpy.random.RandomState(3)
    logits = rng.randn(6, 4).astype(numpy.float32)
    probs = _np_activate(logits, "softmax").astype(numpy.float32)
    labels = numpy.array([0, 1, 2, 3, 1, 2], numpy.int32)
    mask = numpy.array([1, 1, 1, 1, 0, 0], numpy.float32)  # 2 padded rows
    err, metrics = F.softmax_loss(probs, labels, mask)
    onehot = numpy.eye(4, dtype=numpy.float32)[labels]
    numpy.testing.assert_allclose(
        numpy.asarray(err), (probs - onehot) * mask[:, None],
        rtol=RTOL, atol=ATOL)
    pred = probs.argmax(-1)
    want_nerr = int(((pred != labels) & (mask > 0)).sum())
    assert int(metrics["n_err"]) == want_nerr
    want_loss = float((-numpy.log(probs[numpy.arange(6), labels]) * mask).sum())
    assert abs(float(metrics["loss_sum"]) - want_loss) < 1e-4
    conf = numpy.asarray(metrics["confusion"])
    assert conf.sum() == int(mask.sum())
    for i in range(4):
        assert conf[labels[i], pred[i]] >= 1


def test_mse_loss_oracle():
    rng = numpy.random.RandomState(4)
    out = rng.randn(5, 7).astype(numpy.float32)
    tgt = rng.randn(5, 7).astype(numpy.float32)
    mask = numpy.array([1, 1, 1, 0, 0], numpy.float32)
    err, metrics = F.mse_loss(out, tgt, mask)
    want_err = (out - tgt) * mask[:, None]
    numpy.testing.assert_allclose(numpy.asarray(err), want_err,
                                  rtol=RTOL, atol=ATOL)
    per = numpy.sqrt((want_err ** 2).sum(axis=1))
    assert abs(float(metrics["mse_sum"]) - float((per ** 2).sum())) < 1e-4
    assert abs(float(metrics["rmse_max"]) - float(per.max())) < 1e-5


def test_sgd_update_momentum_decay_clip():
    p = numpy.ones(4, numpy.float32)
    v = numpy.zeros(4, numpy.float32)
    g = numpy.array([10.0, -10.0, 0.5, 0.0], numpy.float32)  # batch sum
    new_p, new_v = F.sgd_update(p, v, g, batch_size=2, learning_rate=0.1,
                                momentum=0.0, weight_decay=0.0, l1_vs_l2=0.0,
                                gradient_clip=1.0)
    # g/2 then clipped to ±1
    numpy.testing.assert_allclose(
        numpy.asarray(new_p), [1 - 0.1, 1 + 0.1, 1 - 0.025, 1.0], rtol=1e-6)
    # momentum accumulates
    p2, v2 = F.sgd_update(numpy.asarray(new_p), numpy.asarray(new_v), g * 0,
                          2, 0.1, 0.9, 0.0, 0.0, None)
    numpy.testing.assert_allclose(numpy.asarray(p2 - new_p),
                                  0.9 * numpy.asarray(new_v), rtol=1e-6)
    # pure L2 decay pulls toward zero
    p3, _ = F.sgd_update(p, v, g * 0, 1, 0.1, 0.0, 0.5, 0.0, None)
    assert (numpy.asarray(p3) < p).all()
    # pure L1 decay subtracts sign
    p4, _ = F.sgd_update(p, v, g * 0, 1, 0.1, 0.0, 0.5, 1.0, None)
    numpy.testing.assert_allclose(numpy.asarray(p4), p - 0.1 * 0.5, rtol=1e-6)


def test_activation_derivatives_match_numeric():
    z = numpy.linspace(-2, 2, 41)
    eps = 1e-6
    for kind in ["tanh", "relu", "strict_relu", "sigmoid"]:
        y = _np_activate(z, kind)
        want = (_np_activate(z + eps, kind) - _np_activate(z - eps, kind)) / (2 * eps)
        got = numpy.asarray(F.activation_derivative_from_output(
            y.astype(numpy.float32), kind))
        # skip the kink at 0 for strict relu
        keep = numpy.abs(z) > 1e-3 if kind == "strict_relu" else slice(None)
        numpy.testing.assert_allclose(got[keep], want[keep], rtol=1e-3,
                                      atol=1e-4)
