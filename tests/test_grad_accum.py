"""Gradient accumulation (FusedRunner.grad_accum): microbatched grads
must reproduce the monolithic step exactly on deterministic nets, and a
full training run must converge identically."""

import numpy
import pytest

import jax.numpy as jnp

from veles_tpu import prng
from veles_tpu.config import root


def _configure(mb=64, n_train=256, n_valid=64, max_epochs=2):
    root.mnist.update({
        "loader": {"minibatch_size": mb, "n_train": n_train,
                   "n_valid": n_valid},
        "decision": {"max_epochs": max_epochs, "fail_iterations": 10},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.05, "momentum": 0.9},
        ],
    })


def test_accum_step_matches_monolithic():
    from veles_tpu.samples import mnist
    rng = numpy.random.RandomState(3)
    x = rng.randn(64, 784).astype(numpy.float32)
    labels = rng.randint(0, 10, 64).astype(numpy.int32)
    mask = numpy.ones(64, numpy.float32)

    states, metrics = [], []
    for accum in (1, 4):
        prng.reset(); prng.seed_all(7)
        _configure()
        wf = mnist.build(fused=True, grad_accum=accum)
        wf.initialize()
        runner = wf._fused_runner
        assert runner.grad_accum == accum
        new_state, m = runner._train(
            runner.state, x, labels, mask, jnp.asarray(64, jnp.int32),
            None, jnp.asarray(0, jnp.int32))
        states.append(new_state)
        metrics.append(m)

    assert int(metrics[0]["n_err"]) == int(metrics[1]["n_err"])
    numpy.testing.assert_allclose(float(metrics[0]["loss_sum"]),
                                  float(metrics[1]["loss_sum"]), rtol=1e-5)
    for ea, eb in zip(states[0], states[1]):
        for key in ea:
            numpy.testing.assert_allclose(
                numpy.asarray(ea[key]), numpy.asarray(eb[key]),
                rtol=1e-5, atol=1e-6)


def test_training_run_identical_with_accum():
    """Whole Decision-driven runs: grad_accum=2 ≡ grad_accum=1."""
    from veles_tpu.samples import mnist
    finals = []
    for accum in (1, 2):
        prng.reset(); prng.seed_all(7)
        _configure()
        wf = mnist.train(fused=True, grad_accum=accum)
        finals.append(wf.decision.epoch_metrics[-1]["validation"])
    assert finals[0]["n_err"] == finals[1]["n_err"]
    assert finals[0]["loss"] == pytest.approx(finals[1]["loss"], rel=1e-5)


def test_indivisible_minibatch_raises():
    from veles_tpu.samples import mnist
    prng.reset(); prng.seed_all(7)
    _configure(mb=50)
    wf = mnist.build(fused=True, grad_accum=4)   # 50 % 4 != 0
    wf.initialize()
    runner = wf._fused_runner
    x = numpy.zeros((50, 784), numpy.float32)
    with pytest.raises(ValueError):
        runner._train(runner.state, x,
                      numpy.zeros(50, numpy.int32),
                      numpy.ones(50, numpy.float32),
                      jnp.asarray(50, jnp.int32), None,
                      jnp.asarray(0, jnp.int32))


def test_mse_max_metric_not_summed():
    """rmse_max must combine with maximum across microbatches, not sum."""
    from veles_tpu.samples import mnist_ae
    outs = []
    for accum in (1, 4):
        prng.reset(); prng.seed_all(5)
        root.__dict__.pop("mnist_ae", None)
        mnist_ae.default_config()
        root.mnist_ae.update({
            "loader": {"minibatch_size": 40, "n_train": 80, "n_valid": 40},
            "decision": {"max_epochs": 1, "fail_iterations": 10},
        })
        wf = mnist_ae.build(fused=True, grad_accum=accum)
        wf.initialize()
        runner = wf._fused_runner
        x = numpy.asarray(wf.loader.original_data.mem[:40])
        mask = numpy.ones(40, numpy.float32)
        _, m = runner._train(runner.state, x, x, mask,
                             jnp.asarray(40, jnp.int32), None,
                             jnp.asarray(0, jnp.int32))
        outs.append({k: float(numpy.asarray(v)) for k, v in m.items()
                     if numpy.asarray(v).ndim == 0})
    assert outs[0]["rmse_max"] == pytest.approx(outs[1]["rmse_max"],
                                                rel=1e-5)
    assert outs[0]["mse_sum"] == pytest.approx(outs[1]["mse_sum"],
                                               rel=1e-5)


def test_epoch_scan_honors_grad_accum():
    """The one-dispatch-per-epoch path must run the accumulating step
    too (never silently drop the setting)."""
    from veles_tpu.samples import mnist
    states = []
    for accum in (1, 2):
        prng.reset(); prng.seed_all(7)
        _configure()
        wf = mnist.build(fused=True, grad_accum=accum)
        wf.initialize()
        runner = wf._fused_runner
        loader = wf.loader
        from bench import epoch_plan_arrays
        idx, mask = epoch_plan_arrays(loader)
        train_epoch, _ = runner.epoch_fns()
        state, _ = train_epoch(runner.state,
                               loader.original_data.devmem,
                               loader.original_labels.devmem, idx, mask)
        states.append(state)
    for ea, eb in zip(*states):
        for key in ea:
            numpy.testing.assert_allclose(
                numpy.asarray(ea[key]), numpy.asarray(eb[key]),
                rtol=1e-5, atol=1e-6)


def test_sharded_trainer_honors_grad_accum():
    """The SPMD per-minibatch path must run the accumulating step too."""
    from veles_tpu.samples import mnist
    from veles_tpu.parallel import make_mesh, ShardedTrainer
    rng = numpy.random.RandomState(3)
    x = rng.randn(64, 784).astype(numpy.float32)
    labels = rng.randint(0, 10, 64).astype(numpy.int32)
    mask = numpy.ones(64, numpy.float32)
    states = []
    for accum in (1, 4):
        prng.reset(); prng.seed_all(7)
        _configure()
        wf = mnist.build(fused=True, grad_accum=accum)
        wf.initialize()
        trainer = ShardedTrainer(wf._fused_runner, make_mesh(8))
        trainer.train_step(x, labels, mask, 64)
        states.append(trainer.state)
    for ea, eb in zip(*states):
        for key in ea:
            numpy.testing.assert_allclose(
                numpy.asarray(ea[key]), numpy.asarray(eb[key]),
                rtol=1e-5, atol=1e-6)


def test_grad_accum_reachable_from_config_and_cli():
    """root.<name>.grad_accum flows through the sample scaffolding and
    the CLI leaf-override syntax."""
    import os
    import subprocess
    import sys
    from veles_tpu.samples import mnist
    prng.reset(); prng.seed_all(7)
    _configure()
    root.mnist.grad_accum = 4
    try:
        wf = mnist.build(fused=True)
        wf.initialize()
        assert wf._fused_runner.grad_accum == 4
    finally:
        root.mnist.__dict__.pop("grad_accum", None)

    env = dict(os.environ)
    env["XLA_FLAGS"] = ""
    proc = subprocess.run(
        [sys.executable, "-m", "veles_tpu", "veles_tpu.samples.mnist",
         "-d", "cpu", "--random-seed", "7", "--no-stats",
         "root.mnist.grad_accum=2",
         "root.mnist.loader.n_train=128", "root.mnist.loader.n_valid=64",
         "root.mnist.loader.minibatch_size=64",
         "root.mnist.decision.max_epochs=1"],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
