"""Logger mixin + structured event sinks.

Ref: veles/logger.py::Logger [H] (SURVEY §2.1) — per-class channels and the
optional MongoDB event sink (gated here on pymongo; the JSON-lines file sink
is the dependency-free equivalent writing the same event schema).
"""

import json
import logging
import sys
import time
import types

import pytest

from veles_tpu import logger as vlog


@pytest.fixture()
def fresh_logging():
    """Snapshot and restore the veles logger namespace around each test."""
    base = logging.getLogger(vlog.NAMESPACE)
    saved = (list(base.handlers), base.level, base.propagate,
             vlog._configured, list(vlog._installed))
    yield base
    for h in base.handlers:
        if h not in saved[0]:
            h.close()
    base.handlers, base.level, base.propagate = saved[0], saved[1], saved[2]
    vlog._configured = saved[3]
    vlog._installed = saved[4]


class TestLoggerMixin:
    def test_channel_name_includes_instance_name(self, fresh_logging):
        class Thing(vlog.Logger):
            name = "alpha"

        t = Thing()
        assert t.logger.name == "veles.Thing.alpha"

    def test_convenience_methods_emit(self, fresh_logging, capsys):
        vlog.setup_logging(level=logging.DEBUG)

        class Thing(vlog.Logger):
            pass

        t = Thing()
        t.info("hello %d", 7)
        assert "hello 7" in capsys.readouterr().err


class TestJsonLinesSink:
    def test_events_written_as_json(self, fresh_logging, tmp_path):
        path = tmp_path / "events.jsonl"
        vlog.setup_logging(events_file=str(path))
        logging.getLogger("veles.test").warning("disk %s full", "A")
        lines = path.read_text().strip().splitlines()
        event = json.loads(lines[-1])
        assert event["level"] == "WARNING"
        assert event["msg"] == "disk A full"
        assert event["logger"] == "veles.test"
        assert "t" in event


class TestMongoSink:
    def test_clear_error_without_pymongo(self, fresh_logging, monkeypatch):
        monkeypatch.setitem(sys.modules, "pymongo", None)
        with pytest.raises(RuntimeError, match="pymongo"):
            vlog.MongoHandler("mongodb://localhost:27017")

    def test_events_inserted_with_stub_client(self, fresh_logging,
                                              monkeypatch):
        inserted = []

        class FakeColl:
            def insert_one(self, doc):
                inserted.append(doc)

        class FakeDB(dict):
            def __getitem__(self, name):
                return FakeColl()

        class FakeAdmin:
            def command(self, name):
                assert name == "ping"

        class FakeClient:
            def __init__(self, address, **kwargs):
                self.address = address
                assert kwargs.get("serverSelectionTimeoutMS", 0) <= 5000, \
                    "unreachable servers must fail fast, not 30s per record"
                self.admin = FakeAdmin()

            def __getitem__(self, name):
                return FakeDB()

            def close(self):
                pass

        fake = types.ModuleType("pymongo")
        fake.MongoClient = FakeClient
        monkeypatch.setitem(sys.modules, "pymongo", fake)
        vlog.setup_logging(events_mongo="mongodb://example:27017")
        logging.getLogger("veles.test").error("boom")
        deadline = time.time() + 2  # inserts drain on a background thread
        while not inserted and time.time() < deadline:
            time.sleep(0.01)
        assert inserted and inserted[-1]["msg"] == "boom"
        assert inserted[-1]["level"] == "ERROR"

    def test_file_and_mongo_share_event_schema(self):
        record = logging.LogRecord("veles.x", logging.INFO, __file__, 1,
                                   "m", (), None)
        event = vlog._event_dict(record)
        assert set(event) == {"t", "level", "logger", "msg"}


class TestReconfiguration:
    def test_host_app_handlers_survive_setup(self, fresh_logging, tmp_path):
        host = logging.FileHandler(str(tmp_path / "host.log"))
        fresh_logging.addHandler(host)
        try:
            vlog.setup_logging()
            assert host in fresh_logging.handlers
            assert not host.stream.closed
        finally:
            fresh_logging.removeHandler(host)
            host.close()

    def test_reconfiguration_closes_our_previous_sinks(self, fresh_logging,
                                                       tmp_path):
        vlog.setup_logging(events_file=str(tmp_path / "a.jsonl"))
        first = [h for h in vlog._installed
                 if isinstance(h, vlog.JsonLinesHandler)][0]
        vlog.setup_logging(events_file=str(tmp_path / "b.jsonl"))
        assert first._file.closed
        assert first not in fresh_logging.handlers


class TestCliFlags:
    def test_events_flags_parse(self):
        from veles_tpu.__main__ import build_argparser
        args = build_argparser().parse_args(
            ["wf.py", "--events-file", "e.jsonl",
             "--events-mongo", "mongodb://h:1"])
        assert args.events_file == "e.jsonl"
        assert args.events_mongo == "mongodb://h:1"
