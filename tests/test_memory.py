"""Tier-1 Vector coherence tests (ref behavior: veles/memory.py map/unmap)."""

import numpy
import pickle

from veles_tpu.memory import Vector, roundup


def test_roundup():
    assert roundup(10, 8) == 16
    assert roundup(16, 8) == 16
    assert roundup(1, 128) == 128


def test_host_roundtrip_and_shape():
    v = Vector(numpy.arange(6, dtype=numpy.float32).reshape(2, 3))
    assert v.shape == (2, 3)
    assert v.size == 6
    assert len(v) == 2
    numpy.testing.assert_array_equal(v.mem, [[0, 1, 2], [3, 4, 5]])


def test_device_upload_and_download():
    v = Vector(numpy.ones((4, 4), dtype=numpy.float32))
    dev = v.devmem
    assert tuple(dev.shape) == (4, 4)
    host = v.map_read()
    numpy.testing.assert_array_equal(host, numpy.ones((4, 4)))


def test_host_write_then_device_sees_it():
    v = Vector(numpy.zeros(4, dtype=numpy.float32))
    _ = v.devmem                       # uploaded
    v.map_write()[0] = 7               # host write invalidates device copy
    assert float(v.devmem[0]) == 7.0   # re-upload happens


def test_assign_device_makes_device_canonical():
    import jax.numpy as jnp
    v = Vector(numpy.zeros(3, dtype=numpy.float32))
    v.assign_device(jnp.asarray([1.0, 2.0, 3.0]))
    numpy.testing.assert_allclose(v.mem, [1, 2, 3])


def test_setitem_getitem():
    v = Vector(shape=(3,), dtype=numpy.float32)
    v[1] = 5
    assert v[1] == 5.0


def test_empty_and_reset():
    v = Vector()
    assert v.is_empty and not v
    v.reset(numpy.zeros(2))
    assert not v.is_empty and v


def test_pickle_roundtrip_via_numpy():
    import jax.numpy as jnp
    v = Vector()
    v.assign_device(jnp.arange(5, dtype=jnp.float32))
    blob = pickle.dumps(v)
    v2 = pickle.loads(blob)
    numpy.testing.assert_allclose(v2.mem, numpy.arange(5))
