"""Paged KV-cache allocator lifecycle (ISSUE 6).

``serving/kv_pool.py::KVPagePool`` is pure host-side bookkeeping, so
most of this file is device-free unit coverage of its invariants:
all-or-nothing allocation, ref-counted sharing, pins refusing release,
and the scratch page never entering circulation.  The engine-level legs
pin the three lifecycle behaviors serving correctness leans on —
ref-count release when a lane finishes (shared pages survive in the
trie, owned pages return to the free list), copy-on-write leaving the
shared page bit-identical for its other referents, and pool exhaustion
resolving as 429 (PoolExhausted) or 503 (deadline shed) — never a
hang.
"""

import time

import numpy
import pytest

from veles_tpu.serving.kv_pool import KVPagePool


def _params(max_len=96, vocab=16, n_heads=2, n_layers=2, d_model=32):
    import jax
    import jax.numpy as jnp
    from veles_tpu import prng
    from veles_tpu.ops.transformer import init_transformer_params
    host = init_transformer_params(prng.get("init"), vocab,
                                   d_model=d_model, n_heads=n_heads,
                                   n_layers=n_layers, max_len=max_len)
    return jax.tree.map(jnp.asarray, host)


class TestPoolUnit:
    def test_alloc_all_or_nothing(self):
        pool = KVPagePool(4, 8)
        assert pool.alloc(0) == []
        got = pool.alloc(3)
        assert len(got) == 3 and len(set(got)) == 3
        assert pool.free_pages == 1
        # 2 > 1 free: refused WITHOUT touching the pool
        assert pool.alloc(2) is None
        assert pool.free_pages == 1
        assert pool.alloc(1) is not None
        assert pool.free_pages == 0

    def test_scratch_page_never_allocated(self):
        pool = KVPagePool(3, 8)
        pages = pool.alloc(3)
        assert KVPagePool.SCRATCH not in pages
        assert pool.alloc(1) is None     # nothing left — 0 stayed out

    def test_refcount_share_and_release(self):
        pool = KVPagePool(2, 8)
        (p,) = pool.alloc(1)
        assert not pool.shared(p)
        pool.retain(p)                   # second referent (trie / lane)
        assert pool.shared(p) and pool.refs(p) == 2
        assert pool.release(p) is False  # survivor keeps it
        assert pool.free_pages == 1
        assert pool.release(p) is True   # last referent frees it
        assert pool.free_pages == 2

    def test_release_unallocated_raises(self):
        pool = KVPagePool(2, 8)
        with pytest.raises(RuntimeError, match="unallocated"):
            pool.release(1)              # never allocated
        (p,) = pool.alloc(1)
        pool.release(p)
        with pytest.raises(RuntimeError, match="unallocated"):
            pool.release(p)              # double free
        with pytest.raises(RuntimeError, match="unallocated"):
            pool.retain(KVPagePool.SCRATCH)

    def test_pinned_page_refuses_free(self):
        """A lane's pin turns freeing the page it still reads into a
        loud error (and leaves the reference intact) instead of a
        silent use-after-free recycle."""
        pool = KVPagePool(2, 8)
        (p,) = pool.alloc(1)
        pool.pin(p)
        with pytest.raises(RuntimeError, match="pinned"):
            pool.release(p)
        assert pool.refs(p) == 1         # reference restored
        assert pool.free_pages == 1      # not recycled
        pool.unpin(p)
        assert pool.release(p) is True
        with pytest.raises(RuntimeError, match="unpinned"):
            pool.unpin(p)

    def test_pin_unallocated_raises(self):
        pool = KVPagePool(2, 8)
        with pytest.raises(RuntimeError, match="pin of unallocated"):
            pool.pin(1)

    def test_constructor_validation(self):
        with pytest.raises(ValueError):
            KVPagePool(0, 8)
        with pytest.raises(ValueError):
            KVPagePool(4, 0)
        with pytest.raises(ValueError):
            KVPagePool(4, 8).alloc(-1)


class TestTrieEvictionReleasesPages:
    def test_on_evict_returns_pages_pinned_entries_refuse(self):
        """The paged engine wires ``RadixPrefixCache(on_evict=
        pool.release)``: evicting an unpinned entry returns its page to
        the pool, while entries a lane still pins (trie refs > 0) are
        refused — the reclamation path can never steal pages out from
        under an active lane."""
        from veles_tpu.serving import RadixPrefixCache
        pool = KVPagePool(4, 4)
        trie = RadixPrefixCache(capacity=8, chunk=4,
                                on_evict=pool.release)
        (pa,) = pool.alloc(1)
        (pb,) = pool.alloc(1)
        na = trie.insert(trie.root, (1,) * 4, pa)    # pinned by insert
        nb = trie.insert(na, (2,) * 4, pb)
        trie.release([nb])                           # b evictable
        assert pool.free_pages == 2
        assert trie.evict_one() is True              # drops b → pool
        assert pool.free_pages == 3
        assert trie.evict_one() is False             # a still pinned
        assert pool.free_pages == 3
        trie.release([na])
        assert trie.evict_one() is True
        assert pool.free_pages == 4


class TestEngineLifecycle:
    def test_refcount_release_on_lane_finish(self):
        """Two shared-prefix requests through a paged engine: while the
        trie holds the shared chunks their pages stay allocated (refs
        from the trie), every lane-owned page returns to the free list
        at finish, and evicting the trie drains the pool back to
        FULL — no page leaks across the request lifecycle."""
        from veles_tpu.serving import LMEngine
        params = _params()
        rng = numpy.random.RandomState(7)
        shared = rng.randint(0, 16, 16).tolist()     # 2 full chunks
        prompts = [shared + rng.randint(0, 16, 3).tolist()
                   for _ in range(2)]
        engine = LMEngine(params, n_heads=2, max_len=96, slots=2,
                          paged_kv=True, prefill_chunk=8,
                          prefix_cache=16, name="kv_life").start()
        try:
            for p in prompts:
                engine.submit(p, 4).result(timeout=60)
            pool, trie = engine._pool, engine._trie
            # only the trie's references remain
            assert pool.used_pages == trie.size == 2
            assert pool.pinned_pages == 0            # no active lane
            while trie.evict_one():
                pass
            assert pool.free_pages == pool.num_pages
        finally:
            engine.stop()

    def test_hopeless_reservation_keeps_cache_warm(self):
        """Pool-pressure eviction is bounded by what it can actually
        reclaim: a reservation that even a FULL trie flush could not
        cover evicts nothing (the cache stays warm for the lanes that
        will run), while a reachable one evicts just enough."""
        from veles_tpu.serving import LMEngine
        params = _params()
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          paged_kv=4, prefill_chunk=8, prefix_cache=8,
                          name="kv_warm")
        pool, trie = engine._pool, engine._trie
        (pa,) = pool.alloc(1)
        node = trie.insert(trie.root, (1,) * 8, pa)
        trie.release([node])             # evictable, page refs=1
        assert trie.evictable() == 1
        # free 3 + evictable 1 < 5: hopeless — entry must survive
        assert engine._alloc_pages(5) is None
        assert trie.size == 1
        # free 3 + evictable 1 >= 4: evicts exactly what it needs
        got = engine._alloc_pages(4)
        assert got is not None and len(got) == 4
        assert trie.size == 0

    def test_cow_leaves_shared_page_bit_identical(self):
        """COPY-ON-WRITE: a lane about to append into a page another
        referent shares gets a private copy; the original page's rows
        stay bit-identical for the other referent, the copy starts
        bit-identical too, and the ref/pin bookkeeping moves the lane
        (not the sibling) onto the fresh page."""
        import jax.numpy as jnp
        from veles_tpu.serving import LMEngine
        from veles_tpu.serving.lm_engine import _Request, _Slot
        params = _params()
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          paged_kv=4, prefill_chunk=8, name="kv_cow")
        pool = engine._pool
        (p,) = pool.alloc(1)
        # fill page p with recognizable rows on every block
        engine._kv_pools = [
            (kp.at[p].set(float(i + 1)), vp.at[p].set(float(-i - 1)))
            for i, (kp, vp) in enumerate(engine._kv_pools)]
        before = [(numpy.asarray(kp[p]), numpy.asarray(vp[p]))
                  for kp, vp in engine._kv_pools]
        pool.retain(p)                   # the sibling's reference
        pool.pin(p)                      # this lane's pin
        lane = _Slot(_Request([1, 2, 3], 4, 30.0, pages=1))
        lane.pages = [p]
        engine._page_tables[0, 0] = p
        engine._cow_guard(0, lane, 0, 1)
        q = lane.pages[0]
        assert q != p and engine._page_tables[0, 0] == q
        for (kb, vb), (kp_, vp_) in zip(before, engine._kv_pools):
            numpy.testing.assert_array_equal(kb, numpy.asarray(kp_[p]))
            numpy.testing.assert_array_equal(vb, numpy.asarray(vp_[p]))
            numpy.testing.assert_array_equal(kb, numpy.asarray(kp_[q]))
            numpy.testing.assert_array_equal(vb, numpy.asarray(vp_[q]))
        assert pool.refs(p) == 1 and not pool.pinned(p)   # sibling's
        assert pool.refs(q) == 1 and pool.pinned(q)       # the lane's
        assert engine.metrics.counter("kv_cow_copies") == 1
        # a second write into the now-exclusive page copies nothing
        engine._cow_guard(0, lane, 1, 2)
        assert engine.metrics.counter("kv_cow_copies") == 1

    @pytest.mark.slow
    def test_sustained_pool_churn_no_leaks(self):
        """SLOW: sustained pool-stress — 32 mixed-length requests
        (some sharing a prefix, some unique) churn through a pool far
        smaller than their total demand, with trie eviction reclaiming
        pages throughout.  Every request completes exactly greedy, and
        the pool drains back to FULL once the trie is emptied — no
        page leaks under sustained pressure."""
        import jax
        import jax.numpy as jnp
        from veles_tpu.ops.transformer import generate
        from veles_tpu.serving import LMEngine
        params = _params()
        rng = numpy.random.RandomState(11)
        shared = rng.randint(0, 16, 16).tolist()
        prompts = []
        for i in range(32):
            tail = rng.randint(0, 16, rng.randint(1, 24)).tolist()
            prompts.append((shared + tail) if i % 2 else tail)
        expected = [numpy.asarray(generate(
            params, jnp.asarray([p], jnp.int32), 6, 2,
            temperature=0.0, max_len=96))[0] for p in prompts]
        from veles_tpu.serving import PoolExhausted
        engine = LMEngine(params, n_heads=2, max_len=96, slots=4,
                          paged_kv=10, prefill_chunk=8, prefix_cache=4,
                          queue_depth=64, deadline_s=120.0,
                          name="kv_churn").start()
        try:
            futures = []
            for p in prompts:
                # closed-loop client: honor the 429's Retry-After when
                # the backlog bound trips (the stress IS the point)
                for _ in range(400):
                    try:
                        futures.append(engine.submit(p, 6))
                        break
                    except PoolExhausted as e:
                        time.sleep(min(e.retry_after, 0.05))
                else:
                    raise AssertionError("submit never admitted")
            for p, f, exp in zip(prompts, futures, expected):
                got = numpy.concatenate([p, f.result(timeout=300)])
                numpy.testing.assert_array_equal(got, exp)
            pool, trie = engine._pool, engine._trie
            assert pool.pinned_pages == 0
            assert pool.used_pages == trie.size <= 4
            while trie.evict_one():
                pass
            assert pool.free_pages == pool.num_pages
        finally:
            engine.stop()

    def test_mid_prefill_faults_leak_no_pages(self):
        """ISSUE 10 satellite: injected mid-prefill dispatch failures
        (the engine.chunk site) across several shared-prefix requests
        — every faulted request fails alone, the survivors stay
        exactly greedy, and afterwards the pool returns to baseline
        with zero orphan trie pins and the allocator invariants
        intact."""
        import jax.numpy as jnp
        from veles_tpu.ops.transformer import generate
        from veles_tpu.serving import FaultPlan, InjectedFault, LMEngine
        params = _params()
        rng = numpy.random.RandomState(3)
        shared = rng.randint(0, 16, 16).tolist()     # 2 full chunks
        prompts = [shared + rng.randint(0, 16, 1 + i).tolist()
                   for i in range(6)]
        expected = [numpy.asarray(generate(
            params, jnp.asarray([p], jnp.int32), 4, 2,
            temperature=0.0, max_len=96))[0] for p in prompts]
        # every 3rd chunk dispatch faults — mid-prefill, because these
        # prompts are almost all prefill chunks
        plan = FaultPlan().arm("engine.chunk", every=3)
        engine = LMEngine(params, n_heads=2, max_len=96, slots=2,
                          paged_kv=True, prefill_chunk=8,
                          prefix_cache=16, name="kv_fault",
                          faults=plan).start()
        try:
            futures = [engine.submit(p, 4) for p in prompts]
            failed = ok = 0
            for p, f, exp in zip(prompts, futures, expected):
                try:
                    out = f.result(timeout=60)
                    numpy.testing.assert_array_equal(
                        numpy.concatenate([p, out]), exp)
                    ok += 1
                except InjectedFault:
                    failed += 1
            assert failed > 0 and ok > 0     # both paths exercised
            assert plan.fired("engine.chunk") >= failed
            # leak-freedom: no lane active, no orphan pins, and once
            # the trie is pressed empty the pool refills WHOLE
            assert engine._pool.pinned_pages == 0
            assert engine._trie.live_pins() == 0
            engine.verify_pool_invariants()
            while engine._trie.evict_one():
                pass
            assert engine._pool.free_pages == engine._pool.num_pages
        finally:
            engine.stop()

    def test_mid_cow_fault_releases_orphan_page(self):
        """ISSUE 10 satellite: a faulted copy-on-write dispatch (the
        engine.cow site fires inside the page-copy try) releases the
        just-allocated destination page instead of leaking it, and
        the shared source page's bookkeeping is untouched."""
        from veles_tpu.serving import FaultPlan, InjectedFault, LMEngine
        from veles_tpu.serving.lm_engine import _Request, _Slot
        params = _params()
        plan = FaultPlan().arm("engine.cow", times=1)
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          paged_kv=4, prefill_chunk=8, name="kv_cowf",
                          faults=plan)
        pool = engine._pool
        (p,) = pool.alloc(1)
        pool.retain(p)                   # the sibling's reference
        pool.pin(p)                      # this lane's pin
        lane = _Slot(_Request([1, 2, 3], 4, 30.0, pages=1))
        lane.pages = [p]
        engine._page_tables[0, 0] = p
        free_before = pool.free_pages
        with pytest.raises(InjectedFault):
            engine._cow_guard(0, lane, 0, 1)
        # the orphan destination went back; the shared page still has
        # both referents and the lane's pin — nothing leaked or lost
        assert pool.free_pages == free_before
        assert pool.refs(p) == 2 and pool.pinned(p)
        assert engine.metrics.counter("kv_cow_copies") == 0
        # disarmed, the same write now copies cleanly
        plan.disarm()
        engine._cow_guard(0, lane, 0, 1)
        q = lane.pages[0]
        assert q != p and pool.refs(q) == 1 and pool.pinned(q)
        assert engine.metrics.counter("kv_cow_copies") == 1

    def test_pool_exhaustion_sheds_503_never_hangs(self):
        """A request queued on pool pressure whose pages never free in
        time sheds DeadlineExceeded (503) at its deadline — it does not
        wedge the queue, and the lane holding the pool finishes
        normally."""
        from veles_tpu.serving import DeadlineExceeded, LMEngine
        params = _params()
        engine = LMEngine(params, n_heads=2, max_len=96, slots=2,
                          paged_kv=3, prefill_chunk=8, deadline_s=1.0,
                          name="kv_shed").start()
        real_step = engine._step_jit

        def slow_step(*a):
            time.sleep(0.08)
            return real_step(*a)

        engine._step_jit = slow_step
        try:
            # A takes all 3 pages and decodes ~2.6s; B (3 pages) can
            # only wait — its 1s deadline fires first
            fut_a = engine.submit(list(range(1, 9)), 16)
            fut_b = engine.submit(list(range(2, 10)), 16)
            with pytest.raises(DeadlineExceeded):
                fut_b.result(timeout=30)
            assert len(fut_a.result(timeout=60)) == 16
            assert engine.metrics.snapshot()["shed"] == 1
            assert engine._pool.free_pages == engine._pool.num_pages
        finally:
            engine._step_jit = real_step
            engine.stop()

    def test_standby_ring_faults_and_cancel_leak_no_pages(self):
        """ISSUE 19: standby-ring occupants hold pool pages exactly
        like lanes — a faulted standby prefill (the engine.chunk site
        firing on a ring entry) and a cancelled occupant both return
        their pages immediately, and after the traffic drains the
        pool refills whole with the engine's cross-check clean."""
        from veles_tpu.serving import FaultPlan, InjectedFault, LMEngine
        params = _params(max_len=128)
        # armed only after fa's admission prefill is observed, so the
        # one-shot rule deterministically lands on fb's standby-ring
        # prefill no matter how the serve loop interleaves ticks
        plan = FaultPlan()
        engine = LMEngine(params, n_heads=2, max_len=128, slots=1,
                          megastep=4, megastep_mode="while",
                          paged_kv=True, prefill_chunk=8,
                          refill_ring=2, faults=plan,
                          name="kv_ring").start()
        real = engine._whilestep_jit

        def slow(*a):
            time.sleep(0.05)
            return real(*a)

        engine._whilestep_jit = slow
        try:
            fa = engine.submit([1, 2, 3], 24)    # occupies the slot
            deadline = time.monotonic() + 30.0
            while plan.calls("engine.chunk") < 1:
                assert time.monotonic() < deadline
                time.sleep(0.01)
            plan.arm("engine.chunk", kind="error", times=1)
            fb = engine.submit([2, 4, 6], 6)     # standby prefill faults
            with pytest.raises(InjectedFault):
                fb.result(timeout=60)
            engine.verify_pool_invariants()      # fb's pages came back
            fc = engine.submit([4, 4, 4], 6)     # ring-prefilled, then
            engine._cancel(fc.request)           # withdrawn in the ring
            assert len(fa.result(timeout=120)) == 24
            deadline = time.monotonic() + 30.0
            while engine.metrics.snapshot()["gauges"].get(
                    "standby_ring_occupancy", 0):
                assert time.monotonic() < deadline
                time.sleep(0.01)
            assert fc.cancelled()
            assert engine._pool.pinned_pages == 0
            assert engine._pool.free_pages == engine._pool.num_pages
            engine.verify_pool_invariants()
        finally:
            engine._whilestep_jit = real
            engine.stop()
