"""Pallas kernels (interpret mode on CPU = same kernel code as TPU) and
stochastic pooling (SURVEY §2.4 custom-kernel candidates)."""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.ops import functional as F
from veles_tpu.ops import pallas_kernels as PK


class TestFusedSGD:
    @pytest.mark.parametrize("shape", [(7,), (64, 10), (3, 5, 5, 8)])
    def test_matches_functional(self, shape):
        r = numpy.random.RandomState(0)
        p = r.randn(*shape).astype(numpy.float32)
        v = r.randn(*shape).astype(numpy.float32) * 0.1
        g = r.randn(*shape).astype(numpy.float32)
        args = dict(batch_size=jnp.asarray(32), learning_rate=0.05,
                    momentum=0.9, weight_decay=0.001, l1_vs_l2=0.3)
        ref_p, ref_v = F.sgd_update(jnp.asarray(p), jnp.asarray(v),
                                    jnp.asarray(g), gradient_clip=None,
                                    **args)
        new_p, new_v = PK.fused_sgd_update(jnp.asarray(p), jnp.asarray(v),
                                           jnp.asarray(g), **args)
        numpy.testing.assert_allclose(numpy.asarray(new_p),
                                      numpy.asarray(ref_p), rtol=1e-6,
                                      atol=1e-6)
        numpy.testing.assert_allclose(numpy.asarray(new_v),
                                      numpy.asarray(ref_v), rtol=1e-6,
                                      atol=1e-6)

    def test_backend_flag_routes_hot_path(self):
        """set_sgd_backend('pallas') swaps the kernel into the DEFAULT
        update path (VERDICT r3 Weak #5: wire it, don't shelve it) with
        identical numerics; gradient_clip falls back to the xla path."""
        r = numpy.random.RandomState(1)
        p = jnp.asarray(r.randn(40, 30).astype(numpy.float32))
        v = jnp.zeros_like(p)
        g = jnp.asarray(r.randn(40, 30).astype(numpy.float32))
        args = (jnp.asarray(16), 0.05, 0.9, 0.001, 0.3)
        ref_p, ref_v = F.sgd_update(p, v, g, *args, gradient_clip=None)
        clip_p, clip_v = F.sgd_update(p, v, g, *args, gradient_clip=0.01)
        F.set_sgd_backend("pallas")
        try:
            new_p, new_v = F.sgd_update(p, v, g, *args, gradient_clip=None)
            fb_p, fb_v = F.sgd_update(p, v, g, *args, gradient_clip=0.01)
        finally:
            F.set_sgd_backend("xla")
        numpy.testing.assert_allclose(numpy.asarray(new_p),
                                      numpy.asarray(ref_p), rtol=1e-6,
                                      atol=1e-6)
        numpy.testing.assert_allclose(numpy.asarray(fb_p),
                                      numpy.asarray(clip_p), rtol=1e-6,
                                      atol=1e-6)
        with pytest.raises(ValueError):
            F.set_sgd_backend("nope")

    def test_traced_scalars_jit(self):
        """lr/batch_size as traced values inside jit (lr policies)."""
        r = numpy.random.RandomState(1)
        p = r.randn(100).astype(numpy.float32)

        @jax.jit
        def step(p, lr, bs):
            return PK.fused_sgd_update(p, jnp.zeros_like(p),
                                       jnp.ones_like(p), bs, lr,
                                       momentum=0.5)

        new_p, _ = step(jnp.asarray(p), jnp.asarray(0.1, jnp.float32),
                        jnp.asarray(10))
        numpy.testing.assert_allclose(numpy.asarray(new_p), p - 0.01,
                                      rtol=1e-5, atol=1e-6)


class TestPallasDropout:
    def test_deterministic_per_seed(self):
        x = jnp.ones((130,), jnp.float32)   # forces lane padding
        a = PK.dropout(x, 7, 0.5)
        b = PK.dropout(x, 7, 0.5)
        numpy.testing.assert_array_equal(numpy.asarray(a), numpy.asarray(b))
        c = PK.dropout(x, 8, 0.5)
        assert not numpy.array_equal(numpy.asarray(a), numpy.asarray(c))

    def test_statistics_and_scaling(self):
        x = jnp.ones((100, 128), jnp.float32)
        out = numpy.asarray(PK.dropout(x, 3, 0.3))
        kept = out > 0
        assert abs(kept.mean() - 0.7) < 0.02
        numpy.testing.assert_allclose(out[kept], 1.0 / 0.7, rtol=1e-5)

    def test_zero_rate_identity(self):
        x = jnp.asarray(numpy.random.RandomState(0).randn(16, 16),
                        jnp.float32)
        numpy.testing.assert_array_equal(numpy.asarray(PK.dropout(x, 1, 0.0)),
                                         numpy.asarray(x))

    @pytest.mark.skipif(not PK.on_tpu(),
                        reason="real-kernel path needs the TPU PRNG")
    @pytest.mark.parametrize("rate", [0.3, 0.5, 0.7])
    def test_real_kernel_statistics(self, rate):
        """Keep fraction of the NON-interpret kernel — the signed int32
        random bits must be compared in the signed domain (the unsigned
        misread made rate<=0.5 a silent no-op on hardware)."""
        keep_prob = 1.0 - rate
        x = jnp.ones((256, 512), jnp.float32)
        out = numpy.asarray(PK.dropout(x, 5, rate, interpret=False))
        kept = out > 0
        assert abs(kept.mean() - keep_prob) < 0.01, kept.mean()
        numpy.testing.assert_allclose(out[kept], 1.0 / keep_prob, rtol=1e-5)


class TestStochasticPooling:
    def test_train_samples_from_window(self):
        r = numpy.random.RandomState(0)
        x = r.randn(2, 4, 4, 3).astype(numpy.float32)
        out = F.stochastic_pooling(jnp.asarray(x), (2, 2), None,
                                   jax.random.PRNGKey(0), True, True)
        assert out.shape == (2, 2, 2, 3)
        # every output must equal SOME element of its window
        for b in range(2):
            for oy in range(2):
                for ox in range(2):
                    for c in range(3):
                        window = x[b, oy * 2:oy * 2 + 2,
                                   ox * 2:ox * 2 + 2, c].ravel()
                        assert numpy.isclose(window,
                                             float(out[b, oy, ox, c])).any()

    def test_eval_weighted_average(self):
        x = numpy.zeros((1, 2, 2, 1), numpy.float32)
        x[0, :, :, 0] = [[1.0, 3.0], [0.0, 0.0]]
        out = F.stochastic_pooling(jnp.asarray(x), (2, 2), None, None,
                                   train=False, use_abs=True)
        # probs = [.25, .75, 0, 0] → expected value 0.25*1 + 0.75*3 = 2.5
        numpy.testing.assert_allclose(numpy.asarray(out)[0, 0, 0, 0], 2.5,
                                      rtol=1e-5)

    def test_empty_window_uniform(self):
        x = jnp.zeros((1, 2, 2, 1), jnp.float32)
        out = F.stochastic_pooling(x, (2, 2), None, jax.random.PRNGKey(0),
                                   True, True)
        assert float(out[0, 0, 0, 0]) == 0.0

    def test_unit_in_training(self):
        """The layer type trains end-to-end in a conv net (fused mode)."""
        from veles_tpu import prng
        from veles_tpu.config import root
        prng.reset()
        prng.seed_all(1)
        root.cifar.update({
            "loader": {"minibatch_size": 25, "n_train": 100, "n_valid": 50},
            "decision": {"max_epochs": 2, "fail_iterations": 5},
            "layers": [
                {"type": "conv_relu", "n_kernels": 8, "kx": 3, "ky": 3,
                 "padding": "SAME", "learning_rate": 0.02, "momentum": 0.9},
                {"type": "stochastic_abs_pooling", "kx": 2, "ky": 2},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.02, "momentum": 0.9},
            ],
        })
        from veles_tpu.samples import cifar
        wf = cifar.train(fused=True)
        errs = [m["validation"]["n_err"] for m in wf.decision.epoch_metrics
                if "validation" in m]
        assert numpy.isfinite(errs).all()
        # 2 epochs x 50 valid samples: just require training stays sane
        assert errs[-1] <= errs[0] + 5


class TestPallasLRN:
    def _x(self, shape=(4, 7, 7, 96), seed=0, scale=1.0):
        return jax.random.normal(jax.random.PRNGKey(seed), shape,
                                 jnp.float32) * scale

    @pytest.mark.parametrize("c", [16, 96, 128, 200])
    def test_forward_matches_functional(self, c):
        """One-pass banded-matmul LRN ≡ the shifted-slice XLA form at
        every channel width (below/at/above the 128-lane tile)."""
        from veles_tpu.ops import pallas_kernels as PK
        x = self._x((3, 5, 5, c), seed=c)
        ref = F.lrn_forward(x)
        got = PK.lrn_forward(x)
        numpy.testing.assert_allclose(numpy.asarray(got),
                                      numpy.asarray(ref),
                                      rtol=2e-6, atol=2e-6)

    @pytest.mark.parametrize("n", [4, 5])
    def test_even_and_odd_window_match_xla(self, n):
        """Even n has an ASYMMETRIC window in the XLA form (pad n//2 +
        n shifted slices); the band must replicate it, values AND
        grads — not the symmetric |i-j|<=n//2 approximation."""
        from veles_tpu.ops import pallas_kernels as PK
        x = self._x((2, 3, 3, 24), seed=n)
        dy = self._x((2, 3, 3, 24), seed=n + 10)
        ref, ref_vjp = jax.vjp(lambda a: F.lrn_forward(a, n=n), x)
        got, got_vjp = jax.vjp(lambda a: PK.lrn_forward(a, n=n), x)
        numpy.testing.assert_allclose(numpy.asarray(got),
                                      numpy.asarray(ref),
                                      rtol=2e-6, atol=2e-6)
        numpy.testing.assert_allclose(numpy.asarray(got_vjp(dy)[0]),
                                      numpy.asarray(ref_vjp(dy)[0]),
                                      rtol=3e-5, atol=3e-6)

    def test_gradient_matches_functional(self):
        """The fused custom VJP ≡ jax autodiff of the XLA form."""
        from veles_tpu.ops import pallas_kernels as PK
        x = self._x((2, 4, 4, 32), seed=1, scale=2.0)
        dy = self._x((2, 4, 4, 32), seed=2)

        ref = jax.vjp(lambda a: F.lrn_forward(a, 2e-4, 0.7, 5, 1.5), x)[1](
            dy)[0]
        got = jax.vjp(lambda a: PK.lrn_forward(a, 2e-4, 0.7, 5, 1.5), x)[1](
            dy)[0]
        numpy.testing.assert_allclose(numpy.asarray(got),
                                      numpy.asarray(ref),
                                      rtol=3e-5, atol=3e-6)

    def test_backend_flag_routes(self):
        """set_lrn_backend('pallas') swaps the kernel into the DEFAULT
        lrn path (what the norm unit calls) and back."""
        x = self._x((2, 3, 3, 24), seed=3)
        ref = numpy.asarray(F.lrn_forward(x))
        F.set_lrn_backend("pallas")
        try:
            got = numpy.asarray(F.lrn_forward(x))
        finally:
            F.set_lrn_backend("xla")
        numpy.testing.assert_allclose(got, ref, rtol=2e-6, atol=2e-6)
        with pytest.raises(ValueError):
            F.set_lrn_backend("nope")

    def test_trains_under_jit(self):
        """The custom-VJP kernel composes with jit + grad at AlexNet-LRN1
        shape fragments (the path the fused step takes)."""
        from veles_tpu.ops import pallas_kernels as PK
        x = self._x((2, 6, 6, 96), seed=4)

        @jax.jit
        def loss(a):
            return (PK.lrn_forward(a) ** 2).sum()
        g = jax.grad(loss)(x)
        assert numpy.isfinite(numpy.asarray(g)).all()
