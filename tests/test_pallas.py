"""Pallas kernels (interpret mode on CPU = same kernel code as TPU) and
stochastic pooling (SURVEY §2.4 custom-kernel candidates)."""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.ops import functional as F
from veles_tpu.ops import pallas_kernels as PK


class TestFusedSGD:
    @pytest.mark.parametrize("shape", [(7,), (64, 10), (3, 5, 5, 8)])
    def test_matches_functional(self, shape):
        r = numpy.random.RandomState(0)
        p = r.randn(*shape).astype(numpy.float32)
        v = r.randn(*shape).astype(numpy.float32) * 0.1
        g = r.randn(*shape).astype(numpy.float32)
        args = dict(batch_size=jnp.asarray(32), learning_rate=0.05,
                    momentum=0.9, weight_decay=0.001, l1_vs_l2=0.3)
        ref_p, ref_v = F.sgd_update(jnp.asarray(p), jnp.asarray(v),
                                    jnp.asarray(g), gradient_clip=None,
                                    **args)
        new_p, new_v = PK.fused_sgd_update(jnp.asarray(p), jnp.asarray(v),
                                           jnp.asarray(g), **args)
        numpy.testing.assert_allclose(numpy.asarray(new_p),
                                      numpy.asarray(ref_p), rtol=1e-6,
                                      atol=1e-6)
        numpy.testing.assert_allclose(numpy.asarray(new_v),
                                      numpy.asarray(ref_v), rtol=1e-6,
                                      atol=1e-6)

    def test_backend_flag_routes_hot_path(self):
        """set_sgd_backend('pallas') swaps the kernel into the DEFAULT
        update path (VERDICT r3 Weak #5: wire it, don't shelve it) with
        identical numerics; gradient_clip falls back to the xla path."""
        r = numpy.random.RandomState(1)
        p = jnp.asarray(r.randn(40, 30).astype(numpy.float32))
        v = jnp.zeros_like(p)
        g = jnp.asarray(r.randn(40, 30).astype(numpy.float32))
        args = (jnp.asarray(16), 0.05, 0.9, 0.001, 0.3)
        ref_p, ref_v = F.sgd_update(p, v, g, *args, gradient_clip=None)
        clip_p, clip_v = F.sgd_update(p, v, g, *args, gradient_clip=0.01)
        F.set_sgd_backend("pallas")
        try:
            new_p, new_v = F.sgd_update(p, v, g, *args, gradient_clip=None)
            fb_p, fb_v = F.sgd_update(p, v, g, *args, gradient_clip=0.01)
        finally:
            F.set_sgd_backend("xla")
        numpy.testing.assert_allclose(numpy.asarray(new_p),
                                      numpy.asarray(ref_p), rtol=1e-6,
                                      atol=1e-6)
        numpy.testing.assert_allclose(numpy.asarray(fb_p),
                                      numpy.asarray(clip_p), rtol=1e-6,
                                      atol=1e-6)
        with pytest.raises(ValueError):
            F.set_sgd_backend("nope")

    def test_traced_scalars_jit(self):
        """lr/batch_size as traced values inside jit (lr policies)."""
        r = numpy.random.RandomState(1)
        p = r.randn(100).astype(numpy.float32)

        @jax.jit
        def step(p, lr, bs):
            return PK.fused_sgd_update(p, jnp.zeros_like(p),
                                       jnp.ones_like(p), bs, lr,
                                       momentum=0.5)

        new_p, _ = step(jnp.asarray(p), jnp.asarray(0.1, jnp.float32),
                        jnp.asarray(10))
        numpy.testing.assert_allclose(numpy.asarray(new_p), p - 0.01,
                                      rtol=1e-5, atol=1e-6)


class TestPallasDropout:
    def test_deterministic_per_seed(self):
        x = jnp.ones((130,), jnp.float32)   # forces lane padding
        a = PK.dropout(x, 7, 0.5)
        b = PK.dropout(x, 7, 0.5)
        numpy.testing.assert_array_equal(numpy.asarray(a), numpy.asarray(b))
        c = PK.dropout(x, 8, 0.5)
        assert not numpy.array_equal(numpy.asarray(a), numpy.asarray(c))

    def test_statistics_and_scaling(self):
        x = jnp.ones((100, 128), jnp.float32)
        out = numpy.asarray(PK.dropout(x, 3, 0.3))
        kept = out > 0
        assert abs(kept.mean() - 0.7) < 0.02
        numpy.testing.assert_allclose(out[kept], 1.0 / 0.7, rtol=1e-5)

    def test_zero_rate_identity(self):
        x = jnp.asarray(numpy.random.RandomState(0).randn(16, 16),
                        jnp.float32)
        numpy.testing.assert_array_equal(numpy.asarray(PK.dropout(x, 1, 0.0)),
                                         numpy.asarray(x))

    @pytest.mark.skipif(not PK.on_tpu(),
                        reason="real-kernel path needs the TPU PRNG")
    @pytest.mark.parametrize("rate", [0.3, 0.5, 0.7])
    def test_real_kernel_statistics(self, rate):
        """Keep fraction of the NON-interpret kernel — the signed int32
        random bits must be compared in the signed domain (the unsigned
        misread made rate<=0.5 a silent no-op on hardware)."""
        keep_prob = 1.0 - rate
        x = jnp.ones((256, 512), jnp.float32)
        out = numpy.asarray(PK.dropout(x, 5, rate, interpret=False))
        kept = out > 0
        assert abs(kept.mean() - keep_prob) < 0.01, kept.mean()
        numpy.testing.assert_allclose(out[kept], 1.0 / keep_prob, rtol=1e-5)


class TestStochasticPooling:
    def test_train_samples_from_window(self):
        r = numpy.random.RandomState(0)
        x = r.randn(2, 4, 4, 3).astype(numpy.float32)
        out = F.stochastic_pooling(jnp.asarray(x), (2, 2), None,
                                   jax.random.PRNGKey(0), True, True)
        assert out.shape == (2, 2, 2, 3)
        # every output must equal SOME element of its window
        for b in range(2):
            for oy in range(2):
                for ox in range(2):
                    for c in range(3):
                        window = x[b, oy * 2:oy * 2 + 2,
                                   ox * 2:ox * 2 + 2, c].ravel()
                        assert numpy.isclose(window,
                                             float(out[b, oy, ox, c])).any()

    def test_eval_weighted_average(self):
        x = numpy.zeros((1, 2, 2, 1), numpy.float32)
        x[0, :, :, 0] = [[1.0, 3.0], [0.0, 0.0]]
        out = F.stochastic_pooling(jnp.asarray(x), (2, 2), None, None,
                                   train=False, use_abs=True)
        # probs = [.25, .75, 0, 0] → expected value 0.25*1 + 0.75*3 = 2.5
        numpy.testing.assert_allclose(numpy.asarray(out)[0, 0, 0, 0], 2.5,
                                      rtol=1e-5)

    def test_empty_window_uniform(self):
        x = jnp.zeros((1, 2, 2, 1), jnp.float32)
        out = F.stochastic_pooling(x, (2, 2), None, jax.random.PRNGKey(0),
                                   True, True)
        assert float(out[0, 0, 0, 0]) == 0.0

    def test_unit_in_training(self):
        """The layer type trains end-to-end in a conv net (fused mode)."""
        from veles_tpu import prng
        from veles_tpu.config import root
        prng.reset()
        prng.seed_all(1)
        root.cifar.update({
            "loader": {"minibatch_size": 25, "n_train": 100, "n_valid": 50},
            "decision": {"max_epochs": 2, "fail_iterations": 5},
            "layers": [
                {"type": "conv_relu", "n_kernels": 8, "kx": 3, "ky": 3,
                 "padding": "SAME", "learning_rate": 0.02, "momentum": 0.9},
                {"type": "stochastic_abs_pooling", "kx": 2, "ky": 2},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.02, "momentum": 0.9},
            ],
        })
        from veles_tpu.samples import cifar
        wf = cifar.train(fused=True)
        errs = [m["validation"]["n_err"] for m in wf.decision.epoch_metrics
                if "validation" in m]
        assert numpy.isfinite(errs).all()
        # 2 epochs x 50 valid samples: just require training stays sane
        assert errs[-1] <= errs[0] + 5


class TestPallasLRN:
    def _x(self, shape=(4, 7, 7, 96), seed=0, scale=1.0):
        return jax.random.normal(jax.random.PRNGKey(seed), shape,
                                 jnp.float32) * scale

    @pytest.mark.parametrize("c", [16, 96, 128, 200])
    def test_forward_matches_functional(self, c):
        """One-pass banded-matmul LRN ≡ the shifted-slice XLA form at
        every channel width (below/at/above the 128-lane tile)."""
        from veles_tpu.ops import pallas_kernels as PK
        x = self._x((3, 5, 5, c), seed=c)
        ref = F.lrn_forward(x)
        got = PK.lrn_forward(x)
        numpy.testing.assert_allclose(numpy.asarray(got),
                                      numpy.asarray(ref),
                                      rtol=2e-6, atol=2e-6)

    @pytest.mark.parametrize("n", [4, 5])
    def test_even_and_odd_window_match_xla(self, n):
        """Even n has an ASYMMETRIC window in the XLA form (pad n//2 +
        n shifted slices); the band must replicate it, values AND
        grads — not the symmetric |i-j|<=n//2 approximation."""
        from veles_tpu.ops import pallas_kernels as PK
        x = self._x((2, 3, 3, 24), seed=n)
        dy = self._x((2, 3, 3, 24), seed=n + 10)
        ref, ref_vjp = jax.vjp(lambda a: F.lrn_forward(a, n=n), x)
        got, got_vjp = jax.vjp(lambda a: PK.lrn_forward(a, n=n), x)
        numpy.testing.assert_allclose(numpy.asarray(got),
                                      numpy.asarray(ref),
                                      rtol=2e-6, atol=2e-6)
        numpy.testing.assert_allclose(numpy.asarray(got_vjp(dy)[0]),
                                      numpy.asarray(ref_vjp(dy)[0]),
                                      rtol=3e-5, atol=3e-6)

    def test_gradient_matches_functional(self):
        """The fused custom VJP ≡ jax autodiff of the XLA form."""
        from veles_tpu.ops import pallas_kernels as PK
        x = self._x((2, 4, 4, 32), seed=1, scale=2.0)
        dy = self._x((2, 4, 4, 32), seed=2)

        ref = jax.vjp(lambda a: F.lrn_forward(a, 2e-4, 0.7, 5, 1.5), x)[1](
            dy)[0]
        got = jax.vjp(lambda a: PK.lrn_forward(a, 2e-4, 0.7, 5, 1.5), x)[1](
            dy)[0]
        numpy.testing.assert_allclose(numpy.asarray(got),
                                      numpy.asarray(ref),
                                      rtol=3e-5, atol=3e-6)

    def test_backend_flag_routes(self):
        """set_lrn_backend('pallas') swaps the kernel into the DEFAULT
        lrn path (what the norm unit calls) and back."""
        x = self._x((2, 3, 3, 24), seed=3)
        ref = numpy.asarray(F.lrn_forward(x))
        F.set_lrn_backend("pallas")
        try:
            got = numpy.asarray(F.lrn_forward(x))
        finally:
            F.set_lrn_backend("xla")
        numpy.testing.assert_allclose(got, ref, rtol=2e-6, atol=2e-6)
        with pytest.raises(ValueError):
            F.set_lrn_backend("nope")

    def test_trains_under_jit(self):
        """The custom-VJP kernel composes with jit + grad at AlexNet-LRN1
        shape fragments (the path the fused step takes)."""
        from veles_tpu.ops import pallas_kernels as PK
        x = self._x((2, 6, 6, 96), seed=4)

        @jax.jit
        def loss(a):
            return (PK.lrn_forward(a) ** 2).sum()
        g = jax.grad(loss)(x)
        assert numpy.isfinite(numpy.asarray(g)).all()


@pytest.mark.kernel_parity
class TestPagedFlashDecode:
    """ISSUE 7: the flash-decode serving kernel (interpret mode = the
    SAME kernel code the TPU compiles) against the XLA paged path —
    ``paged_view`` gather + dense masked softmax — which the serving
    parity matrix has already pinned bit-identical to ``generate``."""

    def _setup(self, b=2, h=4, kv=2, c=1, dh=16, page=8, m=4,
               n_pages=9, seed=0):
        rng = numpy.random.RandomState(seed)
        q = jnp.asarray(rng.randn(b, h, c, dh), jnp.float32)
        kp = jnp.asarray(rng.randn(n_pages, kv, page, dh), jnp.float32)
        vp = jnp.asarray(rng.randn(n_pages, kv, page, dh), jnp.float32)
        ptab = jnp.asarray(rng.choice(
            n_pages, size=(b, m), replace=False).reshape(b, m),
            jnp.int32)
        pos = jnp.asarray(rng.randint(0, m * page - c + 1, b),
                          jnp.int32)
        return q, kp, vp, ptab, pos

    def _xla(self, q, kp, vp, ptab, pos, c, window=None, sinks=0):
        from veles_tpu.ops import attention as A
        h, kv = q.shape[1], kp.shape[1]
        kx, vx = A.paged_view(kp, ptab), A.paged_view(vp, ptab)
        kr = A._repeat_kv(kx, h)
        vr = A._repeat_kv(vx, h)
        s = jnp.einsum("bhcd,bhld->bhcl", q, kr) / jnp.sqrt(
            jnp.float32(q.shape[-1]))
        live = jax.vmap(lambda p: A.chunk_live_mask(
            p, c, kx.shape[-2], window, sinks))(pos)
        s = jnp.where(live[:, None], s, A.NEG_INF)
        return jnp.einsum("bhcl,bhld->bhcd",
                          jax.nn.softmax(s, axis=-1), vr)

    @pytest.mark.parametrize("c,window,sinks", [
        (1, None, 0),          # decode step
        (4, None, 0),          # speculative verify (k+1)
        (1, 10, 0),            # sliding window
        (4, 10, 2),            # window + sinks, multi-query
        (1, 10, 1),            # single query at the sink edge
    ])
    def test_matches_xla_paged_path(self, c, window, sinks):
        from veles_tpu.ops import pallas_kernels as PK
        q, kp, vp, ptab, pos = self._setup(c=c, m=6, n_pages=13,
                                           seed=c + (window or 0))
        got = PK.paged_flash_decode(q, kp, vp, ptab, pos,
                                    window=window, sinks=sinks)
        ref = self._xla(q, kp, vp, ptab, pos, c, window, sinks)
        numpy.testing.assert_allclose(numpy.asarray(got),
                                      numpy.asarray(ref),
                                      rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("h,kv", [(4, 1), (4, 4), (8, 2)])
    def test_grouped_query_layouts(self, h, kv):
        """GQA folds into the kernel as a (kv, g·c) row reshape — every
        grouping must agree with jnp.repeat's head mapping."""
        from veles_tpu.ops import pallas_kernels as PK
        q, kp, vp, ptab, pos = self._setup(h=h, kv=kv, c=3, seed=h * kv)
        got = PK.paged_flash_decode(q, kp, vp, ptab, pos)
        ref = self._xla(q, kp, vp, ptab, pos, 3)
        numpy.testing.assert_allclose(numpy.asarray(got),
                                      numpy.asarray(ref),
                                      rtol=1e-5, atol=1e-6)

    def test_early_position_masks_garbage_pages(self):
        """A lane at pos=0 attends ONE row; the other pages hold
        garbage the NEG_INF band + online rescale must zero exactly
        (the blockwise_attention transient-term argument, in-kernel)."""
        from veles_tpu.ops import pallas_kernels as PK
        q, kp, vp, ptab, _ = self._setup(c=1, seed=5)
        pos = jnp.zeros(q.shape[0], jnp.int32)
        got = PK.paged_flash_decode(q, kp, vp, ptab, pos)
        ref = self._xla(q, kp, vp, ptab, pos, 1)
        numpy.testing.assert_allclose(numpy.asarray(got),
                                      numpy.asarray(ref),
                                      rtol=1e-5, atol=1e-6)

    def test_mha_paged_chunk_step_kernel_route(self):
        """attention.mha_paged_chunk_step(attn_kernel='decode') —
        the wired route the engine's step/verify programs take —
        matches its own XLA path: same projections, same rope, same
        pool writes (bit-identical), attention to fp32 roundoff."""
        from veles_tpu import prng
        from veles_tpu.ops.attention import (init_mha_params,
                                             mha_paged_chunk_step)
        rng = numpy.random.RandomState(3)
        d_model, n_heads, page, m, n_pages, b, c = 32, 4, 8, 4, 9, 2, 2
        params = jax.tree.map(
            jnp.asarray, init_mha_params(prng.get("init"), d_model,
                                         n_heads, n_kv_heads=2))
        x = jnp.asarray(rng.randn(b, c, d_model), jnp.float32)
        kp = jnp.asarray(rng.randn(n_pages, 2, page, 8), jnp.float32)
        vp = jnp.asarray(rng.randn(n_pages, 2, page, 8), jnp.float32)
        ptab = jnp.asarray(rng.choice(n_pages, (b, m), replace=False)
                           .reshape(b, m), jnp.int32)
        pos = jnp.asarray([5, 13], jnp.int32)
        ref_o, ref_k, ref_v = mha_paged_chunk_step(
            params, x, kp, vp, ptab, pos, n_heads, rope=True,
            window=16, sinks=1)
        got_o, got_k, got_v = mha_paged_chunk_step(
            params, x, kp, vp, ptab, pos, n_heads, rope=True,
            window=16, sinks=1, attn_kernel="decode")
        numpy.testing.assert_array_equal(numpy.asarray(got_k),
                                         numpy.asarray(ref_k))
        numpy.testing.assert_array_equal(numpy.asarray(got_v),
                                         numpy.asarray(ref_v))
        numpy.testing.assert_allclose(numpy.asarray(got_o),
                                      numpy.asarray(ref_o),
                                      rtol=1e-4, atol=1e-5)


@pytest.mark.kernel_parity
class TestPagedFlashPrefill:
    """ISSUE 7: the fused chunked-prefill kernel — history streamed
    below the frontier, the chunk's K/V attended from VMEM, and the
    page install folded into the kernel epilogue (aliased outputs)."""

    def _setup(self, b=1, h=4, kv=2, dh=16, page=8, m=4, n_pages=9,
               n_hist=2, seed=0):
        rng = numpy.random.RandomState(seed)
        q = jnp.asarray(rng.randn(b, h, page, dh), jnp.float32)
        kn = jnp.asarray(rng.randn(b, kv, page, dh), jnp.float32)
        vn = jnp.asarray(rng.randn(b, kv, page, dh), jnp.float32)
        kp = jnp.asarray(rng.randn(n_pages, kv, page, dh), jnp.float32)
        vp = jnp.asarray(rng.randn(n_pages, kv, page, dh), jnp.float32)
        ptab = jnp.asarray(rng.permutation(n_pages)[:b * m]
                           .reshape(b, m), jnp.int32)
        pos = jnp.asarray([n_hist * page] * b, jnp.int32)
        return q, kn, vn, kp, vp, ptab, pos

    def _xla(self, q, kn, vn, kp, vp, ptab, pos, window=None, sinks=0):
        from veles_tpu.ops import attention as A
        h, c = q.shape[1], q.shape[2]
        kp = A.paged_write(kp, ptab, pos, kn)
        vp = A.paged_write(vp, ptab, pos, vn)
        kx, vx = A.paged_view(kp, ptab), A.paged_view(vp, ptab)
        s = jnp.einsum("bhcd,bhld->bhcl", q, A._repeat_kv(kx, h)) \
            / jnp.sqrt(jnp.float32(q.shape[-1]))
        live = jax.vmap(lambda p: A.chunk_live_mask(
            p, c, kx.shape[-2], window, sinks))(pos)
        s = jnp.where(live[:, None], s, A.NEG_INF)
        o = jnp.einsum("bhcl,bhld->bhcd", jax.nn.softmax(s, axis=-1),
                       A._repeat_kv(vx, h))
        return o, kp, vp

    @pytest.mark.parametrize("n_hist,window,sinks", [
        (0, None, 0),          # FIRST chunk: empty history
        (2, None, 0),
        (3, 20, 2),            # window reaching into history + sinks
    ])
    def test_matches_xla_and_installs(self, n_hist, window, sinks):
        from veles_tpu.ops import pallas_kernels as PK
        q, kn, vn, kp, vp, ptab, pos = self._setup(
            n_hist=n_hist, seed=n_hist + (window or 0))
        got_o, got_k, got_v = PK.paged_flash_prefill(
            q, kn, vn, kp, vp, ptab, pos, window=window, sinks=sinks)
        ref_o, ref_k, ref_v = self._xla(q, kn, vn, kp, vp, ptab, pos,
                                        window, sinks)
        # the install is a ROW COPY — bit-identical, and pages outside
        # the chunk's target untouched (the aliasing contract)
        numpy.testing.assert_array_equal(numpy.asarray(got_k),
                                         numpy.asarray(ref_k))
        numpy.testing.assert_array_equal(numpy.asarray(got_v),
                                         numpy.asarray(ref_v))
        numpy.testing.assert_allclose(numpy.asarray(got_o),
                                      numpy.asarray(ref_o),
                                      rtol=1e-5, atol=1e-6)

    def test_batched_lanes_install_their_own_pages(self):
        from veles_tpu.ops import pallas_kernels as PK
        q, kn, vn, kp, vp, ptab, _ = self._setup(b=2, m=4, n_pages=11,
                                                 seed=9)
        pos = jnp.asarray([8, 24], jnp.int32)   # different frontiers
        got_o, got_k, got_v = PK.paged_flash_prefill(
            q, kn, vn, kp, vp, ptab, pos)
        ref_o, ref_k, ref_v = self._xla(q, kn, vn, kp, vp, ptab, pos)
        numpy.testing.assert_array_equal(numpy.asarray(got_k),
                                         numpy.asarray(ref_k))
        numpy.testing.assert_allclose(numpy.asarray(got_o),
                                      numpy.asarray(ref_o),
                                      rtol=1e-5, atol=1e-6)

    def test_chunk_must_equal_page(self):
        from veles_tpu.ops import pallas_kernels as PK
        q, kn, vn, kp, vp, ptab, pos = self._setup()
        with pytest.raises(ValueError, match="page"):
            PK.paged_flash_prefill(q[:, :, :4], kn[:, :, :4],
                                   vn[:, :, :4], kp, vp, ptab, pos)

    def test_mha_paged_chunk_step_prefill_route(self):
        """The engine's chunk program route ('prefill') against the
        XLA path at a page-aligned frontier — outputs to roundoff,
        pool installs bit-identical."""
        from veles_tpu import prng
        from veles_tpu.ops.attention import (init_mha_params,
                                             mha_paged_chunk_step)
        rng = numpy.random.RandomState(4)
        d_model, n_heads, page, m, n_pages = 32, 4, 8, 4, 9
        params = jax.tree.map(
            jnp.asarray, init_mha_params(prng.get("init"), d_model,
                                         n_heads))
        x = jnp.asarray(rng.randn(1, page, d_model), jnp.float32)
        kp = jnp.asarray(rng.randn(n_pages, 4, page, 8), jnp.float32)
        vp = jnp.asarray(rng.randn(n_pages, 4, page, 8), jnp.float32)
        ptab = jnp.asarray(rng.permutation(n_pages)[:m].reshape(1, m),
                           jnp.int32)
        pos = jnp.asarray([2 * page], jnp.int32)
        ref_o, ref_k, ref_v = mha_paged_chunk_step(
            params, x, kp, vp, ptab, pos, n_heads, rope=True)
        got_o, got_k, got_v = mha_paged_chunk_step(
            params, x, kp, vp, ptab, pos, n_heads, rope=True,
            attn_kernel="prefill")
        numpy.testing.assert_array_equal(numpy.asarray(got_k),
                                         numpy.asarray(ref_k))
        numpy.testing.assert_array_equal(numpy.asarray(got_v),
                                         numpy.asarray(ref_v))
        numpy.testing.assert_allclose(numpy.asarray(got_o),
                                      numpy.asarray(ref_o),
                                      rtol=1e-4, atol=1e-5)


class TestServingKernelSupport:
    def test_structural_checks(self):
        from veles_tpu.ops import pallas_kernels as PK
        assert PK.serving_kernels_supported(True, 4, 2, 16, 8) \
            == (True, None)
        ok, reason = PK.serving_kernels_supported(False, 4, 2, 16, 8)
        assert not ok and "paged_kv" in reason
        ok, reason = PK.serving_kernels_supported(True, 4, 3, 16, 8)
        assert not ok and "divisible" in reason


class TestFlashAttentionTPUCoverage:
    """Satellite (ISSUE 7): flash_attention_tpu — the bundled jax TPU
    kernel — pinned at its edges.  The kernel itself has no CPU
    lowering in this jax (its interpret path trips a discharge-rule
    bug upstream), so off-TPU coverage pins the ROUTING: the loud
    error and the window/sink fallback; numerics are pinned by the
    TPU-marked leg."""

    def test_window_routes_away_from_kernel(self):
        """mha_forward under backend 'flash_pallas' with a window (or
        sinks) must take the XLA band path — bit-identical to backend
        'xla', even off-TPU where the kernel itself would raise."""
        from veles_tpu import prng
        from veles_tpu.ops import attention as A
        params = jax.tree.map(jnp.asarray, A.init_mha_params(
            prng.get("init"), 32, 4))
        x = jnp.asarray(numpy.random.RandomState(0).randn(2, 16, 32),
                        jnp.float32)
        ref = numpy.asarray(A.mha_forward(params, x, 4, causal=True,
                                          window=8, sinks=2))
        A.set_attention_backend("flash_pallas")
        try:
            got = numpy.asarray(A.mha_forward(params, x, 4,
                                              causal=True, window=8,
                                              sinks=2))
            if not PK.on_tpu():
                with pytest.raises(RuntimeError, match="TPU"):
                    A.mha_forward(params, x, 4, causal=True)
        finally:
            A.set_attention_backend("xla")
        numpy.testing.assert_array_equal(got, ref)

    def test_flash_serve_backend_keeps_mha_on_xla(self):
        """'flash_serve' only flips the SERVING engines' default —
        mha_forward's path stays the XLA one (bit-identical), on any
        platform."""
        from veles_tpu import prng
        from veles_tpu.ops import attention as A
        params = jax.tree.map(jnp.asarray, A.init_mha_params(
            prng.get("init"), 32, 4))
        x = jnp.asarray(numpy.random.RandomState(1).randn(2, 16, 32),
                        jnp.float32)
        ref = numpy.asarray(A.mha_forward(params, x, 4, causal=True))
        A.set_attention_backend("flash_serve")
        try:
            assert A.serving_kernel_default()
            got = numpy.asarray(A.mha_forward(params, x, 4,
                                              causal=True))
        finally:
            A.set_attention_backend("xla")
        assert not A.serving_kernel_default()
        numpy.testing.assert_array_equal(got, ref)

    @pytest.mark.skipif(not PK.on_tpu(),
                        reason="the bundled kernel has no CPU lowering")
    def test_matches_attention_on_tpu(self):
        """The hardware parity pin: the bundled kernel vs our
        ``attention`` oracle at serving-ish shape."""
        from veles_tpu.ops import attention as A
        key = jax.random.PRNGKey(2)
        q = jax.random.normal(key, (2, 4, 256, 64), jnp.float32)
        k = jax.random.normal(jax.random.fold_in(key, 1), q.shape)
        v = jax.random.normal(jax.random.fold_in(key, 2), q.shape)
        ref = A.attention(q, k, v, causal=True)
        got = A.flash_attention_tpu(q, k, v, causal=True)
        numpy.testing.assert_allclose(numpy.asarray(got),
                                      numpy.asarray(ref),
                                      rtol=2e-3, atol=2e-3)
