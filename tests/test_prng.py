"""Tier-1 PRNG stream tests: determinism, independence, snapshot."""

import numpy

from veles_tpu import prng


def test_same_seed_same_stream():
    a = prng.RandomGenerator("x", seed=7)
    b = prng.RandomGenerator("x", seed=7)
    numpy.testing.assert_array_equal(a.permutation(10), b.permutation(10))


def test_named_streams_are_decorrelated():
    a = prng.RandomGenerator("alpha", seed=7)
    b = prng.RandomGenerator("beta", seed=7)
    assert not numpy.array_equal(a.permutation(100), b.permutation(100))


def test_registry_get_and_seed_all():
    s1 = prng.get("loader")
    s2 = prng.get("loader")
    assert s1 is s2
    prng.seed_all(99)
    v1 = prng.get("loader").randint(0, 1 << 30)
    prng.seed_all(99)
    v2 = prng.get("loader").randint(0, 1 << 30)
    assert v1 == v2


def test_fill_inplace():
    arr = numpy.zeros((5, 5), dtype=numpy.float32)
    prng.get("init").fill(arr, -0.1, 0.1)
    assert arr.min() >= -0.1 and arr.max() <= 0.1
    assert arr.std() > 0


def test_device_keys_unique_and_deterministic():
    a = prng.RandomGenerator("d", seed=3)
    k1, k2 = a.key(), a.key()
    assert not numpy.array_equal(numpy.asarray(k1), numpy.asarray(k2))
    b = prng.RandomGenerator("d", seed=3)
    numpy.testing.assert_array_equal(numpy.asarray(b.key()),
                                     numpy.asarray(k1))


def test_state_dict_roundtrip():
    s = prng.get("snap")
    s.permutation(5)
    saved = prng.state_dict()
    before = s.permutation(100)
    prng.load_state_dict(saved)
    after = prng.get("snap").permutation(100)
    numpy.testing.assert_array_equal(before, after)


def test_get_after_seed_all_honors_default_seed():
    prng.seed_all(42)
    assert prng.get("fresh_stream").initial_seed == 42


def test_pinned_streams_survive_snapshot_restore():
    """Restoring prng state must re-pin data streams, else a later
    seed_all (ensemble/genetics resume) would regenerate the dataset."""
    from veles_tpu import prng
    prng.reset()
    prng.seed_all(1)
    data = prng.get("synth_data", pinned=True)
    baseline = data.uniform(size=4).tolist()
    saved = prng.state_dict()

    prng.reset()
    prng.seed_all(1)
    replay = prng.get("synth_data", pinned=True).uniform(size=4).tolist()
    assert replay == baseline

    prng.reset()
    prng.load_state_dict(saved)
    prng.seed_all(99)          # must NOT touch the restored pinned stream
    stream = prng.get("synth_data")
    assert stream.initial_seed == 1
    # old-format snapshots (bare name->state mapping) still load
    prng.reset()
    prng.load_state_dict(saved["streams"])
    assert prng.get("synth_data").initial_seed == 1


def test_base_key_is_stateless():
    """ISSUE 19: base_key never advances the counter — interleaved
    key() calls by other consumers must not shift a counter-based
    sampling stream."""
    s = prng.RandomGenerator("sampler", 7)
    a = numpy.asarray(s.base_key())
    s.key()
    s.key()
    b = numpy.asarray(s.base_key())
    numpy.testing.assert_array_equal(a, b)
    # and key() itself still never repeats
    assert not numpy.array_equal(numpy.asarray(s.key()),
                                 numpy.asarray(s.key()))


def test_key_at_deterministic_and_order_independent():
    """key_at(lane, pos) is a pure function of the coordinates: the
    same key whenever (and in whatever order) it is asked for — what
    lets a fused device loop and a per-tick host loop sample
    bit-identical tokens at the same (lane seed, position)."""
    s = prng.RandomGenerator("sampler", 7)
    grid = [(lane, pos) for lane in (0, 1, 5) for pos in (0, 3, 17)]
    first = {c: numpy.asarray(s.key_at(*c)) for c in grid}
    for c in reversed(grid):          # revisit in a different order
        numpy.testing.assert_array_equal(
            numpy.asarray(s.key_at(*c)), first[c])


def test_key_at_independent_per_lane_and_position():
    """Counter-stream independence: every (lane, position) coordinate
    owns a distinct key, keys differ across stream seeds, and the
    coordinate fold is order-sensitive (key_at(a, b) != key_at(b, a))."""
    s = prng.RandomGenerator("sampler", 7)
    keys = {}
    for lane in range(4):
        for pos in range(8):
            keys[(lane, pos)] = tuple(
                numpy.asarray(s.key_at(lane, pos)).tolist())
    assert len(set(keys.values())) == len(keys)
    assert keys[(1, 2)] != tuple(
        numpy.asarray(s.key_at(2, 1)).tolist())
    other = prng.RandomGenerator("sampler", 8)
    assert tuple(numpy.asarray(other.key_at(1, 2)).tolist()) \
        != keys[(1, 2)]
