"""RBM tests: CD math vs numpy oracle, masking, MNIST-RBM convergence."""

import numpy

import jax
import jax.numpy as jnp

from veles_tpu.ops import functional as F


def sigmoid(z):
    return 1.0 / (1.0 + numpy.exp(-z))


class TestRBMFunctional:
    def test_hidden_visible_match_numpy(self):
        r = numpy.random.RandomState(1)
        v = r.rand(8, 12).astype(numpy.float32)
        w = r.randn(12, 6).astype(numpy.float32) * 0.1
        vb = r.randn(12).astype(numpy.float32)
        hb = r.randn(6).astype(numpy.float32)
        h = F.rbm_hidden(jnp.asarray(v), jnp.asarray(w), jnp.asarray(hb))
        numpy.testing.assert_allclose(numpy.asarray(h),
                                      sigmoid(v @ w + hb), rtol=1e-5,
                                      atol=1e-5)
        v2 = F.rbm_visible(h, jnp.asarray(w), jnp.asarray(vb))
        numpy.testing.assert_allclose(
            numpy.asarray(v2), sigmoid(numpy.asarray(h) @ w.T + vb),
            rtol=1e-5, atol=1e-5)

    def test_masked_rows_do_not_move_params(self):
        r = numpy.random.RandomState(2)
        w = (r.randn(10, 4) * 0.1).astype(numpy.float32)
        vb = numpy.zeros(10, numpy.float32)
        hb = numpy.zeros(4, numpy.float32)
        v = r.rand(5, 10).astype(numpy.float32)
        dead = jnp.zeros(5, jnp.float32)
        nw, nvb, nhb, m = F.rbm_cd_step(
            jnp.asarray(w), jnp.asarray(vb), jnp.asarray(hb),
            jnp.asarray(v), dead, jax.random.PRNGKey(0),
            jnp.asarray(0.1, jnp.float32))
        numpy.testing.assert_allclose(numpy.asarray(nw), w, atol=1e-6)
        numpy.testing.assert_allclose(numpy.asarray(nvb), vb, atol=1e-6)
        assert float(m["recon_sum"]) == 0.0

    def test_cd_reduces_recon_error_on_fixed_batch(self):
        r = numpy.random.RandomState(3)
        w = (r.randn(16, 8) * 0.01).astype(numpy.float32)
        vb = numpy.zeros(16, numpy.float32)
        hb = numpy.zeros(8, numpy.float32)
        # two binary prototypes repeated — an easy distribution
        protos = (r.rand(2, 16) > 0.5).astype(numpy.float32)
        v = protos[numpy.arange(32) % 2]
        mask = jnp.ones(32, jnp.float32)
        params = (jnp.asarray(w), jnp.asarray(vb), jnp.asarray(hb))
        errs = []
        for step in range(60):
            nw, nvb, nhb, m = F.rbm_cd_step(
                *params, jnp.asarray(v), mask,
                jax.random.PRNGKey(step),
                jnp.asarray(0.5, jnp.float32))
            params = (nw, nvb, nhb)
            errs.append(float(m["recon_sum"]))
        assert numpy.mean(errs[-10:]) < numpy.mean(errs[:10]), (
            errs[:5], errs[-5:])


class TestMnistRBMSample:
    def test_validation_minibatches_do_not_update(self):
        """Held-out sets are scored, never trained on (eval-leak guard)."""
        from veles_tpu.config import root
        root.mnist_rbm.update({
            "loader": {"minibatch_size": 50, "n_train": 100, "n_valid": 100},
            "trainer": {"n_hidden": 16, "learning_rate": 0.1},
            "decision": {"max_epochs": 2, "fail_iterations": 20},
        })
        from veles_tpu.samples import mnist_rbm
        wf = mnist_rbm.train()
        # 2 epochs x 2 train minibatches; valid minibatches must not count
        assert wf.trainer.time == 4
        metrics = wf.decision.epoch_metrics[-1]
        assert "validation" in metrics and "train" in metrics

    def test_converges(self):
        from veles_tpu.config import root
        root.mnist_rbm.update({
            "loader": {"minibatch_size": 50, "n_train": 300, "n_valid": 0},
            "trainer": {"n_hidden": 64, "learning_rate": 0.1, "cd_k": 1},
            "decision": {"max_epochs": 4, "fail_iterations": 20},
        })
        from veles_tpu.samples import mnist_rbm
        wf = mnist_rbm.train()
        errs = [m["train"]["recon_err"] for m in wf.decision.epoch_metrics]
        assert len(errs) == 4
        assert errs[-1] < errs[0], errs
        # forward produced hidden features at completion
        assert wf.forward.output.shape == (50, 64)
