"""Concurrency and invariant analysis (ISSUE 15): the veles_lint
static passes, the lock-order witness, and the shutdown-ordering
contract they pin.

Three layers under test:

- the LINTER itself, against fixture modules with seeded violations
  (``tests/lint_fixtures/``): each must be caught at exactly the
  marked file:line, the clean fixture at zero findings, and the
  suppression hygiene (reason required, stale suppressions flagged)
  must hold;
- the FULL TREE: ``tools/veles_lint.py --check`` semantics ride
  tier-1 here, so a future unguarded access or impure traced body
  fails the suite, not a review round;
- the RUNTIME witness (``serving/lockcheck.py``): a deliberately
  inverted acquisition order and a lock held across a device-dispatch
  site are caught with both stacks, and the serving stack's stop()
  ordering — retry timers, the hedge loop, the health prober, the
  telemetry sampler — runs under an armed witness without violations
  or wedged futures.
"""

import os
import re
import sys
import threading
import time

import numpy
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

import veles_lint  # noqa: E402

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")

EXPECT_RE = re.compile(r"#\s*EXPECT-LINT\s+([\w-]+)")


def _expected(name):
    """[(line, check)] markers in a fixture module."""
    out = []
    with open(os.path.join(FIXTURES, name), "r",
              encoding="utf-8") as f:
        for i, line in enumerate(f, start=1):
            m = EXPECT_RE.search(line)
            if m:
                out.append((i, m.group(1)))
    return out


def _run_fixture(name, purity=False):
    """(findings, suppressions) for one fixture module through the
    full check (lock pass + purity pass + suppression hygiene)."""
    findings, sups, _stats = veles_lint.run_check(
        root=FIXTURES, modules=(name,),
        purity_modules=(name,) if purity else (), registry=())
    return findings, sups


class TestLintFixtures:
    def test_clean_fixture_zero_findings(self):
        findings, sups = _run_fixture("clean_module.py", purity=True)
        assert findings == [], "\n".join(map(repr, findings))
        assert sups == []

    def test_unlocked_guarded_access_caught_at_line(self):
        findings, _ = _run_fixture("bad_guarded.py")
        got = sorted((f.line, f.check) for f in findings)
        assert got == sorted(_expected("bad_guarded.py")), \
            "\n".join(map(repr, findings))
        assert all(f.file == "bad_guarded.py" for f in findings)
        # the messages name the attribute AND the missing lock
        assert any("_items" in f.message and "_lock" in f.message
                   for f in findings)

    def test_broken_caller_holds_chain_caught(self):
        findings, _ = _run_fixture("bad_chain.py")
        got = [(f.line, f.check) for f in findings]
        assert got == _expected("bad_chain.py"), \
            "\n".join(map(repr, findings))
        assert "caller-holds chain broken" in findings[0].message

    def test_purity_violations_caught_at_line(self):
        findings, _ = _run_fixture("bad_purity.py", purity=True)
        got = sorted((f.line, f.check) for f in findings)
        assert got == sorted(_expected("bad_purity.py")), \
            "\n".join(map(repr, findings))
        msgs = " | ".join(f.message for f in findings)
        assert "time.time" in msgs
        assert "np.random" in msgs
        assert "print" in msgs
        assert "TRACE_LOG" in msgs and "mutates" in msgs

    def test_reasoned_suppression_silences_and_is_listed(self):
        findings, sups = _run_fixture("suppressed.py")
        assert findings == [], "\n".join(map(repr, findings))
        assert len(sups) == 1
        assert sups[0].check == "lock-discipline"
        assert "benign racy peek" in sups[0].reason
        assert sups[0].used

    def test_trailing_suppression_covers_only_its_own_line(self):
        """A trailing `# lint: allow` must not reach the next line —
        else one reasoned exception could silently swallow a second,
        unrelated violation."""
        findings, sups = _run_fixture("trailing_suppression.py")
        got = [(f.line, f.check) for f in findings]
        assert got == _expected("trailing_suppression.py"), \
            "\n".join(map(repr, findings))
        assert len(sups) == 1 and sups[0].used
        assert not sups[0].standalone

    def test_reasonless_suppression_is_a_finding(self):
        findings, sups = _run_fixture("bad_suppression.py")
        assert sups == []          # rejected, never registered
        checks = sorted(f.check for f in findings)
        # the malformed suppression AND the access it failed to cover
        assert checks == ["lock-discipline", "suppression"]
        sup = next(f for f in findings if f.check == "suppression")
        assert "no reason" in sup.message


class TestFullTree:
    def test_full_tree_lint_clean(self):
        """THE tier-1 enforcement: the shipped tree has zero findings
        and every suppression carries a reason — a future unguarded
        access or impure traced body fails here, not in review."""
        findings, sups, stats = veles_lint.run_check()
        assert findings == [], (
            "veles_lint found %d problem(s) in the tree:\n%s"
            % (len(findings), "\n".join(map(repr, findings))))
        assert all(s.reason for s in sups)
        # the analysis actually covered the serving tier (a silently
        # empty pass must not read as a clean one)
        assert stats["files"] >= 10
        assert stats["guarded_attrs"] >= 50
        assert stats["module_globals"] >= 2
        assert stats["traced_functions"] >= 40

    def test_summary_record_shape(self):
        rec = veles_lint.summary_record(
            {"findings": 0, "stats": {"files": 11}})[0]
        for key in ("metric", "value", "unit", "vs_baseline",
                    "configs"):
            assert key in rec
        assert rec["metric"] == "lint_findings"
        # the empty-results worst case conforms too (the
        # check_stream_records builtin contract)
        empty = veles_lint.summary_record({})[0]
        assert empty["value"] == 0


class TestLockOrderWitness:
    def test_deliberate_inversion_caught_with_both_stacks(self):
        from veles_tpu.serving import lockcheck
        w = lockcheck.LockOrderWitness(name="t_invert")
        lockcheck.arm(w)
        try:
            a = lockcheck.make_lock("fixture.A")
            b = lockcheck.make_lock("fixture.B")
            with a:
                with b:
                    pass
            with b:                # the documented order, inverted
                with a:
                    pass
        finally:
            lockcheck.disarm()
        assert len(w.violations) == 1
        report = w.violations[0]
        assert "cycle" in report
        assert "fixture.A" in report and "fixture.B" in report
        # both stacks: where the held lock was taken, where the
        # conflicting acquire happened
        assert report.count("test_lint.py") >= 2

    def test_inversion_raises_when_asked(self):
        from veles_tpu.serving import lockcheck
        w = lockcheck.LockOrderWitness(raise_on_violation=True)
        lockcheck.arm(w)
        try:
            a = lockcheck.make_lock("fixture.C")
            b = lockcheck.make_lock("fixture.D")
            with a:
                with b:
                    pass
            with pytest.raises(lockcheck.LockOrderViolation):
                with b:
                    with a:
                        pass
        finally:
            lockcheck.disarm()

    def test_lock_held_across_dispatch_caught(self):
        from veles_tpu.serving import lockcheck
        w = lockcheck.LockOrderWitness(name="t_dispatch")
        lockcheck.arm(w)
        try:
            lock = lockcheck.make_lock("fixture.E")
            lockcheck.note_dispatch("engine.step")   # lock-free: fine
            with lock:
                lockcheck.note_dispatch("engine.step")
        finally:
            lockcheck.disarm()
        assert len(w.violations) == 1
        assert "held across device dispatch" in w.violations[0]
        assert "engine.step" in w.violations[0]

    def test_nonreentrant_reacquire_caught(self):
        from veles_tpu.serving import lockcheck
        w = lockcheck.LockOrderWitness(name="t_reent",
                                       raise_on_violation=True)
        lockcheck.arm(w)
        try:
            lock = lockcheck.make_lock("fixture.F")
            with lock:
                with pytest.raises(lockcheck.LockOrderViolation):
                    with lock:
                        pass
        finally:
            lockcheck.disarm()

    def test_condition_wait_notify_under_witness(self):
        """The Condition wrapper keeps primitive semantics while
        armed: wait releases (held-stack popped — a concurrent
        notifier acquiring is no violation) and re-acquires."""
        from veles_tpu.serving import lockcheck
        w = lockcheck.LockOrderWitness(name="t_cond")
        lockcheck.arm(w)
        try:
            cond = lockcheck.make_condition("fixture.cond")
            seen = []

            def waiter():
                with cond:
                    while not seen:
                        cond.wait(5.0)
                    seen.append("woke")

            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.05)
            with cond:
                seen.append("go")
                cond.notify_all()
            t.join(timeout=10)
            assert not t.is_alive()
            assert seen == ["go", "woke"]
        finally:
            lockcheck.disarm()
        assert w.violations == []
        assert w.acquisitions >= 2

    def test_unarmed_shims_are_inert(self):
        from veles_tpu.serving import lockcheck
        assert lockcheck.armed() is None
        lock = lockcheck.make_lock("fixture.G")
        with lock:
            lockcheck.note_dispatch("engine.step")
        cond = lockcheck.make_condition("fixture.H")
        with cond:
            cond.notify_all()


def _tiny_params(max_len=48, vocab=16, n_heads=2, n_layers=2):
    import jax
    import jax.numpy as jnp
    from veles_tpu import prng
    from veles_tpu.ops.transformer import init_transformer_params
    host = init_transformer_params(prng.get("init"), vocab, d_model=32,
                                   n_heads=n_heads, n_layers=n_layers,
                                   max_len=max_len)
    return jax.tree.map(jnp.asarray, host)


class TestStopOrderingUnderWitness:
    def test_serving_stack_stop_ordering(self):
        """The ISSUE 15 shutdown audit, pinned: a fleet with a parked
        retry timer (long backoff), a live hedge loop, a health
        prober and the telemetry sampler+SLO listener stops in the
        serve_lm order — every outstanding future resolves loudly
        (never wedges on a cancelled timer), every daemon joins, and
        the armed witness sees no ordering violation across the whole
        teardown."""
        from veles_tpu.serving import (FaultPlan, HealthChecker,
                                       LMEngine, Router, SLOMonitor,
                                       lockcheck, telemetry_for)
        params = _tiny_params()
        plan = FaultPlan(seed=0)
        # replica 0 poisons every step dispatch: the first attempt
        # faults and schedules a retry with a deliberately HUGE
        # backoff, so stop() runs with the timer still parked
        plan.arm("engine.step", kind="error")
        witness = lockcheck.LockOrderWitness(name="t_stop")
        lockcheck.arm(witness)
        try:
            replicas = [
                LMEngine(params, n_heads=2, max_len=48, slots=2,
                         name="lint_stop0", faults=plan),
                LMEngine(params, n_heads=2, max_len=48, slots=2,
                         name="lint_stop1"),
            ]
            router = Router(replicas, retries=3,
                            retry_backoff_s=30.0,
                            retry_backoff_cap_s=60.0,
                            hedge_after_s=5.0, seed=0)
            router.start()
            checker = HealthChecker(router, interval_s=0.2,
                                    stall_s=60.0).warm_probes()
            checker.start()
            store = telemetry_for(router, interval_s=0.2)
            monitor = SLOMonitor(store,
                                 SLOMonitor.default_objectives(),
                                 windows_s=(1.0, 5.0), min_events=1,
                                 checker=checker)
            store.add_listener(monitor.sample_once)
            store.start()
            # exclude the healthy replica so the first placement hits
            # the poisoned one and schedules the long-backoff retry
            with router._lock:
                router._live[1] = False
            fut = router.submit([1, 2, 3], 4)
            deadline = time.monotonic() + 30.0
            while router.metrics.counter("requests_retried") < 1:
                assert time.monotonic() < deadline, \
                    "retry was never scheduled"
                time.sleep(0.01)
            with router._lock:
                router._live[1] = True
            # the serve_lm stop order: telemetry → publisher (none) →
            # health prober → router (timers, hedge, replicas)
            store.stop()
            checker.stop()
            router.stop()
            # the parked-timer job fails LOUDLY instead of wedging
            with pytest.raises(Exception):
                fut.result(timeout=10)
            assert fut.done()
            assert router._hedge_thread is None
            with router._lock:
                assert not router._timers
            assert store._thread is None
            assert checker._thread is None
            for e in replicas:
                assert e._thread is None
        finally:
            plan.release()
            lockcheck.disarm()
        assert witness.violations == [], \
            "\n\n".join(witness.violations)
        assert witness.acquisitions > 0


class TestStreamRecordIntegration:
    def test_check_stream_records_validates_lint_record(self):
        """The <1s builtin path: check_stream_records --tool
        veles_lint validates exactly this tool's record without
        importing the jax-heavy benches."""
        import check_stream_records
        problems = check_stream_records.check_tool("veles_lint")
        assert problems == []
