"""Concurrency and invariant analysis (ISSUE 15): the veles_lint
static passes, the lock-order witness, and the shutdown-ordering
contract they pin.

Three layers under test:

- the LINTER itself, against fixture modules with seeded violations
  (``tests/lint_fixtures/``): each must be caught at exactly the
  marked file:line, the clean fixture at zero findings, and the
  suppression hygiene (reason required, stale suppressions flagged)
  must hold;
- the FULL TREE: ``tools/veles_lint.py --check`` semantics ride
  tier-1 here, so a future unguarded access or impure traced body
  fails the suite, not a review round;
- the RUNTIME witness (``serving/lockcheck.py``): a deliberately
  inverted acquisition order and a lock held across a device-dispatch
  site are caught with both stacks, and the serving stack's stop()
  ordering — retry timers, the hedge loop, the health prober, the
  telemetry sampler — runs under an armed witness without violations
  or wedged futures.
"""

import os
import re
import sys
import threading
import time

import numpy
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

import veles_lint  # noqa: E402

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")

EXPECT_RE = re.compile(r"#\s*EXPECT-LINT\s+([\w-]+)")


def _expected(name):
    """[(line, check)] markers in a fixture module."""
    out = []
    with open(os.path.join(FIXTURES, name), "r",
              encoding="utf-8") as f:
        for i, line in enumerate(f, start=1):
            m = EXPECT_RE.search(line)
            if m:
                out.append((i, m.group(1)))
    return out


def _run_fixture(name, purity=False, census=(), fixtures=()):
    """(findings, suppressions) for one fixture module through the
    full check (lock + purity + recompile + host-sync + lifecycle +
    suppression hygiene).  ``census``/``fixtures`` point the census
    cross-check at fixture stand-ins."""
    findings, sups, _stats = veles_lint.run_check(
        root=FIXTURES, modules=(name,),
        purity_modules=(name,) if purity else (), registry=(),
        census_modules=census, jit_guard_fixtures=fixtures,
        hot_path_registry=())
    return findings, sups


class TestLintFixtures:
    def test_clean_fixture_zero_findings(self):
        findings, sups = _run_fixture("clean_module.py", purity=True)
        assert findings == [], "\n".join(map(repr, findings))
        assert sups == []

    def test_unlocked_guarded_access_caught_at_line(self):
        findings, _ = _run_fixture("bad_guarded.py")
        got = sorted((f.line, f.check) for f in findings)
        assert got == sorted(_expected("bad_guarded.py")), \
            "\n".join(map(repr, findings))
        assert all(f.file == "bad_guarded.py" for f in findings)
        # the messages name the attribute AND the missing lock
        assert any("_items" in f.message and "_lock" in f.message
                   for f in findings)

    def test_broken_caller_holds_chain_caught(self):
        findings, _ = _run_fixture("bad_chain.py")
        got = [(f.line, f.check) for f in findings]
        assert got == _expected("bad_chain.py"), \
            "\n".join(map(repr, findings))
        assert "caller-holds chain broken" in findings[0].message

    def test_purity_violations_caught_at_line(self):
        findings, _ = _run_fixture("bad_purity.py", purity=True)
        got = sorted((f.line, f.check) for f in findings)
        assert got == sorted(_expected("bad_purity.py")), \
            "\n".join(map(repr, findings))
        msgs = " | ".join(f.message for f in findings)
        assert "time.time" in msgs
        assert "np.random" in msgs
        assert "print" in msgs
        assert "TRACE_LOG" in msgs and "mutates" in msgs

    def test_reasoned_suppression_silences_and_is_listed(self):
        findings, sups = _run_fixture("suppressed.py")
        assert findings == [], "\n".join(map(repr, findings))
        assert len(sups) == 1
        assert sups[0].check == "lock-discipline"
        assert "benign racy peek" in sups[0].reason
        assert sups[0].used

    def test_trailing_suppression_covers_only_its_own_line(self):
        """A trailing `# lint: allow` must not reach the next line —
        else one reasoned exception could silently swallow a second,
        unrelated violation."""
        findings, sups = _run_fixture("trailing_suppression.py")
        got = [(f.line, f.check) for f in findings]
        assert got == _expected("trailing_suppression.py"), \
            "\n".join(map(repr, findings))
        assert len(sups) == 1 and sups[0].used
        assert not sups[0].standalone

    def test_reasonless_suppression_is_a_finding(self):
        findings, sups = _run_fixture("bad_suppression.py")
        assert sups == []          # rejected, never registered
        checks = sorted(f.check for f in findings)
        # the malformed suppression AND the access it failed to cover
        assert checks == ["lock-discipline", "suppression"]
        sup = next(f for f in findings if f.check == "suppression")
        assert "no reason" in sup.message

    def test_recompile_hazards_caught_at_line(self):
        """ISSUE 17: traced-body closure/shape/concretization hazards
        plus the program-family census — including both directions of
        the census↔jit-guard-fixture agreement check — each at the
        exact marked file:line."""
        findings, _ = _run_fixture(
            "bad_recompile.py", purity=True,
            census=("bad_recompile.py",),
            fixtures=("jitguard_fixture.py",))
        got = sorted((f.file, f.line, f.check) for f in findings)
        want = sorted(
            [("bad_recompile.py", line, check)
             for line, check in _expected("bad_recompile.py")]
            + [("jitguard_fixture.py", line, check)
               for line, check in _expected("jitguard_fixture.py")])
        assert got == want, "\n".join(map(repr, findings))
        msgs = " | ".join(f.message for f in findings)
        assert "closes over self.scale" in msgs
        assert ".shape" in msgs
        assert "census" in msgs
        assert "silently-compiled twin" in msgs
        assert "fixture drift" in msgs
        # ISSUE 19: while-loop-built programs join the census — an
        # unmarked `lax.while_loop` and one naming an uninstalled
        # family are both findings
        assert "silently-compiled while-twin" in msgs
        assert "no `self._phantom_jit" in msgs

    def test_hostsync_violations_caught_at_line(self):
        """ISSUE 17: implicit device→host coercions, jnp staging,
        un-fenced timing and dispatch-under-lock in hot-path methods;
        the xfer.to_device/to_host shapes pass clean."""
        findings, _ = _run_fixture("bad_hostsync.py")
        got = sorted((f.line, f.check) for f in findings)
        assert got == sorted(_expected("bad_hostsync.py")), \
            "\n".join(map(repr, findings))
        msgs = " | ".join(f.message for f in findings)
        assert "int(...)" in msgs
        assert ".item()" in msgs
        assert "jnp.asarray" in msgs
        assert "timing read with a dispatch in flight" in msgs
        assert "inside a `with self.<lock>:`" in msgs

    def test_lifecycle_violations_caught_at_line(self):
        """ISSUE 17: dropped futures and straight-line span/page
        resolution flagged; finally/except ownership and handoff
        escapes pass clean."""
        findings, _ = _run_fixture("bad_lifecycle.py")
        got = sorted((f.line, f.check) for f in findings)
        assert got == sorted(_expected("bad_lifecycle.py")), \
            "\n".join(map(repr, findings))
        msgs = " | ".join(f.message for f in findings)
        assert "leaked on every path" in msgs
        assert "exception path" in msgs

    def test_hot_path_registry_drift_is_a_finding(self):
        """A rename (or a dropped marker) must not silently shrink
        the host-sync analysis set."""
        findings, _, _ = veles_lint.run_check(
            root=FIXTURES, modules=("bad_hostsync.py",),
            purity_modules=(), registry=(), census_modules=(),
            jit_guard_fixtures=(),
            hot_path_registry=(("bad_hostsync.py", "_renamed_away"),))
        drift = [f for f in findings
                 if f.check == "host-sync"
                 and "registry drift" in f.message]
        assert len(drift) == 1
        assert "_renamed_away" in drift[0].message


class TestFullTree:
    def test_full_tree_lint_clean(self):
        """THE tier-1 enforcement: the shipped tree has zero findings
        and every suppression carries a reason — a future unguarded
        access or impure traced body fails here, not in review."""
        findings, sups, stats = veles_lint.run_check()
        assert findings == [], (
            "veles_lint found %d problem(s) in the tree:\n%s"
            % (len(findings), "\n".join(map(repr, findings))))
        assert all(s.reason for s in sups)
        # the ISSUE 17 suppression budget: at most 6 named+reasoned
        # exceptions tree-wide
        assert len(sups) <= 6
        # the analysis actually covered the serving tier (a silently
        # empty pass must not read as a clean one)
        assert stats["files"] >= 10
        assert stats["guarded_attrs"] >= 50
        assert stats["module_globals"] >= 2
        assert stats["traced_functions"] >= 40
        assert stats["census_sites"] >= 10
        assert stats["hot_path_methods"] >= 12
        assert stats["lifecycle_sites"] >= 1
        # the shared-parse satellite: one ast.parse per file, under
        # the 10s budget
        assert stats["parses"] <= 2 * stats["files"] + 10
        assert stats["wall_s"] < 10.0

    def test_summary_record_shape(self):
        rec = veles_lint.summary_record(
            {"findings": 0, "stats": {"files": 11}})[0]
        for key in ("metric", "value", "unit", "vs_baseline",
                    "configs"):
            assert key in rec
        assert rec["metric"] == "lint_findings"
        assert "wall_s" in rec["configs"]
        # the empty-results worst case conforms too (the
        # check_stream_records builtin contract)
        empty = veles_lint.summary_record({})[0]
        assert empty["value"] == 0

    def test_clean_record_shape(self):
        """The bench-leg `lint_clean` record (lm_bench/chaos_bench
        stream it after their lint leg)."""
        rec = veles_lint.clean_record(
            0, {"files": 11, "wall_s": 0.8})[0]
        for key in ("metric", "value", "unit", "vs_baseline",
                    "configs"):
            assert key in rec
        assert rec["metric"] == "lint_clean"
        assert rec["value"] == 1
        assert rec["configs"]["wall_s"] == 0.8
        dirty = veles_lint.clean_record(
            [veles_lint.Finding("x.py", 1, "host-sync", "m")], {})[0]
        assert dirty["value"] == 0
        assert dirty["configs"]["findings"] == 1


class TestCLIContract:
    """ISSUE 17 CI/tooling satellite: one entry point, every pass in
    the default set, per-pass exit codes — pinned so a pass silently
    dropping out fails loudly here."""

    def test_every_pass_has_a_distinct_exit_bit(self):
        assert set(veles_lint.PASS_BITS) == set(veles_lint.CHECKS)
        bits = sorted(veles_lint.PASS_BITS.values())
        assert len(set(bits)) == len(bits)
        for b in bits:
            assert b > 0 and (b & (b - 1)) == 0   # one bit each

    def test_default_pass_set_is_complete(self):
        assert veles_lint.CHECKS == (
            "lock-discipline", "traced-purity", "suppression",
            "recompile-hazard", "host-sync", "resource-lifecycle")

    def test_exit_code_is_a_per_pass_bitmask(self):
        mk = lambda check: veles_lint.Finding("x.py", 1, check, "m")
        assert veles_lint.exit_code([]) == 0
        assert veles_lint.exit_code([mk("lock-discipline")]) == 1
        assert veles_lint.exit_code([mk("host-sync")]) == 16
        assert veles_lint.exit_code(
            [mk("recompile-hazard"), mk("host-sync"),
             mk("host-sync")]) == 24
        assert veles_lint.exit_code(
            [mk(c) for c in veles_lint.CHECKS]) == 63

    def test_main_all_runs_clean_and_streams_record(self, capsys):
        """`--all` == `--check`: every pass over the shipped tree,
        exit 0, one conforming record on stdout."""
        import json
        rc = veles_lint.main(["--all"])
        assert rc == 0
        out = capsys.readouterr().out.strip().splitlines()[-1]
        rec = json.loads(out)
        assert rec["metric"] == "lint_findings"
        assert rec["value"] == 0
        assert rec["configs"]["hot_path_methods"] >= 12
        assert rec["configs"]["wall_s"] < 10.0


class TestLockOrderWitness:
    def test_deliberate_inversion_caught_with_both_stacks(self):
        from veles_tpu.serving import lockcheck
        w = lockcheck.LockOrderWitness(name="t_invert")
        lockcheck.arm(w)
        try:
            a = lockcheck.make_lock("fixture.A")
            b = lockcheck.make_lock("fixture.B")
            with a:
                with b:
                    pass
            with b:                # the documented order, inverted
                with a:
                    pass
        finally:
            lockcheck.disarm()
        assert len(w.violations) == 1
        report = w.violations[0]
        assert "cycle" in report
        assert "fixture.A" in report and "fixture.B" in report
        # both stacks: where the held lock was taken, where the
        # conflicting acquire happened
        assert report.count("test_lint.py") >= 2

    def test_inversion_raises_when_asked(self):
        from veles_tpu.serving import lockcheck
        w = lockcheck.LockOrderWitness(raise_on_violation=True)
        lockcheck.arm(w)
        try:
            a = lockcheck.make_lock("fixture.C")
            b = lockcheck.make_lock("fixture.D")
            with a:
                with b:
                    pass
            with pytest.raises(lockcheck.LockOrderViolation):
                with b:
                    with a:
                        pass
        finally:
            lockcheck.disarm()

    def test_lock_held_across_dispatch_caught(self):
        from veles_tpu.serving import lockcheck
        w = lockcheck.LockOrderWitness(name="t_dispatch")
        lockcheck.arm(w)
        try:
            lock = lockcheck.make_lock("fixture.E")
            lockcheck.note_dispatch("engine.step")   # lock-free: fine
            with lock:
                lockcheck.note_dispatch("engine.step")
        finally:
            lockcheck.disarm()
        assert len(w.violations) == 1
        assert "held across device dispatch" in w.violations[0]
        assert "engine.step" in w.violations[0]

    def test_nonreentrant_reacquire_caught(self):
        from veles_tpu.serving import lockcheck
        w = lockcheck.LockOrderWitness(name="t_reent",
                                       raise_on_violation=True)
        lockcheck.arm(w)
        try:
            lock = lockcheck.make_lock("fixture.F")
            with lock:
                with pytest.raises(lockcheck.LockOrderViolation):
                    with lock:
                        pass
        finally:
            lockcheck.disarm()

    def test_condition_wait_notify_under_witness(self):
        """The Condition wrapper keeps primitive semantics while
        armed: wait releases (held-stack popped — a concurrent
        notifier acquiring is no violation) and re-acquires."""
        from veles_tpu.serving import lockcheck
        w = lockcheck.LockOrderWitness(name="t_cond")
        lockcheck.arm(w)
        try:
            cond = lockcheck.make_condition("fixture.cond")
            seen = []

            def waiter():
                with cond:
                    while not seen:
                        cond.wait(5.0)
                    seen.append("woke")

            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.05)
            with cond:
                seen.append("go")
                cond.notify_all()
            t.join(timeout=10)
            assert not t.is_alive()
            assert seen == ["go", "woke"]
        finally:
            lockcheck.disarm()
        assert w.violations == []
        assert w.acquisitions >= 2

    def test_unarmed_shims_are_inert(self):
        from veles_tpu.serving import lockcheck
        assert lockcheck.armed() is None
        lock = lockcheck.make_lock("fixture.G")
        with lock:
            lockcheck.note_dispatch("engine.step")
        cond = lockcheck.make_condition("fixture.H")
        with cond:
            cond.notify_all()


def _tiny_params(max_len=48, vocab=16, n_heads=2, n_layers=2):
    import jax
    import jax.numpy as jnp
    from veles_tpu import prng
    from veles_tpu.ops.transformer import init_transformer_params
    host = init_transformer_params(prng.get("init"), vocab, d_model=32,
                                   n_heads=n_heads, n_layers=n_layers,
                                   max_len=max_len)
    return jax.tree.map(jnp.asarray, host)


class TestStopOrderingUnderWitness:
    def test_serving_stack_stop_ordering(self):
        """The ISSUE 15 shutdown audit, pinned: a fleet with a parked
        retry timer (long backoff), a live hedge loop, a health
        prober and the telemetry sampler+SLO listener stops in the
        serve_lm order — every outstanding future resolves loudly
        (never wedges on a cancelled timer), every daemon joins, and
        the armed witness sees no ordering violation across the whole
        teardown."""
        from veles_tpu.serving import (FaultPlan, HealthChecker,
                                       LMEngine, Router, SLOMonitor,
                                       lockcheck, telemetry_for)
        params = _tiny_params()
        plan = FaultPlan(seed=0)
        # replica 0 poisons every step dispatch: the first attempt
        # faults and schedules a retry with a deliberately HUGE
        # backoff, so stop() runs with the timer still parked
        plan.arm("engine.step", kind="error")
        witness = lockcheck.LockOrderWitness(name="t_stop")
        lockcheck.arm(witness)
        try:
            replicas = [
                LMEngine(params, n_heads=2, max_len=48, slots=2,
                         name="lint_stop0", faults=plan),
                LMEngine(params, n_heads=2, max_len=48, slots=2,
                         name="lint_stop1"),
            ]
            router = Router(replicas, retries=3,
                            retry_backoff_s=30.0,
                            retry_backoff_cap_s=60.0,
                            hedge_after_s=5.0, seed=0)
            router.start()
            checker = HealthChecker(router, interval_s=0.2,
                                    stall_s=60.0).warm_probes()
            checker.start()
            store = telemetry_for(router, interval_s=0.2)
            monitor = SLOMonitor(store,
                                 SLOMonitor.default_objectives(),
                                 windows_s=(1.0, 5.0), min_events=1,
                                 checker=checker)
            store.add_listener(monitor.sample_once)
            store.start()
            # exclude the healthy replica so the first placement hits
            # the poisoned one and schedules the long-backoff retry
            with router._lock:
                router._live[1] = False
            fut = router.submit([1, 2, 3], 4)
            deadline = time.monotonic() + 30.0
            while router.metrics.counter("requests_retried") < 1:
                assert time.monotonic() < deadline, \
                    "retry was never scheduled"
                time.sleep(0.01)
            with router._lock:
                router._live[1] = True
            # the serve_lm stop order: telemetry → publisher (none) →
            # health prober → router (timers, hedge, replicas)
            store.stop()
            checker.stop()
            router.stop()
            # the parked-timer job fails LOUDLY instead of wedging
            with pytest.raises(Exception):
                fut.result(timeout=10)
            assert fut.done()
            assert router._hedge_thread is None
            with router._lock:
                assert not router._timers
            assert store._thread is None
            assert checker._thread is None
            for e in replicas:
                assert e._thread is None
        finally:
            plan.release()
            lockcheck.disarm()
        assert witness.violations == [], \
            "\n\n".join(witness.violations)
        assert witness.acquisitions > 0


class TestStreamRecordIntegration:
    def test_check_stream_records_validates_lint_record(self):
        """The <1s builtin path: check_stream_records --tool
        veles_lint validates exactly this tool's record without
        importing the jax-heavy benches."""
        import check_stream_records
        problems = check_stream_records.check_tool("veles_lint")
        assert problems == []


class TestTransferGuardWitness:
    """The runtime half of the host-sync pass (ISSUE 17): the serving
    suites run with ``jax.transfer_guard("disallow")`` armed via
    serving/xfer.py, entered on the engine worker thread itself."""

    def test_unarmed_guard_is_inert(self):
        from veles_tpu.serving import xfer
        assert not xfer.armed()
        with xfer.guard():
            pass                     # a null context, zero jax work

    def test_arm_rejects_unknown_mode(self):
        from veles_tpu.serving import xfer
        with pytest.raises(ValueError):
            xfer.arm("explode")
        assert not xfer.armed()

    def test_explicit_shims_pass_under_armed_guard(self):
        from veles_tpu.serving import xfer
        xfer.arm("disallow")
        try:
            with xfer.guard():
                dev = xfer.to_device([1, 2, 3], numpy.int32)
                host = xfer.to_host(dev)
        finally:
            xfer.disarm()
        assert list(host) == [1, 2, 3]

    def test_implicit_transfer_fails_the_request_loudly(self):
        """Deliberately poison a decode step with an implicit
        host→device transfer: under the armed guard the worker-loop
        dispatch raises and the request future carries the loud
        transfer-guard error — the PR 15 witness discipline, applied
        to transfers."""
        import jax.numpy as jnp
        from veles_tpu.serving import LMEngine, xfer
        params = _tiny_params()
        engine = LMEngine(params, n_heads=2, max_len=48, slots=2,
                          name="xfer_witness")
        xfer.arm("disallow")
        try:
            engine.start()     # warmup runs clean under the guard
            real_step = engine._step_jit

            def poisoned(*args):
                # jnp.asarray of a python scalar is an implicit
                # host→device transfer — exactly what the static
                # host-sync pass bans from hot-path methods
                return real_step(*args) + jnp.asarray(0, jnp.int32)

            engine._step_jit = poisoned
            fut = engine.submit([1, 2, 3], n_new=4)
            with pytest.raises(Exception) as ei:
                fut.result(timeout=60)
            msg = str(ei.value).lower()
            assert "transfer" in msg or "disallow" in msg
        finally:
            engine.stop()
            xfer.disarm()


class TestTruePositivePins:
    """The PR 15 precedent: every true positive a new pass finds in
    the shipped tree gets fixed in the same PR *with a pin*, so the
    fix cannot quietly revert."""

    def test_batcher_dispatch_routes_through_xfer_shims(self):
        """The one true positive the host-sync pass found: batcher
        ``_dispatch`` coerced the dispatched result with
        ``numpy.asarray(self.forward(chunk))`` — an implicit
        device→host sync on the hot path.  Zero-copy on CPU (so the
        runtime transfer guard cannot see it here), a full device
        round-trip stall on TPU — exactly the class the STATIC pass
        exists for.  Pin the fix at both levels: the dispatch hot
        path is audited (marked + registered, so a clean result is
        not clean-by-omission) and moves data through the explicit
        shims."""
        findings, _sups, _stats = veles_lint.run_check()
        assert [f for f in findings
                if f.file.endswith("batcher.py")] == []
        registered = {m for r, m in veles_lint.HOT_PATH_REGISTRY
                      if r.endswith("serving/batcher.py")}
        assert {"_take_batch", "_dispatch",
                "_serve_batches"} <= registered
        src = open(os.path.join(
            os.path.dirname(FIXTURES), "..", "veles_tpu", "serving",
            "batcher.py"), encoding="utf-8").read()
        assert "xfer.to_host(self.forward(xfer.to_device(" in src
        assert "= numpy.asarray(self.forward(chunk" not in src
