"""Transformer LM tests: forward shapes, loss math, char-LM convergence,
snapshot round-trip of a params-pytree trainer."""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.ops import transformer as T


def tiny_config():
    root.char_lm.update({
        "loader": {"minibatch_size": 32, "n_train": 128, "n_valid": 64,
                   "seq_len": 32, "vocab": 16},
        # every optional knob pinned to its default: root is process-global
        # and update() merges — without explicit resets, a previous test's
        # MoE/PP/rope/window settings would silently leak into later
        # "dense sequential" runs (PP rejects rope/window, so a leaked
        # rope=True breaks unrelated pipeline tests)
        "trainer": {"vocab": 16, "d_model": 32, "n_heads": 2, "n_layers": 1,
                    "max_len": 32, "learning_rate": 3e-3,
                    "n_experts": 0, "pipeline_stages": 0, "remat": False,
                    "rope": False, "window": None, "attn_sinks": 0,
                    "n_kv_heads": None},
        "decision": {"max_epochs": 4, "fail_iterations": 10},
    })


class TestForward:
    def test_shapes_and_causality(self):
        prng.reset()
        prng.seed_all(1)
        params = jax.tree.map(jnp.asarray, T.init_transformer_params(
            prng.get("init"), vocab=11, d_model=16, n_heads=2, n_layers=1,
            max_len=16))
        tokens = jnp.asarray(
            numpy.random.RandomState(0).randint(0, 11, (2, 8)))
        logits = T.transformer_forward(params, tokens, n_heads=2)
        assert logits.shape == (2, 8, 11)
        # causality: changing a LATER token must not affect earlier logits
        tokens2 = tokens.at[:, 5].set((tokens[:, 5] + 1) % 11)
        logits2 = T.transformer_forward(params, tokens2, n_heads=2)
        numpy.testing.assert_allclose(numpy.asarray(logits[:, :5]),
                                      numpy.asarray(logits2[:, :5]),
                                      rtol=1e-4, atol=1e-5)
        assert not numpy.allclose(numpy.asarray(logits[:, 5:]),
                                  numpy.asarray(logits2[:, 5:]))

    def test_loss_uniform_baseline(self):
        """Untrained-ish loss should be near log(vocab)."""
        prng.reset()
        prng.seed_all(1)
        vocab = 16
        params = jax.tree.map(jnp.asarray, T.init_transformer_params(
            prng.get("init"), vocab=vocab, d_model=16, n_heads=2,
            n_layers=1, max_len=16))
        tokens = jnp.asarray(
            numpy.random.RandomState(0).randint(0, vocab, (4, 16)))
        mask = jnp.ones(4, jnp.float32)
        loss = float(T.lm_loss(params, tokens, mask, n_heads=2))
        assert abs(loss - numpy.log(vocab)) < 1.0


class TestCharLM:
    def test_converges(self):
        prng.reset()
        prng.seed_all(1)
        tiny_config()
        from veles_tpu.samples import char_lm
        wf = char_lm.train()
        losses = [m["validation"]["loss"] for m in wf.decision.epoch_metrics
                  if "validation" in m]
        assert len(losses) == 4
        # the cyclic grammar is easy: loss must drop well below uniform
        assert losses[-1] < losses[0] * 0.7, losses
        assert losses[-1] < numpy.log(16), losses

    def test_blockwise_matches_dense_training(self):
        """One train step with flash attention == one with dense."""
        prng.reset()
        prng.seed_all(1)
        params = jax.tree.map(jnp.asarray, T.init_transformer_params(
            prng.get("init"), vocab=16, d_model=16, n_heads=2, n_layers=1,
            max_len=33))
        tokens = jnp.asarray(
            numpy.random.RandomState(0).randint(0, 16, (2, 33)))
        mask = jnp.ones(2, jnp.float32)
        dense = float(T.lm_loss(params, tokens, mask, 2))
        blocked = float(T.lm_loss(params, tokens, mask, 2, block_size=8))
        assert abs(dense - blocked) < 1e-4

    def test_snapshot_roundtrip(self, tmp_path):
        prng.reset()
        prng.seed_all(1)
        tiny_config()
        root.char_lm.update({"decision": {"max_epochs": 2,
                                          "fail_iterations": 10}})
        from veles_tpu.samples import char_lm
        wf = char_lm.build()
        wf.initialize()
        from veles_tpu.snapshotter import Snapshotter
        snap = Snapshotter(wf, directory=str(tmp_path), prefix="lm",
                           name="snapshotter")
        snap.link_from(wf.decision)
        snap.link_attrs(wf.decision, "improved", "complete")
        snap.link_attrs(wf.loader, "epoch_number", "epoch_ended")
        wf.initialize()
        wf.run()
        assert snap.destination
        # restore into a fresh workflow; params must match bit-exactly
        prng.reset()
        prng.seed_all(77)
        wf2 = char_lm.build()
        wf2.initialize()
        from veles_tpu import snapshotter as snap_mod
        snap_mod.restore(wf2, snap.destination)
        a = jax.tree.leaves(wf.trainer.params)
        b = jax.tree.leaves(wf2.trainer.params)
        for x, y in zip(a, b):
            numpy.testing.assert_array_equal(numpy.asarray(x),
                                             numpy.asarray(y))


class TestMoETrainer:
    def test_moe_char_lm_converges(self):
        """n_experts > 0 swaps every block's FFN for the routed MoE; the
        char LM must still learn the cyclic grammar."""
        prng.reset()
        prng.seed_all(1)
        tiny_config()
        root.char_lm.update({"trainer": {"n_experts": 4, "n_layers": 2}})
        from veles_tpu.samples import char_lm
        wf = char_lm.train()
        losses = [m["validation"]["loss"] for m in wf.decision.epoch_metrics
                  if "validation" in m]
        assert losses[-1] < losses[0] * 0.7, losses
        # the params really carry routed experts
        blk0 = wf.trainer.params["blocks"][0]
        assert "moe" in blk0 and blk0["moe"]["w1"].shape[0] == 4


class TestPipelinedTrainer:
    def test_pp_training_matches_sequential(self):
        """pipeline_stages > 0 trains through the GPipe schedule; the loss
        stream must equal the sequential trainer's exactly (same adam on
        the same per-layer values, just stacked)."""
        from veles_tpu.samples import char_lm

        def train(stages):
            prng.reset()
            prng.seed_all(1)
            tiny_config()
            root.char_lm.update({
                "trainer": {"n_layers": 4,
                            "pipeline_stages": stages,
                            "pipeline_microbatches": 4},
                "decision": {"max_epochs": 2, "fail_iterations": 10},
            })
            wf = char_lm.train()
            return [m["validation"]["loss"]
                    for m in wf.decision.epoch_metrics
                    if "validation" in m]

        seq = train(0)
        pp = train(4)
        numpy.testing.assert_allclose(pp, seq, rtol=2e-5, atol=1e-6)

    @pytest.mark.slow
    def test_pp_snapshot_portable_to_sequential(self):
        # slow-marked for tier-1 runtime headroom: PP training parity
        # stays tier-1 above; the snapshot-portability claim re-runs a
        # second full PP training and rides the slow suite
        """Snapshots carry blocks UNSTACKED, so a pipelined trainer's
        state restores into a sequential trainer (single-chip eval) and
        scores identically."""
        from veles_tpu.samples import char_lm

        def build(stages):
            prng.reset()
            prng.seed_all(1)
            tiny_config()
            root.char_lm.update({
                "trainer": {"n_layers": 4, "pipeline_stages": stages,
                            "pipeline_microbatches": 4},
                "decision": {"max_epochs": 2, "fail_iterations": 10},
            })
            return char_lm

        wf = build(4).train()
        state = wf.snapshot_state()
        # portable form: per-layer list, not the stacked pytree
        snap_blocks = state["units"]["trainer"]["params"]["blocks"]
        assert isinstance(snap_blocks, list) and len(snap_blocks) == 4

        wf2 = build(0).build()
        wf2.initialize()
        wf2.load_snapshot_state(state)
        rng = numpy.random.RandomState(2)
        tokens = jnp.asarray(rng.randint(0, 16, (8, 32)), jnp.int32)
        mask = jnp.ones(8, jnp.float32)
        a = wf.trainer._evalf(wf.trainer.params, tokens, mask)
        b = wf2.trainer._evalf(wf2.trainer.params, tokens, mask)
        numpy.testing.assert_allclose(
            float(a["loss_sum"]), float(b["loss_sum"]), rtol=2e-5)


class TestRingLMForward:
    def test_ring_attention_in_transformer(self):
        """Sequence-parallel attention slots into the transformer forward
        and matches the dense path (8-dev CPU mesh)."""
        devices = jax.devices("cpu")
        if len(devices) < 8:
            pytest.skip("needs 8 virtual devices")
        from veles_tpu.parallel.ring import make_seq_mesh, ring_attention
        from veles_tpu.ops.functional import matmul
        mesh = make_seq_mesh(8, data_parallel=1, devices=devices[:8])
        prng.reset()
        prng.seed_all(1)
        params = jax.tree.map(jnp.asarray, T.init_transformer_params(
            prng.get("init"), vocab=16, d_model=16, n_heads=2, n_layers=1,
            max_len=64))
        tokens = jnp.asarray(
            numpy.random.RandomState(0).randint(0, 16, (2, 64)))

        def ring_attn(attn_params, x):
            b, s, d = x.shape
            heads, dh = 2, d // 2

            def split(w):
                return matmul(x, w).reshape(b, s, heads, dh).transpose(
                    0, 2, 1, 3)

            q, k, v = (split(attn_params[key])
                       for key in ("wq", "wk", "wv"))
            o = ring_attention(q, k, v, mesh, causal=True)
            o = o.transpose(0, 2, 1, 3).reshape(b, s, d)
            return matmul(o, attn_params["wo"])

        dense = T.transformer_forward(params, tokens, n_heads=2)
        ringed = T.transformer_forward(params, tokens, n_heads=2,
                                       attn_fn=ring_attn)
        numpy.testing.assert_allclose(numpy.asarray(ringed),
                                      numpy.asarray(dense),
                                      rtol=1e-3, atol=1e-4)


class TestRemat:
    def test_remat_loss_and_grads_identical(self):
        """jax.checkpoint changes memory scheduling, not math: loss and
        gradients must match the stored-activation path exactly."""
        prng.reset(); prng.seed_all(3)
        host = T.init_transformer_params(prng.get("init"), vocab=16,
                                         d_model=32, n_heads=2, n_layers=3,
                                         max_len=33)
        params = jax.tree.map(jnp.asarray, host)
        key = jax.random.PRNGKey(1)
        tokens = jax.random.randint(key, (4, 33), 0, 16, jnp.int32)
        mask = jnp.ones((4,), jnp.float32)

        def loss(remat):
            return lambda p: T.lm_loss(p, tokens, mask, n_heads=2,
                                       remat=remat)
        l0, g0 = jax.value_and_grad(loss(False))(params)
        l1, g1 = jax.value_and_grad(loss(True))(params)
        assert float(l0) == pytest.approx(float(l1), rel=1e-6)
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            numpy.testing.assert_allclose(numpy.asarray(a),
                                          numpy.asarray(b),
                                          rtol=1e-5, atol=1e-7)

    def test_char_lm_trains_with_remat(self):
        prng.reset(); prng.seed_all(11)
        tiny_config()
        root.char_lm.trainer.update({"remat": True})
        try:
            from veles_tpu.samples import char_lm
            wf = char_lm.train()
            losses = [m["validation"]["loss"]
                      for m in wf.decision.epoch_metrics]
            assert losses[-1] < losses[0]
        finally:
            root.char_lm.trainer.update({"remat": False})   # don't leak


class TestGenerate:
    def _params(self, n_experts=0):
        prng.reset(); prng.seed_all(13)
        host = T.init_transformer_params(prng.get("init"), vocab=16,
                                         d_model=32, n_heads=2,
                                         n_layers=2, max_len=24,
                                         n_experts=n_experts)
        return jax.tree.map(jnp.asarray, host)

    def test_kv_cached_decode_matches_full_forward(self):
        """Teacher-forced: stepping each position through the KV-cached
        decode path must reproduce the full forward's logits exactly."""
        params = self._params()
        key = jax.random.PRNGKey(2)
        tokens = jax.random.randint(key, (3, 10), 0, 16, jnp.int32)
        full = T.transformer_forward(params, tokens, n_heads=2)

        s0 = 4                       # prefill 4, decode the rest
        h, caches = T.prefill(params, tokens[:, :s0], 2, max_len=10)
        got = [T.head_logits(params, h)]           # positions 0..3
        for p in range(s0, 10):
            x = (jnp.take(params["embed"], tokens[:, p], axis=0)[:, None]
                 + params["pos"][p][None, None])
            new = []
            for blk, (kc, vc) in zip(params["blocks"], caches):
                x, kc, vc = T.block_decode_step(blk, x, kc, vc, p, 2)
                new.append((kc, vc))
            caches = new
            got.append(T.head_logits(params, x))
        stepped = jnp.concatenate(got, axis=1)
        numpy.testing.assert_allclose(numpy.asarray(full),
                                      numpy.asarray(stepped),
                                      rtol=2e-5, atol=2e-5)

    def test_generate_greedy_deterministic(self):
        params = self._params()
        prompt = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
        out1 = T.generate(params, prompt, n_new=8, n_heads=2,
                          temperature=0)
        out2 = T.generate(params, prompt, n_new=8, n_heads=2,
                          temperature=0)
        assert out1.shape == (2, 11)
        numpy.testing.assert_array_equal(numpy.asarray(out1),
                                         numpy.asarray(out2))
        numpy.testing.assert_array_equal(numpy.asarray(out1[:, :3]),
                                         numpy.asarray(prompt))
        assert int(out1.max()) < 16 and int(out1.min()) >= 0

    def test_generate_greedy_matches_full_forward_argmax(self):
        """Greedy decode must pick exactly the argmax the full forward
        assigns at every step (the KV cache changes nothing)."""
        params = self._params()
        prompt = jnp.asarray([[7, 3]], jnp.int32)
        out = numpy.asarray(T.generate(params, prompt, n_new=5,
                                       n_heads=2, temperature=0))[0]
        seq = list(map(int, prompt[0]))
        for step in range(5):
            logits = T.transformer_forward(
                params, jnp.asarray([seq], jnp.int32), n_heads=2)
            nxt = int(jnp.argmax(logits[0, -1]))
            assert nxt == int(out[len(seq)]), (step, seq)
            seq.append(nxt)

    def test_generate_bucketed_prompt_bit_exact(self):
        """Serving buckets right-pad the prompt and decode with a traced
        true_len — the continuation must be BIT-IDENTICAL to the
        unpadded decode (mha_decode_step masks cache positions > pos, so
        pad garbage can never leak in)."""
        params = self._params()
        prompt = jnp.asarray([[1, 2, 3, 4, 5], [9, 8, 7, 6, 5]],
                             jnp.int32)
        plain = numpy.asarray(T.generate(params, prompt, n_new=6,
                                         n_heads=2, temperature=0,
                                         max_len=16))
        padded = jnp.pad(prompt, ((0, 0), (0, 3)))      # bucket width 8
        bucketed = numpy.asarray(T.generate(params, padded, n_new=6,
                                            n_heads=2, temperature=0,
                                            max_len=16, true_len=5))
        numpy.testing.assert_array_equal(plain[:, 5:], bucketed[:, 8:])
        # sampling path too: same rng => same tokens
        key = jax.random.PRNGKey(3)
        plain_s = numpy.asarray(T.generate(
            params, prompt, n_new=6, n_heads=2, rng=key,
            temperature=0.8, max_len=16))
        bucket_s = numpy.asarray(T.generate(
            params, padded, n_new=6, n_heads=2, rng=key,
            temperature=0.8, max_len=16, true_len=5))
        numpy.testing.assert_array_equal(plain_s[:, 5:], bucket_s[:, 8:])
        with pytest.raises(ValueError):
            T.generate(params, padded, n_new=2, n_heads=2, temperature=0,
                       max_len=16, true_len=9)   # exceeds prompt width

    def test_generate_sampling_and_moe(self):
        params = self._params(n_experts=2)
        prompt = jnp.asarray([[1, 2]], jnp.int32)
        out = T.generate(params, prompt, n_new=6, n_heads=2,
                         rng=jax.random.PRNGKey(5), temperature=0.8)
        assert out.shape == (1, 8)
        assert int(out.max()) < 16
        with pytest.raises(ValueError):
            T.generate(params, prompt, n_new=6, n_heads=2)  # no rng
        with pytest.raises(ValueError):
            T.generate(params, prompt, n_new=99, n_heads=2,
                       temperature=0)   # exceeds positional table


def test_char_lm_generates_the_grammar():
    """End-to-end: a char-LM trained on the cyclic grammar must greedily
    CONTINUE the pattern t[i+1] = (t[i] + step) % vocab from a prompt."""
    prng.reset(); prng.seed_all(4)
    root.char_lm.update({
        "loader": {"minibatch_size": 64, "n_train": 512, "n_valid": 128,
                   "seq_len": 48, "vocab": 16},
        "trainer": {"vocab": 16, "d_model": 64, "n_heads": 4,
                    "n_layers": 2, "max_len": 48, "learning_rate": 3e-3,
                    "n_experts": 0, "pipeline_stages": 0, "remat": False},
        "decision": {"max_epochs": 14, "fail_iterations": 30},
    })
    from veles_tpu.samples import char_lm
    wf = char_lm.train()
    # prompt follows the grammar with step 3: 1, 4, 7, 10, ...
    prompt = [(1 + 3 * i) % 16 for i in range(8)]
    out = char_lm.sample_tokens(wf, [prompt], n_new=12, temperature=0.0)
    expect = [(1 + 3 * i) % 16 for i in range(20)]
    assert out[0].tolist() == expect, (out[0].tolist(), expect)


class TestTopK:
    def _params(self):
        prng.reset(); prng.seed_all(13)
        host = T.init_transformer_params(prng.get("init"), vocab=16,
                                         d_model=32, n_heads=2,
                                         n_layers=1, max_len=16)
        return jax.tree.map(jnp.asarray, host)

    def test_top_k_1_equals_greedy(self):
        params = self._params()
        prompt = jnp.asarray([[3, 1, 4]], jnp.int32)
        greedy = T.generate(params, prompt, 6, 2, temperature=0)
        k1 = T.generate(params, prompt, 6, 2, rng=jax.random.PRNGKey(0),
                        temperature=0.7, top_k=1)
        numpy.testing.assert_array_equal(numpy.asarray(greedy),
                                         numpy.asarray(k1))

    def test_top_k_vocab_equals_unrestricted(self):
        params = self._params()
        prompt = jnp.asarray([[3, 1, 4]], jnp.int32)
        key = jax.random.PRNGKey(2)
        full = T.generate(params, prompt, 6, 2, rng=key, temperature=0.9)
        k16 = T.generate(params, prompt, 6, 2, rng=key, temperature=0.9,
                         top_k=16)
        numpy.testing.assert_array_equal(numpy.asarray(full),
                                         numpy.asarray(k16))

    def test_top_k_out_of_range(self):
        params = self._params()
        prompt = jnp.asarray([[3]], jnp.int32)
        with pytest.raises(ValueError):
            T.generate(params, prompt, 2, 2, rng=jax.random.PRNGKey(0),
                       top_k=0)
        with pytest.raises(ValueError):
            T.generate(params, prompt, 2, 2, rng=jax.random.PRNGKey(0),
                       top_k=99)


class TestLongContextOptions:
    """RoPE / grouped-query / sliding-window attention (beyond-parity
    long-context depth): every option must keep the KV-cached decode
    bit-consistent with the full forward, and train end-to-end."""

    def _params(self, n_kv_heads=None, rope=False, vocab=16):
        prng.reset(); prng.seed_all(7)
        return jax.tree.map(jnp.asarray, T.init_transformer_params(
            prng.get("init"), vocab=vocab, d_model=32, n_heads=4,
            n_layers=2, max_len=16, n_kv_heads=n_kv_heads, rope=rope))

    def test_gqa_shapes_and_cache_width(self):
        params = self._params(n_kv_heads=2)
        attn = params["blocks"][0]["attn"]
        assert attn["wq"].shape == (32, 32)
        assert attn["wk"].shape == (32, 16)      # 2 kv heads x dh 8
        from veles_tpu.ops.attention import kv_heads_of
        assert kv_heads_of(attn, 4, 32) == 2
        with pytest.raises(ValueError):
            from veles_tpu.ops.attention import init_mha_params
            init_mha_params(prng.get("init"), 32, 4, n_kv_heads=3)

    def test_rope_drops_pos_table(self):
        params = self._params(rope=True)
        assert "pos" not in params

    @pytest.mark.parametrize("opts", [
        # tier-1 keeps the INTERACTION legs (each single feature also
        # rides inside a combined leg); the two single-feature
        # geometries run in the slow suite — 870s-watchdog headroom,
        # the PR-3 trim discipline
        pytest.param({"n_kv_heads": 2}, marks=pytest.mark.slow),
        pytest.param({"rope": True}, marks=pytest.mark.slow),
        {"rope": True, "n_kv_heads": 1},
        {"n_kv_heads": 2, "window": 4},
        {"rope": True, "n_kv_heads": 2, "window": 3},
    ])
    def test_generate_matches_full_forward_argmax(self, opts):
        """Greedy KV-cached decode must reproduce the full forward's
        argmax under every option combination (GQA cache width, rotated
        cached keys)."""
        window = opts.pop("window", None)
        params = self._params(**opts)
        rope = opts.get("rope", False)
        prompt = jnp.asarray([[7, 3, 9]], jnp.int32)
        out = numpy.asarray(T.generate(
            params, prompt, n_new=6, n_heads=4, temperature=0,
            max_len=16, rope=rope, window=window))[0]
        seq = list(map(int, prompt[0]))
        for _ in range(6):
            logits = T.transformer_forward(
                params, jnp.asarray([seq], jnp.int32), n_heads=4,
                rope=rope, window=window)
            nxt = int(jnp.argmax(logits[0, -1]))
            assert nxt == int(out[len(seq)]), seq
            seq.append(nxt)

    def test_window_decode_matches_full_forward(self):
        """Sliding-window decode masks old cache entries exactly as the
        full forward's banded causal mask does."""
        params = self._params(rope=True)
        prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        out = numpy.asarray(T.generate(
            params, prompt, n_new=8, n_heads=4, temperature=0,
            max_len=16, rope=True, window=3))[0]
        seq = list(map(int, prompt[0]))
        for _ in range(8):
            logits = T.transformer_forward(
                params, jnp.asarray([seq], jnp.int32), n_heads=4,
                rope=True, window=3)
            nxt = int(jnp.argmax(logits[0, -1]))
            assert nxt == int(out[len(seq)]), seq
            seq.append(nxt)

    def test_window_wider_than_seq_is_plain_causal(self):
        params = self._params()
        tokens = jnp.asarray(
            numpy.random.RandomState(1).randint(0, 16, (2, 8)))
        plain = T.transformer_forward(params, tokens, n_heads=4)
        wide = T.transformer_forward(params, tokens, n_heads=4,
                                     window=99)
        numpy.testing.assert_allclose(numpy.asarray(plain),
                                      numpy.asarray(wide),
                                      rtol=1e-5, atol=1e-6)

    def test_window_restricts_context(self):
        """With window=1 every position sees only itself — changing an
        EARLIER token must not change later logits' window-1 view."""
        params = self._params()
        t1 = jnp.asarray(
            numpy.random.RandomState(2).randint(0, 16, (1, 8)))
        t2 = t1.at[0, 2].set((t1[0, 2] + 1) % 16)
        a = T.transformer_forward(params, t1, n_heads=4, window=1)
        b = T.transformer_forward(params, t2, n_heads=4, window=1)
        # position 5+ never attends to position 2 under window=1
        numpy.testing.assert_allclose(numpy.asarray(a[:, 5:]),
                                      numpy.asarray(b[:, 5:]),
                                      rtol=1e-5, atol=1e-6)

    def test_char_lm_trains_with_rope_gqa_window(self):
        """End-to-end: the grammar sample converges with all three
        options on (and the sample helper decodes through the same
        configured path)."""
        prng.reset(); prng.seed_all(4)
        root.char_lm.update({
            "loader": {"minibatch_size": 32, "n_train": 256, "n_valid": 64,
                       "seq_len": 32, "vocab": 16},
            "trainer": {"vocab": 16, "d_model": 32, "n_heads": 4,
                        "n_layers": 1, "max_len": 32,
                        "learning_rate": 3e-3, "n_experts": 0,
                        "pipeline_stages": 0, "remat": False,
                        "n_kv_heads": 2, "rope": True, "window": 16},
            "decision": {"max_epochs": 6, "fail_iterations": 10},
        })
        from veles_tpu.samples import char_lm
        wf = char_lm.train()
        losses = [m["validation"]["loss"]
                  for m in wf.decision.epoch_metrics
                  if "validation" in m]
        assert losses[-1] < losses[0] * 0.7, losses
        out = char_lm.sample_tokens(wf, [[1, 2, 3]], n_new=5)
        assert out.shape == (1, 8)

    def test_bucketed_prompt_bit_exact_with_rope_gqa(self):
        """Serving composes prompt BUCKETING (traced true_len) with
        RoPE+GQA: right-padded decode must equal the unpadded decode —
        pad keys are rotated at pad positions but masked/overwritten,
        so rotation of dead slots can never leak in."""
        params = self._params(rope=True, n_kv_heads=2)
        prompt = jnp.asarray([[1, 2, 3, 4, 5]], jnp.int32)
        plain = numpy.asarray(T.generate(
            params, prompt, n_new=6, n_heads=4, temperature=0,
            max_len=16, rope=True))
        padded = jnp.pad(prompt, ((0, 0), (0, 3)))
        bucketed = numpy.asarray(T.generate(
            params, padded, n_new=6, n_heads=4, temperature=0,
            max_len=16, rope=True, true_len=5))
        numpy.testing.assert_array_equal(plain[:, 5:], bucketed[:, 8:])

    def test_pipeline_rejects_rope_window(self):
        from veles_tpu.workflow import Workflow
        wf = Workflow(None, name="w")
        with pytest.raises(ValueError, match="pipeline"):
            T.TransformerTrainer(wf, pipeline_stages=2, rope=True,
                                 name="t")


def test_char_lm_trains_on_real_text_file(tmp_path):
    """text_path switches the LM to a REAL byte-level corpus: vocab
    follows the data source (256), the validation split is by file
    position, loss drops, and the trained model continues text."""
    corpus = tmp_path / "corpus.txt"
    # highly regular text => provably reducible loss in a few epochs
    corpus.write_text("the quick brown fox jumps over the lazy dog. " * 300)
    prng.reset(); prng.seed_all(1)
    root.__dict__.pop("char_lm", None)
    root.char_lm.update({
        "loader": {"minibatch_size": 32, "n_train": 256, "n_valid": 64,
                   "seq_len": 32, "text_path": str(corpus)},
        "trainer": {"d_model": 64, "n_heads": 4, "n_layers": 1,
                    "max_len": 32, "learning_rate": 3e-3,
                    "n_experts": 0, "pipeline_stages": 0,
                    "remat": False},
        "decision": {"max_epochs": 6, "fail_iterations": 10},
    })
    from veles_tpu.samples import char_lm
    try:
        wf = char_lm.train()
        assert wf.trainer.vocab == 256       # followed the data source
        assert wf.loader.vocab == 256
        losses = [m["validation"]["loss"]
                  for m in wf.decision.epoch_metrics
                  if "validation" in m]
        assert losses[-1] < losses[0] * 0.75, losses
        prompt = numpy.frombuffer(b"the quick b",
                                  numpy.uint8)[None].astype(numpy.int32)
        out = char_lm.sample_tokens(wf, prompt, n_new=8)
        text = bytes(out[0].tolist()).decode("latin-1")
        assert text.startswith("the quick b")
        # every generated byte is printable ascii from the corpus
        assert all(31 < b < 127 for b in out[0][11:]), text
        # a stale-config mismatch (trainer vocab < loader's byte range)
        # must fail LOUDLY, not clamp-train on garbage
        root.char_lm.trainer.vocab = 16
        wf2 = char_lm.build()
        with pytest.raises(ValueError, match="vocab"):
            wf2.initialize()
        # a typo'd corpus path must not fall back to synthetic data
        root.__dict__.pop("char_lm", None)
        root.char_lm.update({
            "loader": {"text_path": str(tmp_path / "nope.txt")}})
        with pytest.raises(FileNotFoundError):
            char_lm.build().initialize()
    finally:
        # root is process-global: leave no text_path behind for later
        # char-LM tests (the tiny_config leak class)
        root.__dict__.pop("char_lm", None)


class TestRollingCache:
    """Unbounded decode in O(window) memory (ring-buffer KV cache) —
    the serving capstone of rope+window: no positional table, no
    max_len-sized cache, n_new limited by nothing."""

    def _params(self, n_kv_heads=None):
        prng.reset(); prng.seed_all(11)
        return jax.tree.map(jnp.asarray, T.init_transformer_params(
            prng.get("init"), vocab=16, d_model=32, n_heads=4,
            n_layers=2, max_len=16, n_kv_heads=n_kv_heads, rope=True))

    @pytest.mark.parametrize("kv", [
        # GQA (kv=2) is the superset shape; plain MHA rides the slow
        # suite (tier-1 runtime headroom)
        pytest.param(None, marks=pytest.mark.slow), 2])
    def test_matches_full_cache_generate(self, kv):
        params = self._params(n_kv_heads=kv)
        prompt = jnp.asarray([[1, 2, 3, 4, 5], [9, 8, 7, 6, 5]],
                             jnp.int32)
        full = numpy.asarray(T.generate(
            params, prompt, n_new=8, n_heads=4, temperature=0,
            max_len=16, rope=True, window=3))
        rolling = numpy.asarray(T.generate_rolling(
            params, prompt, n_new=8, n_heads=4, window=3,
            temperature=0))
        numpy.testing.assert_array_equal(full, rolling)
        # sampling path: same rng => same tokens
        key = jax.random.PRNGKey(2)
        full_s = numpy.asarray(T.generate(
            params, prompt, n_new=8, n_heads=4, rng=key,
            temperature=0.8, max_len=16, rope=True, window=3, top_k=8))
        roll_s = numpy.asarray(T.generate_rolling(
            params, prompt, n_new=8, n_heads=4, window=3, rng=key,
            temperature=0.8, top_k=8))
        numpy.testing.assert_array_equal(full_s, roll_s)

    def test_decodes_far_beyond_any_max_len(self):
        """The whole point: n_new that the full-cache path REJECTS
        (positional table and cache bound) decodes fine rolling."""
        params = self._params()
        prompt = jnp.asarray([[3, 1, 4, 1]], jnp.int32)
        with pytest.raises(ValueError):
            T.generate(params, prompt, n_new=100, n_heads=4,
                       temperature=0, max_len=16, rope=True, window=4)
        out = numpy.asarray(T.generate_rolling(
            params, prompt, n_new=100, n_heads=4, window=4,
            temperature=0))
        assert out.shape == (1, 104)
        assert out.min() >= 0 and out.max() < 16
        # short-window decode becomes eventually periodic for a greedy
        # deterministic model — sanity that it's not stuck on one token
        tail = out[0, -50:]
        assert len(set(tail.tolist())) >= 2

    def test_requires_rope_model(self):
        prng.reset(); prng.seed_all(11)
        params = jax.tree.map(jnp.asarray, T.init_transformer_params(
            prng.get("init"), vocab=16, d_model=32, n_heads=4,
            n_layers=1, max_len=16))      # learned pos table
        prompt = jnp.asarray([[1, 2]], jnp.int32)
        with pytest.raises(ValueError, match="RoPE"):
            T.generate_rolling(params, prompt, n_new=4, n_heads=4,
                               window=2, temperature=0)


class TestAttentionSinksDecode:
    """sinks must hold at DECODE time too — prefill/train masks and both
    KV-cache forms (linear and ring, where sinks are physically pinned
    slots) all agree."""

    def _params(self):
        prng.reset(); prng.seed_all(13)
        return jax.tree.map(jnp.asarray, T.init_transformer_params(
            prng.get("init"), vocab=16, d_model=32, n_heads=4,
            n_layers=2, max_len=24, rope=True))

    def test_full_cache_decode_matches_forward(self):
        """Greedy decode with window+sinks reproduces the full
        forward's argmax at every step (no train/serve mask drift —
        the exact scenario sinks exist for)."""
        params = self._params()
        prompt = jnp.asarray([[7, 3, 9, 1]], jnp.int32)
        out = numpy.asarray(T.generate(
            params, prompt, n_new=10, n_heads=4, temperature=0,
            max_len=24, rope=True, window=3, sinks=2))[0]
        seq = list(map(int, prompt[0]))
        for _ in range(10):
            logits = T.transformer_forward(
                params, jnp.asarray([seq], jnp.int32), n_heads=4,
                rope=True, window=3, sinks=2)
            nxt = int(jnp.argmax(logits[0, -1]))
            assert nxt == int(out[len(seq)]), seq
            seq.append(nxt)

    def test_rolling_matches_full_cache_and_runs_unbounded(self):
        params = self._params()
        prompt = jnp.asarray([[2, 4, 6, 8, 1]], jnp.int32)
        full = numpy.asarray(T.generate(
            params, prompt, n_new=12, n_heads=4, temperature=0,
            max_len=24, rope=True, window=4, sinks=2))
        rolling = numpy.asarray(T.generate_rolling(
            params, prompt, n_new=12, n_heads=4, window=4, sinks=2,
            temperature=0))
        numpy.testing.assert_array_equal(full, rolling)
        # unbounded with pinned sinks: far beyond the pos table bound
        out = numpy.asarray(T.generate_rolling(
            params, prompt, n_new=80, n_heads=4, window=4, sinks=2,
            temperature=0))
        assert out.shape == (1, 85)
        assert out.min() >= 0 and out.max() < 16

    def test_trainer_sinks_require_window(self):
        from veles_tpu.workflow import Workflow
        wf = Workflow(None, name="w")
        with pytest.raises(ValueError, match="window"):
            T.TransformerTrainer(wf, attn_sinks=2, name="t")

    def test_char_lm_trains_with_sinks(self):
        prng.reset(); prng.seed_all(5)
        root.__dict__.pop("char_lm", None)
        root.char_lm.update({
            "loader": {"minibatch_size": 32, "n_train": 256,
                       "n_valid": 64, "seq_len": 32, "vocab": 16},
            "trainer": {"vocab": 16, "d_model": 32, "n_heads": 4,
                        "n_layers": 1, "max_len": 32,
                        "learning_rate": 3e-3, "n_experts": 0,
                        "pipeline_stages": 0, "remat": False,
                        "rope": True, "window": 8, "attn_sinks": 2},
            "decision": {"max_epochs": 6, "fail_iterations": 10},
        })
        from veles_tpu.samples import char_lm
        wf = char_lm.train()
        losses = [m["validation"]["loss"]
                  for m in wf.decision.epoch_metrics
                  if "validation" in m]
        assert losses[-1] < losses[0] * 0.7, losses
