"""Tier-3 functional tests: seeded MNIST-FC convergence (SURVEY §4 tier 3).

Mirrors the reference's znicz functional tests: pinned seed, small epoch
budget, assert bounded validation error, plus fused/unit-mode equivalence
(our analogue of their numpy-vs-device backend cross-check).
"""

import numpy

from veles_tpu import prng
from veles_tpu.config import root


def _configure(n_train=1000, n_valid=300, max_epochs=3, mb=100):
    root.mnist.update({
        "loader": {"minibatch_size": mb, "n_train": n_train,
                   "n_valid": n_valid},
        "decision": {"max_epochs": max_epochs, "fail_iterations": 50},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 64,
             "learning_rate": 0.03, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.03, "momentum": 0.9},
        ],
    })


def test_mnist_converges_fused():
    prng.reset(); prng.seed_all(42)
    _configure()
    from veles_tpu.samples import mnist
    wf = mnist.train(fused=True)
    metrics = wf.decision.epoch_metrics
    assert len(metrics) <= 3
    final_val = metrics[-1]["validation"]
    assert final_val["err_pct"] < 5.0, final_val
    # loss decreased epoch over epoch
    losses = [m["validation"]["loss"] for m in metrics]
    assert losses[-1] < losses[0]


def test_fused_and_unit_mode_identical():
    from veles_tpu.samples import mnist
    finals, weights = [], []
    for fused in (True, False):
        prng.reset(); prng.seed_all(42)
        _configure(n_train=500, n_valid=200, max_epochs=2)
        wf = mnist.train(fused=fused)
        finals.append(wf.decision.epoch_metrics[-1]["validation"])
        # snapshot_state syncs fused device state back into the Vectors
        wf.snapshot_state()
        weights.append([numpy.array(f.weights.mem) for f in wf.forwards])
    assert finals[0]["n_err"] == finals[1]["n_err"]
    assert abs(finals[0]["loss"] - finals[1]["loss"]) < 1e-5
    # FINAL WEIGHTS must match exactly too — catches divergence in how the
    # last train minibatch is gated (decision.complete skips the update)
    for wa, wb in zip(weights[0], weights[1]):
        numpy.testing.assert_allclose(wa, wb, rtol=1e-6, atol=1e-7)


def test_gd_skipped_on_validation_minibatches():
    """Weights must not change during the validation portion of an epoch."""
    prng.reset(); prng.seed_all(42)
    _configure(n_train=300, n_valid=200, max_epochs=1)
    from veles_tpu.samples import mnist
    wf = mnist.build(fused=False)
    wf.initialize()
    w_before = numpy.array(wf.forwards[0].weights.mem)
    # run the validation portion only: 2 minibatches of 100
    wf.loader.run()
    wf.evaluator.output  # touch to ensure links resolve
    for unit in (wf.forwards[0], wf.forwards[1], wf.evaluator, wf.decision):
        unit.run()
    assert bool(wf.decision.gd_skip)          # VALID minibatch -> no GD
    numpy.testing.assert_array_equal(w_before, wf.forwards[0].weights.mem)


def test_decision_fail_iterations_early_stop():
    """With an unlearnable lr=0 the run must stop via fail_iterations."""
    prng.reset(); prng.seed_all(42)
    root.mnist.update({
        "loader": {"minibatch_size": 100, "n_train": 200, "n_valid": 100},
        "decision": {"max_epochs": 50, "fail_iterations": 2},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.0},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.0},
        ],
    })
    from veles_tpu.samples import mnist
    wf = mnist.train(fused=True)
    n_epochs = len(wf.decision.epoch_metrics)
    assert n_epochs <= 4  # 1 improving epoch + 2 failing + margin


def test_snapshot_state_roundtrip_weights():
    prng.reset(); prng.seed_all(42)
    _configure(n_train=300, n_valid=100, max_epochs=1)
    from veles_tpu.samples import mnist
    wf = mnist.train(fused=True)
    state = wf.snapshot_state()
    w_trained = numpy.array(wf.forwards[0].weights.mem)

    prng.reset(); prng.seed_all(7)  # different seed: different init
    _configure(n_train=300, n_valid=100, max_epochs=1)
    wf2 = mnist.build(fused=True)
    wf2.initialize()
    wf2.load_snapshot_state(state)
    numpy.testing.assert_array_equal(
        w_trained, wf2.forwards[0].weights.mem)
