"""Snapshot / resume / CLI tests (SURVEY §4 tier-1 + tier-3 resume
equivalence, ref: veles snapshotter round-trip + functional resume tests)."""

import json
import os
import subprocess
import sys

import numpy
import pytest


def _mnist_config(max_epochs=3, n_train=192, n_valid=64, mb=64,
                  snapshotter=None):
    from veles_tpu.config import root
    root.__dict__.pop("mnist", None)   # fresh subtree per test
    cfg = {
        "loader": {"minibatch_size": mb, "n_train": n_train,
                   "n_valid": n_valid},
        "decision": {"max_epochs": max_epochs, "fail_iterations": 50},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.05, "momentum": 0.9},
        ],
    }
    if snapshotter is not None:
        cfg["snapshotter"] = snapshotter
    root.mnist.update(cfg)


def _weights(wf):
    runner = getattr(wf, "_fused_runner", None)
    if runner is not None:
        runner.sync_to_units()
    return [f.weights.to_numpy() for f in wf.forwards if f.has_params]


def test_snapshot_compressions(tmp_path):
    from veles_tpu import snapshotter
    from veles_tpu.samples import mnist

    _mnist_config(max_epochs=1)
    for comp in ("", "gz", "bz2", "xz"):
        from veles_tpu import prng
        prng.reset()
        prng.seed_all(1)
        wf = mnist.build(snapshotter_config={
            "directory": str(tmp_path / ("c_" + (comp or "none"))),
            "compression": comp})
        wf.initialize()
        wf.run()
        path = wf.snapshotter.destination
        assert path and os.path.exists(path)
        payload = snapshotter.import_(path)
        assert payload["epoch"] == 1
        state = payload["state"]
        w = state["units"]["All2AllTanh"]["weights"]
        assert w[0] == "__vector__"
        numpy.testing.assert_array_equal(w[1], _weights(wf)[0])


def test_resume_equivalence(tmp_path):
    """Resuming a MID-RUN snapshot (crash recovery) reproduces the straight
    run bit-exactly: 3-epoch run writing per-epoch snapshots == restore the
    epoch-2 file in a fresh process and run the remaining epoch.

    (A snapshot taken at COMPLETION intentionally differs from a longer
    straight run: the `complete` gate skips the final minibatch's update —
    reference gds gating semantics, veles/znicz/standard_workflow.py [H].)
    """
    import glob
    from veles_tpu import prng, snapshotter
    from veles_tpu.samples import mnist

    # ---- straight run: 3 epochs, snapshot written at every epoch boundary
    _mnist_config(max_epochs=3)
    straight = mnist.train(snapshotter_config={"directory": str(tmp_path)})
    w_straight = _weights(straight)
    mid_files = glob.glob(str(tmp_path / "mnist_2_*.pickle.gz"))
    assert len(mid_files) == 1
    payload = snapshotter.import_(mid_files[0])
    assert payload["epoch"] == 2

    # ---- fresh process state, restore epoch-2, run the remaining epoch.
    # Same boot seed: the synthetic DATASET is generated from the PRNG at
    # load time, so a different seed would mean a different dataset — the
    # on-disk-data analogue is "point the resumed run at the same files".
    # All run-state randomness (shuffle order, dropout) comes from the
    # snapshot's restored stream states, not this seed.
    prng.reset()
    prng.seed_all(1)
    _mnist_config(max_epochs=3)
    resumed = mnist.build()
    resumed.initialize()
    snapshotter.restore(resumed, mid_files[0])
    assert not bool(resumed.decision.complete)
    assert int(resumed.loader.epoch_number) == 2
    resumed.run()
    w_resumed = _weights(resumed)

    assert int(resumed.loader.epoch_number) == 3
    for a, b in zip(w_straight, w_resumed):
        numpy.testing.assert_allclose(a, b, rtol=0, atol=0)


def test_resume_restores_decision_history(tmp_path):
    from veles_tpu import prng, snapshotter
    from veles_tpu.samples import mnist

    _mnist_config(max_epochs=2)
    wf = mnist.train(snapshotter_config={"directory": str(tmp_path)})
    payload = snapshotter.import_(wf.snapshotter.destination)

    prng.reset()
    prng.seed_all(1)
    _mnist_config(max_epochs=2)
    fresh = mnist.build()
    fresh.initialize()
    snapshotter.restore(fresh, payload)
    assert fresh.decision.best_metric == wf.decision.best_metric
    assert fresh.decision.best_epoch == wf.decision.best_epoch
    assert len(fresh.decision.epoch_metrics) == 2
    # completed run stays complete when limits are unchanged
    assert bool(fresh.decision.complete)


def test_cli_end_to_end(tmp_path):
    """The reference's `veles <workflow> <config>` ergonomics (SURVEY §3.1)."""
    result_file = tmp_path / "result.json"
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    cmd = [
        sys.executable, "-m", "veles_tpu", "veles_tpu.samples.mnist",
        "-d", "cpu", "--random-seed", "7", "--no-stats",
        "--result-file", str(result_file),
        "--snapshot-dir", str(tmp_path),
        "root.mnist.loader.n_train=128", "root.mnist.loader.n_valid=64",
        "root.mnist.loader.minibatch_size=64",
        "root.mnist.decision.max_epochs=1",
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True, env=env,
                          cwd="/root/repo", timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    summary = json.loads(result_file.read_text())
    assert summary["workflow"] == "mnist"
    assert summary["best_epoch"] >= 0
    assert os.path.exists(summary["snapshot"])


def test_cli_dump_config_and_list_units(tmp_path):
    env = dict(os.environ)
    proc = subprocess.run(
        [sys.executable, "-m", "veles_tpu", "veles_tpu.samples.mnist",
         "--dump-config", "root.x.y=3"],
        capture_output=True, text=True, env=env, cwd="/root/repo",
        timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "y: 3" in proc.stdout
    proc = subprocess.run(
        [sys.executable, "-m", "veles_tpu", "veles_tpu.samples.mnist",
         "--list-units"],
        capture_output=True, text=True, env=env, cwd="/root/repo",
        timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "veles_tpu.units.TrivialUnit" in proc.stdout


def test_snapshotter_skip_gates_stop_write(tmp_path):
    """skip=True must suppress BOTH the periodic write and the final
    stop() write — an evaluation-only run touches no lineage."""
    from veles_tpu import prng
    from veles_tpu.config import root
    prng.reset(); prng.seed_all(1)
    _mnist_config(max_epochs=1, n_train=100, n_valid=50, mb=50,
                  snapshotter={"directory": str(tmp_path), "interval": 1})
    from veles_tpu.samples import mnist
    wf = mnist.build(fused=True)
    try:
        wf.initialize()
        wf.snapshotter.skip.set(True)
        wf.run()
        assert bool(wf.decision.complete)
        assert wf.snapshotter.destination is None
        assert not list(tmp_path.glob("*.pickle*"))
    finally:
        # the snapshotter config must not leak into later tests that
        # share the process-global root
        root.__dict__.pop("mnist", None)


def test_atomic_write_and_corrupt_rejection(tmp_path):
    """Satellite (ISSUE 11): snapshots publish via temp-file + fsync +
    atomic rename, so a crash mid-write leaves only a ``*.tmp``
    staging file — the old snapshot still resolves and loads — and
    the loader rejects a partial/corrupt file with a LOUD ValueError
    (the model_manager's publish loop must never act on one)."""
    from veles_tpu import snapshotter

    class _WF:
        name = "t"

        @staticmethod
        def snapshot_state():
            return {"units": {}, "prng": {}}

    path = str(tmp_path / "wf_current.pickle.gz")
    snapshotter.save(_WF(), path)
    assert snapshotter.import_(path)["format"] == snapshotter.FORMAT
    # no staging residue after a clean save
    assert not list(tmp_path.glob("*.tmp"))
    # "kill mid-write": the staging file exists, truncated — the
    # resolver must ignore it and keep serving the OLD snapshot
    (tmp_path / "wf_current.pickle.gz.tmp").write_bytes(
        (tmp_path / "wf_current.pickle.gz").read_bytes()[:17])
    assert snapshotter.find_current(str(tmp_path)) == path
    assert snapshotter.import_(path)["workflow_name"] == "t"
    # a truncated published file (torn copy, not our writer) is a loud
    # structured refusal, not a codec traceback
    bad = tmp_path / "bad_current.pickle.gz"
    whole = (tmp_path / "wf_current.pickle.gz").read_bytes()
    bad.write_bytes(whole[:25])
    with pytest.raises(ValueError, match="corrupt or truncated"):
        snapshotter.import_(str(bad))
    # garbage that is not a pickled archive at all
    raw = tmp_path / "junk_current.pickle"
    raw.write_bytes(b"this is not a snapshot")
    with pytest.raises(ValueError, match="corrupt or truncated"):
        snapshotter.import_(str(raw))
    # a valid pickle that is not a snapshot payload
    import pickle
    notsnap = tmp_path / "n_current.pickle"
    notsnap.write_bytes(pickle.dumps(["not", "a", "payload"]))
    with pytest.raises(ValueError, match="format"):
        snapshotter.import_(str(notsnap))


def test_snapshotter_keep_last_prunes(tmp_path):
    """keep_last retains only the newest N epoch files; the *_current
    pointer survives so --snapshot auto still resumes."""
    from veles_tpu import prng
    from veles_tpu.config import root
    prng.reset(); prng.seed_all(1)
    _mnist_config(max_epochs=5, n_train=100, n_valid=50, mb=50,
                  snapshotter={"directory": str(tmp_path), "interval": 1,
                               "keep_last": 2})
    from veles_tpu.samples import mnist
    try:
        wf = mnist.train(fused=True)
        suffix = wf.snapshotter._suffix()
        prefix = wf.snapshotter.prefix
        epoch_files = [p for p in tmp_path.iterdir()
                       if p.name.endswith(suffix)
                       and not p.name.startswith(prefix + "_current")]
        assert len(epoch_files) == 2, sorted(p.name
                                             for p in tmp_path.iterdir())
        current = [p for p in tmp_path.iterdir()
                   if p.name.startswith(prefix + "_current")]
        assert current, "the resume pointer must never be pruned"
        from veles_tpu import snapshotter
        assert snapshotter.find_current(str(tmp_path)) is not None
    finally:
        root.__dict__.pop("mnist", None)
