"""Pipeline parallelism (GPipe over the transformer block stack).

Equivalence contract: the staged, microbatched, ppermute-scheduled
pipeline computes EXACTLY the sequential stack — forward loss and every
gradient — on the virtual CPU mesh (SURVEY §4 loopback-style proof).
"""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu import prng
from veles_tpu.ops.transformer import (init_transformer_params, lm_loss)
from veles_tpu.parallel.pipeline import (make_pipeline_mesh, stack_blocks,
                                         unstack_blocks, pipeline_blocks,
                                         pipeline_lm_loss)

VOCAB, D_MODEL, N_HEADS, N_LAYERS, SEQ = 32, 16, 2, 4, 17


def _setup(seed=3):
    prng.reset()
    prng.seed_all(seed)
    params = init_transformer_params(
        prng.get("init"), VOCAB, d_model=D_MODEL, n_heads=N_HEADS,
        n_layers=N_LAYERS, max_len=64)
    params = jax.tree.map(jnp.asarray, params)
    rng = numpy.random.RandomState(7)
    tokens = jnp.asarray(rng.randint(0, VOCAB, (8, SEQ)), jnp.int32)
    mask = jnp.ones(8, jnp.float32)
    return params, tokens, mask


# one geometry stays tier-1 (the deepest microbatching); the other two
# re-verify the same loss/grad parity property at ~19 s apiece — the
# tier-1 suite runs within ~2% of its outer watchdog, so the redundant
# geometries ride in the slow suite
@pytest.mark.parametrize("n_stages,n_micro", [
    pytest.param(4, 4, marks=pytest.mark.slow),
    (2, 8),
    pytest.param(4, 2, marks=pytest.mark.slow),
])
def test_pipeline_matches_sequential_loss_and_grads(n_stages, n_micro):
    params, tokens, mask = _setup()
    mesh = make_pipeline_mesh(n_stages)

    ref_loss, ref_grads = jax.value_and_grad(lm_loss)(
        params, tokens, mask, N_HEADS)

    stacked = dict(params, blocks=stack_blocks(params["blocks"]))

    def pp_loss(p):
        return pipeline_lm_loss(p, tokens, mask, N_HEADS, mesh, n_micro)

    pp_loss_val, pp_grads = jax.value_and_grad(pp_loss)(stacked)

    numpy.testing.assert_allclose(float(pp_loss_val), float(ref_loss),
                                  rtol=1e-5, atol=1e-6)
    # non-block params: embed/pos/ln_f grads must match directly
    for key in ("embed", "pos", "ln_f"):
        jax.tree.map(
            lambda a, b: numpy.testing.assert_allclose(
                numpy.asarray(a), numpy.asarray(b), rtol=2e-4, atol=1e-5),
            pp_grads[key], ref_grads[key])
    # block grads: unstack the pipeline's stacked grads layer by layer
    unstacked = unstack_blocks(pp_grads["blocks"], N_LAYERS)
    for i, (pp_blk, ref_blk) in enumerate(zip(unstacked,
                                              ref_grads["blocks"])):
        jax.tree.map(
            lambda a, b: numpy.testing.assert_allclose(
                numpy.asarray(a), numpy.asarray(b), rtol=2e-4, atol=1e-5,
                err_msg="block %d grad diverged under PP" % i),
            pp_blk, ref_blk)


def test_pipeline_blocks_forward_only():
    """Activation-level equality of the staged block stack."""
    from veles_tpu.ops.transformer import block_forward
    params, tokens, _ = _setup(seed=5)
    h = jnp.take(params["embed"], tokens, axis=0) + params["pos"][:SEQ]
    ref = h
    for blk in params["blocks"]:
        ref = block_forward(blk, ref, N_HEADS)
    mesh = make_pipeline_mesh(4)
    out = pipeline_blocks(stack_blocks(params["blocks"]), h, mesh,
                          N_HEADS, n_microbatches=4)
    # tolerance matches the grad test above: shard_map backends fuse
    # the stage body differently across jax versions (0.4.x experimental
    # vs jax.shard_map), shifting last-ulp rounding on a few elements
    numpy.testing.assert_allclose(numpy.asarray(out), numpy.asarray(ref),
                                  rtol=2e-4, atol=1e-5)


def test_pipeline_shape_guards():
    params, tokens, mask = _setup()
    mesh = make_pipeline_mesh(4)
    stacked = stack_blocks(params["blocks"])
    h = jnp.zeros((8, SEQ - 1, D_MODEL))
    with pytest.raises(ValueError, match="n_microbatches"):
        pipeline_blocks(stacked, h, mesh, N_HEADS, n_microbatches=3)
    mesh3 = make_pipeline_mesh(3)
    with pytest.raises(ValueError, match="n_stages"):
        pipeline_blocks(stacked, h, mesh3, N_HEADS, n_microbatches=4)
