"""Adaptive solvers (adagrad / adadelta) — the reference's ADADELTA-style
per-unit optimizer options (ref: veles/znicz/nn_units.py::GradientDescentBase
[H], SURVEY §2.3 row 1).

Tier 1: adaptive_update math vs a numpy oracle; momentum mode must delegate
bit-for-bit to sgd_update.  Tier 3: a per-layer-configured adadelta MNIST run
converges, fused ≡ unit mode, and the accumulators survive a snapshot
round-trip.
"""

import numpy
import pytest

from veles_tpu import prng
from veles_tpu.config import root


def _np_effective_grad(p, g, bs, wd, l1_vs_l2, clip):
    g = g / max(bs, 1)
    if clip:
        g = numpy.clip(g, -clip, clip)
    if wd:
        g = g + wd * (l1_vs_l2 * numpy.sign(p) + (1 - l1_vs_l2) * p)
    return g


class TestAdaptiveUpdate:
    def setup_method(self):
        from veles_tpu.ops import functional as F
        self.F = F
        rng = numpy.random.RandomState(7)
        self.p = rng.randn(6, 5).astype(numpy.float32)
        self.g = rng.randn(6, 5).astype(numpy.float32) * 4
        self.v = rng.rand(6, 5).astype(numpy.float32)
        self.a = rng.rand(6, 5).astype(numpy.float32)

    def test_momentum_delegates_to_sgd_update(self):
        import jax.numpy as jnp
        args = (jnp.asarray(self.p), jnp.asarray(self.v))
        ref_p, ref_v = self.F.sgd_update(*args, jnp.asarray(self.g), 4,
                                         0.05, 0.9, 0.01, 0.3, 0.5)
        new_p, new_v, new_a = self.F.adaptive_update(
            *args, None, jnp.asarray(self.g), 4, 0.05, 0.9, 0.01, 0.3, 0.5,
            solver="momentum")
        assert new_a is None
        numpy.testing.assert_array_equal(numpy.array(ref_p),
                                         numpy.array(new_p))
        numpy.testing.assert_array_equal(numpy.array(ref_v),
                                         numpy.array(new_v))

    def test_adagrad_matches_numpy_oracle(self):
        import jax.numpy as jnp
        lr, eps, bs, wd, mix, clip = 0.1, 1e-6, 4, 0.01, 0.25, 1.0
        new_p, new_v, new_a = self.F.adaptive_update(
            jnp.asarray(self.p), jnp.asarray(self.v), jnp.asarray(self.a),
            jnp.asarray(self.g), bs, lr, 0.0, wd, mix, clip,
            solver="adagrad", epsilon=eps)
        g = _np_effective_grad(self.p, self.g, bs, wd, mix, clip)
        acc = self.a + g * g
        exp_p = self.p - lr * g / numpy.sqrt(acc + eps)
        numpy.testing.assert_allclose(numpy.array(new_a), acc, rtol=1e-6)
        numpy.testing.assert_allclose(numpy.array(new_p), exp_p, rtol=1e-5)
        # velocity slot passes through untouched
        numpy.testing.assert_array_equal(numpy.array(new_v), self.v)

    def test_adadelta_matches_numpy_oracle(self):
        import jax.numpy as jnp
        lr, rho, eps, bs = 1.0, 0.9, 1e-6, 2
        new_p, new_v, new_a = self.F.adaptive_update(
            jnp.asarray(self.p), jnp.asarray(self.v), jnp.asarray(self.a),
            jnp.asarray(self.g), bs, lr, 0.0, 0.0, 0.0, None,
            solver="adadelta", rho=rho, epsilon=eps)
        g = self.g / bs
        acc = rho * self.a + (1 - rho) * g * g
        dx = -lr * numpy.sqrt(self.v + eps) / numpy.sqrt(acc + eps) * g
        vel = rho * self.v + (1 - rho) * dx * dx
        numpy.testing.assert_allclose(numpy.array(new_a), acc, rtol=1e-6)
        numpy.testing.assert_allclose(numpy.array(new_p), self.p + dx,
                                      rtol=1e-5)
        numpy.testing.assert_allclose(numpy.array(new_v), vel, rtol=1e-6)

    def test_adadelta_moves_without_lr_tuning(self):
        """The point of adadelta: usable step sizes from lr=1.0 cold."""
        import jax.numpy as jnp
        p = jnp.zeros((4, 4))
        v = jnp.zeros((4, 4))
        a = jnp.zeros((4, 4))
        g = jnp.ones((4, 4))
        p, v, a = self.F.adaptive_update(p, v, a, g, 1, 1.0, 0.0, 0.0, 0.0,
                                         None, solver="adadelta")
        step = float(numpy.abs(numpy.array(p)).max())
        assert 0 < step < 0.1   # small, bounded first step

    def test_adam_matches_numpy_oracle(self):
        import jax.numpy as jnp
        lr, b1, b2, eps, bs, t = 0.001, 0.9, 0.999, 1e-8, 2, 7
        new_p, new_v, new_a = self.F.adaptive_update(
            jnp.asarray(self.p), jnp.asarray(self.v), jnp.asarray(self.a),
            jnp.asarray(self.g), bs, lr, b1, 0.0, 0.0, None,
            solver="adam", rho=b2, epsilon=eps, step=t)
        g = self.g / bs
        vel = b1 * self.v + (1 - b1) * g
        acc = b2 * self.a + (1 - b2) * g * g
        m_hat = vel / (1 - b1 ** (t + 1))
        v_hat = acc / (1 - b2 ** (t + 1))
        exp_p = self.p - lr * m_hat / (numpy.sqrt(v_hat) + eps)
        numpy.testing.assert_allclose(numpy.array(new_v), vel, rtol=1e-6)
        numpy.testing.assert_allclose(numpy.array(new_a), acc, rtol=1e-6)
        numpy.testing.assert_allclose(numpy.array(new_p), exp_p, rtol=1e-5)

    def test_adam_default_beta1_when_momentum_unset(self):
        """momentum=None (unset) means the standard β1=0.9, while an
        EXPLICIT momentum=0.0 is honored as β1=0 (first-moment smoothing
        off) — a truthiness test would silently promote it to 0.9
        (ADVICE r4)."""
        import jax.numpy as jnp
        args = (jnp.asarray(self.p), jnp.asarray(self.v),
                jnp.asarray(self.a), jnp.asarray(self.g), 1, 0.01)
        explicit = self.F.adaptive_update(*args, 0.9, 0.0, 0.0, None,
                                          solver="adam", step=0)
        default = self.F.adaptive_update(*args, None, 0.0, 0.0, None,
                                         solver="adam", step=0)
        for e, d in zip(explicit, default):
            numpy.testing.assert_array_equal(numpy.array(e),
                                             numpy.array(d))
        # explicit 0.0 must DIFFER from the default (m_hat becomes g)
        zero = self.F.adaptive_update(*args, 0.0, 0.0, 0.0, None,
                                      solver="adam", step=0)
        assert not numpy.allclose(numpy.array(zero[0]),
                                  numpy.array(default[0]))

    def test_unknown_solver_raises(self):
        with pytest.raises(ValueError):
            self.F.adaptive_update(self.p, self.v, self.a, self.g, 1, 0.1,
                                   0.0, 0.0, 0.0, None, solver="rmsprop")


def _configure(solver, n_train=500, n_valid=200, max_epochs=3, lr=0.5):
    root.mnist.update({
        "loader": {"minibatch_size": 100, "n_train": n_train,
                   "n_valid": n_valid},
        "decision": {"max_epochs": max_epochs, "fail_iterations": 50},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 64,
             "<-": {"learning_rate": lr, "solver": solver}},
            {"type": "softmax", "output_sample_shape": 10,
             "<-": {"learning_rate": lr, "solver": solver}},
        ],
    })


class TestSolverWorkflows:
    @pytest.mark.parametrize("solver", ["adagrad", "adadelta", "adam"])
    def test_converges_fused(self, solver):
        prng.reset(); prng.seed_all(42)
        lr = {"adagrad": 0.5, "adadelta": 1.0, "adam": 0.005}[solver]
        _configure(solver, lr=lr)
        from veles_tpu.samples import mnist
        wf = mnist.train(fused=True)
        metrics = wf.decision.epoch_metrics
        losses = [m["validation"]["loss"] for m in metrics]
        assert losses[-1] < losses[0]
        assert metrics[-1]["validation"]["err_pct"] < 15.0

    def test_fused_and_unit_mode_identical_adadelta(self):
        from veles_tpu.samples import mnist
        finals, weights = [], []
        for fused in (True, False):
            prng.reset(); prng.seed_all(42)
            _configure("adadelta", max_epochs=2, lr=1.0)
            wf = mnist.train(fused=fused)
            finals.append(wf.decision.epoch_metrics[-1]["validation"])
            wf.snapshot_state()
            weights.append([numpy.array(f.weights.mem) for f in wf.forwards])
        assert finals[0]["n_err"] == finals[1]["n_err"]
        assert abs(finals[0]["loss"] - finals[1]["loss"]) < 1e-5
        for wa, wb in zip(weights[0], weights[1]):
            numpy.testing.assert_allclose(wa, wb, rtol=1e-6, atol=1e-7)

    def test_accumulators_survive_snapshot_roundtrip(self):
        from veles_tpu.samples import mnist
        prng.reset(); prng.seed_all(42)
        _configure("adadelta", max_epochs=1, lr=1.0)
        wf = mnist.train(fused=True)
        state = wf.snapshot_state()
        gd = wf.gds[0]
        acc_before = numpy.array(gd.accum_weights.mem)
        assert acc_before.any()   # training actually fed the accumulator
        # a fresh workflow restored from the state carries the accumulators
        prng.reset(); prng.seed_all(7)
        _configure("adadelta", max_epochs=1, lr=1.0)
        wf2 = mnist.build(fused=False)
        wf2.initialize()
        wf2.load_snapshot_state(state)
        numpy.testing.assert_array_equal(
            numpy.array(wf2.gds[0].accum_weights.mem), acc_before)
        numpy.testing.assert_array_equal(
            numpy.array(wf2.forwards[0].weights.mem),
            numpy.array(wf.forwards[0].weights.mem))

    def test_momentum_snapshot_resumes_under_adaptive_solver(self):
        """Fine-tune flow: a snapshot trained with the default momentum
        solver restores into an adadelta-configured workflow — the empty
        snapshot accumulators must not clear the fresh zeros, and the
        resumed run must train without tracing errors."""
        from veles_tpu.samples import mnist
        prng.reset(); prng.seed_all(42)
        root.mnist.update({
            "loader": {"minibatch_size": 100, "n_train": 300, "n_valid": 100},
            "decision": {"max_epochs": 1, "fail_iterations": 50},
            "layers": [
                {"type": "all2all_tanh", "output_sample_shape": 32,
                 "<-": {"learning_rate": 0.05, "momentum": 0.9}},
                {"type": "softmax", "output_sample_shape": 10,
                 "<-": {"learning_rate": 0.05, "momentum": 0.9}},
            ],
        })
        wf = mnist.train(fused=True)
        state = wf.snapshot_state()

        prng.reset(); prng.seed_all(42)
        root.mnist.update({
            "decision": {"max_epochs": 2, "fail_iterations": 50},
            "layers": [
                {"type": "all2all_tanh", "output_sample_shape": 32,
                 "<-": {"learning_rate": 1.0, "solver": "adadelta"}},
                {"type": "softmax", "output_sample_shape": 10,
                 "<-": {"learning_rate": 1.0, "solver": "adadelta"}},
            ],
        })
        wf2 = mnist.build(fused=True)
        wf2.initialize()
        wf2.load_snapshot_state(state)
        gd = wf2.gds[0]
        assert not gd.accum_weights.is_empty        # zeros preserved
        assert not numpy.array(gd.accum_weights.mem).any()
        # momentum velocities are signed; adadelta must NOT inherit them
        # as its E[dx^2] memory (sqrt of a negative entry -> NaN weights)
        assert not numpy.array(gd.velocity_weights.mem).any()
        # params DID carry over from the momentum run's snapshot
        numpy.testing.assert_array_equal(
            numpy.array(wf2.forwards[0].weights.mem),
            numpy.array(wf.forwards[0].weights.mem))
        wf2.run()                                   # trains, no trace error
        wf2.snapshot_state()                        # sync fused state back
        w = numpy.array(wf2.forwards[0].weights.mem)
        assert numpy.isfinite(w).all()              # the NaN regression
        assert numpy.array(gd.accum_weights.mem).any()
        losses = [m["validation"]["loss"]
                  for m in wf2.decision.epoch_metrics]
        assert numpy.isfinite(losses).all()
