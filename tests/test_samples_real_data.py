"""Real-data path + sample-zoo tail (VERDICT r3 Missing #4).

- MnistLoader._load_real/_read_idx against tiny on-disk IDX fixtures
  (plain and gzipped), ref: veles/loader/mnist.py [H] IDX decode;
- the MNIST-conv sample (conv topology over 28x28x1);
- the directory-image sample driving loader/image.py end to end.
"""

import gzip
import struct

import numpy
import pytest


# ------------------------------------------------------------- IDX fixtures
def _write_idx_images(path, arr, compress=False):
    """IDX3 ubyte image file (magic 0x00000803), optionally gzipped."""
    header = struct.pack(">IIII", 0x00000803, *arr.shape)
    payload = header + arr.astype(numpy.uint8).tobytes()
    opener = gzip.open if compress else open
    with opener(path, "wb") as f:
        f.write(payload)


def _write_idx_labels(path, labels, compress=False):
    header = struct.pack(">II", 0x00000801, len(labels))
    payload = header + labels.astype(numpy.uint8).tobytes()
    opener = gzip.open if compress else open
    with opener(path, "wb") as f:
        f.write(payload)


def _make_mnist_dir(tmp_path, n_train=30, n_valid=20, compress=False):
    rng = numpy.random.RandomState(0)
    suffix = ".gz" if compress else ""
    train_x = rng.randint(0, 256, (n_train, 28, 28), numpy.uint8)
    train_y = (numpy.arange(n_train) % 10).astype(numpy.uint8)
    test_x = rng.randint(0, 256, (n_valid, 28, 28), numpy.uint8)
    test_y = (numpy.arange(n_valid) % 10).astype(numpy.uint8)
    _write_idx_images(str(tmp_path / ("train-images-idx3-ubyte" + suffix)),
                      train_x, compress)
    _write_idx_labels(str(tmp_path / ("train-labels-idx1-ubyte" + suffix)),
                      train_y, compress)
    _write_idx_images(str(tmp_path / ("t10k-images-idx3-ubyte" + suffix)),
                      test_x, compress)
    _write_idx_labels(str(tmp_path / ("t10k-labels-idx1-ubyte" + suffix)),
                      test_y, compress)
    return train_x, train_y, test_x, test_y


@pytest.mark.parametrize("compress", [False, True],
                         ids=["plain", "gzipped"])
def test_mnist_load_real_idx(tmp_path, compress):
    from veles_tpu.samples.mnist import MnistLoader
    train_x, train_y, test_x, test_y = _make_mnist_dir(
        tmp_path, compress=compress)
    loader = MnistLoader(None, n_train=30, n_valid=20,
                         data_dir=str(tmp_path), minibatch_size=10,
                         name="loader")
    loader.initialize()
    assert loader.class_lengths == [0, 20, 30]
    data = numpy.asarray(loader.original_data.mem)
    assert data.shape == (50, 784)
    # [test|valid|train] layout: first 20 rows are the t10k set, scaled
    expect_valid = test_x.reshape(20, -1).astype(numpy.float32) / 127.5 - 1.0
    numpy.testing.assert_allclose(data[:20], expect_valid, atol=1e-6)
    labels = numpy.asarray(loader.original_labels.mem)
    numpy.testing.assert_array_equal(labels[:20], test_y)
    numpy.testing.assert_array_equal(labels[20:], train_y)
    assert data.min() >= -1.0 and data.max() <= 1.0


def test_mnist_load_real_truncates_to_requested_sizes(tmp_path):
    from veles_tpu.samples.mnist import MnistLoader
    _make_mnist_dir(tmp_path, n_train=30, n_valid=20)
    loader = MnistLoader(None, n_train=12, n_valid=8,
                         data_dir=str(tmp_path), minibatch_size=4,
                         name="loader")
    loader.initialize()
    assert loader.class_lengths == [0, 8, 12]


def test_mnist_conv_sample_shape_real_data(tmp_path):
    """The conv loader serves the SAME IDX files in NHWC layout."""
    from veles_tpu.samples.mnist_conv import MnistConvLoader
    train_x, _, test_x, _ = _make_mnist_dir(tmp_path)
    loader = MnistConvLoader(None, n_train=30, n_valid=20,
                             data_dir=str(tmp_path), minibatch_size=10,
                             name="loader")
    loader.initialize()
    assert loader.original_data.shape == (50, 28, 28, 1)


# --------------------------------------------------------- mnist_conv sample
def _structured_digits(n, rng):
    """Spatially-STRUCTURED 10-class images a conv net can learn (the
    loader's iid-noise synthetic prototypes are FC-learnable but carry no
    translation-robust signal, so they are wrong for a conv topology):
    class c < 5 — horizontal bar in row band c; c >= 5 — vertical bar in
    column band c-5."""
    labels = (numpy.arange(n) % 10).astype(numpy.uint8)
    rng.shuffle(labels)
    imgs = rng.randint(0, 40, (n, 28, 28)).astype(numpy.uint8)
    for i, c in enumerate(labels):
        band = slice(5 * (c % 5) + 1, 5 * (c % 5) + 4)
        if c < 5:
            imgs[i, band, :] = 255
        else:
            imgs[i, :, band] = 255
    return imgs, labels


def test_mnist_conv_converges_on_real_idx(tmp_path):
    """Full conv training run fed through the REAL IDX decode path."""
    from veles_tpu import prng
    from veles_tpu.config import root
    rng = numpy.random.RandomState(5)
    train_x, train_y = _structured_digits(300, rng)
    test_x, test_y = _structured_digits(60, rng)
    _write_idx_images(str(tmp_path / "train-images-idx3-ubyte"), train_x)
    _write_idx_labels(str(tmp_path / "train-labels-idx1-ubyte"), train_y)
    _write_idx_images(str(tmp_path / "t10k-images-idx3-ubyte"), test_x)
    _write_idx_labels(str(tmp_path / "t10k-labels-idx1-ubyte"), test_y)

    prng.reset()
    prng.seed_all(1)
    root.__dict__.pop("mnist_conv", None)
    root.mnist_conv.update({
        "loader": {"minibatch_size": 30, "n_train": 300, "n_valid": 60,
                   "data_dir": str(tmp_path)},
        "decision": {"max_epochs": 6, "fail_iterations": 20},
    })
    from veles_tpu.samples import mnist_conv
    wf = mnist_conv.train()
    assert wf.decision.complete
    errs = [m["validation"]["n_err"] for m in wf.decision.epoch_metrics
            if "validation" in m]
    assert errs[-1] <= errs[0] // 4, \
        "conv sample did not learn the structured digits: %s" % errs
    # topology sanity: conv stack flattened into the FC trunk
    assert wf.forwards[0].weights.shape == (5, 5, 1, 32)
    assert wf.forwards[-1].output.shape == (30, 10)


# ----------------------------------------------------- directory-image sample
def _write_image_tree(tmp_path, per_class=12, size=(40, 36)):
    from PIL import Image
    rng = numpy.random.RandomState(3)
    # two visually-distinct classes: bright-red-ish vs dark-blue-ish
    for cls, base in (("red", (200, 30, 30)), ("blue", (20, 40, 180))):
        d = tmp_path / cls
        d.mkdir()
        for i in range(per_class):
            arr = numpy.clip(rng.normal(
                base, 25, size + (3,)), 0, 255).astype(numpy.uint8)
            Image.fromarray(arr).save(d / ("img_%02d.png" % i))


def test_image_dir_sample_end_to_end(tmp_path):
    from veles_tpu import prng
    from veles_tpu.config import root
    _write_image_tree(tmp_path)
    prng.reset()
    prng.seed_all(1)
    root.__dict__.pop("image_dir", None)
    root.image_dir.update({
        "loader": {"minibatch_size": 8, "scale": (16, 16),
                   "validation_ratio": 0.25},
        "decision": {"max_epochs": 4, "fail_iterations": 10},
    })
    from veles_tpu.samples import image_dir
    wf = image_dir.train(loader={"directory": str(tmp_path)})
    assert wf.decision.complete
    assert wf.loader.label_names == ["blue", "red"]
    errs = [m["validation"]["n_err"] for m in wf.decision.epoch_metrics
            if "validation" in m]
    # 2 trivially-separable color classes: the net must solve them
    assert errs[-1] == 0, "image_dir sample failed to separate: %s" % errs


def test_image_dir_ignores_imageless_subdirs(tmp_path):
    """Empty/hidden subdirectories must not widen the softmax: the loader
    labels only classes that contain images, and the net must agree."""
    from veles_tpu.config import root
    _write_image_tree(tmp_path, per_class=4)
    (tmp_path / ".cache").mkdir()
    (tmp_path / "empty_class").mkdir()
    root.__dict__.pop("image_dir", None)
    from veles_tpu.samples import image_dir
    wf = image_dir.build(loader={"directory": str(tmp_path),
                                 "minibatch_size": 4, "scale": (8, 8)})
    assert wf.layers_config[-1]["output_sample_shape"] == 2


def test_image_dir_build_accepts_generic_overrides(tmp_path):
    """build(**overrides) must merge like every make_sample-based sample."""
    from veles_tpu.config import root
    _write_image_tree(tmp_path, per_class=4)
    root.__dict__.pop("image_dir", None)
    from veles_tpu.samples import image_dir
    layers = [{"type": "softmax", "output_sample_shape": 2,
               "learning_rate": 0.05}]
    wf = image_dir.build(loader={"directory": str(tmp_path),
                                 "minibatch_size": 4, "scale": (8, 8)},
                         layers=layers, name="custom")
    assert wf.name == "custom"
    assert len(wf.layers_config) == 1


def test_image_dir_sample_requires_directory():
    from veles_tpu.config import root
    root.__dict__.pop("image_dir", None)
    from veles_tpu.samples import image_dir
    with pytest.raises(ValueError, match="directory"):
        image_dir.build()
