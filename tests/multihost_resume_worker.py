"""Worker for the multi-host snapshot/resume test (tests/test_multihost.py).

Two processes form one 8-device CPU mesh (4 virtual devices each) and
train MNIST-FC with a ShardedTrainer.  Three phases, selected by argv[4]:

- ``full``    — train 2·K steps straight through; print the final digest.
- ``first``   — train K steps, then process 0 publishes a snapshot of the
  gathered global state (sync_to_runner → snapshot_state → atomic file);
  both processes exit.
- ``second``  — every process restores the SAME snapshot file into its
  local runner, rebuilds the ShardedTrainer (whose init-digest guard
  cross-checks the restored state), and trains the remaining K steps
  continuing the step counter; print the final digest.

The parent asserts digest(full) == digest(second) on every process —
interrupt + restore across the mesh is bit-exact, the multi-host form of
the single-process SIGKILL contract (SURVEY §5.3 downgrade note).
"""

import json
import os
import sys
import zlib


K = 3


def digest(runner):
    import jax
    import numpy
    return [zlib.crc32(numpy.ascontiguousarray(leaf).tobytes())
            for leaf in jax.tree.leaves(
                jax.tree.map(numpy.asarray, runner.state))]


def build():
    import numpy  # noqa: F401
    from veles_tpu import prng
    from veles_tpu.config import root
    prng.reset()
    prng.seed_all(1)
    root.mnist.update({
        "loader": {"minibatch_size": 32, "n_train": 128, "n_valid": 32},
        "decision": {"max_epochs": 100, "fail_iterations": 50},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.05, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    return mnist.build(fused=True)


def train_steps(trainer, loader, steps, step0=0):
    import numpy
    from veles_tpu.loader.base import TRAIN
    done = 0
    while done < steps:
        loader.run()
        if loader.minibatch_class != TRAIN:
            continue
        trainer.train_step(
            numpy.asarray(loader.minibatch_data.mem),
            numpy.asarray(loader.minibatch_labels.mem),
            numpy.asarray(loader.minibatch_mask.mem),
            loader.minibatch_size, step=step0 + done)
        done += 1


def main(coordinator, num_processes, process_id, phase, snap_dir):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    from veles_tpu import snapshotter
    from veles_tpu.parallel import (ShardedTrainer, make_mesh,
                                    spmd_loader_shard)

    wf = build()
    mesh = make_mesh(len(jax.devices()))
    shard_idx, shard_cnt = spmd_loader_shard(mesh)
    wf.loader.shard_spmd(shard_idx, shard_cnt)
    wf.initialize()
    snap_path = os.path.join(snap_dir, "mid.pickle.gz")

    if phase == "second":
        # every process restores the SAME published snapshot, THEN
        # shards it — the trainer's init digest guard cross-checks
        snapshotter.restore(wf, snap_path)
        trainer = ShardedTrainer(wf._fused_runner, mesh)
        train_steps(trainer, wf.loader, K, step0=K)
        trainer.sync_to_runner()
        print("DIGEST " + json.dumps(digest(wf._fused_runner)))
        return

    trainer = ShardedTrainer(wf._fused_runner, mesh)
    train_steps(trainer, wf.loader, K)
    if phase == "first":
        trainer.sync_to_runner()
        if jax.process_index() == 0:     # single-writer rule
            snapshotter.save(wf, snap_path)
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("snapshot written")
        print("SNAPSHOT OK")
        return
    assert phase == "full"
    train_steps(trainer, wf.loader, K, step0=K)
    trainer.sync_to_runner()
    print("DIGEST " + json.dumps(digest(wf._fused_runner)))


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
         sys.argv[5])
