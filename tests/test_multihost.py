"""Multi-host SPMD: 2 real processes over one global mesh (loopback).

The reference tested its distributed backbone with master and slaves
in-process on localhost (SURVEY §4, test_client_server.py [M]); the
TPU-native analogue is N jax processes joined by
``jax.distributed.initialize`` over 127.0.0.1, each owning 4 virtual CPU
devices of one 8-device mesh.  Asserts (1) both processes compute
IDENTICAL per-step metrics — the collectives really span processes — and
(2) those metrics equal a single-process run on the same global batches,
i.e. multi-host changes the wiring, not the math.  Covered layouts:

- ``dp``: blocked mesh, batch split by process (the reference's only
  strategy, rebuilt as GSPMD all-reduce);
- ``tp``: interleaved mesh whose MODEL axis spans the two processes —
  megatron-style cross-host tensor parallelism, with layer-0 weights
  output-sharded across hosts and the batch replicated.
"""

import json
import os
import socket
import subprocess
import sys

import functools

import numpy
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # each worker re-adds its own 4-device flag; strip the conftest's 8
    env["XLA_FLAGS"] = ""
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # match the conftest's suite-wide rng scheme (sharded and
    # single-process runs must draw identical random bits — see
    # veles_tpu.compat.ensure_partitionable_rng)
    env["JAX_THREEFRY_PARTITIONABLE"] = "1"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


#: the error this jaxlib's CPU backend raises for any cross-process
#: collective — the whole multihost suite is hardware-gated on it
_NO_MULTIPROC = "Multiprocess computations aren't implemented on the CPU"


@functools.lru_cache(maxsize=1)
def _multiproc_skip_reason():
    """Probe ONCE whether this jaxlib can run cross-process collectives
    at all (one cheap 2-process broadcast instead of every test paying
    a full worker pair to rediscover the same missing backend).
    Returns the skip reason, or None when the backend is capable — any
    OTHER probe failure also returns None so the real tests surface it
    with their full diagnostics."""
    port = _free_port()
    code = ("import sys, jax\n"
            "jax.distributed.initialize('127.0.0.1:%d', 2, "
            "int(sys.argv[1]))\n"
            "from jax.experimental import multihost_utils\n"
            "multihost_utils.broadcast_one_to_all(jax.numpy.ones(1))\n"
            % port)
    procs = [subprocess.Popen([sys.executable, "-c", code, str(pid)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True,
                              env=_worker_env(), cwd=REPO)
             for pid in range(2)]
    gated = False
    try:
        for p in procs:
            _, stderr = p.communicate(timeout=120)
            if p.returncode != 0 and _NO_MULTIPROC in stderr:
                gated = True
    except Exception:   # noqa: BLE001 — probe hang/crash: let tests run
        return None
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    if gated:
        return ("multi-process collectives unsupported by this jaxlib "
                "CPU backend")
    return None


def _parse_metrics(stdout):
    for line in stdout.splitlines():
        if line.startswith("METRICS "):
            return json.loads(line[len("METRICS "):])
    raise AssertionError("no METRICS line in worker output:\n" + stdout)


def _spawn_workers(script, extra_args):
    """Launch 2 coordinated worker processes of ``script``; return their
    stdouts (asserting rc=0), killing stragglers on the way out.
    Hardware-gated environments (no cross-process collectives) skip —
    explicitly, with the reason — instead of failing."""
    reason = _multiproc_skip_reason()
    if reason:
        pytest.skip(reason)
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(HERE, script),
             "127.0.0.1:%d" % port, "2", str(pid)] + list(extra_args),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=_worker_env(), cwd=REPO)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=300)
            if p.returncode != 0 and _NO_MULTIPROC in stderr:
                # hardware-gated, not broken: this jaxlib's CPU backend
                # has no cross-process collectives (they need a TPU/GPU
                # backend or a gloo-enabled jaxlib build).  Explicit
                # skip so the suite stays honest on capable platforms.
                pytest.skip("multi-process collectives unsupported by "
                            "this jaxlib CPU backend")
            assert p.returncode == 0, (
                "worker failed rc=%d\nstdout:\n%s\nstderr:\n%s"
                % (p.returncode, stdout, stderr[-4000:]))
            outs.append(stdout)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return outs


def _run_workers(mode):
    return [_parse_metrics(out)
            for out in _spawn_workers("multihost_worker.py", [mode])]


@functools.lru_cache(maxsize=1)
def _single_process_reference(steps=3):
    """Expected per-step metrics from a single-process run on the same
    global batches (global plan, same PRNG → same minibatch order).
    Cached: the reference is mode-independent, so the dp and tp
    parametrizations share one build+compile+train."""
    from veles_tpu import prng
    from veles_tpu.config import root
    from veles_tpu.parallel import make_mesh, ShardedTrainer
    from veles_tpu.loader.base import TRAIN
    prng.reset()
    prng.seed_all(1)
    root.mnist.update({
        "loader": {"minibatch_size": 32, "n_train": 128, "n_valid": 32},
        "decision": {"max_epochs": 1, "fail_iterations": 5},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.05, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    wf = mnist.build(fused=True)
    wf.initialize()
    import jax
    mesh = make_mesh(8, devices=jax.devices("cpu"))
    trainer = ShardedTrainer(wf._fused_runner, mesh)
    assert not trainer.multiprocess

    loader = wf.loader
    expect, step = [], 0
    while step < steps:
        loader.run()
        if loader.minibatch_class != TRAIN:
            continue
        metrics = trainer.train_step(
            numpy.asarray(loader.minibatch_data.mem),
            numpy.asarray(loader.minibatch_labels.mem),
            numpy.asarray(loader.minibatch_mask.mem),
            loader.minibatch_size, step=step)
        host = ShardedTrainer.fetch(metrics)
        expect.append({k: float(numpy.ravel(v)[0]) for k, v in host.items()})
        step += 1
    return expect


@pytest.mark.parametrize("mode", ["dp", "tp"])
def test_two_process_spmd_matches_single_process(mode):
    outs = _run_workers(mode)

    # (1) both processes saw the same replicated metrics each step
    assert outs[0] == outs[1]
    assert len(outs[0]) == 3

    # (2) equal to the single-process reference on the same global batches
    for step, expect in enumerate(_single_process_reference()):
        for key, val in expect.items():
            assert abs(outs[0][step][key] - val) <= 1e-4 * (1 + abs(val)), (
                mode, step, key, outs[0][step][key], val)


def test_cli_distributed_trains_spmd_and_matches_single_process():
    """The PRODUCT --distributed path (Launcher.boot(distributed=True)):
    both processes train lock-step through the mesh (identical per-epoch
    decision metrics and final weights), and the result matches a plain
    single-process run of the same config — the documented 'gradient
    averaging is the XLA all-reduce' semantics, now through the CLI
    graph loop itself."""
    outs = [_parse_metrics(out)
            for out in _spawn_workers("multihost_cli_worker.py", [])]
    assert outs[0] == outs[1]
    assert len(outs[0]["epochs"]) == 2

    # single-process reference: plain graph loop, same seed/config
    from veles_tpu import prng
    from veles_tpu.config import root
    from veles_tpu.launcher import Launcher
    prng.reset()
    prng.seed_all(1)
    root.__dict__.pop("mnist", None)
    root.mnist.update({
        "loader": {"minibatch_size": 32, "n_train": 128, "n_valid": 32},
        "decision": {"max_epochs": 2, "fail_iterations": 5},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.05, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    wf = mnist.build(fused=True)
    Launcher(wf, stats=False).boot()
    ref_epochs = wf.decision.epoch_metrics
    assert len(ref_epochs) == len(outs[0]["epochs"])
    for ref, got in zip(ref_epochs, outs[0]["epochs"]):
        for set_name, metrics in ref.items():
            for key, val in metrics.items():
                if not isinstance(val, (int, float)):
                    continue
                g = got[set_name][key]
                assert abs(g - val) <= 1e-4 * (1 + abs(val)), (
                    set_name, key, g, val)
    wsum = float(numpy.abs(
        numpy.asarray(wf.forwards[0].weights.mem)).sum())
    assert abs(outs[0]["wsum"] - wsum) <= 1e-3 * (1 + wsum)


def test_cli_distributed_epoch_scan_matches_graph_loop():
    """--distributed --epoch-scan composed: 2 processes run k-epoch
    chunks as single programs under the global mesh and reach the same
    per-epoch metrics and weights as the 2-process per-minibatch path
    (which itself equals single-process — previous test)."""
    outs = [_parse_metrics(out)
            for out in _spawn_workers("multihost_cli_worker.py", ["2"])]
    assert outs[0] == outs[1]
    base = [_parse_metrics(out)
            for out in _spawn_workers("multihost_cli_worker.py", [])]
    assert len(outs[0]["epochs"]) == len(base[0]["epochs"])
    for ref, got in zip(base[0]["epochs"], outs[0]["epochs"]):
        for set_name, metrics in ref.items():
            for key, val in metrics.items():
                g = got[set_name][key]
                assert abs(g - val) <= 1e-4 * (1 + abs(val)), (
                    set_name, key, g, val)
    assert abs(outs[0]["wsum"] - base[0]["wsum"]) <= 1e-3 * (
        1 + base[0]["wsum"])


def test_two_process_divergent_init_detected():
    """ShardedTrainer assembles device shards from process-LOCAL host
    copies, so divergent init across processes must fail loudly at
    construction (digest cross-check, ADVICE r4) — not silently train a
    Frankenstein tensor."""
    for out in _spawn_workers("multihost_worker.py", ["diverge"]):
        assert "DIVERGE-CAUGHT" in out, out


def _run_resume_workers(phase, snap_dir):
    return _spawn_workers("multihost_resume_worker.py", [phase, snap_dir])


def _digests(outs):
    got = []
    for out in outs:
        for line in out.splitlines():
            if line.startswith("DIGEST "):
                got.append(json.loads(line[len("DIGEST "):]))
    return got


def test_two_process_snapshot_resume_bit_exact(tmp_path):
    """Interrupt + restore ACROSS THE MESH: a 2-process SPMD run
    snapshotted at step K and resumed in fresh processes must reach the
    bit-identical state of an uninterrupted 2-process run — the
    multi-host form of the kill-and-resume contract (SURVEY §5.3)."""
    full = _digests(_run_resume_workers("full", str(tmp_path)))
    assert len(full) == 2 and full[0] == full[1]

    outs = _run_resume_workers("first", str(tmp_path))
    assert all("SNAPSHOT OK" in o for o in outs)
    assert os.path.exists(os.path.join(str(tmp_path), "mid.pickle.gz"))

    resumed = _digests(_run_resume_workers("second", str(tmp_path)))
    assert len(resumed) == 2 and resumed[0] == resumed[1]
    assert resumed[0] == full[0], "resumed run diverged from straight run"


def test_spmd_loader_shard_single_process_collapses():
    """All devices in one process → one data block, full batch locally;
    the data axis is found by NAME, not position."""
    import jax
    from jax.sharding import Mesh
    from veles_tpu.parallel import spmd_loader_shard
    devices = jax.devices("cpu")[:8]
    blocked = Mesh(numpy.array(devices).reshape(4, 2), ("data", "model"))
    assert spmd_loader_shard(blocked) == (0, 1)
    swapped = Mesh(numpy.array(devices).reshape(2, 4), ("model", "data"))
    assert spmd_loader_shard(swapped) == (0, 1)
    with pytest.raises(ValueError):
        spmd_loader_shard(Mesh(numpy.array(devices[:2]), ("model",)))
