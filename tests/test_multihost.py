"""Multi-host SPMD: 2 real processes over one global mesh (loopback).

The reference tested its distributed backbone with master and slaves
in-process on localhost (SURVEY §4, test_client_server.py [M]); the
TPU-native analogue is N jax processes joined by
``jax.distributed.initialize`` over 127.0.0.1, each owning 4 virtual CPU
devices of one 8-device mesh.  Asserts (1) both processes compute
IDENTICAL per-step metrics — the all-reduce really spans processes — and
(2) those metrics equal a single-process run on the same global batches,
i.e. multi-host changes the wiring, not the math.
"""

import json
import os
import socket
import subprocess
import sys

import numpy

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # each worker re-adds its own 4-device flag; strip the conftest's 8
    env["XLA_FLAGS"] = ""
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def _parse_metrics(stdout):
    for line in stdout.splitlines():
        if line.startswith("METRICS "):
            return json.loads(line[len("METRICS "):])
    raise AssertionError("no METRICS line in worker output:\n" + stdout)


def test_two_process_spmd_matches_single_process():
    port = _free_port()
    coordinator = "127.0.0.1:%d" % port
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(HERE, "multihost_worker.py"),
             coordinator, "2", str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=_worker_env(), cwd=REPO)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=300)
            assert p.returncode == 0, (
                "worker failed rc=%d\nstdout:\n%s\nstderr:\n%s"
                % (p.returncode, stdout, stderr[-4000:]))
            outs.append(_parse_metrics(stdout))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()

    # (1) both processes saw the same replicated metrics each step
    assert outs[0] == outs[1]
    assert len(outs[0]) == 3

    # (2) equal to the single-process reference on the same global batches
    from veles_tpu import prng
    from veles_tpu.config import root
    from veles_tpu.parallel import make_mesh, ShardedTrainer
    prng.reset()
    prng.seed_all(1)
    root.mnist.update({
        "loader": {"minibatch_size": 32, "n_train": 128, "n_valid": 32},
        "decision": {"max_epochs": 1, "fail_iterations": 5},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.05, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    from veles_tpu.loader.base import TRAIN
    wf = mnist.build(fused=True)
    wf.initialize()     # NOT sharded: global plan, same PRNG → same order
    import jax
    mesh = make_mesh(8, devices=jax.devices("cpu"))
    trainer = ShardedTrainer(wf._fused_runner, mesh)
    assert not trainer.multiprocess

    loader = wf.loader
    step = 0
    while step < 3:
        loader.run()
        if loader.minibatch_class != TRAIN:
            continue
        metrics = trainer.train_step(
            numpy.asarray(loader.minibatch_data.mem),
            numpy.asarray(loader.minibatch_labels.mem),
            numpy.asarray(loader.minibatch_mask.mem),
            loader.minibatch_size, step=step)
        host = ShardedTrainer.fetch(metrics)
        expect = {k: float(numpy.ravel(v)[0]) for k, v in host.items()}
        for key, val in expect.items():
            assert abs(outs[0][step][key] - val) <= 1e-4 * (1 + abs(val)), (
                step, key, outs[0][step][key], val)
        step += 1
