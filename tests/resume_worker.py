"""Subprocess worker for the kill-and-resume failure-recovery harness.

Trains the tiny MNIST-FC config for a fixed number of epochs, snapshotting
every epoch, and writes a digest of the FINAL model state on completion.
Modes:
  control — straight run to completion;
  victim  — same run but slowed per epoch so the parent can SIGKILL it
            mid-training (never writes the digest);
  resume  — ``--snapshot auto`` semantics: picks up the victim's latest
            snapshot and finishes the run.
Ref: SURVEY §5.3 — the reference's drop_slave/job-reissue elasticity is
downgraded by design to kill-and-resume on the SPMD substrate; this worker
is the proof harness.
"""
import hashlib
import json
import os
import sys
import time


def main():
    out_dir, mode = sys.argv[1], sys.argv[2]
    epoch_sleep = float(sys.argv[3]) if len(sys.argv) > 3 else 0.0
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax
    jax.config.update("jax_platforms", "cpu")

    from veles_tpu import prng
    from veles_tpu.config import root
    prng.reset()
    prng.seed_all(1)
    root.mnist.update({
        "loader": {"minibatch_size": 50, "n_train": 300, "n_valid": 100},
        "decision": {"max_epochs": 6, "fail_iterations": 100},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.05, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    from veles_tpu.launcher import Launcher
    wf = mnist.build(fused=True, snapshotter_config={
        "directory": os.path.join(out_dir, "snaps"),
        "interval": 1, "compression": ""})

    if epoch_sleep > 0.0:
        decision = wf.decision
        orig_run = decision.run

        def slow_run():
            orig_run()
            if bool(wf.loader.epoch_ended):
                time.sleep(epoch_sleep)
        decision.run = slow_run

    Launcher(wf, stats=False,
             snapshot="auto" if mode == "resume" else None).boot()

    digest = hashlib.sha256()
    for fwd in wf.forwards:
        digest.update(bytes(memoryview(fwd.weights.mem)))
        digest.update(bytes(memoryview(fwd.bias.mem)))
    result = {
        "weights_sha": digest.hexdigest(),
        "best_metric": wf.decision.best_metric,
        "best_epoch": wf.decision.best_epoch,
        "epochs": int(wf.loader.epoch_number),
    }
    with open(os.path.join(out_dir, mode + ".json"), "w",
              encoding="utf-8") as f:
        json.dump(result, f)


if __name__ == "__main__":
    main()
