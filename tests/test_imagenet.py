"""ImageNet pipeline tests: device augmentation, record files, tiny-AlexNet
convergence, and the multi-chip sharded path (BASELINE configs[2]/[4])."""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.ops import functional as F


class TestAugmentation:
    def test_eval_center_crop(self):
        x = jnp.arange(1 * 6 * 6 * 1, dtype=jnp.float32).reshape(1, 6, 6, 1)
        y = F.random_crop_flip(x, None, (4, 4), train=False)
        numpy.testing.assert_array_equal(numpy.asarray(y),
                                         numpy.asarray(x)[:, 1:5, 1:5, :])

    def test_train_crop_shapes_and_determinism(self):
        x = jnp.asarray(numpy.random.RandomState(0).rand(8, 10, 10, 3)
                        .astype(numpy.float32))
        key = jax.random.PRNGKey(7)
        a = F.random_crop_flip(x, key, (6, 6))
        b = F.random_crop_flip(x, key, (6, 6))
        assert a.shape == (8, 6, 6, 3)
        numpy.testing.assert_array_equal(numpy.asarray(a), numpy.asarray(b))
        c = F.random_crop_flip(x, jax.random.PRNGKey(8), (6, 6))
        assert not numpy.array_equal(numpy.asarray(a), numpy.asarray(c))

    def test_crops_are_subwindows(self):
        x = jnp.asarray(numpy.random.RandomState(1).rand(4, 8, 8, 1)
                        .astype(numpy.float32))
        out = numpy.asarray(F.random_crop_flip(x, jax.random.PRNGKey(0),
                                               (5, 5), flip=False))
        xn = numpy.asarray(x)
        for i in range(4):
            found = any(
                numpy.allclose(out[i], xn[i, t:t + 5, l:l + 5])
                for t in range(4) for l in range(4))
            assert found, "crop %d is not a window of the source" % i

    def test_vjp_routes_gradient_into_window(self):
        x = jnp.ones((1, 6, 6, 1))
        _, vjp = jax.vjp(
            lambda a: F.random_crop_flip(a, None, (4, 4), train=False), x)
        g = numpy.asarray(vjp(jnp.ones((1, 4, 4, 1)))[0])
        assert g.sum() == 16.0
        assert g[0, 0, 0, 0] == 0.0 and g[0, 1, 1, 0] == 1.0


class TestRecords:
    def test_roundtrip_and_loader(self, tmp_path):
        from veles_tpu.loader.records import (write_records, open_records,
                                              RecordsLoader)
        from veles_tpu.workflow import Workflow
        r = numpy.random.RandomState(0)
        data = (r.rand(30, 4, 4, 3) * 255).astype(numpy.uint8)
        labels = (numpy.arange(30) % 3).astype(numpy.int32)
        path = str(tmp_path / "set.rec")
        write_records(path, data, labels, [0, 10, 20])
        header, mapped, mapped_labels = open_records(path)
        numpy.testing.assert_array_equal(numpy.asarray(mapped), data)
        numpy.testing.assert_array_equal(numpy.asarray(mapped_labels), labels)

        wf = Workflow(None, name="wf")
        loader = RecordsLoader(wf, path=path, minibatch_size=8,
                               name="loader")
        loader.initialize()
        loader.run()
        assert loader.minibatch_data.shape == (8, 4, 4, 3)
        # uint8 rescaled to [-1, 1]
        assert float(loader.minibatch_data.mem.max()) <= 1.0
        assert float(loader.minibatch_data.mem.min()) >= -1.0

    def test_bad_magic_rejected(self, tmp_path):
        from veles_tpu.loader.records import open_records
        path = tmp_path / "junk.rec"
        path.write_bytes(b"not a record file")
        with pytest.raises(ValueError):
            open_records(str(path))


class TestImagenetSample:
    @pytest.mark.parametrize("fused", [True, False])
    def test_tiny_alexnet_converges(self, fused):
        from veles_tpu import prng
        from veles_tpu.config import root
        prng.reset()
        prng.seed_all(1)
        root.imagenet.update({
            "loader": {"minibatch_size": 32, "records_path": None,
                       "n_train": 160, "n_valid": 64, "image_hw": (32, 32),
                       "n_classes": 4},
            "decision": {"max_epochs": 3, "fail_iterations": 10},
        })
        from veles_tpu.samples import imagenet
        root.imagenet.layers = imagenet.tiny_layers(n_classes=4,
                                                    crop=(28, 28), lr=0.02)
        wf = imagenet.train(fused=fused)
        errs = [m["validation"]["n_err"] for m in wf.decision.epoch_metrics
                if "validation" in m]
        assert errs[-1] < errs[0], errs

    def test_full_alexnet_topology_builds(self):
        """The real 227x227 AlexNet graph compiles its shapes (no train)."""
        from veles_tpu import prng
        from veles_tpu.config import root
        prng.reset()
        prng.seed_all(1)
        from veles_tpu.samples import imagenet
        root.imagenet.update({
            "loader": {"minibatch_size": 4, "records_path": None,
                       "n_train": 8, "n_valid": 4, "image_hw": (256, 256),
                       "n_classes": 1000},
            "decision": {"max_epochs": 1, "fail_iterations": 1},
            "layers": imagenet.alexnet_layers(),
        })
        wf = imagenet.build(fused=False)
        wf.initialize()
        shapes = [tuple(f.output.shape) for f in wf.forwards]
        # canonical AlexNet feature-map progression
        assert shapes[0] == (4, 227, 227, 3)       # crop
        assert shapes[1] == (4, 55, 55, 96)        # conv1
        assert shapes[3] == (4, 27, 27, 96)        # pool1
        assert shapes[-1] == (4, 1000)             # softmax
        assert wf.forwards[-1].weights.shape == (4096, 1000)


class TestShardedImagenet:
    def test_dp_sharded_train_step(self):
        from veles_tpu import prng
        from veles_tpu.config import root
        from veles_tpu.parallel import make_mesh, ShardedTrainer
        devices = jax.devices("cpu")
        if len(devices) < 8:
            pytest.skip("needs 8 virtual devices")
        prng.reset()
        prng.seed_all(1)
        root.imagenet.update({
            "loader": {"minibatch_size": 32, "records_path": None,
                       "n_train": 64, "n_valid": 32, "image_hw": (16, 16),
                       "n_classes": 4},
            "decision": {"max_epochs": 1, "fail_iterations": 5},
        })
        from veles_tpu.samples import imagenet
        root.imagenet.layers = imagenet.tiny_layers(n_classes=4,
                                                    crop=(12, 12))
        wf = imagenet.build(fused=True)
        wf.initialize()
        mesh = make_mesh(8, devices=devices[:8])
        trainer = ShardedTrainer(wf._fused_runner, mesh)
        x = numpy.zeros((32, 16, 16, 3), numpy.float32)
        labels = numpy.zeros(32, numpy.int32)
        mask = numpy.ones(32, numpy.float32)
        metrics = trainer.train_step(x, labels, mask, 32)
        jax.block_until_ready(metrics)
        assert numpy.isfinite(float(metrics["loss_sum"]))
        metrics = trainer.eval_step(x, labels, mask)
        assert numpy.isfinite(float(metrics["loss_sum"]))
