"""Streaming windowed epoch-scan (epoch_driver.py + compiled.window_scan_fn).

Contract under test: an out-of-core (records/LMDB) dataset streamed
through HBM in device-resident windows — one lax.scan dispatch per
window, next window staged concurrently — trains the SAME model the
full-batch epoch-scan and the per-minibatch graph loop train (identical
plan, no stochastic layers), while cutting dispatches per epoch from
~minibatches to ~windows.
"""

import os

import numpy
import pytest

LAYERS = [
    {"type": "all2all_tanh", "output_sample_shape": 12,
     "learning_rate": 0.05, "momentum": 0.9},
    {"type": "softmax", "output_sample_shape": 5,
     "learning_rate": 0.05, "momentum": 0.9},
]
N_VALID, N_TRAIN, MB = 40, 160, 16


def _dataset():
    rng = numpy.random.RandomState(3)
    data = rng.normal(0, 1, (N_VALID + N_TRAIN, 8)).astype(numpy.float32)
    labels = (numpy.arange(N_VALID + N_TRAIN) % 5).astype(numpy.int32)
    return data, labels


def _records_path(tmp_path, data, labels):
    from veles_tpu.loader.records import write_records
    return write_records(str(tmp_path / "stream.rec"), data, labels,
                         [0, N_VALID, N_TRAIN])


def _build(loader_factory, loader_cfg, seed=21, max_epochs=4,
           layers=LAYERS):
    from veles_tpu import prng
    from veles_tpu.standard_workflow import StandardWorkflow
    prng.reset()
    prng.seed_all(seed)
    return StandardWorkflow(
        None, name="stream_test", loader_factory=loader_factory,
        loader_config=dict(minibatch_size=MB, **loader_cfg),
        layers=[dict(layer) for layer in layers],
        decision_config={"max_epochs": max_epochs, "fail_iterations": 10},
        loss_function="softmax")


def _fullbatch_factory(data, labels):
    from veles_tpu.loader.fullbatch import FullBatchLoader

    class ArrayFullBatch(FullBatchLoader):
        def load_data(self):
            self.original_data.reset(data.copy())
            self.original_labels.reset(labels.copy())
            self.class_lengths = [0, N_VALID, N_TRAIN]

    return ArrayFullBatch

def _assert_same_training(wf_a, wf_b):
    assert len(wf_a.decision.epoch_metrics) == \
        len(wf_b.decision.epoch_metrics)
    for ma, mb in zip(wf_a.decision.epoch_metrics,
                      wf_b.decision.epoch_metrics):
        assert set(ma) == set(mb)
        for set_name in ma:
            for key in ("n_err", "count", "loss"):
                if key in ma[set_name]:
                    numpy.testing.assert_allclose(
                        ma[set_name][key], mb[set_name][key], rtol=1e-5,
                        err_msg="%s/%s" % (set_name, key))
    for fa, fb in zip(wf_a.forwards, wf_b.forwards):
        if fa.has_params:
            numpy.testing.assert_allclose(
                numpy.asarray(fa.weights.mem),
                numpy.asarray(fb.weights.mem), rtol=2e-5, atol=2e-6)
            numpy.testing.assert_allclose(
                numpy.asarray(fa.bias.mem),
                numpy.asarray(fb.bias.mem), rtol=2e-5, atol=2e-6)


class TestStreamingParity:
    def test_matches_fullbatch_epoch_scan(self, tmp_path):
        """Acceptance pin: streaming windowed training on a records
        dataset == the full-batch epoch-scan path — same final weights,
        same per-epoch metrics (identical plan, no stochastic layers).
        Window 3 over 10 train minibatches also exercises the TAIL
        window (10 = 3+3+3+1)."""
        from veles_tpu.launcher import Launcher
        from veles_tpu.loader.records import RecordsLoader
        data, labels = _dataset()

        wf_a = _build(_fullbatch_factory(data, labels), {})
        Launcher(wf_a, stats=False, epoch_scan=1).boot()

        rec = _records_path(tmp_path, data, labels)
        wf_b = _build(RecordsLoader, {"path": rec, "scale_uint8": False})
        Launcher(wf_b, stats=False, epoch_scan=1, stream_window=3,
                 stage_ahead=2).boot()
        assert wf_b.is_finished and bool(wf_b.decision.complete)
        _assert_same_training(wf_a, wf_b)

    def test_matches_graph_loop(self, tmp_path):
        """Direct graph-loop parity (covers the completion-gate replay:
        the stopping epoch's last minibatch update is computed but
        DISCARDED in graph mode — the streaming driver replays the final
        window truncated to reproduce it)."""
        from veles_tpu.launcher import Launcher
        from veles_tpu.loader.records import RecordsLoader
        data, labels = _dataset()
        rec = _records_path(tmp_path, data, labels)

        wf_a = _build(RecordsLoader, {"path": rec, "scale_uint8": False})
        Launcher(wf_a, stats=False).boot()     # per-minibatch graph loop

        wf_b = _build(RecordsLoader, {"path": rec, "scale_uint8": False})
        Launcher(wf_b, stats=False, stream_window=4).boot()
        _assert_same_training(wf_a, wf_b)
        # the counter parity a resumed lr policy depends on
        assert wf_a.fused_step.train_steps == wf_b.fused_step.train_steps

    def test_window_size_invariance(self, tmp_path):
        """Any window size trains the same trajectory (the window split
        only changes dispatch granularity, never the step sequence)."""
        from veles_tpu.launcher import Launcher
        from veles_tpu.loader.records import RecordsLoader
        data, labels = _dataset()
        rec = _records_path(tmp_path, data, labels)
        wf_a = _build(RecordsLoader, {"path": rec, "scale_uint8": False})
        Launcher(wf_a, stats=False, stream_window=1).boot()
        wf_b = _build(RecordsLoader, {"path": rec, "scale_uint8": False})
        Launcher(wf_b, stats=False, stream_window=100).boot()
        _assert_same_training(wf_a, wf_b)


class TestStreamingDriverPlumbing:
    def test_bare_epoch_scan_streams_records_loader(self, tmp_path):
        """--epoch-scan alone on an out-of-core loader used to refuse;
        it now streams with the default window."""
        from veles_tpu.epoch_driver import (EpochScanDriver,
                                            DEFAULT_STREAM_WINDOW)
        from veles_tpu.loader.records import RecordsLoader
        data, labels = _dataset()
        rec = _records_path(tmp_path, data, labels)
        wf = _build(RecordsLoader, {"path": rec, "scale_uint8": False},
                    max_epochs=2)
        wf.initialize()
        driver = EpochScanDriver(wf, chunk=1)
        assert driver.streaming
        assert driver.stream_window == DEFAULT_STREAM_WINDOW
        driver.run()
        assert wf.is_finished and bool(wf.decision.complete)

    def test_stream_stats_shape(self, tmp_path):
        """Overlap is measured: windows/dispatches per epoch and the
        staging-stall fraction land on the workflow for print_stats and
        the /metrics gauges."""
        from veles_tpu.launcher import Launcher
        from veles_tpu.loader.records import RecordsLoader
        data, labels = _dataset()
        rec = _records_path(tmp_path, data, labels)
        wf = _build(RecordsLoader, {"path": rec, "scale_uint8": False},
                    max_epochs=3)
        Launcher(wf, stats=False, stream_window=5).boot()
        stats = wf._stream_stats
        epochs = stats["epochs"]
        assert epochs == len(wf.decision.epoch_metrics)
        # 10 train minibatches, window 5 -> 2 windows/epoch; dispatches =
        # windows + 1 valid eval per epoch + 1 completion replay
        assert stats["windows"] == 2 * epochs
        assert stats["dispatches"] == stats["windows"] + epochs + 1
        assert 0.0 <= stats["staging_stall_fraction"] <= 1.0
        assert stats["samples_per_sec"] > 0
        assert stats["train_samples"] == N_TRAIN * epochs
        wf.print_stats()          # streaming lines must render

    def test_stream_window_needs_capable_loader(self):
        """A loader without a random-access backing store cannot
        stream — clear error instead of a silent graph-loop fallback."""
        from veles_tpu.epoch_driver import EpochScanDriver
        from veles_tpu.loader.base import Loader

        class NoWindowLoader(Loader):
            def load_data(self):
                self.class_lengths = [0, 8, 16]

            def create_minibatch_data(self):
                self.minibatch_data.reset(
                    numpy.zeros((self.max_minibatch_size, 8),
                                numpy.float32))
                self.minibatch_labels.reset(
                    numpy.zeros(self.max_minibatch_size, numpy.int32))

            def fill_minibatch(self, indices, actual_size):
                self.minibatch_data.reset(
                    numpy.zeros((len(indices), 8), numpy.float32))
                self.minibatch_labels.reset(
                    numpy.zeros(len(indices), numpy.int32))

        wf = _build(NoWindowLoader, {})
        wf.initialize()
        assert not wf.loader.can_gather_windows
        with pytest.raises(ValueError, match="stream-window"):
            EpochScanDriver(wf, stream_window=4)
        # and bare --epoch-scan still refuses it with the guidance error
        with pytest.raises(ValueError, match="full-batch"):
            EpochScanDriver(wf)

    def test_dropout_network_streams_and_completes(self, tmp_path):
        """Stochastic layers ride the streaming path (scan-path dropout
        keys — the documented epoch-scan divergence): the run completes
        and trains."""
        from veles_tpu.launcher import Launcher
        from veles_tpu.loader.records import RecordsLoader
        data, labels = _dataset()
        rec = _records_path(tmp_path, data, labels)
        layers = [dict(LAYERS[0]),
                  {"type": "dropout", "dropout_ratio": 0.2},
                  dict(LAYERS[1])]
        wf = _build(RecordsLoader, {"path": rec, "scale_uint8": False},
                    max_epochs=3, layers=layers)
        Launcher(wf, stats=False, stream_window=4).boot()
        assert wf.is_finished and bool(wf.decision.complete)
        assert len(wf.decision.epoch_metrics) == 3


class TestGatherWindow:
    def test_records_gather_window_matches_fill(self, tmp_path):
        from veles_tpu.loader.records import RecordsLoader, write_records
        rng = numpy.random.RandomState(5)
        data = rng.randint(0, 256, (60, 4, 4, 3)).astype(numpy.uint8)
        labels = (numpy.arange(60) % 7).astype(numpy.int32)
        path = write_records(str(tmp_path / "g.rec"), data, labels,
                             [0, 20, 40])
        loader = RecordsLoader(None, path=path, minibatch_size=10,
                               name="loader")
        loader.initialize()
        idx = numpy.asarray([3, 59, 17, 17, 0], numpy.int32)
        win, win_labels = loader.gather_window(idx)
        loader.fill_minibatch(idx, len(idx))
        numpy.testing.assert_array_equal(
            win, numpy.asarray(loader.minibatch_data.mem)[:len(idx)])
        numpy.testing.assert_array_equal(
            win_labels,
            numpy.asarray(loader.minibatch_labels.mem)[:len(idx)])

    def test_capability_flags(self, tmp_path):
        from veles_tpu.loader.base import Loader
        from veles_tpu.loader.records import RecordsLoader
        from veles_tpu.loader.stream import StreamLoaderBase
        assert RecordsLoader(None, path="x", name="l").can_gather_windows
        assert not StreamLoaderBase(None, name="s").can_gather_windows
        with pytest.raises(NotImplementedError):
            Loader.gather_window(
                StreamLoaderBase(None, name="s2"),
                numpy.arange(3))


def test_metrics_gauges_render_stream_stats():
    """The /metrics scrape carries the streaming gauges once a workflow
    row holds stream stats (fed by StatusReporter from
    wf._stream_stats)."""
    from veles_tpu.web_status import WebStatus
    status = WebStatus()
    status.update("wf_row", workflow="stream_test", process=0, epoch=2,
                  complete=False,
                  stream={"samples_per_sec": 123.5,
                          "staging_stall_fraction": 0.25,
                          "windows": 8, "dispatches": 11})
    text = status.render_metrics()
    assert 'veles_stream_samples_per_sec{workflow="stream_test"' in text
    assert "123.5" in text
    assert "veles_stream_staging_stall_fraction" in text
    assert "veles_stream_dispatches_total" in text
