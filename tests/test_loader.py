"""Tier-2 loader tests: epoch plan, masking, shuffling, sharding."""

import numpy

from veles_tpu.loader.base import TEST, VALID, TRAIN
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.workflow import Workflow


class ArrayLoader(FullBatchLoader):
    """Test loader over a deterministic arange dataset."""

    def __init__(self, workflow, lengths=(6, 10, 25), **kwargs):
        super().__init__(workflow, **kwargs)
        self._lengths = list(lengths)

    def load_data(self):
        total = sum(self._lengths)
        data = numpy.arange(total, dtype=numpy.float32)[:, None] * [1.0, 2.0]
        self.original_data.reset(data)
        self.original_labels.reset(
            numpy.arange(total, dtype=numpy.int32) % 3)
        self.class_lengths = list(self._lengths)


def _make(lengths=(6, 10, 25), mb=8, shuffle=True):
    wf = Workflow(None, name="wf")
    loader = ArrayLoader(wf, lengths=lengths, minibatch_size=mb,
                         shuffle=shuffle)
    loader.initialize()
    return loader


def test_epoch_order_and_class_boundaries():
    loader = _make()
    seen = []
    for _ in range(7):  # ceil(6/8)+ceil(10/8)+ceil(25/8) = 1+2+4
        loader.run()
        seen.append((loader.minibatch_class, loader.minibatch_size))
    assert [c for c, _ in seen] == [TEST, VALID, VALID, TRAIN, TRAIN, TRAIN,
                                    TRAIN]
    # short minibatches at each class tail, masked not shrunk
    assert seen[0] == (TEST, 6)
    assert seen[2] == (VALID, 2)
    assert seen[6] == (TRAIN, 1)
    assert loader.last_minibatch and loader.epoch_ended
    assert loader.epoch_number == 1


def test_mask_and_padding():
    loader = _make()
    loader.run()  # TEST minibatch: 6 live rows padded to 8
    mask = loader.minibatch_mask.mem
    assert mask.sum() == 6 and (mask[:6] == 1).all() and (mask[6:] == 0).all()
    assert loader.minibatch_data.shape[0] == 8  # static shape


def test_minibatch_content_matches_indices():
    loader = _make(shuffle=False)
    loader.run()
    idx = loader.minibatch_indices.mem
    data = loader.minibatch_data.mem
    numpy.testing.assert_allclose(data[:, 0], idx.astype(numpy.float32))
    labels = loader.minibatch_labels.mem
    numpy.testing.assert_array_equal(labels, idx % 3)


def test_train_shuffles_each_epoch_but_not_eval_sets():
    loader = _make(mb=25)
    orders = []
    for _ in range(2):  # two epochs
        epoch_idx = []
        while True:
            loader.run()
            if loader.minibatch_class == TRAIN:
                epoch_idx.append(numpy.array(loader.minibatch_indices.mem))
            if loader.last_minibatch:
                break
        orders.append(numpy.concatenate(epoch_idx))
    assert not numpy.array_equal(orders[0], orders[1])   # reshuffled
    assert set(orders[0]) == set(orders[1])              # same samples
    # eval sets: deterministic ascending
    loader2 = _make(mb=25)
    loader2.run()
    numpy.testing.assert_array_equal(
        numpy.sort(loader2.minibatch_indices.mem[:6]), numpy.arange(6))


def test_determinism_with_seed():
    from veles_tpu import prng
    prng.reset(); prng.seed_all(5)
    a = _make()
    a.run(); a.run(); a.run(); a.run()
    first = numpy.array(a.minibatch_indices.mem)
    prng.reset(); prng.seed_all(5)
    b = _make()
    b.run(); b.run(); b.run(); b.run()
    numpy.testing.assert_array_equal(first, b.minibatch_indices.mem)


def test_sharding_partitions_every_set():
    full = set(range(41))
    covered = set()
    counts = []
    for pi in range(4):
        loader = _make()
        loader.shard(pi, 4)
        loader._plan_epoch()
        mine = set()
        for cls, idx, actual in loader._order:
            mine.update(idx[:actual].tolist())
        counts.append(len(mine))
        assert covered.isdisjoint(mine)
        covered |= mine
    assert covered == full
    assert max(counts) - min(counts) <= 3  # balanced within one per set


def test_shard_spmd_slices_global_minibatches():
    """SPMD mode: all processes plan the SAME global minibatches; each
    yields its contiguous local rows; masks/indices reassemble exactly the
    unsharded plan, and minibatch_size stays the global live count."""
    from veles_tpu import prng

    def plans(pc):
        out = []
        for pi in range(pc):
            prng.reset(); prng.seed_all(7)
            wf = Workflow(None, name="wf%d" % pi)
            loader = ArrayLoader(wf, lengths=(6, 10, 25), minibatch_size=8)
            if pc > 1:
                loader.shard_spmd(pi, pc)
            loader.initialize()
            steps = []
            for _ in range(7):
                loader.run()
                steps.append((loader.minibatch_class,
                              loader.minibatch_size,
                              numpy.array(loader.minibatch_indices.mem),
                              numpy.array(loader.minibatch_mask.mem),
                              numpy.array(loader.minibatch_data.mem)))
            return_local = loader.local_minibatch_size
            out.append((steps, return_local))
        return out

    (global_steps, g_local), = plans(1)
    shards = plans(2)
    assert shards[0][1] == 4 and shards[1][1] == 4
    for step in range(7):
        cls_g, size_g, idx_g, mask_g, data_g = global_steps[step]
        for pi in range(2):
            cls_l, size_l, idx_l, mask_l, data_l = shards[pi][0][step]
            assert cls_l == cls_g
            assert size_l == size_g          # GLOBAL live count
            lo = pi * 4
            numpy.testing.assert_array_equal(idx_l, idx_g[lo:lo + 4])
            numpy.testing.assert_array_equal(mask_l, mask_g[lo:lo + 4])
            numpy.testing.assert_array_equal(data_l, data_g[lo:lo + 4])
        # every shard step count identical: lock-step guaranteed


def test_shard_spmd_rejects_indivisible_minibatch():
    import pytest
    wf = Workflow(None, name="wf")
    loader = ArrayLoader(wf, minibatch_size=9)
    with pytest.raises(ValueError):
        loader.shard_spmd(0, 2)
