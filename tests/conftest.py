"""Test harness config: force CPU with 8 virtual devices.

This is the TPU analogue of the reference's loopback master/slave trick
(SURVEY §4): distributed semantics are exercised on a virtual 8-device mesh
without hardware.  Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_prng():
    from veles_tpu import prng
    prng.reset()
    prng.seed_all(1)
    yield
    prng.reset()
