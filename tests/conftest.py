"""Test harness config: force CPU with 8 virtual devices.

This is the TPU analogue of the reference's loopback master/slave trick
(SURVEY §4): distributed semantics are exercised on a virtual 8-device mesh
without hardware.

Environment note: this image's sitecustomize registers the 'axon' TPU-tunnel
PJRT plugin in every process and forces JAX_PLATFORMS=axon, which OVERRIDES
the env var — only a jax.config update reliably selects CPU.  Keeping tests
off the tunnel matters doubly here: the tunnel admits one client at a time
and first-compiles are 20-40s.
"""

import os

#: VELES_TEST_TPU=1 leaves the platform alone so TPU-only tests (the
#: Pallas PRNG kernels) can run against the real device once per round;
#: everything else in the suite stays CPU-mesh as documented above.
_tpu_mode = os.environ.get("VELES_TEST_TPU", "0") not in ("", "0")

_flags = os.environ.get("XLA_FLAGS", "")
if not _tpu_mode and "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not _tpu_mode:
    jax.config.update("jax_platforms", "cpu")

# Partition-invariant jax.random bits for the WHOLE suite (this jax
# build defaults the flag off): parity tests compile replicated
# references and sharded runs in one process, and the two must draw the
# same dropout/augmentation bits (see veles_tpu.compat
# ensure_partitionable_rng — make_mesh flips it anyway; setting it here
# keeps every reference, whatever the test order, on one rng scheme).
jax.config.update("jax_threefry_partitionable", True)

# NOTE: do NOT arm the persistent jax compile cache here (bench.py's
# enable_compile_cache trick): this jaxlib's CPU executable
# deserialization segfaulted mid-suite when a warm .jax_cache was
# reused across pytest processes.  The tunnel-facing bench keeps the
# cache (TPU executables serialize fine and the 20-40s conv compiles
# are what wedge the relay); the CPU test suite stays cold.

import functools  # noqa: E402
import subprocess  # noqa: E402
import sys  # noqa: E402

import pytest  # noqa: E402


@functools.lru_cache(maxsize=1)
def _forced_device_count_probe():
    """Spawn ONE subprocess that forces a 2-device CPU host platform
    and report whether this jaxlib honors the flag — the serving-mesh
    analogue of test_multihost's cached collective probe: every
    sharded-serving test shares this single cheap check instead of
    each discovering (or flaking on) a single-device jaxlib on its
    own.  Returns (ok, detail)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    code = ("import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "print('DEVICES=%d' % jax.device_count())\n")
    try:
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True,
                             timeout=120)
    except Exception as e:   # noqa: BLE001 — probe infra failure
        return False, "probe subprocess failed: %s" % e
    for line in out.stdout.splitlines():
        if line.startswith("DEVICES="):
            n = int(line.split("=", 1)[1])
            return n >= 2, "forced-CPU subprocess saw %d device(s)" % n
    return False, ("probe printed no device count (rc %s): %s"
                   % (out.returncode, (out.stderr or "").strip()[-200:]))


@pytest.fixture(scope="session")
def serving_mesh():
    """Loud, cached gate for sharded-serving tests: ``serving_mesh(n)``
    returns the in-process device count when >= n and otherwise skips
    with a reason that says WHY this environment cannot host an
    n-device serving mesh (platform pinned vs jaxlib ignoring
    xla_force_host_platform_device_count) — a deterministic skip, not
    a flaky failure, on single-device jaxlibs."""
    import jax

    def require(n):
        have = jax.device_count()
        if have >= n:
            return have
        ok, detail = _forced_device_count_probe()
        why = ("the jaxlib CAN force host devices — this process's "
               "platform/flags pin it smaller" if ok else
               "this jaxlib ignores xla_force_host_platform_"
               "device_count")
        pytest.skip("serving-mesh test needs %d devices; this process "
                    "has %d (%s; %s)" % (n, have, why, detail))

    return require


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: sustained/heavy tests excluded from tier-1 "
                   "(deselected by -m 'not slow')")
    config.addinivalue_line(
        "markers", "kernel_parity: interpret-mode Pallas-vs-XLA parity "
                   "tests for the serving attention kernels (ISSUE 7) "
                   "— tier-1, and runnable standalone in <60s via "
                   "tools/check_kernel_parity.py")


@pytest.fixture(autouse=True)
def _fresh_prng():
    from veles_tpu import prng
    prng.reset()
    prng.seed_all(1)
    yield
    prng.reset()


#: suites the lock-order witness (ISSUE 15) is armed around: the
#: concurrency-heavy serving tests.  Everything else keeps the
#: unarmed one-None-check shims; tests/test_lint.py manages its own
#: witness (it asserts deliberate violations ARE caught).
_WITNESSED_SUITES = frozenset((
    "test_serving", "test_kv_pool", "test_tracing", "test_timeseries",
))


#: suites the TRANSFER-GUARD witness (ISSUE 17) is armed around: the
#: engine-worker hot path must only move data through the explicit
#: xfer shims.  Arming is via serving/xfer.py module state — the
#: engine worker thread enters ``jax.transfer_guard("disallow")``
#: itself (JAX guard state is thread-local), so the armed suites catch
#: implicit transfers exactly where they matter: inside the serving
#: loop and warmup, not in test-helper host math.
_TRANSFER_GUARDED_SUITES = frozenset((
    "test_serving", "test_lm_fastpath", "test_kv_pool",
))


@pytest.fixture(autouse=True)
def _transfer_guard_witness(request):
    """Arm ``jax.transfer_guard("disallow")`` for the serving suites:
    every LMEngine worker loop (and ``start()`` warmup) started during
    the test runs under the guard, so an implicit device↔host
    transfer on the hot path raises with the offending stack instead
    of silently syncing."""
    module = getattr(request.node, "module", None)
    name = getattr(module, "__name__", "")
    if name.rsplit(".", 1)[-1] not in _TRANSFER_GUARDED_SUITES:
        yield
        return
    from veles_tpu.serving import xfer
    xfer.arm("disallow")
    try:
        yield
    finally:
        xfer.disarm()


@pytest.fixture(autouse=True)
def _lock_order_witness(request):
    """Arm the serving lock-order witness for the serving suites: a
    fresh witness per test, disarmed at teardown, and any recorded
    violation — an acquisition-order cycle or a lock held across a
    device dispatch — fails the test loudly with both stacks."""
    module = getattr(request.node, "module", None)
    name = getattr(module, "__name__", "")
    if name.rsplit(".", 1)[-1] not in _WITNESSED_SUITES:
        yield
        return
    from veles_tpu.serving import lockcheck
    witness = lockcheck.LockOrderWitness(name="conftest:%s" % name)
    lockcheck.arm(witness)
    try:
        yield
    finally:
        lockcheck.disarm()
    assert not witness.violations, (
        "lock-order witness recorded %d violation(s) during %s:\n\n%s"
        % (len(witness.violations), request.node.nodeid,
           "\n\n".join(witness.violations)))
