"""Test harness config: force CPU with 8 virtual devices.

This is the TPU analogue of the reference's loopback master/slave trick
(SURVEY §4): distributed semantics are exercised on a virtual 8-device mesh
without hardware.

Environment note: this image's sitecustomize registers the 'axon' TPU-tunnel
PJRT plugin in every process and forces JAX_PLATFORMS=axon, which OVERRIDES
the env var — only a jax.config update reliably selects CPU.  Keeping tests
off the tunnel matters doubly here: the tunnel admits one client at a time
and first-compiles are 20-40s.
"""

import os

#: VELES_TEST_TPU=1 leaves the platform alone so TPU-only tests (the
#: Pallas PRNG kernels) can run against the real device once per round;
#: everything else in the suite stays CPU-mesh as documented above.
_tpu_mode = os.environ.get("VELES_TEST_TPU", "0") not in ("", "0")

_flags = os.environ.get("XLA_FLAGS", "")
if not _tpu_mode and "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not _tpu_mode:
    jax.config.update("jax_platforms", "cpu")

# Partition-invariant jax.random bits for the WHOLE suite (this jax
# build defaults the flag off): parity tests compile replicated
# references and sharded runs in one process, and the two must draw the
# same dropout/augmentation bits (see veles_tpu.compat
# ensure_partitionable_rng — make_mesh flips it anyway; setting it here
# keeps every reference, whatever the test order, on one rng scheme).
jax.config.update("jax_threefry_partitionable", True)

# NOTE: do NOT arm the persistent jax compile cache here (bench.py's
# enable_compile_cache trick): this jaxlib's CPU executable
# deserialization segfaulted mid-suite when a warm .jax_cache was
# reused across pytest processes.  The tunnel-facing bench keeps the
# cache (TPU executables serialize fine and the 20-40s conv compiles
# are what wedge the relay); the CPU test suite stays cold.

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: sustained/heavy tests excluded from tier-1 "
                   "(deselected by -m 'not slow')")
    config.addinivalue_line(
        "markers", "kernel_parity: interpret-mode Pallas-vs-XLA parity "
                   "tests for the serving attention kernels (ISSUE 7) "
                   "— tier-1, and runnable standalone in <60s via "
                   "tools/check_kernel_parity.py")


@pytest.fixture(autouse=True)
def _fresh_prng():
    from veles_tpu import prng
    prng.reset()
    prng.seed_all(1)
    yield
    prng.reset()
