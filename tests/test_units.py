"""Tier-1 graph mechanics: linking, gating, attribute aliasing.

Mirrors the reference's veles/tests/test_units.py coverage (SURVEY §4).
"""

import pytest

from veles_tpu.units import Unit, TrivialUnit
from veles_tpu.workflow import Workflow, Repeater


class Recorder(Unit):
    def __init__(self, workflow, log, **kwargs):
        super().__init__(workflow, **kwargs)
        self.log = log

    def run(self):
        self.log.append(self.name)


def test_link_from_and_open_gate_and_semantics():
    wf = Workflow(None, name="wf")
    a = TrivialUnit(wf, name="a")
    b = TrivialUnit(wf, name="b")
    c = TrivialUnit(wf, name="c")
    c.link_from(a, b)
    assert not c.open_gate(a)      # only one of two fired
    assert c.open_gate(b)          # both fired -> opens
    assert not c.open_gate(a)      # marks were reset by the open


def test_repeater_or_semantics():
    wf = Workflow(None, name="wf")
    a = TrivialUnit(wf, name="a")
    b = TrivialUnit(wf, name="b")
    r = Repeater(wf, name="rep")
    r.link_from(a, b)
    assert r.open_gate(a)
    assert r.open_gate(b)


def test_self_link_rejected():
    wf = Workflow(None, name="wf")
    a = TrivialUnit(wf, name="a")
    with pytest.raises(ValueError):
        a.link_from(a)


def test_unlink():
    wf = Workflow(None, name="wf")
    a = TrivialUnit(wf, name="a")
    b = TrivialUnit(wf, name="b")
    b.link_from(a)
    assert b in a.links_to
    b.unlink_from(a)
    assert b not in a.links_to and a not in b.links_from


def test_link_attrs_read_and_two_way_write():
    wf = Workflow(None, name="wf")
    src = TrivialUnit(wf, name="src")
    dst = TrivialUnit(wf, name="dst")
    src.output = 123
    dst.link_attrs(src, ("input", "output"))
    assert dst.input == 123
    src.output = 456
    assert dst.input == 456
    dst.input = 789              # two-way: writes through to src
    assert src.output == 789


def test_link_attrs_same_name_and_shadow_removed():
    wf = Workflow(None, name="wf")
    src = TrivialUnit(wf, name="src")
    dst = TrivialUnit(wf, name="dst")
    src.value = 1
    dst.value = 99               # local value must be dropped by the link
    dst.link_attrs(src, "value")
    assert dst.value == 1


def test_missing_attr_raises():
    wf = Workflow(None, name="wf")
    u = TrivialUnit(wf, name="u")
    with pytest.raises(AttributeError):
        u.no_such_attribute


def test_gate_block_stops_propagation():
    wf = Workflow(None, name="wf")
    log = []
    a = Recorder(wf, log, name="a")
    b = Recorder(wf, log, name="b")
    c = Recorder(wf, log, name="c")
    a.link_from(wf.start_point)
    b.link_from(a)
    c.link_from(b)
    b.gate_block <<= True
    wf.end_point.link_from(c)
    wf.run()
    assert log == ["a"]          # b blocked, c never reached


def test_gate_skip_propagates_without_running():
    wf = Workflow(None, name="wf")
    log = []
    a = Recorder(wf, log, name="a")
    b = Recorder(wf, log, name="b")
    c = Recorder(wf, log, name="c")
    a.link_from(wf.start_point)
    b.link_from(a)
    c.link_from(b)
    b.gate_skip <<= True
    wf.end_point.link_from(c)
    wf.run()
    assert log == ["a", "c"]


def test_gate_expression_flips_mid_run():
    wf = Workflow(None, name="wf")
    log = []

    class Flipper(Recorder):
        def run(self):
            super().run()
            gate.set(True)

    from veles_tpu.mutable import Bool
    gate = Bool(False)
    a = Flipper(wf, log, name="a")
    b = Recorder(wf, log, name="b")
    a.link_from(wf.start_point)
    b.link_from(a)
    b.gate_skip = ~(~gate)       # derived expression evaluated at fire time
    wf.end_point.link_from(b)
    wf.run()
    assert log == ["a"]          # flipped during a.run -> b skipped


def test_link_attrs_overrides_class_level_default():
    wf = Workflow(None, name="wf")

    class WithDefault(Unit):
        value = "CLASS_DEFAULT"

    src = TrivialUnit(wf, name="src")
    src.value = 42
    dst = WithDefault(wf, name="dst")
    dst.link_attrs(src, "value")
    assert dst.value == 42            # alias beats the class attribute


def test_registry_qualified_names():
    from veles_tpu.units import UnitRegistry
    key = "%s.%s" % (TrivialUnit.__module__, "TrivialUnit")
    assert UnitRegistry.units[key] is TrivialUnit
