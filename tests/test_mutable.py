"""Tier-1 Bool gate expression tests (ref behavior: veles/mutable.py)."""

import pytest

from veles_tpu.mutable import Bool


def test_plain_bool_assign():
    b = Bool()
    assert not b
    b <<= True
    assert b
    b.unset()
    assert not b


def test_derived_and_or_invert_track_sources():
    a, b = Bool(False), Bool(False)
    both = a & b
    either = a | b
    nota = ~a
    assert not both and not either and nota
    a <<= True
    assert not both and either and not nota
    b <<= True
    assert both and either


def test_derived_is_not_assignable():
    a = Bool(True)
    expr = ~a
    with pytest.raises(ValueError):
        expr <<= True
    with pytest.raises(ValueError):
        expr.set(True)


def test_compose_with_raw_python_bool():
    a = Bool(True)
    assert (a & True) and (a | False)
    a <<= False
    assert not (a & True)
