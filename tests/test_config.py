"""Tier-1 config-tree tests (ref behavior: veles/config.py, SURVEY §4)."""

import io

from veles_tpu.config import Config, Tune, get, parse_override


def test_autocreate_and_set():
    cfg = Config("root")
    cfg.loader.minibatch_size = 100
    assert cfg.loader.minibatch_size == 100
    assert get(cfg.loader.minibatch_size) == 100


def test_get_default_for_unset_leaf():
    cfg = Config("root")
    assert get(cfg.never.set_before, 42) == 42
    assert get(cfg.never.set_before) is None


def test_update_recursive_merge():
    cfg = Config("root")
    cfg.a.x = 1
    cfg.update({"a": {"y": 2}, "b": 3})
    assert cfg.a.x == 1
    assert cfg.a.y == 2
    assert cfg.b == 3


def test_dict_assignment_becomes_subtree():
    cfg = Config("root")
    cfg.layers = [{"type": "all2all", "n": 100}]
    assert cfg.layers[0]["type"] == "all2all"
    cfg.decision = {"max_epochs": 3}
    assert cfg.decision.max_epochs == 3


def test_tune_unwrap():
    t = Tune(0.01, 0.001, 0.1)
    assert get(t) == 0.01
    assert t.minv == 0.001 and t.maxv == 0.1


def test_parse_override_literal_and_string():
    cfg = Config("root")
    parse_override("root.loader.minibatch_size=64", cfg)
    parse_override("root.name=hello", cfg)
    parse_override("root.lr=0.05", cfg)
    assert cfg.loader.minibatch_size == 64
    assert cfg.name == "hello"
    assert abs(cfg.lr - 0.05) < 1e-12


def test_print(capsys=None):
    cfg = Config("root")
    cfg.a.b = 1
    out = io.StringIO()
    cfg.print_(file=out)
    assert "a:" in out.getvalue() and "b: 1" in out.getvalue()


def test_logger_does_not_touch_root_handlers():
    import logging
    sentinel = logging.NullHandler()
    logging.root.addHandler(sentinel)
    try:
        from veles_tpu.units import TrivialUnit
        from veles_tpu.workflow import Workflow
        u = TrivialUnit(Workflow(None, name="wf"), name="u")
        u.info("hello")
        assert sentinel in logging.root.handlers
    finally:
        logging.root.removeHandler(sentinel)
