"""LM serving fast path (ISSUE 4): radix prefix cache, chunked prefill,
prompt-lookup speculative decoding.

The contract under test: WHATEVER fast-path combination is enabled, the
engine's greedy output is BIT-IDENTICAL to ``ops/transformer.py::
generate`` — the features may only change how fast tokens appear, never
which tokens.  Plus the compile-count bound (one program per (bucket,
k) shape, via the jit-cache guard fixture), the cache-poisoning case,
eviction-then-reuse, and the shared-system-prompt hit-rate acceptance
criterion.
"""

import time

import numpy
import pytest


def _params(max_len=96, vocab=16, n_heads=2, n_layers=2, d_model=32):
    import jax
    import jax.numpy as jnp
    from veles_tpu import prng
    from veles_tpu.ops.transformer import init_transformer_params
    host = init_transformer_params(prng.get("init"), vocab,
                                   d_model=d_model, n_heads=n_heads,
                                   n_layers=n_layers, max_len=max_len)
    return jax.tree.map(jnp.asarray, host)


def _greedy(params, prompt, n_new, max_len, n_heads=2):
    import jax.numpy as jnp
    from veles_tpu.ops.transformer import generate
    return numpy.asarray(generate(
        params, jnp.asarray([prompt], jnp.int32), n_new, n_heads,
        temperature=0.0, max_len=max_len))[0]


@pytest.fixture
def jit_guard():
    """Collects an engine's jitted programs and asserts the compile
    count stayed bounded: ONE program per (shape) family — chunk
    prefill, verify, install/extract, step — regardless of how many
    prompt lengths and feature mixes the workload threw at it.  The
    acceptance criterion's guard: a fast path that silently forked a
    compile per prompt length would be a dispatch-latency regression
    dressed as a feature."""
    def check(engine, prefill_buckets=1):
        if engine._paged:
            # paged mode (ISSUE 6): the page-table indirection is
            # traced DATA, so the whole mixed-length workload owns
            # exactly one chunk and one page-copy program; step/verify
            # own one program PER LIVE-WIDTH LADDER ENTRY (ISSUE 7
            # satellite — the table is sliced to the batch's live page
            # span, the paged analogue of the contiguous prompt
            # buckets), still a static bound independent of the
            # workload's prompt-length mix
            widths = len(engine._width_ladder)
            progs = {
                "step": (engine._step_jit, widths),
                "chunk": (engine._chunk_jit, 1),
                "page_copy": (engine._page_copy_jit, 1),
            }
            if engine._verify_jit is not None:
                progs["verify"] = (engine._verify_jit, widths)
            if engine._megastep_jit is not None:
                # ISSUE 13: the fused program's asserted compile bound
                # — ONE megastep program per (live-width ladder entry
                # × K) family, K fixed per engine
                progs["megastep"] = (engine._megastep_jit, widths)
            if engine._whilestep_jit is not None:
                # ISSUE 19: the while-loop megastep keeps the SAME
                # bound — the iteration count is carry data, so early
                # exit adds zero program variants
                progs["whilestep"] = (engine._whilestep_jit, widths)
            for name, (fn, bound) in progs.items():
                size = fn._cache_size()
                assert size <= bound, (
                    "%s program compiled %d variants (bound %d)"
                    % (name, size, bound))
            return
        progs = {
            "step": (engine._step_jit, 1),
            "install": (engine._install_jit, 1),
            "prefill": (engine._prefill_jit, prefill_buckets),
        }
        if engine._chunk_jit is not None:
            progs["chunk"] = (engine._chunk_jit, 1)
            progs["chunk_install"] = (engine._chunk_install_jit, 1)
            progs["chunk_extract"] = (engine._chunk_extract_jit, 1)
        if engine._verify_jit is not None:
            progs["verify"] = (engine._verify_jit, 1)
        if engine._megastep_jit is not None:
            progs["megastep"] = (engine._megastep_jit, 1)
        if engine._whilestep_jit is not None:
            progs["whilestep"] = (engine._whilestep_jit, 1)
        for name, (fn, bound) in progs.items():
            size = fn._cache_size()
            assert size <= bound, (
                "%s program compiled %d variants (bound %d)"
                % (name, size, bound))
    return check


#: the feature-off engine's parity (incl. slot reuse) is already pinned
#: by tests/test_serving.py::TestLMEngine — these legs cover what's new
FEATURE_SETS = [
    {"prefill_chunk": 8},
    {"spec_k": 3},
    {"prefix_cache": 32, "prefill_chunk": 8},
    {"prefix_cache": 32, "prefill_chunk": 8, "spec_k": 3},
    # paged KV (ISSUE 6) — the page-table indirection under every
    # fast-path combination; paged_kv=12 also exercises a pool SMALLER
    # than slots×max_pages (lanes contend for pages and still finish)
    {"paged_kv": True, "prefill_chunk": 8},
    {"paged_kv": 12, "prefill_chunk": 8},
    {"paged_kv": True, "prefill_chunk": 8, "prefix_cache": 32},
    # paged+chunk+spec WITHOUT the cache rides the slow suite: the
    # full-stack superset two lines down keeps the same paths tier-1
    # (the PR 3/8 watchdog-headroom discipline, renewed for ISSUE 17's
    # armed-transfer-guard cost on this suite)
    pytest.param({"paged_kv": True, "prefill_chunk": 8, "spec_k": 3},
                 marks=pytest.mark.slow),
    {"paged_kv": True, "prefill_chunk": 8, "prefix_cache": 32,
     "spec_k": 3},
    # Pallas serving kernels (ISSUE 7): 'force' runs the REAL kernels
    # in interpret mode on CPU — the end-to-end kernel parity leg (the
    # full fast-path combination, so chunked prefill, prefix installs
    # and speculative verify all route through the kernels); 'auto'
    # off-TPU exercises the automatic XLA fallback end to end (parity
    # via the fallback, counter asserted in TestAttnKernelRouting)
    {"paged_kv": True, "prefill_chunk": 8, "prefix_cache": 32,
     "spec_k": 3, "attn_kernel": "force"},
    {"paged_kv": True, "prefill_chunk": 8, "attn_kernel": True},
    # sharded serving (ISSUE 8): the SAME programs under a 2-device
    # tensor-parallel mesh — plain decode, chunked+speculative, the
    # full paged fast path, and kernels-requested (which must fall
    # back to the XLA path under the mesh, metered, parity intact).
    # Skips loudly via the cached conftest probe on 1-device jaxlibs.
    {"tp": 2},
    {"tp": 2, "prefill_chunk": 8, "spec_k": 3},
    # the tp2 FULL paged stack rides the slow suite: tp2+chunk+spec
    # above and the non-tp full stack keep both dimensions tier-1
    # (watchdog-headroom discipline)
    pytest.param({"tp": 2, "paged_kv": True, "prefill_chunk": 8,
                  "prefix_cache": 32, "spec_k": 3},
                 marks=pytest.mark.slow),
    {"tp": 2, "paged_kv": True, "prefill_chunk": 8,
     "attn_kernel": True},
]


class TestFastPathParity:
    @pytest.mark.parametrize("features", FEATURE_SETS,
                             ids=lambda f: "+".join(sorted(f)) or "off")
    def test_bit_identical_with_slot_reuse(self, features, jit_guard,
                                           serving_mesh):
        """5 prompts of assorted lengths through 2 slots (forced slot
        reuse) under every feature combination: every output equals the
        direct greedy generate, and the jit cache stays at one program
        per family."""
        from veles_tpu.serving import LMEngine
        if features.get("tp"):
            serving_mesh(features["tp"])
        params = _params()
        prompts = [[1, 2, 3], [2, 4, 6, 8, 10], [7, 7],
                   [5, 1, 5, 1, 5, 1, 5, 1, 5],
                   list(range(1, 15)) + list(range(1, 15))]
        n_new = 7
        expected = [_greedy(params, p, n_new, 96) for p in prompts]
        engine = LMEngine(params, n_heads=2, max_len=96, slots=2,
                          name="fp_par", **features).start()
        try:
            futures = [engine.submit(p, n_new) for p in prompts]
            for p, f, exp in zip(prompts, futures, expected):
                got = numpy.concatenate([p, f.result(timeout=120)])
                numpy.testing.assert_array_equal(got, exp)
            # without chunking, whole-prompt prefill legitimately owns
            # one program per power-of-two bucket (incl. the warmup's);
            # with chunking, the chunk program replaces them all
            if features.get("prefill_chunk"):
                buckets = 1
            else:
                from veles_tpu.serving import prompt_bucket
                buckets = len({prompt_bucket(n, 96)
                               for n in [1] + [len(p) for p in prompts]})
            jit_guard(engine, prefill_buckets=buckets)
            if features.get("tp") and features.get("attn_kernel"):
                # kernels under a tp mesh are a structural fallback —
                # the XLA path must have served (and metered) every
                # dispatch
                c = engine.metrics.snapshot()["counters"]
                assert c.get("attn_kernel_fallbacks", 0) > 0
                assert "attn_kernel_dispatches" not in c
        finally:
            engine.stop()

    def test_cache_poisoning_diverge_mid_chunk(self):
        """Two prompts share a prefix but diverge MID-chunk: the second
        must not reuse the first's chunk (keys are the literal chunk
        tokens) and both outputs stay exactly greedy."""
        from veles_tpu.serving import LMEngine
        params = _params()
        C = 8
        a = [1, 2, 3, 4, 5, 6, 7, 8,   9, 10, 11, 12, 13, 14, 15, 1, 2]
        b = list(a)
        b[11] = 3          # diverges inside the SECOND chunk
        exp_a = _greedy(params, a, 6, 96)
        exp_b = _greedy(params, b, 6, 96)
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          prefix_cache=32, prefill_chunk=C,
                          name="fp_poison").start()
        try:
            got_a = numpy.concatenate(
                [a, engine.submit(a, 6).result(timeout=60)])
            got_b = numpy.concatenate(
                [b, engine.submit(b, 6).result(timeout=60)])
            numpy.testing.assert_array_equal(got_a, exp_a)
            numpy.testing.assert_array_equal(got_b, exp_b)
            c = engine.metrics.snapshot()["counters"]
            # b reused ONLY the first (identical) chunk — the diverged
            # second chunk missed and was recomputed
            assert c["prefix_hit_chunks"] == 1
            assert c["prefix_hit_tokens"] == C
        finally:
            engine.stop()

    def test_slot_reuse_after_eviction(self):
        """A capacity-2 cache thrashed by distinct prompts: entries
        evict (LRU), slots recycle, and every output — including a
        RE-submission of the first prompt after its entry was evicted —
        stays exactly greedy."""
        from veles_tpu.serving import LMEngine
        params = _params()
        rng = numpy.random.RandomState(4)
        prompts = [rng.randint(0, 16, 20).tolist() for _ in range(4)]
        prompts.append(list(prompts[0]))     # resubmit the evicted one
        expected = [_greedy(params, p, 5, 96) for p in prompts]
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          prefix_cache=2, prefill_chunk=8,
                          name="fp_evict").start()
        try:
            for p, exp in zip(prompts, expected):
                got = numpy.concatenate(
                    [p, engine.submit(p, 5).result(timeout=60)])
                numpy.testing.assert_array_equal(got, exp)
            assert engine._trie.size <= 2      # capacity held
        finally:
            engine.stop()

    def test_shared_system_prompt_hit_rate(self):
        """ACCEPTANCE: 8 requests sharing a 40-token system prompt —
        the cache serves >= 7/8 of the shared rows (only the first
        request computes them) and every reply is bit-identical to the
        per-request greedy generate."""
        from veles_tpu.serving import LMEngine
        params = _params(max_len=128)
        rng = numpy.random.RandomState(0)
        C = 8
        shared = rng.randint(0, 16, 40).tolist()       # 5 full chunks
        prompts = [shared + rng.randint(0, 16, 5).tolist()
                   for _ in range(8)]
        expected = [_greedy(params, p, 4, 128) for p in prompts]
        engine = LMEngine(params, n_heads=2, max_len=128, slots=2,
                          prefix_cache=64, prefill_chunk=C,
                          name="fp_shared").start()
        try:
            for p, exp in zip(prompts, expected):
                got = numpy.concatenate(
                    [p, engine.submit(p, 4).result(timeout=60)])
                numpy.testing.assert_array_equal(got, exp)
            c = engine.metrics.snapshot()["counters"]
            shared_rows = (len(shared) // C) * C       # 40
            assert c["prefix_hit_tokens"] >= 7 * shared_rows, c
            # prefilled-token count dropped by what the cache served
            total = sum(len(p) for p in prompts)
            assert c["prefill_tokens"] == total - c["prefix_hit_tokens"]
        finally:
            engine.stop()

    def test_speculative_sub_unit_dispatches(self):
        """ACCEPTANCE: on repetitive (prompt-lookup-friendly) text the
        engine emits MORE than one token per decode dispatch — and the
        tokens are still exactly the greedy ones."""
        from veles_tpu.serving import LMEngine
        params = _params(max_len=128)
        rep = [3, 1, 4, 1, 5, 9, 2, 6] * 4
        exp = _greedy(params, rep, 32, 128)
        engine = LMEngine(params, n_heads=2, max_len=128, slots=1,
                          spec_k=4, name="fp_spec").start()
        try:
            got = numpy.concatenate(
                [rep, engine.submit(rep, 32).result(timeout=120)])
            numpy.testing.assert_array_equal(got, exp)
            c = engine.metrics.snapshot()["counters"]
            assert c["decode_dispatches"] < c["tokens_out"], c
            assert c["draft_accepted"] > 0
        finally:
            engine.stop()

    def test_mixed_workload_compile_bound(self, jit_guard):
        """ACCEPTANCE: a mixed chunked-prefill/decode/speculative
        workload over many distinct prompt lengths compiles ONE program
        per (bucket, k) shape — the jit-cache guard holds after the
        storm."""
        from veles_tpu.serving import LMEngine
        params = _params(max_len=96)
        rng = numpy.random.RandomState(1)
        engine = LMEngine(params, n_heads=2, max_len=96, slots=3,
                          prefix_cache=16, prefill_chunk=8, spec_k=3,
                          name="fp_mixed").start()
        try:
            futures = []
            for length in (1, 3, 7, 13, 17, 25, 41):
                p = rng.randint(0, 16, length).tolist()
                futures.append((p, engine.submit(p, 5)))
            for p, f in futures:
                got = numpy.concatenate([p, f.result(timeout=120)])
                numpy.testing.assert_array_equal(
                    got, _greedy(params, p, 5, 96))
            jit_guard(engine)
        finally:
            engine.stop()

    def test_spec_headroom_validation(self):
        """spec_k writes up to k positions past the committed front, so
        admission requires that headroom explicitly."""
        from veles_tpu.serving import LMEngine
        params = _params(max_len=32)
        engine = LMEngine(params, n_heads=2, max_len=32, slots=1,
                          spec_k=4, name="fp_head").start()
        try:
            with pytest.raises(ValueError, match="speculative headroom"):
                engine.submit(list(range(1, 21)), 9)   # 20+9+4 > 32
            fut = engine.submit(list(range(1, 20)), 9)  # 19+9+4 == 32
            assert len(fut.result(timeout=60)) == 9
        finally:
            engine.stop()


class TestPagedKV:
    """ISSUE 6 acceptance: zero-copy prefix sharing, the paged compile
    bound, and pool-pressure behavior (queue/shed, never a hang)."""

    def test_shared_prefix_zero_copy(self):
        """ACCEPTANCE: 8 requests sharing a 40-token system prompt
        under paged_kv — every shared-prefix hit installs a page
        REFERENCE (kv_pages_referenced >= 7 requests × 5 chunks), the
        row-copy counter stays at ZERO on the pure-hit path, no
        copy-on-write fires (appends land past the prompt), and every
        reply is bit-identical to the per-request greedy generate."""
        from veles_tpu.serving import LMEngine
        params = _params(max_len=128)
        rng = numpy.random.RandomState(0)
        C = 8
        shared = rng.randint(0, 16, 40).tolist()       # 5 full chunks
        prompts = [shared + rng.randint(0, 16, 5).tolist()
                   for _ in range(8)]
        expected = [_greedy(params, p, 4, 128) for p in prompts]
        engine = LMEngine(params, n_heads=2, max_len=128, slots=2,
                          prefix_cache=64, prefill_chunk=C,
                          paged_kv=True, name="pg_zc").start()
        try:
            for p, exp in zip(prompts, expected):
                got = numpy.concatenate(
                    [p, engine.submit(p, 4).result(timeout=60)])
                numpy.testing.assert_array_equal(got, exp)
            c = engine.metrics.snapshot()["counters"]
            assert c.get("kv_row_copies", 0) == 0, c
            assert c.get("kv_cow_copies", 0) == 0, c
            assert c["kv_pages_referenced"] >= 7 * (len(shared) // C), c
            assert c["prefix_hit_tokens"] >= 7 * len(shared) // C * C
        finally:
            engine.stop()

    def test_mixed_length_compile_bound(self, jit_guard):
        """Satellite (CI guard): a mixed-length paged workload with
        speculation compiles ONE program per family — the page-table
        indirection must not reintroduce a shape-keyed compile
        ladder."""
        from veles_tpu.serving import LMEngine
        params = _params(max_len=96)
        rng = numpy.random.RandomState(1)
        engine = LMEngine(params, n_heads=2, max_len=96, slots=3,
                          prefix_cache=16, prefill_chunk=8, spec_k=3,
                          paged_kv=True, name="pg_mixed").start()
        try:
            futures = []
            for length in (1, 3, 7, 13, 17, 25, 41):
                p = rng.randint(0, 16, length).tolist()
                futures.append((p, engine.submit(p, 5)))
            for p, f in futures:
                got = numpy.concatenate([p, f.result(timeout=120)])
                numpy.testing.assert_array_equal(
                    got, _greedy(params, p, 5, 96))
            jit_guard(engine)
        finally:
            engine.stop()

    @pytest.mark.parametrize("attn", [
        # tier-1 keeps ONE representative: the kernel leg covers the
        # window/sink band, batched rope AND the Pallas in-kernel
        # reproduction in a single run; the two XLA-only geometries
        # ride the slow suite (same discipline as the PR-3 runtime
        # trim — the 870s watchdog pays per redundant heavyweight leg)
        pytest.param({"rope": True}, marks=pytest.mark.slow),
        pytest.param({"rope": True, "window": 24, "sinks": 2},
                     marks=pytest.mark.slow),
        {"rope": True, "window": 24, "sinks": 2,
         "_attn_kernel": "force"},
    ], ids=lambda a: "+".join(sorted(a)))
    def test_rope_window_sinks_parity(self, attn):
        """serve_lm forwards the trainer's rope/window/sinks into the
        engine, so the paged path must hold bit-parity under them too —
        rope_rotate_batched (per-lane traced positions) and the vmapped
        chunk_live_mask against generate's shared-position math, across
        slot reuse and speculation."""
        import jax.numpy as jnp
        from veles_tpu.ops.transformer import generate
        from veles_tpu.serving import LMEngine
        params = _params()
        attn = dict(attn)
        attn_kernel = attn.pop("_attn_kernel", 0)
        prompts = [[1, 2, 3], [2, 4, 6, 8, 10, 12, 14],
                   [5, 1] * 9, list(range(1, 14))]
        n_new = 7

        def greedy(p):
            return numpy.asarray(generate(
                params, jnp.asarray([p], jnp.int32), n_new, 2,
                temperature=0.0, max_len=96, **attn))[0]

        expected = [greedy(p) for p in prompts]
        engine = LMEngine(params, n_heads=2, max_len=96, slots=2,
                          paged_kv=True, prefill_chunk=8, spec_k=2,
                          name="pg_attn", attn_kernel=attn_kernel,
                          **attn).start()
        try:
            futures = [engine.submit(p, n_new) for p in prompts]
            for p, f, exp in zip(prompts, futures, expected):
                got = numpy.concatenate([p, f.result(timeout=120)])
                numpy.testing.assert_array_equal(got, exp)
        finally:
            engine.stop()

    def test_pool_pressure_queues_then_completes(self):
        """More concurrent demand than the pool covers: later requests
        QUEUE on pages (slots are free, pages are not) and complete as
        earlier lanes release — nothing hangs, everything stays exactly
        greedy, and the pool drains back to full when done."""
        from veles_tpu.serving import LMEngine
        params = _params(max_len=96)
        rng = numpy.random.RandomState(3)
        # each request: ceil((16 + 8)/8) = 3 pages; pool of 6 runs at
        # most 2 of the 4 slots concurrently
        engine = LMEngine(params, n_heads=2, max_len=96, slots=4,
                          paged_kv=6, prefill_chunk=8,
                          name="pg_press").start()
        try:
            prompts = [rng.randint(0, 16, 16).tolist() for _ in range(4)]
            expected = [_greedy(params, p, 8, 96) for p in prompts]
            futures = [engine.submit(p, 8) for p in prompts]
            for p, f, exp in zip(prompts, futures, expected):
                got = numpy.concatenate([p, f.result(timeout=120)])
                numpy.testing.assert_array_equal(got, exp)
            assert engine._pool.free_pages == engine._pool.num_pages
        finally:
            engine.stop()

    def test_pool_flood_rejects_with_pool_exhausted(self):
        """ACCEPTANCE (never a hang): once the queued page demand
        covers 2× the pool, new arrivals 429 with PoolExhausted —
        distinguishable from queue-depth Overloaded — and every
        admitted request still finishes."""
        import time as time_mod
        from veles_tpu.serving import LMEngine, Overloaded, PoolExhausted
        params = _params(max_len=96)
        engine = LMEngine(params, n_heads=2, max_len=96, slots=4,
                          paged_kv=6, prefill_chunk=8,
                          name="pg_flood").start()
        real_step = engine._step_jit

        def slow_step(*a):
            time_mod.sleep(0.05)
            return real_step(*a)

        engine._step_jit = slow_step
        try:
            prompt = list(range(1, 17))          # 3 pages per request
            futures, rejected = [], 0
            for _ in range(12):
                try:
                    futures.append(engine.submit(prompt, 8))
                except PoolExhausted as e:
                    assert isinstance(e, Overloaded)   # same 429 path
                    assert e.retry_after > 0
                    rejected += 1
            engine._step_jit = real_step
            assert rejected > 0
            for f in futures:
                assert len(f.result(timeout=120)) == 8
            snap = engine.metrics.snapshot()
            assert snap["counters"]["rejected_pages"] == 3 * rejected
        finally:
            engine._step_jit = real_step
            engine.stop()

    def test_unplaceable_request_refused_up_front(self):
        """A request whose worst-case span exceeds the WHOLE pool can
        never run — submit raises ValueError immediately instead of
        letting it queue to its deadline."""
        from veles_tpu.serving import LMEngine
        params = _params(max_len=96)
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          paged_kv=2, prefill_chunk=8,
                          name="pg_big").start()
        try:
            with pytest.raises(ValueError, match="never be placed"):
                engine.submit(list(range(1, 30)), 8)   # needs 5 > 2
            fut = engine.submit([1, 2, 3], 8)          # 2 pages: fits
            assert len(fut.result(timeout=60)) == 8
        finally:
            engine.stop()

    def test_max_len_must_divide_by_page(self):
        from veles_tpu.serving import LMEngine
        params = _params(max_len=96)
        with pytest.raises(ValueError, match="divisible"):
            LMEngine(params, n_heads=2, max_len=96, slots=1,
                     paged_kv=True, prefill_chunk=7, name="pg_div")
        # defaulted page size (no prefill_chunk given) must pick a
        # DIVISOR of max_len, not a flat 32 that 48 can't divide by
        eng = LMEngine(params, n_heads=2, max_len=48, slots=1,
                       paged_kv=True, name="pg_div_def")
        assert eng.prefill_chunk == 24
        assert 48 % eng.prefill_chunk == 0

    def test_pool_gauges_in_metrics(self):
        """Satellite: the KV pool gauges land in the snapshot
        (/metrics.json) and the Prometheus text (/metrics)."""
        from veles_tpu.serving import LMEngine
        from veles_tpu.serving import metrics as metrics_mod
        params = _params(max_len=96)
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          paged_kv=True, prefill_chunk=8,
                          prefix_cache=8, name="pg_gauge",
                          metrics=metrics_mod.new("pg_gauge")).start()
        try:
            engine.submit([1, 2, 3, 4, 5], 4).result(timeout=60)
            snap = engine.metrics.snapshot()
            g = snap["gauges"]
            assert g["kv_pages_total"] == 12 * 1     # max_pages × slots
            assert g["kv_pages_free"] <= g["kv_pages_total"]
            assert g["kv_pages_pinned"] == 0         # lane finished
            text = metrics_mod.render_prometheus()
            assert text.count(
                "# TYPE veles_serving_kv_pages_total gauge") == 1
            assert 'veles_serving_kv_pages_free{engine="pg_gauge"}' \
                in text
        finally:
            engine.stop()


class TestAttnKernelRouting:
    """ISSUE 7: the serving-kernel switch — fallback rules, the
    per-dispatch counters, the live-width ladder, and the engine-level
    validation."""

    def test_cpu_auto_falls_back_and_counts(self):
        """On CPU, attn_kernel='auto' must serve through the XLA path
        (parity trivially intact), increment attn_kernel_fallbacks per
        dispatch, record the reason, and render the counter on
        /metrics with one # TYPE line."""
        from veles_tpu.serving import LMEngine
        from veles_tpu.serving import metrics as metrics_mod
        from veles_tpu.ops.pallas_kernels import on_tpu
        if on_tpu():
            pytest.skip("on-TPU: auto resolves to the kernel path")
        params = _params()
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          paged_kv=True, prefill_chunk=8,
                          attn_kernel="auto", name="ak_auto",
                          metrics=metrics_mod.new("ak_auto")).start()
        try:
            assert not engine._kernel_active
            assert "TPU" in engine._kernel_fallback_reason
            got = numpy.concatenate(
                [[1, 2, 3], engine.submit([1, 2, 3], 4).result(
                    timeout=60)])
            numpy.testing.assert_array_equal(
                got, _greedy(params, [1, 2, 3], 4, 96))
            snap = engine.metrics.snapshot()
            assert snap["counters"]["attn_kernel_fallbacks"] > 0
            assert "attn_kernel_dispatches" not in snap["counters"]
            assert snap["gauges"]["attn_kernel_active"] == 0
            text = metrics_mod.render_prometheus()
            assert text.count("# TYPE veles_serving_"
                              "attn_kernel_fallbacks_total counter") == 1
            assert ('veles_serving_attn_kernel_fallbacks_total'
                    '{engine="ak_auto"}') in text
        finally:
            engine.stop()

    def test_contiguous_geometry_falls_back(self):
        """attn_kernel on a CONTIGUOUS engine is an unsupported
        geometry — fallback with a reason naming paged_kv, never an
        error, and the serving output stays exactly greedy."""
        from veles_tpu.serving import LMEngine
        params = _params()
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          prefill_chunk=8, attn_kernel="force",
                          name="ak_contig").start()
        try:
            assert not engine._kernel_active
            assert "paged_kv" in engine._kernel_fallback_reason
            got = numpy.concatenate(
                [[7, 7, 7], engine.submit([7, 7, 7], 4).result(
                    timeout=60)])
            numpy.testing.assert_array_equal(
                got, _greedy(params, [7, 7, 7], 4, 96))
            c = engine.metrics.snapshot()["counters"]
            assert c["attn_kernel_fallbacks"] > 0
        finally:
            engine.stop()

    def test_force_counts_kernel_dispatches(self):
        """'force' on CPU runs the interpret-mode kernels for real:
        every decode/prefill dispatch lands in attn_kernel_dispatches
        and none in the fallback counter."""
        from veles_tpu.serving import LMEngine
        params = _params()
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          paged_kv=True, prefill_chunk=8,
                          attn_kernel="force", name="ak_force").start()
        try:
            assert engine._kernel_active
            got = numpy.concatenate(
                [[1, 2, 3], engine.submit([1, 2, 3], 3).result(
                    timeout=120)])
            numpy.testing.assert_array_equal(
                got, _greedy(params, [1, 2, 3], 3, 96))
            c = engine.metrics.snapshot()["counters"]
            assert c["attn_kernel_dispatches"] > 0
            assert "attn_kernel_fallbacks" not in c
        finally:
            engine.stop()

    def test_flash_serve_backend_default(self):
        """set_attention_backend('flash_serve') flips the DEFAULT for
        engines built while it is set (attn_kernel=None follows it;
        explicit 0 still wins), without touching mha_forward's path."""
        from veles_tpu.ops import attention as A
        from veles_tpu.serving import LMEngine
        params = _params()
        A.set_attention_backend("flash_serve")
        try:
            eng = LMEngine(params, n_heads=2, max_len=96, slots=1,
                           paged_kv=True, prefill_chunk=8,
                           name="ak_glob")
            assert eng.attn_kernel == "auto"
            off = LMEngine(params, n_heads=2, max_len=96, slots=1,
                           paged_kv=True, prefill_chunk=8,
                           attn_kernel=0, name="ak_glob_off")
            assert off.attn_kernel == 0
        finally:
            A.set_attention_backend("xla")
        plain = LMEngine(params, n_heads=2, max_len=96, slots=1,
                         paged_kv=True, prefill_chunk=8,
                         name="ak_glob_plain")
        assert plain.attn_kernel == 0

    def test_invalid_mode_rejected(self):
        from veles_tpu.serving import LMEngine
        params = _params()
        with pytest.raises(ValueError, match="attn_kernel"):
            LMEngine(params, n_heads=2, max_len=96, slots=1,
                     paged_kv=True, prefill_chunk=8,
                     attn_kernel="sometimes", name="ak_bad")

    def test_live_width_ladder(self):
        """The decode/verify table slice (ISSUE 7 satellite): the
        width ladder is the power-of-two chain capped at max_pages,
        and _live_width covers every slot's frontier — including a
        prefilling lane parked deep in its prompt — so no write can
        clamp onto a live page."""
        from veles_tpu.serving import LMEngine
        params = _params()
        engine = LMEngine(params, n_heads=2, max_len=96, slots=2,
                          paged_kv=True, prefill_chunk=8,
                          name="ak_width")
        assert engine._width_ladder == [1, 2, 4, 8, 12]
        engine._pos[:] = 0
        assert engine._live_width(1) == 1
        engine._pos[0] = 7          # page 0 frontier
        assert engine._live_width(1) == 1
        assert engine._live_width(2) == 2   # straddles into page 1
        engine._pos[1] = 40         # a lane parked 5 pages deep
        assert engine._live_width(1) == 8
        engine._pos[1] = 88         # deepest legal frontier
        assert engine._live_width(8) == 12  # capped at max_pages


class TestShardedDecode:
    """ISSUE 8: tensor-parallel decode under a ('tp',) mesh — the
    acceptance criteria beyond the parity matrix: a 4-device mesh,
    real weight/KV sharding (not silent replication), the
    kernel-fallback rule, device-slice pinning for replicas, and the
    validation surface."""

    @pytest.mark.slow   # tp=2 legs keep sharded decode tier-1; the
    # 4-way width re-proof pays 16s per run (watchdog-headroom)
    def test_tp4_mesh_full_fastpath_parity(self, serving_mesh,
                                           jit_guard):
        """4-way sharded decode with the whole fast path stacked
        (paged + prefix cache + chunking + speculation) is
        bit-identical to single-device generate, at one program per
        family (n_heads=4 so whole heads shard 4 ways)."""
        serving_mesh(4)
        from veles_tpu.serving import LMEngine
        params = _params(n_heads=4)
        prompts = [[1, 2, 3], [2, 4, 6, 8, 10, 12, 14], [5, 1] * 9]
        n_new = 5
        expected = [_greedy(params, p, n_new, 96, n_heads=4)
                    for p in prompts]
        engine = LMEngine(params, n_heads=4, max_len=96, slots=2,
                          tp=4, paged_kv=True, prefill_chunk=8,
                          prefix_cache=32, spec_k=3,
                          name="tp4").start()
        try:
            futures = [engine.submit(p, n_new) for p in prompts]
            for p, f, exp in zip(prompts, futures, expected):
                got = numpy.concatenate([p, f.result(timeout=120)])
                numpy.testing.assert_array_equal(got, exp)
            jit_guard(engine)
        finally:
            engine.stop()

    def test_weights_and_kv_actually_sharded(self, serving_mesh):
        """The mesh must SHARD, not replicate: wq/wk/wv split over
        their output dim, wo over its input dim, and the KV pool over
        its kv_heads axis — each device holds 1/tp of the bytes."""
        serving_mesh(2)
        from veles_tpu.serving import LMEngine
        params = _params()
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          tp=2, paged_kv=True, prefill_chunk=8,
                          name="tp_shard")
        blk = engine.params["blocks"][0]
        for name, axis in (("wq", 1), ("wk", 1), ("wv", 1), ("wo", 0)):
            arr = blk["attn"][name]
            shards = list(arr.addressable_shards)
            assert len(shards) == 2, name
            assert shards[0].data.shape[axis] \
                == arr.shape[axis] // 2, name
        k_pool, _ = engine._kv_pools[0]
        shards = list(k_pool.addressable_shards)
        assert len(shards) == 2
        assert shards[0].data.shape[1] == k_pool.shape[1] // 2
        # replicated leaves stay whole everywhere
        emb = engine.params["embed"]
        assert all(s.data.shape == emb.shape
                   for s in emb.addressable_shards)

    def test_kernel_fallback_under_mesh(self, serving_mesh):
        """attn_kernel under tp is a structural fallback (a
        pallas_call is single-device): resolved at CONSTRUCTION with a
        reason naming the mesh, even 'force' — the decode-through-
        the-fallback parity and per-dispatch metering ride the
        attn_kernel+tp leg of the parity matrix, so this stays a
        cheap constructor check."""
        serving_mesh(2)
        from veles_tpu.serving import LMEngine
        params = _params()
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          tp=2, paged_kv=True, prefill_chunk=8,
                          attn_kernel="force", name="tp_kern")
        assert not engine._kernel_active
        assert "tensor-parallel" in engine._kernel_fallback_reason
        assert engine.metrics.gauge("attn_kernel_active") == 0

    def test_single_device_replica_pinned(self, serving_mesh):
        """``devices=[d]`` (a data-parallel replica's slice) commits
        weights and KV to that device — programs run there, output
        unchanged."""
        serving_mesh(2)
        import jax
        from veles_tpu.serving import LMEngine
        dev = jax.devices()[1]
        params = _params()
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          devices=[dev], prefill_chunk=8,
                          name="dev_pin").start()
        try:
            assert list(engine.params["embed"].devices()) == [dev]
            assert list(engine._caches[0][0].devices()) == [dev]
            got = numpy.concatenate(
                [[5, 6, 7], engine.submit([5, 6, 7], 4).result(
                    timeout=60)])
            numpy.testing.assert_array_equal(
                got, _greedy(params, [5, 6, 7], 4, 96))
        finally:
            engine.stop()

    def test_tp_validation(self, serving_mesh):
        from veles_tpu.serving import LMEngine
        params = _params()          # n_heads=2
        with pytest.raises(ValueError, match="divide n_heads"):
            LMEngine(params, n_heads=2, max_len=96, slots=1, tp=3,
                     name="tp_bad")
        with pytest.raises(ValueError, match="tp must be >= 0"):
            LMEngine(params, n_heads=2, max_len=96, slots=1, tp=-1,
                     name="tp_neg")
        serving_mesh(2)
        import jax
        with pytest.raises(ValueError, match="devices"):
            LMEngine(params, n_heads=2, max_len=96, slots=1,
                     tp=2, devices=jax.devices()[:1], name="tp_short")


class TestPromptLookup:
    def test_draft_finds_recent_continuation(self):
        from veles_tpu.serving import propose_draft
        hist = [1, 2, 3, 9, 9, 1, 2, 3]
        d = propose_draft(hist, 2, max_ngram=3)
        # last trigram (1,2,3) occurred at 0 → continuation (9, 9)
        numpy.testing.assert_array_equal(d, [9, 9])

    def test_draft_prefers_most_recent_match(self):
        from veles_tpu.serving import propose_draft
        hist = [1, 2, 5, 7, 1, 2, 6, 8, 1, 2]
        d = propose_draft(hist, 2, max_ngram=3)
        # bigram (1,2) matched at index 4 (most recent) → (6, 8)
        numpy.testing.assert_array_equal(d, [6, 8])

    def test_draft_none_without_recurrence(self):
        from veles_tpu.serving import propose_draft
        assert propose_draft([1, 2, 3, 4, 5], 3) is None
        assert propose_draft([1], 3) is None

    def test_draft_short_continuation_unpadded(self):
        from veles_tpu.serving import propose_draft
        d = propose_draft([5, 6, 5, 6], 4, max_ngram=2)
        # only 2 real continuation tokens exist after the match — the
        # draft is exactly those (the engine pads to k for the fixed
        # program shape, but meters only these real tokens)
        numpy.testing.assert_array_equal(d, [5, 6])


class TestRadixCache:
    def test_match_insert_release(self):
        from veles_tpu.serving import RadixPrefixCache
        trie = RadixPrefixCache(capacity=8, chunk=4)
        a, b = (1, 2, 3, 4), (5, 6, 7, 8)
        n1 = trie.insert(trie.root, a, "rows_a")
        n2 = trie.insert(n1, b, "rows_b")
        assert trie.size == 2
        matched = trie.match([a, b])
        assert [n.rows for n in matched] == ["rows_a", "rows_b"]
        assert trie.match([b]) == []             # not a root child
        assert trie.match([a, (9, 9, 9, 9)]) == [matched[0]]
        trie.release(matched + [n1, n2])
        trie.release(trie.match([a]))            # re-pin/release cycle

    def test_eviction_skips_pinned_lru_leaf_first(self):
        from veles_tpu.serving import RadixPrefixCache
        trie = RadixPrefixCache(capacity=2, chunk=4)
        a = trie.insert(trie.root, (1,) * 4, "a")
        trie.insert(trie.root, (2,) * 4, "b")
        trie.release([a])                        # b stays pinned
        # full: inserting c must evict the LRU UNPINNED leaf — a
        c = trie.insert(trie.root, (3,) * 4, "c")
        assert c is not None and trie.size == 2
        assert trie.match([(1,) * 4]) == []      # a is gone
        assert len(trie.match([(2,) * 4])) == 1  # pinned b survived

    def test_insert_refuses_when_all_pinned(self):
        from veles_tpu.serving import RadixPrefixCache
        trie = RadixPrefixCache(capacity=1, chunk=4)
        trie.insert(trie.root, (1,) * 4, "a")    # pinned by insert
        assert trie.insert(trie.root, (2,) * 4, "b") is None
        assert trie.size == 1


#: ISSUE 13 parity matrix: K ∈ {1, 4, 8} × the fast-path features.
#: Tier-1 keeps ONE representative per family (contiguous plain, the
#: full paged+spec stack at K=8, tp=2, interpret kernels; the K=1
#: no-op family is pinned by test_validation_and_noop); redundant
#: K × feature geometries ride the slow suite — the PR 3/8 watchdog-
#: headroom discipline.
MEGASTEP_SETS = [
    # K=1 parity rides the slow suite: test_validation_and_noop pins
    # K=1 == tick path (no fused program built), and the tick path's
    # paged+chunk+spec parity is FastPathParity's full-stack leg —
    # this entry re-proved both at 15s (watchdog-headroom discipline)
    pytest.param(1, {"paged_kv": True, "prefill_chunk": 8,
                     "spec_k": 3}, marks=pytest.mark.slow),
    (4, {}),
    (8, {"paged_kv": True, "prefill_chunk": 8, "prefix_cache": 32,
         "spec_k": 3}),
    (4, {"tp": 2, "paged_kv": True, "prefill_chunk": 8, "spec_k": 3}),
    (4, {"paged_kv": True, "prefill_chunk": 8,
         "attn_kernel": "force"}),
    pytest.param(4, {"prefill_chunk": 8}, marks=pytest.mark.slow),
    pytest.param(4, {"spec_k": 3}, marks=pytest.mark.slow),
    pytest.param(8, {}, marks=pytest.mark.slow),
    pytest.param(4, {"paged_kv": True, "prefill_chunk": 8},
                 marks=pytest.mark.slow),
    pytest.param(8, {"paged_kv": True, "prefill_chunk": 8},
                 marks=pytest.mark.slow),
    pytest.param(4, {"paged_kv": True, "prefill_chunk": 8,
                     "prefix_cache": 32, "spec_k": 3},
                 marks=pytest.mark.slow),
    pytest.param(8, {"tp": 2, "paged_kv": True, "prefill_chunk": 8},
                 marks=pytest.mark.slow),
]


class TestMegastep:
    """ISSUE 13: the fused K-tokens-per-dispatch decode megastep —
    greedy parity across the K × feature matrix, the
    one-program-per-(ladder × K) compile bound, boundary semantics for
    deadlines, fault isolation inside a fused dispatch, and the
    truthful cost-ledger accounting."""

    @pytest.mark.parametrize("K,features", MEGASTEP_SETS,
                             ids=lambda v: str(v) if isinstance(v, int)
                             else "+".join(sorted(v)) or "plain")
    def test_bit_identical_across_matrix(self, K, features, jit_guard,
                                         serving_mesh):
        """4 prompts through 2 slots (forced reuse) at megastep K:
        output equals the direct greedy generate bit for bit, and the
        jit cache holds the (ladder × K) bound.  K=1 must not build a
        fused program at all — the tick path IS the K=1 semantics."""
        from veles_tpu.serving import LMEngine
        if features.get("tp"):
            serving_mesh(features["tp"])
        params = _params()
        prompts = [[1, 2, 3], [2, 4, 6, 8, 10], [7, 7],
                   [5, 1, 5, 1, 5, 1, 5, 1, 5]]
        n_new = 7
        expected = [_greedy(params, p, n_new, 96) for p in prompts]
        engine = LMEngine(params, n_heads=2, max_len=96, slots=2,
                          megastep=K, name="ms_par",
                          **features).start()
        try:
            if K <= 1:
                assert engine._megastep_jit is None
            else:
                assert engine._megastep_jit is not None
            futures = [engine.submit(p, n_new) for p in prompts]
            for p, f, exp in zip(prompts, futures, expected):
                got = numpy.concatenate([p, f.result(timeout=300)])
                numpy.testing.assert_array_equal(got, exp)
            if features.get("prefill_chunk"):
                buckets = 1
            else:
                from veles_tpu.serving import prompt_bucket
                buckets = len({prompt_bucket(n, 96)
                               for n in [1] + [len(p) for p in prompts]})
            jit_guard(engine, prefill_buckets=buckets)
            if K >= 2:
                c = engine.metrics.snapshot()["counters"]
                assert c["megastep_dispatches"] >= 1
                assert c["decode_dispatches"] == \
                    c["megastep_dispatches"]
        finally:
            engine.stop()

    def test_validation_and_noop(self):
        from veles_tpu.serving import LMEngine
        params = _params()
        with pytest.raises(ValueError, match="megastep"):
            LMEngine(params, n_heads=2, max_len=96, slots=1,
                     megastep=-1, name="ms_bad")
        off = LMEngine(params, n_heads=2, max_len=96, slots=1,
                       name="ms_off")
        assert off.megastep == 0 and off._megastep_jit is None
        one = LMEngine(params, n_heads=2, max_len=96, slots=1,
                       megastep=1, name="ms_one")
        assert one._megastep_jit is None    # K=1 IS the tick path

    def test_deadline_mid_megastep_sheds_at_next_boundary(self):
        """BOUNDARY SEMANTICS (documented): a queued request whose
        deadline expires while a megastep is in flight sheds at the
        NEXT boundary — never mid-program, never wedged — while a
        request already decoding keeps its tokens (the deadline only
        ever governed queue wait, so a request that finished its
        tokens is never 503d)."""
        import time as time_mod
        from veles_tpu.serving import LMEngine
        from veles_tpu.serving.batcher import DeadlineExceeded
        params = _params(max_len=96)
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          megastep=4, deadline_s=0.35,
                          name="ms_dead").start()
        real = engine._megastep_jit

        def slow(*a):
            time_mod.sleep(0.25)
            return real(*a)

        engine._megastep_jit = slow
        try:
            fa = engine.submit([1, 2, 3], 8)   # admitted instantly
            time_mod.sleep(0.05)
            fb = engine.submit([4, 5, 6], 4)   # queued behind fa
            # fa spends ~0.5s decoding (2 slow megasteps) — well past
            # deadline_s, but it FINISHES: tokens delivered, no 503
            assert len(fa.result(timeout=60)) == 8
            with pytest.raises(DeadlineExceeded, match="boundary"):
                fb.result(timeout=60)
            assert engine.metrics.snapshot()["shed"] == 1
        finally:
            engine._megastep_jit = real
            engine.stop()

    def test_fault_inside_megastep_fails_exactly_active_lanes(self):
        """CHAOS: an engine.step fault injected into the fused
        dispatch fails the lanes that were IN that megastep — and only
        them; the queued request decodes exactly greedy afterwards,
        and every span tree (including the failed megastep span on the
        failed request's timeline) verifies."""
        from veles_tpu.serving import FaultPlan, LMEngine, SpanTracer
        from veles_tpu.serving.faults import InjectedFault
        from veles_tpu.serving.tracing import verify_integrity
        params = _params(max_len=96)
        plan = FaultPlan().arm("engine.step", calls={1})
        tracer = SpanTracer(mode="all", last=16)
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          megastep=4, faults=plan, tracer=tracer,
                          name="ms_chaos").start()
        try:
            fa = engine.submit([1, 2, 3], 6)
            fb = engine.submit([2, 4, 6, 8], 6)
            with pytest.raises(InjectedFault):
                fa.result(timeout=60)
            got = numpy.concatenate(
                [[2, 4, 6, 8], fb.result(timeout=120)])
            numpy.testing.assert_array_equal(
                got, _greedy(params, [2, 4, 6, 8], 6, 96))
            recs = tracer.requests()
            assert len(recs) == 2
            errs = [r for r in recs if r["error"]]
            assert len(errs) == 1
            verify_integrity(recs)
            assert any(s["name"] == "decode.megastep"
                       and "error" in s["attrs"]
                       for s in errs[0]["spans"])
        finally:
            engine.stop()

    def test_counters_and_ledger_truthful(self):
        """The megastep_* counter family and the ISSUE 12 cost ledger:
        one decode.megastep ledger row family whose deduped dispatch
        count equals the engine's megastep_dispatches — the folded
        per-token work is never double-counted — with per-lane tokens
        riding each request's span copy, and the waste accounting
        closed (tokens + wasted == lane iterations on the plain
        path)."""
        from veles_tpu.serving import LMEngine, SpanTracer
        from veles_tpu.serving.tracing import (cost_ledger,
                                               verify_integrity)
        params = _params(max_len=128)
        tracer = SpanTracer(mode="all", last=64)
        engine = LMEngine(params, n_heads=2, max_len=128, slots=2,
                          megastep=4, paged_kv=True, prefill_chunk=8,
                          tracer=tracer, name="ms_led").start()
        try:
            prompts = [[1, 2, 3], [2, 4, 6, 8]]
            futures = [engine.submit(p, 9) for p in prompts]
            for p, f in zip(prompts, futures):
                got = numpy.concatenate([p, f.result(timeout=120)])
                numpy.testing.assert_array_equal(
                    got, _greedy(params, p, 9, 128))
            c = engine.metrics.snapshot()["counters"]
            assert c["megastep_dispatches"] >= 1
            assert c["megastep_tokens"] == 2 * 8   # n_new minus TTFT
            assert c["megastep_tokens"] \
                + c["megastep_wasted_iterations"] \
                == c["megastep_lane_iterations"]
            assert c["decode_dispatches"] == c["megastep_dispatches"]
            recs = tracer.requests()
            verify_integrity(recs)
            rows = [r for r in cost_ledger(recs)
                    if r["op"] == "decode.megastep"]
            assert rows, "no decode.megastep ledger rows"
            assert sum(r["dispatches"] for r in rows) \
                == c["megastep_dispatches"]
            assert sum(r["lanes"] for r in rows) \
                >= sum(r["dispatches"] for r in rows)
            span = next(s for r in recs for s in r["spans"]
                        if s["name"] == "decode.megastep")
            assert span["attrs"]["K"] == 4
            assert "lane_tokens" in span["attrs"]
            assert "xK4" in str(span["attrs"]["bucket"])
        finally:
            engine.stop()


#: ISSUE 19 while-megastep matrix: one tier-1 representative per
#: family (contiguous while, the full paged+chunk+cache+spec stack,
#: the refill ring, tp=2); redundant K × feature geometries ride the
#: slow suite (the PR 3/8 watchdog-headroom discipline).
WHILESTEP_SETS = [
    (4, {}),
    (8, {"paged_kv": True, "prefill_chunk": 8, "prefix_cache": 32,
         "spec_k": 3}),
    (4, {"paged_kv": True, "prefill_chunk": 8, "refill_ring": 2}),
    (4, {"tp": 2, "paged_kv": True, "prefill_chunk": 8, "spec_k": 3}),
    pytest.param(4, {"prefill_chunk": 8}, marks=pytest.mark.slow),
    pytest.param(8, {}, marks=pytest.mark.slow),
    pytest.param(4, {"spec_k": 3}, marks=pytest.mark.slow),
    pytest.param(8, {"paged_kv": True, "prefill_chunk": 8},
                 marks=pytest.mark.slow),
    pytest.param(8, {"paged_kv": True, "prefill_chunk": 8,
                     "refill_ring": 2, "spec_k": 3},
                 marks=pytest.mark.slow),
    pytest.param(8, {"tp": 2, "paged_kv": True, "prefill_chunk": 8},
                 marks=pytest.mark.slow),
]


class TestWhilestep:
    """ISSUE 19: the persistent while-loop decode megastep — greedy
    parity across the K × feature matrix (early exit must be invisible
    in outputs), the one-program-per-ladder-entry compile bound,
    realized-iteration early exit (the scan waste tail gone), in-graph
    refill from the standby ring, ring deadline semantics (a
    pre-prefilled request never 503s), and fault isolation including
    ring occupants."""

    @pytest.mark.parametrize("K,features", WHILESTEP_SETS,
                             ids=lambda v: str(v) if isinstance(v, int)
                             else "+".join(sorted(v)) or "plain")
    def test_bit_identical_across_matrix(self, K, features, jit_guard,
                                         serving_mesh):
        """4 prompts through 2 slots (forced reuse) at while-megastep
        cap K: output equals the direct greedy generate bit for bit,
        and the jit cache holds the one-program-per-ladder-entry bound
        — the realized iteration count is carry DATA, so early exit
        adds zero variants."""
        from veles_tpu.serving import LMEngine
        if features.get("tp"):
            serving_mesh(features["tp"])
        params = _params()
        prompts = [[1, 2, 3], [2, 4, 6, 8, 10], [7, 7],
                   [5, 1, 5, 1, 5, 1, 5, 1, 5]]
        n_new = 7
        expected = [_greedy(params, p, n_new, 96) for p in prompts]
        engine = LMEngine(params, n_heads=2, max_len=96, slots=2,
                          megastep=K, megastep_mode="while",
                          name="ws_par", **features).start()
        try:
            assert engine._whilestep_jit is not None
            assert engine._megastep_jit is None
            futures = [engine.submit(p, n_new) for p in prompts]
            for p, f, exp in zip(prompts, futures, expected):
                got = numpy.concatenate([p, f.result(timeout=300)])
                numpy.testing.assert_array_equal(got, exp)
            if features.get("prefill_chunk"):
                buckets = 1
            else:
                from veles_tpu.serving import prompt_bucket
                buckets = len({prompt_bucket(n, 96)
                               for n in [1] + [len(p) for p in prompts]})
            jit_guard(engine, prefill_buckets=buckets)
            c = engine.metrics.snapshot()["counters"]
            assert c["megastep_dispatches"] >= 1
            assert c["decode_dispatches"] == c["megastep_dispatches"]
        finally:
            engine.stop()

    def test_validation_and_alias(self):
        from veles_tpu.serving import LMEngine
        params = _params()
        with pytest.raises(ValueError, match="megastep_mode"):
            LMEngine(params, n_heads=2, max_len=96, slots=1,
                     megastep=4, megastep_mode="unroll", name="ws_bad")
        with pytest.raises(ValueError, match="iteration cap"):
            LMEngine(params, n_heads=2, max_len=96, slots=1,
                     megastep_mode="while", name="ws_cap")
        with pytest.raises(ValueError, match="refill_ring"):
            LMEngine(params, n_heads=2, max_len=96, slots=1,
                     megastep=4, refill_ring=2, name="ws_ring")
        # megastep='while' is the K=16 while-mode shorthand
        alias = LMEngine(params, n_heads=2, max_len=96, slots=1,
                         megastep="while", name="ws_alias")
        assert alias.megastep == 16
        assert alias.megastep_mode == "while"
        assert alias._whilestep_jit is not None
        assert alias._megastep_jit is None

    def test_early_exit_kills_waste_tail(self):
        """THE point of the while loop: a single lane with n_new far
        under the cap exits after its realized iterations — zero
        wasted lane iterations and a truthful `iters` span attr —
        where the scan megastep at the same K burns the full fixed
        window (the 0.225 waste record this PR retires)."""
        from veles_tpu.serving import LMEngine, SpanTracer
        params = _params(max_len=128)
        prompt, n_new = [1, 2, 3], 6
        tracer = SpanTracer(mode="all", last=16)
        engine = LMEngine(params, n_heads=2, max_len=128, slots=1,
                          megastep=16, megastep_mode="while",
                          paged_kv=True, prefill_chunk=8,
                          tracer=tracer, name="ws_exit").start()
        try:
            got = numpy.concatenate(
                [prompt, engine.submit(prompt, n_new).result(timeout=120)])
            numpy.testing.assert_array_equal(
                got, _greedy(params, prompt, n_new, 128))
            c = engine.metrics.snapshot()["counters"]
            # prefill emits the first token; the loop exits after the
            # remaining 5 — no masked tail up to K=16
            assert c["megastep_dispatches"] == 1
            assert c["megastep_tokens"] == n_new - 1
            assert c["megastep_wasted_iterations"] == 0
            assert c["megastep_lane_iterations"] == n_new - 1
            span = next(s for r in tracer.requests()
                        for s in r["spans"]
                        if s["name"] == "decode.megastep")
            assert span["attrs"]["K"] == 16
            assert span["attrs"]["iters"] == n_new - 1
        finally:
            engine.stop()
        scan = LMEngine(params, n_heads=2, max_len=128, slots=1,
                        megastep=16, paged_kv=True, prefill_chunk=8,
                        name="ws_scan").start()
        try:
            scan.submit(prompt, n_new).result(timeout=120)
            sc = scan.metrics.snapshot()["counters"]
            # the scan twin burns the whole fixed-K window
            assert sc["megastep_lane_iterations"] == 16
            assert sc["megastep_wasted_iterations"] == 16 - (n_new - 1)
        finally:
            scan.stop()

    def test_refill_ring_rearm_in_graph(self):
        """5 prompts through ONE slot with a 2-deep standby ring:
        every output exactly greedy, at least one lane re-armed
        inside the loop (megastep_refills > 0), the occupancy gauge
        drains to zero and the pool closes leak-free."""
        from veles_tpu.serving import LMEngine
        params = _params(max_len=128)
        prompts = [[1, 2, 3], [2, 4, 6, 8], [7, 7], [3, 1, 4, 1, 5],
                   [9, 8, 7]]
        n_new = 6
        expected = [_greedy(params, p, n_new, 128) for p in prompts]
        engine = LMEngine(params, n_heads=2, max_len=128, slots=1,
                          megastep=8, megastep_mode="while",
                          paged_kv=True, prefill_chunk=8,
                          refill_ring=2, name="ws_ring").start()
        try:
            futures = [engine.submit(p, n_new) for p in prompts]
            for p, f, exp in zip(prompts, futures, expected):
                got = numpy.concatenate([p, f.result(timeout=300)])
                numpy.testing.assert_array_equal(got, exp)
            c = engine.metrics.snapshot()["counters"]
            assert c["megastep_refills"] >= 1
            g = engine.metrics.snapshot()["gauges"]
            assert g["standby_ring_occupancy"] == 0
            assert g["standby_ring_peak"] >= 1
            summary = engine.verify_pool_invariants()
            assert summary["used_pages"] == 0
        finally:
            engine.stop()

    def test_ring_occupant_never_shed(self):
        """DEADLINE SEMANTICS (ISSUE 19 fix): a request sitting
        pre-prefilled in the standby ring past its deadline is
        ADMITTED work — it must complete, never 503 — while a request
        still in the queue sheds at the boundary with the shed window
        quoted from the while-loop's iteration cap."""
        import time as time_mod
        from veles_tpu.serving import LMEngine
        from veles_tpu.serving.batcher import DeadlineExceeded
        params = _params(max_len=128)
        engine = LMEngine(params, n_heads=2, max_len=128, slots=1,
                          megastep=4, megastep_mode="while",
                          paged_kv=True, prefill_chunk=8,
                          refill_ring=1, deadline_s=0.35,
                          name="ws_dead").start()
        real = engine._whilestep_jit

        def slow(*a):
            time_mod.sleep(0.25)
            return real(*a)

        engine._whilestep_jit = slow
        try:
            fa = engine.submit([1, 2, 3], 12)     # occupies the slot
            time_mod.sleep(0.05)
            fb = engine.submit([4, 5, 6], 4)      # ring-prefilled
            fc = engine.submit([6, 5, 4], 4)      # stays queued
            assert len(fa.result(timeout=60)) == 12
            # fb sat in the ring well past deadline_s — it finishes
            assert len(fb.result(timeout=60)) == 4
            with pytest.raises(DeadlineExceeded, match="window"):
                fc.result(timeout=60)
            assert engine.metrics.snapshot()["shed"] == 1
        finally:
            engine._whilestep_jit = real
            engine.stop()

    def test_fault_fails_participants_including_ring(self):
        """CHAOS: an engine.step fault during a while-megastep with a
        published standby-ring occupant fails exactly the
        participating lanes — the decoding lane AND the ring occupant
        — returns their pages leak-free, keeps sound span trees, and
        the engine serves the next request exactly greedy."""
        import time as time_mod
        from veles_tpu.serving import FaultPlan, LMEngine, SpanTracer
        from veles_tpu.serving.faults import InjectedFault
        from veles_tpu.serving.tracing import verify_integrity
        params = _params(max_len=128)
        plan = FaultPlan()
        tracer = SpanTracer(mode="all", last=32)
        engine = LMEngine(params, n_heads=2, max_len=128, slots=1,
                          megastep=4, megastep_mode="while",
                          paged_kv=True, prefill_chunk=8,
                          refill_ring=1, faults=plan, tracer=tracer,
                          name="ws_chaos").start()
        real = engine._whilestep_jit

        def slow(*a):
            time_mod.sleep(0.05)
            return real(*a)

        engine._whilestep_jit = slow
        try:
            fa = engine.submit([1, 2, 3], 40)
            fb = engine.submit([2, 4, 6, 8], 6)
            deadline = time_mod.monotonic() + 30.0
            while not any(e.ready for e in engine._ring):
                assert time_mod.monotonic() < deadline, \
                    "standby entry never became ready"
                time_mod.sleep(0.005)
            plan.arm("engine.step", kind="error", times=1)
            with pytest.raises(InjectedFault):
                fa.result(timeout=60)
            with pytest.raises(InjectedFault):
                fb.result(timeout=60)
            fc = engine.submit([9, 9, 9], 5)
            got = numpy.concatenate([[9, 9, 9], fc.result(timeout=120)])
            numpy.testing.assert_array_equal(
                got, _greedy(params, [9, 9, 9], 5, 128))
            summary = engine.verify_pool_invariants()
            assert summary["used_pages"] == 0
            recs = tracer.requests()
            verify_integrity(recs)
            errs = [r for r in recs if r["error"]]
            assert len(errs) == 2
            # the ring occupant's copy of the failed megastep span is
            # marked standby — its timeline shows WHERE it died
            assert any(s["name"] == "decode.megastep"
                       and s["attrs"].get("standby")
                       for r in errs for s in r["spans"])
        finally:
            plan.release()
            engine._whilestep_jit = real
            engine.stop()


#: ISSUE 19 seeded-sampling parity matrix: every fast-path feature
#: must sample the SAME token at the same (lane seed, position) —
#: the counter-based prng stream is keyed by coordinates, not by how
#: the engine happened to batch, chunk, speculate or fuse the step.
#: tier-1 keeps one representative per family (chunk, scan-vs-while,
#: paged, the full paged+spec while stack, the refill ring); the
#: single-feature legs the supersets subsume ride the slow suite
#: (watchdog-headroom discipline).
SEEDED_SETS = [
    {"prefill_chunk": 8},
    {"megastep": 4},
    {"megastep": 4, "megastep_mode": "while"},
    {"paged_kv": True, "prefill_chunk": 8},
    {"paged_kv": True, "prefill_chunk": 8, "spec_k": 3,
     "megastep": 4, "megastep_mode": "while"},
    {"paged_kv": True, "prefill_chunk": 8, "refill_ring": 2,
     "megastep": 4, "megastep_mode": "while"},
    pytest.param({"spec_k": 3}, marks=pytest.mark.slow),
    pytest.param({"paged_kv": True, "prefill_chunk": 8,
                  "prefix_cache": 32}, marks=pytest.mark.slow),
]


class TestSeededSampling:
    """ISSUE 19: in-graph temperature/top-k sampling with
    counter-based streams keyed by (lane seed, position) —
    bit-reproducible given sample_seed, identical across the whole
    fast-path matrix, and invisible when off (greedy stays the
    default and stays bit-identical to generate)."""

    SEED_KW = dict(temperature=0.8, top_k=5, sample_seed=123)

    def _run(self, params, features, prompts, n_new,
             name, seed_kw=None):
        from veles_tpu.serving import LMEngine
        engine = LMEngine(params, n_heads=2, max_len=96, slots=2,
                          name=name, **dict(self.SEED_KW,
                                            **(seed_kw or {})),
                          **features).start()
        try:
            futures = [engine.submit(p, n_new) for p in prompts]
            return [list(f.result(timeout=300)) for f in futures]
        finally:
            engine.stop()

    @pytest.mark.parametrize("features", SEEDED_SETS,
                             ids=lambda f: "+".join(sorted(f)))
    def test_identical_across_fastpath_matrix(self, features):
        """The per-tick engine with no features is the reference:
        every feature combination must sample the identical
        continuation for the same (sample_seed, submission order)."""
        params = _params()
        prompts = [[1, 2, 3], [2, 4, 6, 8, 10], [7, 7],
                   [5, 1, 5, 1, 5, 1, 5, 1, 5]]
        n_new = 7
        ref = self._run(params, {}, prompts, n_new, "sd_ref")
        got = self._run(params, features, prompts, n_new, "sd_leg")
        assert got == ref

    def test_tp2_identical(self, serving_mesh):
        """The sharded engine samples the same tokens — the sampling
        key is replicated data, not a per-device stream."""
        serving_mesh(2)
        params = _params()
        prompts = [[1, 2, 3], [2, 4, 6, 8, 10]]
        ref = self._run(params, {}, prompts, 6, "sd_tp_ref")
        got = self._run(params, {"tp": 2}, prompts, 6, "sd_tp")
        assert got == ref

    def test_reproducible_and_seed_sensitive(self):
        """Same seed → the identical stream on a FRESH engine; a
        different seed → a different stream (the knob is live)."""
        params = _params()
        prompts = [[1, 2, 3], [4, 5, 6, 7]]
        a = self._run(params, {}, prompts, 8, "sd_a")
        b = self._run(params, {}, prompts, 8, "sd_b")
        assert a == b
        c = self._run(params, {}, prompts, 8, "sd_c",
                      seed_kw={"sample_seed": 321})
        assert c != a

    def test_greedy_default_unchanged(self):
        """temperature=0 (the default) must not even thread the key:
        outputs stay bit-identical to generate and no sampling knob
        leaks into the dispatch signature."""
        from veles_tpu.serving import LMEngine
        params = _params()
        engine = LMEngine(params, n_heads=2, max_len=96, slots=2,
                          megastep=4, megastep_mode="while",
                          paged_kv=True, prefill_chunk=8,
                          name="sd_greedy").start()
        try:
            assert engine._sample_key_host is None
            p = [1, 2, 3]
            got = numpy.concatenate(
                [p, engine.submit(p, 7).result(timeout=120)])
            numpy.testing.assert_array_equal(
                got, _greedy(params, p, 7, 96))
        finally:
            engine.stop()

    def test_sampling_validation(self):
        from veles_tpu.serving import LMEngine
        params = _params()
        with pytest.raises(ValueError, match="sample_seed"):
            LMEngine(params, n_heads=2, max_len=96, slots=1,
                     temperature=0.8, name="sd_bad")
        with pytest.raises(ValueError, match=">= 0"):
            LMEngine(params, n_heads=2, max_len=96, slots=1,
                     temperature=-1.0, sample_seed=1, name="sd_neg")


class TestAdmissionTokenBudget:
    def test_long_prompt_flood_rejects_on_token_budget(self):
        """queue_tokens bounds the queued PREFILL BACKLOG: with the
        worker pinned slow, a flood of long prompts 429s once the
        queued-token budget is spent, instead of stacking unbounded
        head-of-line prefill work."""
        from veles_tpu.serving import LMEngine, Overloaded
        params = _params(max_len=96)
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          queue_depth=64, queue_tokens=50,
                          name="fp_budget").start()
        real_step = engine._step_jit

        def slow_step(*a):
            time.sleep(0.05)
            return real_step(*a)

        engine._step_jit = slow_step
        try:
            prompt = list(range(1, 21))          # 20 tokens each
            futures, rejected = [], 0
            for _ in range(8):
                try:
                    futures.append(engine.submit(prompt, 4))
                except Overloaded:
                    rejected += 1
            assert rejected > 0                  # budget bit
            for f in futures:                    # admitted ones finish
                assert len(f.result(timeout=120)) == 4
            snap = engine.metrics.snapshot()
            assert snap["rejected"] == rejected
            assert snap["counters"]["rejected_tokens"] == 20 * rejected
        finally:
            engine._step_jit = real_step
            engine.stop()


class TestFastPathMetrics:
    def test_ttft_decode_histograms_and_counters_rendered(self):
        """Satellite: TTFT + decode-step histograms and the fast-path
        counters appear in BOTH the snapshot (/metrics.json) and the
        Prometheus text (/metrics), one # TYPE line per family."""
        from veles_tpu.serving import metrics as metrics_mod
        a = metrics_mod.new("fp_m1")
        b = metrics_mod.new("fp_m2")
        for m in (a, b):
            m.record_ttft(0.004)
            m.record_decode_step(0.002)
            m.inc("prefix_hit_tokens", 32)
            m.inc("draft_accepted", 3)
        snap = a.snapshot()
        assert snap["ttft"]["count"] == 1
        assert snap["decode_step"]["count"] == 1
        assert snap["counters"] == {"prefix_hit_tokens": 32,
                                    "draft_accepted": 3}
        text = metrics_mod.render_prometheus()
        assert text.count("# TYPE veles_serving_ttft histogram") == 1
        assert text.count(
            "# TYPE veles_serving_decode_step histogram") == 1
        assert text.count(
            "# TYPE veles_serving_prefix_hit_tokens_total counter") == 1
        assert 'veles_serving_ttft_bucket{engine="fp_m1",le="0.005"} 1' \
            in text
        assert 'veles_serving_draft_accepted_total{engine="fp_m2"} 3' \
            in text

    def test_engine_records_ttft_and_decode_step(self):
        from veles_tpu.serving import LMEngine
        params = _params()
        engine = LMEngine(params, n_heads=2, max_len=96, slots=1,
                          prefill_chunk=8, name="fp_hist").start()
        try:
            engine.submit([1, 2, 3, 4, 5], 4).result(timeout=60)
            snap = engine.metrics.snapshot()
            assert snap["ttft"]["count"] == 1
            assert snap["decode_step"]["count"] >= 1
        finally:
            engine.stop()


class TestLoadGenLM:
    def test_lm_prompts_shared_prefix_and_determinism(self):
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        from load_gen import lm_prompts
        a = lm_prompts(4, 3, vocab=16, mean_len=40, shared_frac=0.5,
                       seed=9)
        b = lm_prompts(4, 3, vocab=16, mean_len=40, shared_frac=0.5,
                       seed=9)
        assert a == b                            # deterministic
        shared_len = 20
        shared = a[(0, 0)][:shared_len]
        for key, prompt in a.items():
            assert prompt[:shared_len] == shared  # common system prompt
            assert len(prompt) > shared_len       # unique tail
            assert all(0 <= t < 16 for t in prompt)
        assert len({tuple(p) for p in a.values()}) == len(a)

    def test_lm_mode_end_to_end_token_accounting(self):
        """run_lm_load against a live serve_lm fast-path engine: every
        reply's generated-token count lands in the lm summary and the
        server's fast-path counters move."""
        import json
        import os
        import sys
        import urllib.request
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        from load_gen import run_lm_load
        from veles_tpu import prng
        from veles_tpu.config import root
        prng.reset()
        prng.seed_all(5)
        root.__dict__.pop("char_lm", None)
        root.char_lm.update({
            "loader": {"minibatch_size": 32, "n_train": 64,
                       "n_valid": 32, "seq_len": 16, "vocab": 16},
            "trainer": {"vocab": 16, "d_model": 32, "n_heads": 2,
                        "n_layers": 1, "max_len": 96,
                        "learning_rate": 3e-3, "n_experts": 0,
                        "pipeline_stages": 0, "remat": False},
            "decision": {"max_epochs": 1, "fail_iterations": 10},
        })
        from veles_tpu.samples import char_lm
        from veles_tpu.restful_api import serve_lm
        wf = char_lm.train()
        api = serve_lm(wf, port=0, max_new=8, slots=2, prefix_cache=32,
                       prefill_chunk=8, spec_k=2)
        try:
            summary = run_lm_load(
                "http://127.0.0.1:%d/predict" % api.port, clients=3,
                requests_per_client=2, vocab=16, mean_len=32,
                shared_frac=0.5, n_new=6, max_len=60, seed=2)
            assert summary["ok"] == summary["sent"] == 6
            assert summary["lm"]["generated_tokens"] == 6 * 6
            assert summary["lm"]["per_request_tokens"]["mean"] == 6
            assert summary["lm"]["tokens_per_sec"] > 0
            # single-engine serving stamps no replica ids — the
            # balance fields must stay absent, not read as 0
            assert "per_replica_requests" not in summary["lm"]
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/metrics.json" % api.port,
                    timeout=10) as resp:
                snap = json.loads(resp.read())
            assert snap["counters"]["tokens_out"] >= 36
            assert snap["ttft"]["count"] >= 6
        finally:
            api.stop()

        # ---- ISSUE 8: the same workflow behind serve_lm(replicas=2):
        # outputs unchanged, every reply stamped with its replica, the
        # client-side balance ratio computed, per-replica labeled
        # metrics on /metrics and replica snapshots on /metrics.json
        import jax
        if jax.device_count() < 2:
            return                       # mesh-less hosts covered above
        api = serve_lm(wf, port=0, max_new=8, slots=2, prefix_cache=32,
                       prefill_chunk=8, spec_k=2, replicas=2)
        try:
            summary = run_lm_load(
                "http://127.0.0.1:%d/predict" % api.port, clients=3,
                requests_per_client=2, vocab=16, mean_len=32,
                shared_frac=0.5, n_new=6, max_len=60, seed=2)
            assert summary["ok"] == summary["sent"] == 6
            assert summary["lm"]["generated_tokens"] == 6 * 6
            per_rep = summary["lm"]["per_replica_requests"]
            assert sum(per_rep.values()) == 6
            assert set(per_rep) <= {"0", "1"}
            ratio = summary["lm"]["replica_balance_ratio"]
            assert ratio is None or ratio >= 1.0
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/metrics.json" % api.port,
                    timeout=10) as resp:
                snap = json.loads(resp.read())
            assert len(snap["replicas"]) == 2
            assert sum(r["counters"].get("tokens_out", 0)
                       for r in snap["replicas"]) >= 36
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/metrics" % api.port,
                    timeout=10) as resp:
                text = resp.read().decode()
            assert text.count(
                "# TYPE veles_serving_requests_total counter") == 1
            assert 'engine="lm",replica="0"' in text
            assert 'engine="lm",replica="1"' in text
        finally:
            api.stop()
