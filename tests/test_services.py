"""Aux services: REST serving, ZMQ/interactive loaders, forge, publishing,
web status, shell (SURVEY §2.1 auxiliary rows + §3.4)."""

import json
import os
import urllib.request

import numpy
import pytest


def _train_tiny_mnist(tmp_path, snapshot=False):
    from veles_tpu import prng
    from veles_tpu.config import root
    prng.reset()
    prng.seed_all(1)
    cfg = {
        "loader": {"minibatch_size": 50, "n_train": 200, "n_valid": 100},
        "decision": {"max_epochs": 2, "fail_iterations": 5},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.03, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.03, "momentum": 0.9},
        ],
    }
    if snapshot:
        cfg["snapshotter"] = {"directory": str(tmp_path / "snaps"),
                              "interval": 1, "compression": "gz"}
    root.mnist.update(cfg)
    from veles_tpu.samples import mnist
    return mnist.train()


class TestRESTServing:
    def test_predict_roundtrip(self, tmp_path):
        from veles_tpu.restful_api import RESTfulAPI
        wf = _train_tiny_mnist(tmp_path)
        api = RESTfulAPI(wf).start(port=0)
        try:
            x = numpy.zeros((2, 784), numpy.float32).tolist()
            req = urllib.request.Request(
                "http://127.0.0.1:%d/predict" % api.port,
                data=json.dumps({"input": x}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                out = json.loads(resp.read())
            assert len(out["output"]) == 2
            assert len(out["output"][0]) == 10
            assert abs(sum(out["output"][0]) - 1.0) < 1e-3   # softmax
            assert out["argmax"][0] in range(10)
        finally:
            api.stop()

    def test_serve_lm_continuation(self):
        """LM serving endpoint: tokens in, KV-cached continuation out."""
        from veles_tpu import prng
        from veles_tpu.config import root
        from veles_tpu.restful_api import serve_lm
        prng.reset(); prng.seed_all(4)
        root.char_lm.update({
            "loader": {"minibatch_size": 32, "n_train": 128, "n_valid": 64,
                       "seq_len": 32, "vocab": 16},
            "trainer": {"vocab": 16, "d_model": 32, "n_heads": 2,
                        "n_layers": 1, "max_len": 32,
                        "learning_rate": 3e-3, "n_experts": 0,
                        "pipeline_stages": 0, "remat": False},
            "decision": {"max_epochs": 2, "fail_iterations": 10},
        })
        from veles_tpu.samples import char_lm
        wf = char_lm.train()
        api = serve_lm(wf, port=0, max_new=8)
        try:
            req = urllib.request.Request(
                "http://127.0.0.1:%d/predict" % api.port,
                data=json.dumps({"input": [[1, 2, 3]], "n_new": 5}
                                ).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                out = json.loads(resp.read())
            row = out["tokens"][0]
            assert len(row) == 8                    # 3 prompt + 5 new
            assert row[:3] == [1, 2, 3]
            assert all(0 <= t < 16 for t in row)
            # n_new clamped to max_new
            req = urllib.request.Request(
                "http://127.0.0.1:%d/predict" % api.port,
                data=json.dumps({"input": [[1, 2, 3]], "n_new": 999,
                                 "temperature": 0.7, "seed": 1}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                out = json.loads(resp.read())
            assert len(out["tokens"][0]) == 3 + 8
            # n_new=1 is honored exactly (quantized decode TIER, reply
            # truncated to the request — ADVICE r4) and a longer prompt
            # in the same bucket still round-trips correctly
            req = urllib.request.Request(
                "http://127.0.0.1:%d/predict" % api.port,
                data=json.dumps({"input": [[2, 4, 6, 8, 10]],
                                 "n_new": 1}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                out = json.loads(resp.read())
            assert len(out["tokens"][0]) == 6
            assert out["tokens"][0][:5] == [2, 4, 6, 8, 10]
        finally:
            api.stop()

    def test_bad_request_is_400(self, tmp_path):
        from veles_tpu.restful_api import RESTfulAPI
        wf = _train_tiny_mnist(tmp_path)
        api = RESTfulAPI(wf).start(port=0)
        try:
            req = urllib.request.Request(
                "http://127.0.0.1:%d/predict" % api.port,
                data=b"{}", headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=10)
            assert err.value.code == 400
        finally:
            api.stop()


class TestZmqLoader:
    def test_stream_minibatch(self):
        from veles_tpu.workflow import Workflow
        from veles_tpu.zmq_loader import ZeroMQLoader, push_samples
        wf = Workflow(None, name="wf")
        loader = ZeroMQLoader(wf, sample_shape=(4,), minibatch_size=3,
                              timeout_ms=10000, name="loader")
        loader.initialize()
        samples = [{"data": numpy.full(4, i, numpy.float32), "label": i}
                   for i in range(5)]
        push_samples(loader.endpoint, samples)
        loader.run()
        assert loader.minibatch_size == 3
        numpy.testing.assert_array_equal(loader.minibatch_labels.mem,
                                         [0, 1, 2])
        loader.run()   # second minibatch: 2 live + end-of-stream
        assert loader.minibatch_size == 2
        assert loader.exhausted
        assert not bool(loader.complete)
        loader.run()   # drained: empty minibatch flips complete
        assert loader.minibatch_size == 0
        assert bool(loader.complete)
        loader.stop()


class TestInteractiveLoader:
    def test_feed_and_fill(self):
        from veles_tpu.workflow import Workflow
        from veles_tpu.loader.interactive import InteractiveLoader
        wf = Workflow(None, name="wf")
        loader = InteractiveLoader(wf, sample_shape=(3,), minibatch_size=2,
                                   name="loader")
        loader.feed(numpy.ones(3), label=7)
        loader.feed(numpy.zeros((2, 3)), label=[1, 2])
        loader.initialize()
        loader.run()
        assert loader.minibatch_size == 2
        numpy.testing.assert_array_equal(loader.minibatch_labels.mem[:2],
                                         [7, 1])
        loader.run()
        assert loader.minibatch_size == 1


class TestForge:
    def test_pack_publish_fetch_restore(self, tmp_path):
        from veles_tpu import forge, prng
        from veles_tpu.config import root
        wf = _train_tiny_mnist(tmp_path, snapshot=True)
        snap = wf.snapshotter.destination
        assert snap and os.path.exists(snap)

        pkg = forge.pack(snap, str(tmp_path / "model.forge.tar.gz"),
                         name="mnist_fc", description="test model",
                         metrics={"n_err": wf.decision.best_metric})
        manifest = forge.read_manifest(pkg)
        assert manifest["name"] == "mnist_fc"
        assert manifest["metrics"]["n_err"] == wf.decision.best_metric

        store = str(tmp_path / "store")
        forge.publish(pkg, store)
        listed = forge.list_store(store)
        assert len(listed) == 1 and listed[0][1]["name"] == "mnist_fc"

        fetched_manifest, snap_path = forge.fetch(
            store, "mnist_fc", str(tmp_path / "fetched"))
        assert os.path.exists(snap_path)

        # restore into a freshly built workflow; weights must match
        prng.reset()
        prng.seed_all(99)  # different seed: restore must overwrite init
        from veles_tpu.samples import mnist
        wf2, _ = forge.restore_package(
            pkg, lambda: mnist.build().initialize(),
            out_dir=str(tmp_path / "restored"))
        runner2 = wf2._fused_runner
        runner2.state = runner2._pull_state()
        numpy.testing.assert_allclose(
            numpy.asarray(wf2.forwards[0].weights.mem),
            numpy.asarray(wf.forwards[0].weights.mem), atol=1e-6)


class TestForgeTraversal:
    """A crafted package whose manifest names members outside the extraction
    dir must be rejected (forge packages are untrusted once fetched)."""

    def _evil_package(self, tmp_path, key, member):
        import json
        import tarfile
        pkg = str(tmp_path / "evil.forge.tar.gz")
        manifest = {"name": "evil", "snapshot": "snap.bin", "format": 1,
                    "packaged_at": 0, key: member}
        payload = tmp_path / "payload.bin"
        payload.write_bytes(b"owned")
        with tarfile.open(pkg, "w:gz") as tar:
            mf = tmp_path / "manifest.json"
            mf.write_text(json.dumps(manifest))
            tar.add(str(mf), arcname="manifest.json")
            tar.add(str(payload), arcname="snap.bin")
        return pkg

    def test_artifact_traversal_rejected(self, tmp_path):
        import pytest
        from veles_tpu import forge
        pkg = self._evil_package(tmp_path, "artifact", "../evil.bin")
        with pytest.raises(ValueError, match="unsafe member"):
            forge.load_artifact(pkg, out_dir=str(tmp_path / "out"))
        assert not (tmp_path / "evil.bin").exists()

    def test_snapshot_traversal_rejected(self, tmp_path):
        import pytest
        from veles_tpu import forge
        pkg = self._evil_package(tmp_path, "snapshot", "../../snap.bin")
        with pytest.raises(ValueError, match="unsafe member"):
            forge.unpack(pkg, str(tmp_path / "out"))

    def test_absolute_member_rejected(self, tmp_path):
        import pytest
        from veles_tpu import forge
        pkg = self._evil_package(tmp_path, "artifact", "/tmp/evil.bin")
        with pytest.raises(ValueError, match="unsafe member"):
            forge.load_artifact(pkg, out_dir=str(tmp_path / "out"))


class TestForgeServer:
    """HTTP transport over the store (ref: veles/forge_server.py [M]) —
    upload/list/fetch against a real loopback server."""

    def test_upload_list_fetch_roundtrip(self, tmp_path):
        from veles_tpu import forge
        from veles_tpu import forge_server
        wf = _train_tiny_mnist(tmp_path, snapshot=True)
        pkg = forge.pack(wf.snapshotter.destination,
                         str(tmp_path / "m.forge.tar.gz"), name="mnist_fc",
                         metrics={"n_err": wf.decision.best_metric})

        server = forge_server.ForgeServer(str(tmp_path / "store")).start()
        try:
            record = forge_server.upload(pkg, server.url)
            assert record["name"] == "mnist_fc"
            listing = forge_server.list_remote(server.url)
            assert len(listing) == 1
            assert listing[0][1]["name"] == "mnist_fc"
            manifest, snap_path = forge_server.fetch_remote(
                server.url, "mnist_fc", str(tmp_path / "fetched"))
            assert manifest["name"] == "mnist_fc"
            assert os.path.exists(snap_path)
            # the fetched snapshot restores to the published weights
            from veles_tpu import prng, snapshotter
            prng.reset()
            prng.seed_all(99)
            from veles_tpu.samples import mnist
            wf2 = mnist.build()
            wf2.initialize()
            snapshotter.restore(wf2, snap_path)
            numpy.testing.assert_allclose(
                numpy.asarray(wf2.forwards[0].weights.mem),
                numpy.asarray(wf.forwards[0].weights.mem), atol=1e-6)
        finally:
            server.stop()

    def test_rejects_garbage_and_unknown(self, tmp_path):
        import urllib.error
        import urllib.request
        from veles_tpu import forge_server
        server = forge_server.ForgeServer(str(tmp_path / "store")).start()
        try:
            garbage = tmp_path / "garbage.bin"
            garbage.write_bytes(b"this is not a tarball")
            with pytest.raises(urllib.error.HTTPError) as err:
                forge_server.upload(str(garbage), server.url)
            assert err.value.code == 400
            assert forge_server.list_remote(server.url) == []
            with pytest.raises(urllib.error.HTTPError) as err:
                forge_server.fetch_remote(server.url, "nope",
                                          str(tmp_path / "out"))
            assert err.value.code == 404
            with pytest.raises(ValueError, match="unsafe package name"):
                forge_server.fetch_remote(server.url, "../evil",
                                          str(tmp_path / "out"))
        finally:
            server.stop()


class TestPublishing:
    def test_reports(self, tmp_path):
        from veles_tpu.publishing import Publisher
        wf = _train_tiny_mnist(tmp_path)
        paths = Publisher(("markdown", "html", "json", "pdf")).publish(
            wf, str(tmp_path / "report"))
        assert len(paths) == 4
        md = open(paths[0], encoding="utf-8").read()
        assert "Training report: mnist" in md
        assert "validation_n_err" in md
        html_text = open(paths[1], encoding="utf-8").read()
        assert "<table>" in html_text
        facts = json.load(open(paths[2], encoding="utf-8"))
        assert facts["best_epoch"] >= 1
        pdf = open(paths[3], "rb").read()
        assert pdf.startswith(b"%PDF-") and pdf.rstrip().endswith(b"%%EOF")
        assert len(pdf) > 5000      # summary + learning-curve pages


class TestWebStatus:
    def test_dashboard(self, tmp_path):
        from veles_tpu.web_status import WebStatus, StatusReporter
        status = WebStatus().start(port=0)
        try:
            wf = _train_tiny_mnist(tmp_path)
            reporter = StatusReporter(wf, status=status, name="reporter")
            reporter._initialized = True
            reporter.run()
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/status.json" % status.port,
                    timeout=10) as resp:
                data = json.loads(resp.read())
            assert "mnist" in data
            assert data["mnist"]["epoch"] >= 2
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/" % status.port,
                    timeout=10) as resp:
                page = resp.read().decode()
            assert "mnist" in page
            # workflow-graph view (VERDICT r4 task 7): dot text and a
            # server-rendered SVG with the unit boxes
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/graph/mnist.dot" % status.port,
                    timeout=10) as resp:
                dot = resp.read().decode()
            assert dot.startswith("digraph") and "loader" in dot
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/graph/mnist.svg" % status.port,
                    timeout=10) as resp:
                assert resp.headers["Content-Type"] == "image/svg+xml"
                svg = resp.read().decode()
            assert "<svg" in svg and "loader" in svg and "<rect" in svg
            assert "marker-end" in svg          # edges drawn
            # remote report-in: a second process's row lands in the
            # same table keyed workflow@process (the slave→master flow)
            from veles_tpu.web_status import post_report
            out = post_report("http://127.0.0.1:%d" % status.port,
                              "mnist@1", workflow="mnist", process=1,
                              processes=2, epoch=3, best=0.5)
            assert out == {"ok": True}
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/status.json" % status.port,
                    timeout=10) as resp:
                data = json.loads(resp.read())
            assert data["mnist@1"]["process"] == 1
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/" % status.port,
                    timeout=10) as resp:
                page = resp.read().decode()
            assert "1/2" in page                # per-process column
        finally:
            status.stop()

    def test_graph_svg_renderer_handles_cycle(self):
        """The built-in layered renderer must not recurse forever on the
        Repeater cycle and must draw back-edges dashed."""
        from veles_tpu.web_status import render_graph_svg
        svg = render_graph_svg(
            ["repeater", "loader", "train", "decision"],
            [(0, 1), (1, 2), (2, 3), (3, 0)])
        assert svg.count("<rect") == 4
        assert "stroke-dasharray" in svg        # the 3->0 back edge


class TestShell:
    def test_skips_without_tty(self, tmp_path, capsys):
        from veles_tpu.workflow import Workflow
        from veles_tpu.interaction import Shell
        wf = Workflow(None, name="wf")
        shell = Shell(wf, name="shell")
        shell.initialize()
        shell.run()          # no tty in tests: must not block
        assert bool(shell.fired)

    def test_interact_receives_workflow(self):
        from veles_tpu.workflow import Workflow
        from veles_tpu.interaction import Shell
        wf = Workflow(None, name="wf")
        seen = {}

        class TestableShell(Shell):
            def interact(self, local):
                seen.update(local)

        shell = TestableShell(wf, name="shell")
        shell.initialize()
        import sys
        real_isatty = sys.stdin.isatty
        sys.stdin.isatty = lambda: True
        try:
            shell.run()
        finally:
            sys.stdin.isatty = real_isatty
        assert seen["wf"] is wf


class TestForgeCLI:
    def test_pack_publish_list_fetch_roundtrip(self, tmp_path):
        """The forge command line (reference: forge_client CLI) drives
        the full local-store flow."""
        import subprocess
        import sys
        wf = _train_tiny_mnist(tmp_path)
        from veles_tpu.snapshotter import Snapshotter
        snap = Snapshotter(wf, directory=str(tmp_path / "s"),
                           name="snapcli").export()

        def cli(*args):
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            proc = subprocess.run(
                [sys.executable, "-m", "veles_tpu.forge_cli"] + list(args),
                capture_output=True, text=True, env=env,
                cwd=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))), timeout=300)
            assert proc.returncode == 0, proc.stderr[-2000:]
            return proc.stdout

        pkg = str(tmp_path / "m.forge.tar.gz")
        cli("pack", snap, pkg, "--name", "cli-model",
            "--metric", "n_err=3", "--description", "from the CLI")
        cli("publish", pkg, str(tmp_path / "store"))
        entries = json.loads(cli("list", str(tmp_path / "store")))
        # list_store yields (filename, manifest) pairs
        assert any(m["name"] == "cli-model" for _, m in entries)
        out = json.loads(cli("fetch", str(tmp_path / "store"),
                             "cli-model", str(tmp_path / "got")))
        assert out["manifest"]["name"] == "cli-model"
        assert out["manifest"]["metrics"]["n_err"] == 3
        assert os.path.exists(out["snapshot"])

    def test_cli_url_store_flow(self, tmp_path):
        """serve + upload/publish/list/fetch through the CLI's http
        branches (the _is_url dispatch)."""
        import signal
        import subprocess
        import sys
        import time
        wf = _train_tiny_mnist(tmp_path)
        from veles_tpu.snapshotter import Snapshotter
        snap = Snapshotter(wf, directory=str(tmp_path / "s2"),
                           name="snapurl").export()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"

        def cli(*args, timeout=300):
            proc = subprocess.run(
                [sys.executable, "-m", "veles_tpu.forge_cli"] + list(args),
                capture_output=True, text=True, env=env, cwd=repo,
                timeout=timeout)
            assert proc.returncode == 0, proc.stderr[-2000:]
            return proc.stdout

        pkg = str(tmp_path / "u.forge.tar.gz")
        cli("pack", snap, pkg, "--name", "url-model")
        server = subprocess.Popen(
            [sys.executable, "-m", "veles_tpu.forge_cli", "serve",
             str(tmp_path / "rstore"), "--port", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            env=env, cwd=repo)
        try:
            line = server.stdout.readline()
            assert line.startswith("FORGE "), line
            url = line.split()[1].strip()
            cli("upload", pkg, url)
            # publish against a URL must route to upload, not mkdir
            cli("publish", pkg, url)
            assert not os.path.exists(os.path.join(repo, "http:"))
            entries = json.loads(cli("list", url))
            assert any(m["name"] == "url-model" for _, m in entries)
            out = json.loads(cli("fetch", url, "url-model",
                                 str(tmp_path / "rgot")))
            assert out["manifest"]["name"] == "url-model"
            assert os.path.exists(out["snapshot"])
        finally:
            server.send_signal(signal.SIGINT)
            try:
                server.wait(timeout=15)
            except subprocess.TimeoutExpired:
                server.kill()
                server.wait()

    def test_cli_bad_metric_rejected(self, tmp_path):
        import subprocess
        import sys
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, "-m", "veles_tpu.forge_cli", "pack",
             "snap", "out", "--metric", "n_err"],
            capture_output=True, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=repo,
            timeout=120)
        assert proc.returncode == 2
        assert "KEY=VALUE" in proc.stderr


def test_attach_web_status_in_graph():
    """attach_web_status wires a reporter off the decision so rows and
    the graph view appear WITHOUT manual reporter plumbing (the CLI
    --web-status path)."""
    from veles_tpu import prng
    from veles_tpu.config import root
    from veles_tpu.web_status import attach_web_status
    prng.reset(); prng.seed_all(3)
    root.mnist.update({
        "loader": {"minibatch_size": 32, "n_train": 128, "n_valid": 64},
        "decision": {"max_epochs": 2, "fail_iterations": 10},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.05, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    wf = mnist.build(fused=True)
    status = attach_web_status(wf, port=0)
    try:
        wf.initialize()
        wf.run()
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/status.json" % status.port,
                timeout=10) as resp:
            data = json.loads(resp.read())
        assert data["mnist"]["epoch"] >= 1
        assert data["mnist"]["metrics"]
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/graph/mnist.svg" % status.port,
                timeout=10) as resp:
            assert b"<svg" in resp.read()
    finally:
        status.stop()


def test_confluence_backend_and_upload():
    """Confluence storage-format rendering + the REST create-page flow
    against a loopback server (the reference's confluence publishing,
    re-based on the stable REST API)."""
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer
    from veles_tpu.publishing import (ConfluenceBackend,
                                      publish_confluence)
    facts = {
        "workflow": "mnist", "workflow_class": "MnistWorkflow",
        "generated_at": "now", "best_metric": 3, "best_epoch": 2,
        "units": ["loader", "fwd"], "run_seconds": 1.0, "plots": [],
        "epochs": [{"epoch": 1, "validation_n_err": 9},
                   {"epoch": 2, "validation_n_err": 3}],
    }
    xml = ConfluenceBackend().render(facts)
    assert "<h1>Training report: mnist</h1>" in xml
    assert "ac:structured-macro" in xml and "<table>" in xml

    got = {}

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            got["path"] = self.path
            got["auth"] = self.headers.get("Authorization")
            ln = int(self.headers.get("Content-Length", 0))
            got["payload"] = json.loads(self.rfile.read(ln))
            body = json.dumps({"id": "123",
                               "_links": {"webui": "/x/123"}}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        out = publish_confluence(
            "http://127.0.0.1:%d" % srv.server_address[1], "ML",
            "mnist report", facts, auth=("bot", "token"))
        assert out["id"] == "123"
        assert got["path"] == "/rest/api/content"
        assert got["auth"].startswith("Basic ")
        assert got["payload"]["space"]["key"] == "ML"
        assert got["payload"]["body"]["storage"]["representation"] == \
            "storage"
        assert "Training report" in \
            got["payload"]["body"]["storage"]["value"]
    finally:
        srv.shutdown()


def test_serve_lm_full_option_stack():
    """HTTP serving composes the whole long-context option set: a
    rope+GQA+window+sinks trainer behind serve_lm with prompt
    bucketing — continuation starts with the prompt and stays in
    vocab."""
    from veles_tpu import prng
    from veles_tpu.config import root
    from veles_tpu.restful_api import serve_lm
    prng.reset(); prng.seed_all(6)
    root.__dict__.pop("char_lm", None)
    root.char_lm.update({
        "loader": {"minibatch_size": 32, "n_train": 128, "n_valid": 64,
                   "seq_len": 32, "vocab": 16},
        "trainer": {"vocab": 16, "d_model": 32, "n_heads": 4,
                    "n_layers": 1, "max_len": 32,
                    "learning_rate": 3e-3, "n_experts": 0,
                    "pipeline_stages": 0, "remat": False,
                    "rope": True, "n_kv_heads": 2, "window": 8,
                    "attn_sinks": 2},
        "decision": {"max_epochs": 2, "fail_iterations": 10},
    })
    from veles_tpu.samples import char_lm
    wf = char_lm.train()
    api = serve_lm(wf, port=0, max_new=8)
    try:
        for prompt in ([[1, 2, 3]], [[2, 4, 6, 8, 10, 12, 1]]):
            req = urllib.request.Request(
                "http://127.0.0.1:%d/predict" % api.port,
                data=json.dumps({"input": prompt, "n_new": 5}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                out = json.loads(resp.read())
            row = out["tokens"][0]
            assert len(row) == len(prompt[0]) + 5
            assert row[:len(prompt[0])] == prompt[0]
            assert all(0 <= t < 16 for t in row)
    finally:
        api.stop()
