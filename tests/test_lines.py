"""Lines sample (generated geometric dataset, SURVEY §2.3 samples row)."""

import numpy

from veles_tpu import prng
from veles_tpu.config import root


def _configure(n_train=800, n_valid=200, max_epochs=4):
    root.__dict__.pop("lines", None)
    from veles_tpu.samples.lines import default_config
    default_config()
    root.lines.update({
        "loader": {"minibatch_size": 100, "n_train": n_train,
                   "n_valid": n_valid},
        "decision": {"max_epochs": max_epochs, "fail_iterations": 20},
    })


def test_draw_lines_shapes_and_classes():
    from veles_tpu.samples.lines import draw_lines, N_CLASSES
    stream = prng.get("t_lines", pinned=True)
    data, labels = draw_lines(stream, 64, hw=16)
    assert data.shape == (64, 16, 16, 1)
    assert data.dtype == numpy.float32
    assert data.min() >= -1.0 and data.max() <= 1.0
    assert set(labels.tolist()) == set(range(N_CLASSES))
    # horizontal-class images vary along y much more than along x
    h = data[labels == 0, :, :, 0]
    assert h.mean(axis=(0, 2)).std() > h.mean(axis=(0, 1)).std()


def test_lines_converges_fused():
    prng.reset(); prng.seed_all(5)
    _configure()
    from veles_tpu.samples import lines
    wf = lines.train(fused=True)
    metrics = wf.decision.epoch_metrics
    errs = [m["validation"]["err_pct"] for m in metrics]
    assert errs[-1] < 15.0, errs          # orientation is nearly separable
    assert errs[-1] < errs[0]
