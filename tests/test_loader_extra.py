"""Loader-expansion tests: normalizers, image/pickle loaders, minibatch
capture/replay, InputJoiner, Wine sample (SURVEY §2.1/§2.2 parity)."""

import os
import pickle

import numpy
import pytest


# ---------------------------------------------------------------- normalizers
def test_linear_normalizer_roundtrip():
    from veles_tpu.normalization import from_spec
    stream = numpy.random.RandomState(0)
    data = stream.uniform(-5, 9, (40, 7)).astype(numpy.float32)
    norm = from_spec("linear")
    norm.analyze(data)
    out = norm.apply(data)
    assert out.min() >= -1.0001 and out.max() <= 1.0001
    numpy.testing.assert_allclose(norm.denormalize(out), data, atol=1e-4)


def test_mean_disp_normalizer():
    from veles_tpu.normalization import from_spec
    stream = numpy.random.RandomState(1)
    data = stream.normal(3.0, 2.0, (64, 5)).astype(numpy.float32)
    norm = from_spec("mean_disp")
    norm.analyze(data)
    out = norm.apply(data)
    numpy.testing.assert_allclose(out.mean(axis=0), 0.0, atol=1e-5)
    numpy.testing.assert_allclose(norm.denormalize(out), data, atol=1e-4)


def test_pointwise_and_exp_and_external_mean():
    from veles_tpu.normalization import from_spec
    stream = numpy.random.RandomState(2)
    data = stream.uniform(0, 10, (16, 4)).astype(numpy.float32)

    pw = from_spec("pointwise")
    pw.analyze(data)
    out = pw.apply(data)
    assert out.min() >= -1.0001 and out.max() <= 1.0001
    numpy.testing.assert_allclose(pw.denormalize(out), data, atol=1e-4)

    ex = from_spec("exp")
    out = ex.apply(data)
    numpy.testing.assert_allclose(out.sum(axis=1), 1.0, atol=1e-5)

    em = from_spec("external_mean")
    em.analyze(data)
    out = em.apply(data)
    numpy.testing.assert_allclose(out.mean(axis=0), 0.0, atol=1e-5)


def test_normalizer_picklable():
    from veles_tpu.normalization import from_spec
    data = numpy.random.RandomState(3).uniform(-1, 4, (8, 3)).astype(
        numpy.float32)
    norm = from_spec("linear")
    norm.analyze(data)
    clone = pickle.loads(pickle.dumps(norm))
    numpy.testing.assert_array_equal(clone.apply(data), norm.apply(data))


def test_unknown_normalizer_rejected():
    from veles_tpu.normalization import from_spec
    with pytest.raises(ValueError):
        from_spec("nope")


# ----------------------------------------------------------------- normalized
def test_loader_normalization_hook():
    from veles_tpu.workflow import Workflow
    from veles_tpu.loader.fullbatch import FullBatchLoader

    class ArrayLoader(FullBatchLoader):
        def load_data(self):
            stream = numpy.random.RandomState(0)
            self.original_data.reset(
                stream.uniform(0, 50, (30, 4)).astype(numpy.float32))
            self.original_labels.reset(
                numpy.zeros(30, numpy.int32))
            self.class_lengths = [0, 10, 20]

    wf = Workflow(None, name="w")
    loader = ArrayLoader(wf, minibatch_size=10,
                         normalization_type="linear")
    loader.initialize()
    # statistics fitted on the TRAIN slice: train rows map into [-1, 1]
    data = loader.original_data.mem
    assert data[10:].min() >= -1.0001 and data[10:].max() <= 1.0001


# -------------------------------------------------------------- image loading
def _write_images(tmp_path, per_class=6, size=(12, 10)):
    from PIL import Image
    for cls, color0 in (("red", (200, 10, 10)), ("blue", (10, 10, 200))):
        d = tmp_path / cls
        d.mkdir(exist_ok=True)
        for i in range(per_class):
            arr = numpy.zeros(size + (3,), numpy.uint8)
            arr[..., :] = color0
            arr[i % size[0], :, :] = 255
            Image.fromarray(arr).save(d / ("img_%d.png" % i))


def test_image_loader_directory_split(tmp_path):
    from veles_tpu.workflow import Workflow
    from veles_tpu.loader.image import AutoSplitImageLoader

    _write_images(tmp_path)
    wf = Workflow(None, name="w")
    loader = AutoSplitImageLoader(wf, str(tmp_path), validation_ratio=0.25,
                                  scale=(8, 8), minibatch_size=4)
    loader.initialize()
    assert loader.class_lengths[0] == 0
    assert sum(loader.class_lengths) == 12
    assert loader.class_lengths[1] == 3   # every 4th file
    assert loader.original_data.shape == (12, 8, 8, 3)
    assert set(loader.label_names) == {"red", "blue"}
    # linear normalization is fitted on the TRAIN slice only
    train = loader.original_data.mem[3:]
    assert train.min() >= -1.0001 and train.max() <= 1.0001


def test_image_decode_gray_and_crop(tmp_path):
    from PIL import Image
    from veles_tpu.loader.image import decode_image
    arr = numpy.arange(20 * 16 * 3, dtype=numpy.uint8).reshape(20, 16, 3)
    path = tmp_path / "x.png"
    Image.fromarray(arr).save(path)
    out = decode_image(str(path), size=(10, 8), color_space="GRAY",
                       crop=(6, 6))
    assert out.shape == (6, 6, 1)


def test_image_loader_shared_label_map(tmp_path):
    """The same class name maps to the same label index in EVERY split, even
    when a split is missing some classes."""
    from PIL import Image
    from veles_tpu.workflow import Workflow
    from veles_tpu.loader.image import FullBatchImageLoader

    def make(split, classes):
        base = tmp_path / split
        for cls in classes:
            d = base / cls
            d.mkdir(parents=True, exist_ok=True)
            arr = numpy.full((6, 6, 3), 100, numpy.uint8)
            Image.fromarray(arr).save(d / "a.png")
        return str(base)

    train = make("train", ["ant", "bee", "cat"])
    valid = make("valid", ["bee", "cat"])   # missing "ant"
    wf = Workflow(None, name="w")
    loader = FullBatchImageLoader(wf, validation_paths=valid,
                                  train_paths=train, scale=(6, 6),
                                  minibatch_size=4)
    loader.initialize()
    assert loader.label_names == ["ant", "bee", "cat"]
    labels = loader.original_labels.to_numpy()
    # layout [test|valid|train]: valid = bee,cat → [1,2]; train → [0,1,2]
    numpy.testing.assert_array_equal(labels, [1, 2, 0, 1, 2])


# ------------------------------------------------------------- pickles loader
def test_pickles_loader(tmp_path):
    from veles_tpu.workflow import Workflow
    from veles_tpu.loader.pickles import PicklesLoader

    stream = numpy.random.RandomState(0)
    for name, n in (("v.pickle", 8), ("t.pickle", 24)):
        with open(tmp_path / name, "wb") as f:
            pickle.dump((stream.normal(size=(n, 6)).astype(numpy.float32),
                         (numpy.arange(n) % 3).astype(numpy.int32)), f)
    wf = Workflow(None, name="w")
    loader = PicklesLoader(
        wf, validation_path=str(tmp_path / "v.pickle"),
        train_path=str(tmp_path / "t.pickle"), minibatch_size=8)
    loader.initialize()
    assert loader.class_lengths == [0, 8, 24]
    assert loader.original_data.shape == (32, 6)
    assert loader.has_labels


def test_pickles_loader_rejects_mixed_labels(tmp_path):
    from veles_tpu.workflow import Workflow
    from veles_tpu.loader.pickles import PicklesLoader

    stream = numpy.random.RandomState(0)
    with open(tmp_path / "v.pickle", "wb") as f:       # bare array: no labels
        pickle.dump(stream.normal(size=(8, 4)).astype(numpy.float32), f)
    with open(tmp_path / "t.pickle", "wb") as f:       # labeled
        pickle.dump((stream.normal(size=(24, 4)).astype(numpy.float32),
                     (numpy.arange(24) % 3).astype(numpy.int32)), f)
    wf = Workflow(None, name="w")
    loader = PicklesLoader(
        wf, validation_path=str(tmp_path / "v.pickle"),
        train_path=str(tmp_path / "t.pickle"), minibatch_size=8)
    with pytest.raises(ValueError, match="mixed"):
        loader.initialize()


# ----------------------------------------------------- capture/replay + joiner
def test_minibatch_capture_replay(tmp_path):
    from veles_tpu.samples import mnist
    from veles_tpu.config import root
    from veles_tpu.loader.saver import MinibatchesSaver, MinibatchesLoader
    from veles_tpu.workflow import Workflow

    root.__dict__.pop("mnist", None)
    root.mnist.update({
        "loader": {"minibatch_size": 16, "n_train": 48, "n_valid": 16},
        "decision": {"max_epochs": 1, "fail_iterations": 5},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "momentum": 0.0},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.05, "momentum": 0.0},
        ],
    })
    path = str(tmp_path / "stream.pickle")
    wf = mnist.build()
    MinibatchesSaver.attach_to(wf.loader, path)
    wf.initialize()
    wf.run()

    replay_wf = Workflow(None, name="replay")
    replay = MinibatchesLoader(replay_wf, path=path)
    replay.initialize()
    assert replay.class_lengths == [0, 16, 48]
    seen = 0
    first_epoch = []
    while True:
        replay.run()
        first_epoch.append(replay.minibatch_data.to_numpy())
        seen += replay.minibatch_size
        if replay.last_minibatch:
            break
    assert seen == 64
    # replay is deterministic: second epoch identical
    replay.run()
    numpy.testing.assert_array_equal(replay.minibatch_data.to_numpy(),
                                     first_epoch[0])


def test_input_joiner():
    from veles_tpu.workflow import Workflow
    from veles_tpu.units import Unit
    from veles_tpu.memory import Vector
    from veles_tpu.input_joiner import InputJoiner

    class Producer(Unit):
        def __init__(self, workflow, value, **kwargs):
            super().__init__(workflow, **kwargs)
            self.output = Vector(value)

    wf = Workflow(None, name="w")
    a = Producer(wf, numpy.ones((4, 3), numpy.float32), name="a")
    b = Producer(wf, numpy.full((4, 2, 2), 2.0, numpy.float32), name="b")
    joiner = InputJoiner(wf, inputs=[a, b])
    joiner.initialize()
    joiner.run()
    out = joiner.output.to_numpy()
    assert out.shape == (4, 7)
    numpy.testing.assert_array_equal(out[:, :3], 1.0)
    numpy.testing.assert_array_equal(out[:, 3:], 2.0)

    c = Producer(wf, numpy.zeros((5, 3), numpy.float32), name="c")
    bad = InputJoiner(wf, inputs=[a, c])
    with pytest.raises(ValueError):
        bad.initialize()


# ------------------------------------------------------------------ wine
def test_wine_converges():
    from veles_tpu.config import root
    from veles_tpu.samples import wine

    root.__dict__.pop("wine", None)
    wine.default_config()
    root.wine.decision.max_epochs = 25
    wf = wine.train()
    last = wf.decision.epoch_metrics[-1]["validation"]
    assert last["n_err"] <= 3, last


# ------------------------------------------------------------------ lmdb
# All tests run against REAL environment bytes (VERDICT r4 task 5):
# fixtures are authored with the vendored stable-format writer
# (mdb.write_env), parsed back through the same B-tree/overflow walk a
# Caffe-era LMDB takes — no fake modules, no monkeypatching.


def _write_caffe_env(path, samples, labels):
    """Author a real Caffe-layout LMDB: Datum protobufs keyed by index."""
    from veles_tpu.loader import mdb
    from veles_tpu.loader.lmdb import serialize_datum
    return mdb.write_env(str(path), [
        (b"%08d" % i, serialize_datum(samples[i], labels[i]))
        for i in range(len(samples))])


class TestMDBFormat:
    def test_roundtrip_with_overflow_and_branch(self, tmp_path):
        """Writer/reader pair over the three structural cases: inline
        leaf values, F_BIGDATA overflow values, and a multi-leaf tree
        under a branch root."""
        from veles_tpu.loader import mdb
        rng = numpy.random.RandomState(0)
        items = [(b"k%04d" % i, bytes(rng.randint(0, 256, i % 60 + 1,
                                                  dtype=numpy.uint8)))
                 for i in range(400)]                    # > 1 leaf page
        items += [(b"z%04d" % i,
                   bytes(rng.randint(0, 256, 10000, dtype=numpy.uint8)))
                  for i in range(3)]                     # overflow values
        env_dir = tmp_path / "env"
        mdb.write_env(str(env_dir), items)
        env = mdb.open_env(str(env_dir))
        assert env.stat()["entries"] == len(items)
        got = list(env.items())
        assert [k for k, _ in got] == sorted(k for k, _ in items)
        lookup = dict(items)
        for k, v in got:
            assert v == lookup[k]

    def test_rejects_garbage(self, tmp_path):
        from veles_tpu.loader import mdb
        bad = tmp_path / "bad.mdb"
        bad.write_bytes(b"\0" * 8192)
        with pytest.raises(ValueError, match="magic"):
            mdb.open_env(str(bad))
        short = tmp_path / "short.mdb"
        short.write_bytes(b"x")
        with pytest.raises(ValueError, match="too small"):
            mdb.open_env(str(short))


def test_lmdb_to_records_rejects_empty(tmp_path):
    from veles_tpu.loader import lmdb as L, mdb
    env_dir = tmp_path / "empty_env"
    mdb.write_env(str(env_dir), [])
    with pytest.raises(ValueError, match="empty LMDB"):
        L.lmdb_to_records(str(env_dir), str(tmp_path / "out.rec"))


def test_lmdb_to_records_rejects_shape_mismatch(tmp_path):
    from veles_tpu.loader import lmdb as L, mdb
    from veles_tpu.loader.lmdb import serialize_datum
    env_dir = tmp_path / "env"
    mdb.write_env(str(env_dir), [
        (b"0", serialize_datum(numpy.zeros((3, 4, 4), numpy.uint8), 0)),
        (b"1", serialize_datum(numpy.zeros((3, 5, 5), numpy.uint8), 0)),
    ])
    with pytest.raises(ValueError, match="uniform shapes"):
        L.lmdb_to_records(str(env_dir), str(tmp_path / "out.rec"))


def test_lmdb_to_records_roundtrip(tmp_path):
    from veles_tpu.loader import lmdb as L
    from veles_tpu.loader.records import open_records
    rng = numpy.random.RandomState(0)
    samples = rng.randint(0, 255, (4, 3, 4, 5)).astype(numpy.uint8)
    labels = [3, 1, 4, 1]
    env_dir = _write_caffe_env(tmp_path / "env", samples, labels)
    out = L.lmdb_to_records(os.path.dirname(env_dir),
                            str(tmp_path / "out.rec"),
                            class_lengths=[0, 1, 3])
    header, data, got_labels = open_records(out)
    assert header["class_lengths"] == [0, 1, 3]
    numpy.testing.assert_array_equal(
        numpy.asarray(data), samples.transpose(0, 2, 3, 1))
    numpy.testing.assert_array_equal(numpy.asarray(got_labels), labels)


def test_lmdb_loader_direct(tmp_path):
    """LMDBLoader reads real env bytes straight into minibatches."""
    from veles_tpu import prng
    from veles_tpu.loader.lmdb import LMDBLoader
    rng = numpy.random.RandomState(3)
    train = rng.randint(0, 255, (20, 3, 6, 6)).astype(numpy.uint8)
    valid = rng.randint(0, 255, (8, 3, 6, 6)).astype(numpy.uint8)
    t_dir = _write_caffe_env(tmp_path / "train", train,
                             numpy.arange(20) % 5)
    v_dir = _write_caffe_env(tmp_path / "valid", valid,
                             numpy.arange(8) % 5)
    prng.reset(); prng.seed_all(5)
    loader = LMDBLoader(None, train_path=os.path.dirname(t_dir),
                        validation_path=os.path.dirname(v_dir),
                        minibatch_size=10, name="loader")
    loader.initialize()
    assert loader.class_lengths == [0, 8, 20]
    loader.run()
    assert loader.minibatch_data.mem.shape == (10, 6, 6, 3)
    assert abs(float(loader.minibatch_data.mem.max())) <= 1.0


def test_lmdb_end_to_end_train_step(tmp_path):
    """The verdict's full chain on real bytes: Caffe LMDB →
    lmdb_to_records → RecordsLoader → one fused train step."""
    from veles_tpu import prng
    from veles_tpu.loader import lmdb as L
    from veles_tpu.loader.records import RecordsLoader
    from veles_tpu.config import root
    rng = numpy.random.RandomState(1)
    samples = rng.randint(0, 255, (30, 3, 24, 24)).astype(numpy.uint8)
    labels = numpy.arange(30) % 4
    env_dir = _write_caffe_env(tmp_path / "env", samples, labels)
    rec = L.lmdb_to_records(os.path.dirname(env_dir),
                            str(tmp_path / "ds.rec"),
                            class_lengths=[0, 10, 20])
    prng.reset(); prng.seed_all(9)
    root.__dict__.pop("imagenet", None)
    from veles_tpu.samples import imagenet
    root.imagenet.update({
        "loader": {"records_path": rec, "minibatch_size": 10},
        "decision": {"max_epochs": 1, "fail_iterations": 5},
        "layers": imagenet.tiny_layers(n_classes=4, crop=(20, 20)),
    })
    wf = imagenet.build(fused=True)
    wf.initialize()
    wf.run()
    assert wf.decision.epoch_metrics, "no epoch completed"
    assert "validation" in wf.decision.epoch_metrics[-1]


class TestRecordsPrefetch:
    def _make(self, tmp_path, prefetch):
        from veles_tpu import prng
        from veles_tpu.loader.records import write_records, RecordsLoader
        rng = numpy.random.RandomState(2)
        data = rng.randint(0, 256, (90, 6, 6, 3), numpy.uint8)
        labels = (numpy.arange(90) % 7).astype(numpy.int32)
        path = write_records(str(tmp_path / "p.rec"), data, labels,
                             [0, 20, 70])
        prng.reset(); prng.seed_all(11)
        loader = RecordsLoader(None, path=path, minibatch_size=16,
                               prefetch=prefetch, name="loader")
        loader.initialize()
        return loader

    def test_prefetch_stream_identical(self, tmp_path):
        """Double-buffered delivery must be byte-identical to the
        synchronous path across epochs (same PRNG -> same plan)."""
        streams = []
        for prefetch in (False, True):
            loader = self._make(tmp_path, prefetch)
            got = []
            for _ in range(2):              # two epochs incl. reshuffle
                while True:
                    loader.run()
                    got.append((loader.minibatch_class,
                                numpy.array(loader.minibatch_data.mem),
                                numpy.array(loader.minibatch_labels.mem),
                                int(loader.minibatch_size)))
                    if loader.last_minibatch:
                        break
            loader.stop()
            streams.append(got)
        assert len(streams[0]) == len(streams[1])
        for (ca, da, la, sa), (cb, db, lb, sb) in zip(*streams):
            assert ca == cb and sa == sb
            numpy.testing.assert_array_equal(da, db)
            numpy.testing.assert_array_equal(la, lb)

    def test_stop_idempotent(self, tmp_path):
        loader = self._make(tmp_path, prefetch=True)
        loader.run()
        loader.stop()
        loader.stop()                        # no double-shutdown crash

    def test_staged_batch_equals_synchronous_gather(self, tmp_path):
        """After run() stages the NEXT minibatch, the pending future's
        payload must equal what a synchronous gather of those indices
        produces — the double buffer changes timing, never bytes."""
        loader = self._make(tmp_path, prefetch=True)
        loader.run()
        assert loader._pending is not None
        key, fut = loader._pending
        staged_batch, staged_labels = fut.result()
        nxt = loader.local_chunk(loader._order[loader._position][1])
        assert key == nxt.tobytes()
        sync_batch, sync_labels = loader._gather(nxt)
        numpy.testing.assert_array_equal(staged_batch, sync_batch)
        numpy.testing.assert_array_equal(staged_labels, sync_labels)
        loader.stop()

    def test_stale_plan_discarded_falls_back_clean(self, tmp_path):
        """A plan change between staging and consumption (key !=
        indices.tobytes()) must discard the staged batch and fall back
        to the synchronous gather for the ACTUAL indices."""
        loader = self._make(tmp_path, prefetch=True)
        loader.run()                         # stages minibatch #2
        assert loader._pending is not None
        stale_key = loader._pending[0]
        # shuffle a fresh plan under the staged future (what a snapshot
        # restore or replan does): position resets, indices change
        loader._plan_epoch()
        loader._position = 0
        loader.run()
        assert loader.minibatch_indices.mem.tobytes() != stale_key
        # delivered rows are the fresh plan's rows, gathered cleanly
        expect, expect_labels = loader._gather(
            numpy.asarray(loader.minibatch_indices.mem))
        numpy.testing.assert_array_equal(
            numpy.asarray(loader.minibatch_data.mem), expect)
        numpy.testing.assert_array_equal(
            numpy.asarray(loader.minibatch_labels.mem), expect_labels)
        loader.stop()

    def test_stop_shuts_pool_without_leaking_pending(self, tmp_path):
        """stop() must drop the pending future and tear the pool down
        (no orphan worker thread keeping the memmap alive)."""
        loader = self._make(tmp_path, prefetch=True)
        loader.run()
        assert loader._pending is not None
        pool = loader._pool
        loader.stop()
        assert loader._pending is None
        assert loader._pool is None
        assert pool._shutdown


def test_lmdb_gather_window_matches_fill(tmp_path):
    """LMDBLoader.gather_window (streaming epoch-scan staging hook)
    applies the exact fill_minibatch conversion."""
    from veles_tpu import prng
    from veles_tpu.loader.lmdb import LMDBLoader
    rng = numpy.random.RandomState(8)
    train = rng.randint(0, 255, (12, 3, 5, 5)).astype(numpy.uint8)
    valid = rng.randint(0, 255, (6, 3, 5, 5)).astype(numpy.uint8)
    t_dir = _write_caffe_env(tmp_path / "gw_train", train,
                             numpy.arange(12) % 4)
    v_dir = _write_caffe_env(tmp_path / "gw_valid", valid,
                             numpy.arange(6) % 4)
    prng.reset(); prng.seed_all(5)
    loader = LMDBLoader(None, train_path=os.path.dirname(t_dir),
                        validation_path=os.path.dirname(v_dir),
                        minibatch_size=6, name="loader")
    loader.initialize()
    assert loader.can_gather_windows
    idx = numpy.asarray([0, 17, 5, 5, 9], numpy.int32)
    win, win_labels = loader.gather_window(idx)
    loader.fill_minibatch(idx, len(idx))
    numpy.testing.assert_array_equal(
        win, numpy.asarray(loader.minibatch_data.mem)[:len(idx)])
    numpy.testing.assert_array_equal(
        win_labels, numpy.asarray(loader.minibatch_labels.mem)[:len(idx)])
