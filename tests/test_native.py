"""Native dataio library: build, correctness vs numpy, fallback parity,
loader integration (SURVEY §2.4 native-components row)."""

import json
import os
import subprocess
import sys

import numpy
import pytest

from veles_tpu import native

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def lib_available():
    ok = native.available()
    if not ok:
        pytest.skip("g++ unavailable — native path untestable")
    return ok


class TestBuild:
    def test_builds_and_loads(self, lib_available):
        assert os.path.exists(os.path.join(
            os.path.dirname(native.__file__), "libdataio.so"))

    def test_makefile_builds_too(self, tmp_path):
        native_dir = os.path.dirname(os.path.abspath(native.__file__))
        result = subprocess.run(
            ["make", "-n", "-C", native_dir], capture_output=True, text=True)
        assert result.returncode == 0


class TestGatherConvert:
    def test_u8_matches_numpy(self, lib_available):
        r = numpy.random.RandomState(0)
        src = r.randint(0, 256, (100, 7, 5), dtype=numpy.uint8)
        idx = r.randint(0, 100, 32).astype(numpy.int32)
        out = native.gather_convert(src, idx, scale=1.0 / 127.5,
                                    offset=-1.0)
        expect = src[idx].astype(numpy.float32) / 127.5 - 1.0
        numpy.testing.assert_allclose(out, expect, rtol=1e-6, atol=1e-6)

    def test_f32_matches_numpy(self, lib_available):
        r = numpy.random.RandomState(1)
        src = r.randn(50, 12).astype(numpy.float32)
        idx = r.randint(0, 50, 20).astype(numpy.int32)
        numpy.testing.assert_array_equal(native.gather_convert(src, idx),
                                         src[idx])

    def test_memmap_source(self, lib_available, tmp_path):
        r = numpy.random.RandomState(2)
        data = r.randint(0, 256, (40, 6), dtype=numpy.uint8)
        path = str(tmp_path / "data.bin")
        data.tofile(path)
        mapped = numpy.memmap(path, numpy.uint8, "r", shape=(40, 6))
        idx = numpy.arange(0, 40, 2, dtype=numpy.int32)
        out = native.gather_convert(mapped, idx, scale=2.0, offset=1.0)
        numpy.testing.assert_allclose(
            out, mapped[idx].astype(numpy.float32) * 2.0 + 1.0)

    def test_labels_and_mean(self, lib_available):
        r = numpy.random.RandomState(3)
        labels = r.randint(0, 10, 100).astype(numpy.int32)
        idx = r.randint(0, 100, 30).astype(numpy.int32)
        numpy.testing.assert_array_equal(
            native.gather_labels(labels, idx), labels[idx])
        batch = r.randn(8, 5).astype(numpy.float32)
        mean = r.randn(5).astype(numpy.float32)
        expect = batch - mean
        out = native.subtract_mean(batch.copy(), mean)
        numpy.testing.assert_allclose(out, expect, rtol=1e-6)

    def test_broadcast_mean_keeps_numpy_semantics(self, lib_available):
        """A per-channel mean (not sample-shaped) must broadcast like
        numpy, not read out of bounds in the native kernel."""
        r = numpy.random.RandomState(4)
        batch = r.randn(4, 6, 6, 3).astype(numpy.float32)
        channel_mean = numpy.array([104.0, 117.0, 123.0], numpy.float32)
        expect = batch - channel_mean
        out = native.subtract_mean(batch.copy(), channel_mean)
        numpy.testing.assert_allclose(out, expect, rtol=1e-6)

    def test_strided_source_matches(self, lib_available):
        r = numpy.random.RandomState(5)
        full = r.randint(0, 256, (20, 4, 4, 4), dtype=numpy.uint8)
        view = full[:, :, :, :3]          # non-contiguous channel slice
        idx = numpy.arange(0, 20, 2, dtype=numpy.int32)
        out = native.gather_convert(view, idx, scale=2.0)
        numpy.testing.assert_allclose(
            out, view[idx].astype(numpy.float32) * 2.0)


class TestFallbackParity:
    def test_env_forced_fallback_matches(self, lib_available):
        """The numpy fallback must produce identical results (subprocess so
        the env var takes effect before first load)."""
        code = """
import os
os.environ["VELES_TPU_NO_NATIVE"] = "1"
import numpy
import sys
sys.path.insert(0, %r)
from veles_tpu import native
assert not native.available()
r = numpy.random.RandomState(0)
src = r.randint(0, 256, (100, 7, 5), dtype=numpy.uint8)
idx = r.randint(0, 100, 32).astype(numpy.int32)
out = native.gather_convert(src, idx, scale=1.0/127.5, offset=-1.0)
expect = src[idx].astype(numpy.float32) / 127.5 - 1.0
numpy.testing.assert_allclose(out, expect, rtol=1e-6, atol=1e-6)
print("fallback-ok")
"""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(
            native.__file__)))
        result = subprocess.run(
            [sys.executable, "-c", code % repo], capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert "fallback-ok" in result.stdout, result.stderr


class TestLoaderIntegration:
    def test_records_loader_uses_native_path(self, lib_available, tmp_path):
        from veles_tpu.loader.records import write_records, RecordsLoader
        from veles_tpu.workflow import Workflow
        r = numpy.random.RandomState(0)
        data = r.randint(0, 256, (30, 4, 4, 3), dtype=numpy.uint8)
        labels = (numpy.arange(30) % 3).astype(numpy.int32)
        path = str(tmp_path / "set.rec")
        write_records(path, data, labels, [0, 10, 20])
        wf = Workflow(None, name="wf")
        loader = RecordsLoader(wf, path=path, minibatch_size=8,
                               name="loader")
        loader.initialize()
        loader.run()
        idx = numpy.asarray(loader.minibatch_indices.mem)
        expect = data[idx].astype(numpy.float32) / 127.5 - 1.0
        # the native kernel computes x*(1/127.5)-1 — one ulp of slack
        numpy.testing.assert_allclose(
            numpy.asarray(loader.minibatch_data.mem), expect,
            rtol=1e-6, atol=1e-6)
        numpy.testing.assert_array_equal(
            numpy.asarray(loader.minibatch_labels.mem), labels[idx])


class TestArtifactRunner:
    """The C++ PJRT standalone runner (libVeles parity, SURVEY §2.4):
    build, plugin loading, and bundle export are exercised everywhere;
    the full compile+execute leg needs a real device and is TPU-marked
    like the Pallas PRNG tests."""

    @pytest.fixture(scope="class")
    def runner_bin(self):
        import subprocess
        d = os.path.join(REPO, "veles_tpu", "native")
        subprocess.run(["make", "artifact_runner"], cwd=d, check=True,
                       stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        return os.path.join(d, "artifact_runner")

    def _plugin(self):
        plugin = native.find_pjrt_plugin()
        if plugin is None:
            pytest.skip("no PJRT plugin .so on this image")
        return plugin

    def test_selfcheck_loads_plugin(self, runner_bin):
        import subprocess
        out = subprocess.run([runner_bin, "--selfcheck", self._plugin()],
                             stdout=subprocess.PIPE, check=True,
                             timeout=120).stdout.decode()
        assert "SELFCHECK OK" in out
        assert "pjrt_api_version" in out

    def test_export_native_bundle(self, tmp_path):
        from veles_tpu import export, prng
        from veles_tpu.config import root
        prng.reset(); prng.seed_all(1)
        root.mnist.update({
            "loader": {"minibatch_size": 50, "n_train": 200,
                       "n_valid": 100},
            "decision": {"max_epochs": 1, "fail_iterations": 5},
            "layers": [
                {"type": "all2all_tanh", "output_sample_shape": 16,
                 "learning_rate": 0.03, "momentum": 0.9},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.03, "momentum": 0.9},
            ],
        })
        from veles_tpu.samples import mnist
        wf = mnist.train()
        bundle = export.export_native_bundle(wf, str(tmp_path / "nb"),
                                             batch=4)
        mlir = open(os.path.join(bundle, "program.mlir")).read()
        # weights are baked in: constants present, module well-formed
        assert "module" in mlir and "stablehlo" in mlir
        assert "4x784" in mlir        # static input shape in signature
        assert os.path.getsize(
            os.path.join(bundle, "compile_options.pb")) > 0
        assert open(os.path.join(bundle, "input.shape")).read() == "4 784"
        manifest = json.load(open(os.path.join(bundle, "manifest.json")))
        assert manifest["output_shape"] == [4, 10]

    @pytest.mark.skipif(
        not __import__("veles_tpu.ops.pallas_kernels",
                       fromlist=["on_tpu"]).on_tpu(),
        reason="full compile+execute needs a real PJRT device")
    def test_execute_on_device(self, runner_bin, tmp_path):
        import subprocess
        from veles_tpu import export, prng
        from veles_tpu.config import root
        prng.reset(); prng.seed_all(1)
        root.mnist.update({
            "loader": {"minibatch_size": 50, "n_train": 200,
                       "n_valid": 100},
            "decision": {"max_epochs": 1, "fail_iterations": 5},
        })
        from veles_tpu.samples import mnist
        wf = mnist.train()
        bundle = export.export_native_bundle(wf, str(tmp_path / "nb"),
                                             batch=2)
        x = numpy.random.RandomState(0).uniform(
            -1, 1, (2, 784)).astype(numpy.float32)
        (tmp_path / "in.bin").write_bytes(x.tobytes())
        out = subprocess.run(
            [runner_bin, bundle, self._plugin(),
             str(tmp_path / "in.bin"), str(tmp_path / "out.bin")],
            stdout=subprocess.PIPE, check=True, timeout=600
        ).stdout.decode()
        assert "EXECUTE OK" in out
        got = numpy.frombuffer(
            (tmp_path / "out.bin").read_bytes(), numpy.float32
        ).reshape(2, 10)
        want = numpy.asarray(
            wf._fused_runner.eval_forward()(wf._fused_runner.state, x))
        numpy.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
