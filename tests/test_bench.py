"""bench.py contract tests: the LAST JSON line of stdout is always a
well-formed summary record (streamed after every completed leg, so even
a SIGKILL preserves what was measured), per-config watchdog isolation,
and the summary_record metric selection.

These run the host-side configs only (records is pure host work;
convergence math is covered elsewhere) so the suite stays fast.
"""

import json
import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
BENCH = os.path.join(REPO, "bench.py")


def _run(args, env_extra=None, timeout=300, pin_cpu=True):
    env = dict(os.environ)
    if pin_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    else:
        env.pop("JAX_PLATFORMS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(env_extra or {})
    proc = subprocess.run(
        [sys.executable, BENCH] + args, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, env=env, cwd=REPO, timeout=timeout)
    lines = proc.stdout.decode().strip().splitlines()
    json_lines = [ln for ln in lines if ln.startswith("{")]
    return proc.returncode, json_lines


def test_orchestrated_final_record_last_line():
    """The default (subprocess-orchestrated) mode: every stdout JSON
    line is a parseable summary record (per-leg partials stream as legs
    complete) and the LAST line is the final well-formed record."""
    rc, lines = _run(["--configs", "records", "--seconds", "0.2",
                      "--smoke"])
    assert rc == 0
    assert lines
    for ln in lines:                      # partials share the shape
        partial = json.loads(ln)
        assert "metric" in partial and "configs" in partial
    rec = json.loads(lines[-1])
    assert rec["metric"] == "records_pipeline_samples_per_sec"
    assert rec["value"] > 0
    assert "records_pipeline" in rec["configs"]


def test_watchdog_records_timeout_and_still_emits():
    """A hung/slow config is killed and recorded as an error; the JSON
    line still appears and the exit code flags the failure.  --seconds
    9999 makes the worker's timing window provably longer than the 2 s
    deadline on ANY machine (deterministic kill, not a startup race)."""
    rc, lines = _run(["--configs", "records", "--seconds", "9999"],
                     env_extra={"VELES_BENCH_CONFIG_TIMEOUT_S": "2"})
    assert rc == 1
    assert lines
    rec = json.loads(lines[-1])
    assert rec["metric"] == "bench_failed"
    assert "records_error" in rec["configs"]
    assert "killed after" in rec["configs"]["records_error"]


def test_unknown_config_rejected():
    proc = subprocess.run(
        [sys.executable, BENCH, "--configs", "nope"],
        capture_output=True, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        cwd=REPO, timeout=60)
    assert proc.returncode == 2
    assert b"unknown configs" in proc.stderr


def test_convergence_sub_config_addressable():
    """convergence:<sub> tokens are valid --configs entries (the
    expansion the orchestrator uses for per-sub watchdogs)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location("bench_mod", BENCH)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert bench.expand_configs(["convergence"]) == [
        "convergence:" + s for s in bench.CONVERGENCE_SUBS]
    assert bench.expand_configs(["mnist", "lm"]) == ["mnist", "lm"]


def test_compile_cache_armed_and_disableable(tmp_path, monkeypatch):
    """enable_compile_cache points jax at the repo cache dir (wedge
    mitigation: a warm cache removes the 20-40s conv-compile RPC for
    every worker after the first); VELES_JAX_CACHE=0 disables."""
    import importlib.util
    spec = importlib.util.spec_from_file_location("bench_mod3", BENCH)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    import jax
    cache_dir = str(tmp_path / "jc")
    monkeypatch.setenv("VELES_JAX_CACHE_DIR", cache_dir)
    before = jax.config.jax_compilation_cache_dir
    try:
        bench.enable_compile_cache()
        assert jax.config.jax_compilation_cache_dir == cache_dir
        assert os.path.isdir(cache_dir)
        other = str(tmp_path / "jc2")
        monkeypatch.setenv("VELES_JAX_CACHE_DIR", other)
        monkeypatch.setenv("VELES_JAX_CACHE", "0")
        bench.enable_compile_cache()        # disabled: must not re-point
        assert jax.config.jax_compilation_cache_dir == cache_dir
        assert not os.path.isdir(other)
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


def test_bench_report_renders_rounds():
    """tools/bench_report.py renders the BENCH_r*.json history as one
    markdown table (round columns, config rows, failures marked)."""
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(BENCH), "tools", "bench_report.py")],
        stdout=subprocess.PIPE, timeout=60, check=True)
    text = out.stdout.decode()
    assert text.startswith("| config |")
    assert "| mnist_fc |" in text
    assert "r03" in text.splitlines()[0]
    # configs that never succeeded still get a (failed) row
    assert "| lm |" in text or "| char_lm |" in text


def test_emit_summary_priority_and_fallbacks():
    import importlib.util
    spec = importlib.util.spec_from_file_location("bench_mod2", BENCH)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    import io
    import contextlib

    def emit(results):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = bench.emit_summary(dict(results))
        return rc, json.loads(buf.getvalue().strip())

    # model result wins and computes the records pipeline ratio
    rc, rec = emit({
        "mnist_fc": {"samples_per_sec": 10.0, "vs_numpy_floor": 2.0},
        "alexnet": {"samples_per_sec": 100.0},
        "alexnet_records": {"samples_per_sec": 90.0},
    })
    assert rc == 0
    assert rec["metric"].startswith("mnist_fc")
    assert rec["configs"]["alexnet_records"][
        "pipeline_ratio_vs_hbm"] == 0.9
    # skipped scaling alone is a success, not a failure
    rc, rec = emit({"dp_scaling": {"skipped": "single device"}})
    assert rc == 0 and rec["metric"] == "dp_scaling_skipped"
    # all-errors still yields the one line with rc=1
    rc, rec = emit({"mnist_error": "boom"})
    assert rc == 1 and rec["metric"] == "bench_failed"


def test_worker_streams_partials_and_collect_merges():
    """Workers stream each completed record as a {"partial": ...} line
    (VELES_BENCH_STREAM=1) so a later watchdog kill cannot discard
    already-measured records; collect_worker_output merges partials and
    lets the final results line win."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", VELES_BENCH_STREAM="1")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, BENCH, "--worker", "records", "--smoke",
         "--seconds", "0.2"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        cwd=REPO, timeout=300)
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.startswith("{")]
    partials = [json.loads(ln) for ln in lines if "partial" in ln]
    assert partials, "worker emitted no partial lines"
    assert any("records_pipeline" in p["partial"] for p in partials)

    import importlib.util
    spec = importlib.util.spec_from_file_location("bench_mod3", BENCH)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    # full output: the final results line wins
    got, complete = bench.collect_worker_output(proc.stdout)
    assert complete and got["records_pipeline"]["samples_per_sec"] > 0
    # truncated output (simulated kill mid-worker): partials survive
    cut = proc.stdout[:proc.stdout.rfind(b'{"worker"')]
    got, complete = bench.collect_worker_output(cut)
    assert not complete
    assert got["records_pipeline"]["samples_per_sec"] > 0


def test_sigterm_emits_partial_json_and_exit_zero():
    """The driver wraps the bench in an outer `timeout`; when the TPU
    relay wedge burns that budget, TERM must produce the one JSON line
    (partial results) and exit 0 — not die mid-probe with rc 124 and
    nothing parseable (BENCH_r05.json's failure mode)."""
    import signal
    import time as time_mod
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.Popen(
        [sys.executable, BENCH, "--configs", "records",
         "--seconds", "9999"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        cwd=REPO)
    time_mod.sleep(5)                    # handler installed; worker busy
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=60)
    assert proc.returncode == 0
    lines = [ln for ln in out.decode().splitlines() if ln.startswith("{")]
    assert lines
    rec = json.loads(lines[-1])
    assert "bench_error" in rec["configs"]
    assert "partial results" in rec["configs"]["bench_error"]


@pytest.mark.slow
def test_sigkill_mid_run_leaves_parsed_record():
    """The BENCH_r04/r05 "parsed": null failure mode: `timeout -k`
    follows TERM with KILL, and a KILLed bench runs no handler at all.
    Per-leg summary streaming means the stdout captured up to the kill
    still ENDS with a parseable record carrying every completed leg.
    (slow-marked: spawns a non-smoke worker; the streaming contract
    itself stays tier-1 via test_orchestrated_final_record_last_line)"""
    import time as time_mod
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # orchestrated mode (not --smoke): leg 1 (records, tiny window)
    # completes and streams its summary line; the KILL lands while
    # leg 2 (mnist) is still working
    proc = subprocess.Popen(
        [sys.executable, BENCH, "--configs", "records,mnist",
         "--seconds", "0.2"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        cwd=REPO)
    streamed = []
    deadline = time_mod.monotonic() + 280
    while time_mod.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        line = line.decode().strip()
        if line.startswith("{"):
            streamed.append(line)
            break                       # leg 1's summary arrived
    assert streamed, "no per-leg summary streamed before the kill"
    proc.kill()                         # SIGKILL — no handler runs
    rest, _ = proc.communicate(timeout=60)
    lines = streamed + [ln for ln in rest.decode().splitlines()
                        if ln.startswith("{")]
    rec = json.loads(lines[-1])         # the driver's "last line wins"
    assert rec["configs"]["records_pipeline"]["samples_per_sec"] > 0


def test_total_deadline_skips_and_exits_zero():
    """VELES_BENCH_TOTAL_S bounds the whole run: configs that would
    start past the deadline are recorded as skipped, the summary still
    emits, and a nothing-measured-because-deadline run exits 0."""
    rc, lines = _run(["--configs", "records", "--seconds", "9999"],
                     env_extra={"VELES_BENCH_TOTAL_S": "1"}, timeout=120)
    assert rc == 0
    assert lines
    rec = json.loads(lines[-1])
    assert "total bench deadline" in rec["configs"]["records_error"]


def test_dead_tunnel_degrades_to_host_records():
    """A dead tunnel must NOT zero the bench (round-4 failure mode):
    device configs record unreachable-errors, but host-side configs
    (records; the native runner's cpu-pinned worker) still produce real
    records and the summary line is VALID with rc=0.  pin_cpu=False:
    the simulate gate must see the mnist worker as a DEVICE worker
    (orchestrate cpu-pins only host_only workers)."""
    rc, lines = _run(["--configs", "mnist,records", "--seconds", "0.2"],
                     env_extra={"VELES_BENCH_SIMULATE_DEAD_TUNNEL": "1",
                                "VELES_BENCH_CONFIG_TIMEOUT_S": "240"},
                     timeout=500, pin_cpu=False)
    assert rc == 0, lines
    rec = json.loads(lines[-1])
    assert rec["metric"] == "records_pipeline_samples_per_sec"
    assert rec["value"] > 0
    assert "unreachable" in rec["configs"]["mnist_error"]
    assert rec["configs"]["records_pipeline"]["samples_per_sec"] > 0
