"""LR policies + utility units (ZeroFiller, ResizableAll2All, ImageSaver,
MeanDispNormalizer) — SURVEY §2.3 utility rows."""

import numpy
import pytest

import jax.numpy as jnp

from veles_tpu.ops.lr_adjust import make_policy


class TestLRPolicies:
    def t(self, v):
        return jnp.asarray(v, jnp.int32)

    def test_fixed(self):
        fn = make_policy({"policy": "fixed"})
        assert float(fn(0.1, self.t(500))) == pytest.approx(0.1)

    def test_exp(self):
        fn = make_policy({"policy": "exp", "gamma": 0.9})
        assert float(fn(1.0, self.t(2))) == pytest.approx(0.81, rel=1e-5)

    def test_step_exp(self):
        fn = make_policy({"policy": "step_exp", "gamma": 0.5, "step": 10})
        assert float(fn(1.0, self.t(9))) == pytest.approx(1.0)
        assert float(fn(1.0, self.t(25))) == pytest.approx(0.25)

    def test_inv(self):
        fn = make_policy({"policy": "inv", "gamma": 0.1, "power": 1.0})
        assert float(fn(1.0, self.t(10))) == pytest.approx(0.5, rel=1e-5)

    def test_linear(self):
        fn = make_policy({"policy": "linear", "final": 0.0, "steps": 100})
        assert float(fn(1.0, self.t(50))) == pytest.approx(0.5, rel=1e-5)
        assert float(fn(1.0, self.t(1000))) == pytest.approx(0.0, abs=1e-7)

    def test_warmup_cosine(self):
        fn = make_policy({"policy": "warmup_cosine", "warmup": 10,
                          "steps": 110, "final_scale": 0.1})
        assert float(fn(1.0, self.t(0))) == pytest.approx(0.0)
        assert float(fn(1.0, self.t(5))) == pytest.approx(0.5, rel=1e-5)
        # peak at the warmup boundary, half-decayed at the midpoint,
        # floor at final_scale after `steps`
        assert float(fn(1.0, self.t(10))) == pytest.approx(1.0, rel=1e-5)
        assert float(fn(1.0, self.t(60))) == pytest.approx(0.55, rel=1e-4)
        assert float(fn(1.0, self.t(110))) == pytest.approx(0.1, abs=1e-6)
        assert float(fn(1.0, self.t(500))) == pytest.approx(0.1, abs=1e-6)
        with pytest.raises(ValueError, match="warmup"):
            make_policy({"policy": "warmup_cosine", "warmup": 10,
                         "steps": 10})

    def test_warmup_rsqrt(self):
        fn = make_policy({"policy": "warmup_rsqrt", "warmup": 100})
        assert float(fn(1.0, self.t(50))) == pytest.approx(0.5, rel=1e-5)
        assert float(fn(1.0, self.t(100))) == pytest.approx(1.0, rel=1e-5)
        assert float(fn(1.0, self.t(400))) == pytest.approx(0.5, rel=1e-5)

    def test_arbitrary(self):
        fn = make_policy({"policy": "arbitrary",
                          "points": [(0, 1.0), (10, 0.1), (20, 0.01)]})
        assert float(fn(2.0, self.t(5))) == pytest.approx(2.0)
        assert float(fn(2.0, self.t(15))) == pytest.approx(0.2, rel=1e-5)
        assert float(fn(2.0, self.t(99))) == pytest.approx(0.02, rel=1e-5)

    @pytest.mark.parametrize("fused", [True, False])
    def test_policy_in_training(self, fused):
        """MNIST-FC with exp decay trains and differs from fixed-lr run."""
        from veles_tpu import prng
        from veles_tpu.config import root

        def run_once(policy):
            prng.reset()
            prng.seed_all(1)
            layers = [
                {"type": "all2all_tanh", "output_sample_shape": 32,
                 "learning_rate": 0.05, "momentum": 0.9},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.05, "momentum": 0.9},
            ]
            if policy:
                for layer in layers:
                    layer["lr_policy"] = policy
            root.mnist.update({
                "loader": {"minibatch_size": 50, "n_train": 300,
                           "n_valid": 100},
                "decision": {"max_epochs": 3, "fail_iterations": 10},
                "layers": layers,
            })
            from veles_tpu.samples import mnist
            wf = mnist.train(fused=fused)
            runner = getattr(wf, "_fused_runner", None)
            if runner is not None:
                runner.sync_to_units()
            return (wf.forwards[0].weights.to_numpy().copy(),
                    [m["validation"]["n_err"]
                     for m in wf.decision.epoch_metrics])

        w_fixed, errs_fixed = run_once(None)
        w_decay, errs_decay = run_once({"policy": "exp", "gamma": 0.99})
        assert errs_decay[-1] < errs_decay[0] * 1.2  # still trains
        assert not numpy.allclose(w_fixed, w_decay)  # decay took effect


class TestZeroFiller:
    @pytest.mark.parametrize("fused", [True, False])
    def test_mask_enforced_through_training(self, fused):
        from veles_tpu import prng
        from veles_tpu.config import root
        from veles_tpu.ops.weights_zerofilling import ZeroFiller
        prng.reset()
        prng.seed_all(1)
        root.mnist.update({
            "loader": {"minibatch_size": 50, "n_train": 200, "n_valid": 50},
            "decision": {"max_epochs": 2, "fail_iterations": 10},
            "layers": [
                {"type": "all2all_tanh", "output_sample_shape": 16,
                 "learning_rate": 0.05, "momentum": 0.9},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.05, "momentum": 0.9},
            ],
        })
        from veles_tpu.samples import mnist
        wf = mnist.build(fused=fused)
        mask = numpy.ones((784, 16), numpy.float32)
        mask[::2, :] = 0.0   # kill every other input row
        zf = ZeroFiller(wf, forward=wf.forwards[0], gd=wf.gds[0], mask=mask,
                        name="zerofiller")
        zf.link_from(wf.gds[0])
        wf.initialize()
        wf.run()
        runner = getattr(wf, "_fused_runner", None)
        if runner is not None:
            runner.sync_to_units()
        w = wf.forwards[0].weights.to_numpy()
        assert numpy.abs(w[::2, :]).max() == 0.0
        assert numpy.abs(w[1::2, :]).max() > 0.0


class TestResizableAll2All:
    def test_resize_preserves_overlap(self):
        from veles_tpu.workflow import Workflow
        from veles_tpu.memory import Vector
        from veles_tpu.ops.resizable_all2all import ResizableAll2All
        wf = Workflow(None, name="wf")
        unit = ResizableAll2All(wf, output_sample_shape=4, name="fc")
        unit.input = Vector(numpy.ones((2, 6), numpy.float32))
        unit.initialize()
        w_before = unit.weights.to_numpy().copy()
        unit.resize(6)
        unit.initialize()
        assert unit.weights.shape == (6, 6)
        numpy.testing.assert_allclose(unit.weights.to_numpy()[:, :4],
                                      w_before)
        unit.resize(3)
        assert unit.weights.shape == (6, 3)
        numpy.testing.assert_allclose(unit.weights.to_numpy(),
                                      w_before[:, :3])


class TestMeanDispNormalizer:
    def test_transform(self):
        from veles_tpu.workflow import Workflow
        from veles_tpu.memory import Vector
        from veles_tpu.ops.mean_disp_normalizer import MeanDispNormalizer
        wf = Workflow(None, name="wf")
        mean = numpy.array([1.0, 2.0], numpy.float32)
        rdisp = numpy.array([0.5, 0.25], numpy.float32)
        unit = MeanDispNormalizer(wf, mean=mean, rdisp=rdisp, name="norm")
        unit.input = Vector(numpy.array([[3.0, 6.0]], numpy.float32))
        unit.initialize()
        unit.run()
        numpy.testing.assert_allclose(unit.output.to_numpy(),
                                      [[1.0, 1.0]], atol=1e-6)


class TestImageSaver:
    def test_saves_mispredictions(self, tmp_path):
        from veles_tpu.workflow import Workflow
        from veles_tpu.memory import Vector
        from veles_tpu.ops.image_saver import ImageSaver
        from veles_tpu.loader.base import VALID
        wf = Workflow(None, name="wf")
        saver = ImageSaver(wf, directory=str(tmp_path / "imgs"),
                           name="image_saver")
        saver.input = Vector(numpy.zeros((4, 16), numpy.float32))
        probs = numpy.zeros((4, 3), numpy.float32)
        probs[:, 0] = 1.0                       # predicts class 0 always
        saver.output = Vector(probs)
        saver.labels = Vector(numpy.array([0, 1, 2, 0], numpy.int32))
        saver.indices = Vector(numpy.arange(4, dtype=numpy.int32))
        saver.minibatch_class = VALID
        saver.minibatch_size = 4
        saver.initialize()
        saver.run()
        files = sorted(p.name for p in (tmp_path / "imgs").iterdir())
        assert files == ["1_as_0_1.png", "2_as_0_2.png"]
