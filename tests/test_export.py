"""StableHLO export artifacts — the libVeles serving parity axis.

Ref: SURVEY §2.4 libVeles row, §3.4: a trained model must leave the
framework as a standalone artifact that serves without constructing the
training workflow.  Round-trips assert artifact output ≡ in-framework
forward, REST serving from an artifact, and forge packages carrying one.
"""

import json
import urllib.request

import numpy
import pytest

from veles_tpu.config import root


def _train_tiny_mnist():
    from veles_tpu import prng
    prng.reset()
    prng.seed_all(3)
    root.__dict__.pop("mnist", None)
    root.mnist.update({
        "loader": {"minibatch_size": 50, "n_train": 300, "n_valid": 100},
        "decision": {"max_epochs": 2, "fail_iterations": 5},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.03, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.03, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    return mnist.train(fused=True)


@pytest.fixture(scope="module")
def trained_and_artifact(tmp_path_factory):
    from veles_tpu import export
    wf = _train_tiny_mnist()
    path = str(tmp_path_factory.mktemp("export") / "mnist.veles")
    export.export_model(wf, path, metadata={"note": "test"})
    return wf, path


class TestExportRoundTrip:
    def test_artifact_matches_in_framework_forward(self,
                                                   trained_and_artifact):
        from veles_tpu import export
        wf, path = trained_and_artifact
        model = export.load_model(path)
        runner = wf._fused_runner
        x = numpy.asarray(wf.loader.original_data.mem[:17])
        expect = numpy.asarray(runner.eval_forward()(runner.state, x))
        got = model.predict(x)
        numpy.testing.assert_allclose(got, expect, rtol=1e-6, atol=1e-6)

    def test_symbolic_batch(self, trained_and_artifact):
        from veles_tpu import export
        _, path = trained_and_artifact
        model = export.load_model(path)
        for n in (1, 3, 64):
            out = model.predict(numpy.zeros((n, 784), numpy.float32))
            assert out.shape == (n, 10)

    def test_manifest_contents(self, trained_and_artifact):
        from veles_tpu import export
        _, path = trained_and_artifact
        model = export.load_model(path)
        m = model.manifest
        assert m["input_sample_shape"] == [784]
        assert m["output_sample_shape"] == [10]
        assert "tpu" in m["platforms"] and "cpu" in m["platforms"]
        assert m["metadata"]["note"] == "test"

    def test_no_velocities_shipped(self, trained_and_artifact):
        from veles_tpu import export
        _, path = trained_and_artifact
        model = export.load_model(path)
        assert all(k.split("/")[1] in ("w", "b")
                   for k in model.manifest["param_keys"])

    def test_int8_quantized_artifact(self, trained_and_artifact, tmp_path):
        """int8 export: smaller file, int8 weights + per-channel scales
        in the bundle, near-identical predictions (int8 is storage-only;
        load_model dequantizes once)."""
        import os
        from veles_tpu import export
        wf, fp32_path = trained_and_artifact
        q_path = str(tmp_path / "mnist_int8.veles")
        export.export_model(wf, q_path, quantize="int8")

        ref = export.load_model(fp32_path)
        qm = export.load_model(q_path)
        assert qm.manifest["quantize"] == "int8"
        # stamped format 2: pre-quantization loaders reject it cleanly
        assert qm.manifest["format"] == export.FORMAT_QUANTIZED
        # stored payload is int8 (+ per-channel scales); loaded params
        # are dequantized ONCE to f32 (no per-call dequant in the
        # program)
        import io as _io
        import tarfile as _tarfile
        with _tarfile.open(q_path, "r:gz") as tar:
            npz = numpy.load(_io.BytesIO(
                tar.extractfile(export.WEIGHTS).read()))
            assert npz["0/w"].dtype == numpy.int8
            assert npz["0/w.scale"].shape == (32,)
        widx = qm.manifest["param_keys"].index("0/w")
        assert qm._params[widx].dtype == numpy.float32

        rng = numpy.random.RandomState(5)
        x = rng.uniform(-1, 1, (200, 784)).astype(numpy.float32)
        a = ref.predict(x).argmax(axis=1)
        b = qm.predict(x).argmax(axis=1)
        assert (a == b).mean() >= 0.98, (a == b).mean()
        # 4x fewer weight bytes dominates the bundle for this model
        assert os.path.getsize(q_path) < 0.6 * os.path.getsize(fp32_path)

    def test_no_solver_accumulators_shipped(self, tmp_path):
        """adagrad/adadelta accumulators are optimizer state, not model
        parameters — the serving artifact must stay weights+biases only."""
        from veles_tpu import export, prng
        from veles_tpu.config import root
        prng.reset(); prng.seed_all(3)
        root.mnist.update({
            "loader": {"minibatch_size": 50, "n_train": 200, "n_valid": 100},
            "decision": {"max_epochs": 1, "fail_iterations": 50},
            "layers": [
                {"type": "all2all_tanh", "output_sample_shape": 16,
                 "<-": {"learning_rate": 0.5, "solver": "adagrad"}},
                {"type": "softmax", "output_sample_shape": 10,
                 "<-": {"learning_rate": 0.5, "solver": "adagrad"}},
            ],
        })
        from veles_tpu.samples import mnist
        wf = mnist.train(fused=True)
        path = str(tmp_path / "adagrad.veles")
        export.export_model(wf, path)
        model = export.load_model(path)
        assert all(k.split("/")[1] in ("w", "b")
                   for k in model.manifest["param_keys"])


class TestArtifactServing:
    def test_rest_serves_artifact_without_workflow(self,
                                                   trained_and_artifact):
        from veles_tpu.restful_api import serve_artifact
        wf, path = trained_and_artifact
        api = serve_artifact(path, port=0)
        try:
            x = numpy.asarray(wf.loader.original_data.mem[:5])
            req = urllib.request.Request(
                "http://127.0.0.1:%d/predict" % api.port,
                data=json.dumps({"input": x.tolist()}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as resp:
                payload = json.load(resp)
        finally:
            api.stop()
        assert len(payload["output"]) == 5
        runner = wf._fused_runner
        expect = numpy.asarray(
            runner.eval_forward()(runner.state, x)).argmax(1)
        assert payload["argmax"] == expect.tolist()


def _snapshot_of(wf, tmp_path):
    from veles_tpu.snapshotter import Snapshotter
    snapper = Snapshotter(wf, directory=str(tmp_path / "snaps"),
                          name="snap_%d" % id(wf))
    return snapper.export()


class TestForgeArtifact:
    def test_package_carries_and_serves_artifact(self, trained_and_artifact,
                                                 tmp_path):
        from veles_tpu import forge
        wf, artifact = trained_and_artifact
        snap = _snapshot_of(wf, tmp_path)
        pkg = str(tmp_path / "mnist.forge.tar.gz")
        forge.pack(snap, pkg, name="mnist-test", artifact_path=artifact,
                   metrics={"val_err": 1})
        manifest = forge.read_manifest(pkg)
        assert manifest["artifact"] == "mnist.veles"
        model = forge.load_artifact(pkg, out_dir=str(tmp_path / "unpacked"))
        out = model.predict(numpy.zeros((2, 784), numpy.float32))
        assert out.shape == (2, 10)

    def test_missing_artifact_raises(self, trained_and_artifact, tmp_path):
        from veles_tpu import forge
        wf, _ = trained_and_artifact
        snap = _snapshot_of(wf, tmp_path)
        pkg = str(tmp_path / "plain.forge.tar.gz")
        forge.pack(snap, pkg, name="plain")
        with pytest.raises(KeyError):
            forge.load_artifact(pkg, out_dir=str(tmp_path / "u2"))
