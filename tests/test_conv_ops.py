"""Tier-2 conv-stack op tests vs independent numpy oracles (SURVEY §4)."""

import numpy
import pytest

import jax

from veles_tpu.ops import functional as F

RTOL, ATOL = 5e-4, 1e-4


def np_conv2d(x, w, stride=(1, 1), padding=(0, 0)):
    """Direct-loop NHWC/HWIO convolution oracle."""
    b, h, ww, cin = x.shape
    kh, kw, _, cout = w.shape
    ph, pw = padding
    xp = numpy.pad(x, [(0, 0), (ph, ph), (pw, pw), (0, 0)])
    oh = (h + 2 * ph - kh) // stride[0] + 1
    ow = (ww + 2 * pw - kw) // stride[1] + 1
    out = numpy.zeros((b, oh, ow, cout), numpy.float32)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, i * stride[0]:i * stride[0] + kh,
                       j * stride[1]:j * stride[1] + kw, :]
            out[:, i, j, :] = numpy.tensordot(patch, w, axes=([1, 2, 3],
                                                              [0, 1, 2]))
    return out


def test_conv2d_valid_matches_oracle():
    rng = numpy.random.RandomState(1)
    x = rng.randn(2, 8, 9, 3).astype(numpy.float32)
    w = rng.randn(3, 3, 3, 5).astype(numpy.float32)
    b = rng.randn(5).astype(numpy.float32)
    got = numpy.asarray(F.conv2d_forward(x, w, b, (1, 1), "VALID"))
    want = np_conv2d(x, w) + b
    numpy.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)


def test_conv2d_int_padding_and_stride():
    rng = numpy.random.RandomState(2)
    x = rng.randn(2, 10, 10, 2).astype(numpy.float32)
    w = rng.randn(5, 5, 2, 4).astype(numpy.float32)
    got = numpy.asarray(F.conv2d_forward(x, w, None, (2, 2), 2))
    want = np_conv2d(x, w, (2, 2), (2, 2))
    numpy.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)


def test_conv2d_same_shape():
    rng = numpy.random.RandomState(3)
    x = rng.randn(1, 12, 12, 3).astype(numpy.float32)
    w = rng.randn(5, 5, 3, 7).astype(numpy.float32)
    y = F.conv2d_forward(x, w, None, (1, 1), "SAME")
    assert y.shape == (1, 12, 12, 7)


def test_conv_gradients_finite_differences():
    rng = numpy.random.RandomState(4)
    x = rng.randn(2, 6, 6, 2).astype(numpy.float32)
    w = rng.randn(3, 3, 2, 3).astype(numpy.float32) * 0.3
    b = rng.randn(3).astype(numpy.float32) * 0.1
    r = rng.randn(2, 4, 4, 3).astype(numpy.float32)

    def loss(x_, w_, b_):
        return float((numpy.asarray(
            F.conv2d_forward(x_, w_, b_, (1, 1), "VALID", "tanh")) * r).sum())

    _, vjp = jax.vjp(
        lambda x_, w_, b_: F.conv2d_forward(x_, w_, b_, (1, 1), "VALID",
                                            "tanh"), x, w, b)
    dx, dw, db = vjp(r)
    eps = 1e-3
    # spot-check a handful of coordinates of each gradient
    rs = numpy.random.RandomState(0)
    for arr, grad in ((x, dx), (w, dw), (b, db)):
        flat = arr.reshape(-1)
        gflat = numpy.asarray(grad).reshape(-1)
        for _ in range(5):
            i = rs.randint(flat.size)
            old = flat[i]
            flat[i] = old + eps
            up = loss(x, w, b)
            flat[i] = old - eps
            down = loss(x, w, b)
            flat[i] = old
            num = (up - down) / (2 * eps)
            assert abs(num - gflat[i]) < 5e-2 * max(1.0, abs(num)), \
                (num, gflat[i])


def _np_patches(x, window, stride, pad_value=0.0):
    """Ceil-covering patches oracle (pads right/bottom like the reference)."""
    b, h, w, c = x.shape
    kh, kw = window

    def ceil_out(size, k, s):
        return 1 if size <= k else -(-(size - k) // s) + 1

    oh, ow = ceil_out(h, kh, stride[0]), ceil_out(w, kw, stride[1])
    ph = (oh - 1) * stride[0] + kh - h
    pw = (ow - 1) * stride[1] + kw - w
    xp = numpy.pad(x, [(0, 0), (0, ph), (0, pw), (0, 0)],
                   constant_values=pad_value)
    out = numpy.zeros((b, oh, ow, kh * kw, c), x.dtype)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, i * stride[0]:i * stride[0] + kh,
                       j * stride[1]:j * stride[1] + kw, :]
            out[:, i, j] = patch.reshape(b, kh * kw, c)
    return out


@pytest.mark.parametrize("window,stride", [((2, 2), (2, 2)),
                                           ((3, 3), (2, 2))])
@pytest.mark.parametrize("size", [8, 7])   # 7 exercises ceil-pad tails
def test_pooling_oracles(window, stride, size):
    rng = numpy.random.RandomState(5)
    x = rng.randn(2, size, size, 3).astype(numpy.float32)
    patches_inf = _np_patches(x, window, stride,
                              numpy.finfo(numpy.float32).min / 2)
    patches_zero = _np_patches(x, window, stride, 0.0)
    numpy.testing.assert_allclose(
        numpy.asarray(F.max_pooling(x, window, stride)),
        patches_inf.max(axis=3), rtol=RTOL, atol=ATOL)
    numpy.testing.assert_allclose(
        numpy.asarray(F.avg_pooling(x, window, stride)),
        patches_zero.mean(axis=3), rtol=RTOL, atol=ATOL)
    idx = numpy.abs(patches_zero).argmax(axis=3)
    want = numpy.take_along_axis(patches_zero, idx[:, :, :, None, :],
                                 axis=3)[:, :, :, 0, :]
    numpy.testing.assert_allclose(
        numpy.asarray(F.maxabs_pooling(x, window, stride)), want,
        rtol=RTOL, atol=ATOL)


def test_pooling_ceil_covers_whole_input():
    """7x7 with 2x2/2 pooling -> 4x4 (reference ceil semantics), and the
    last row/col contributes to the gradient."""
    x = numpy.ones((1, 7, 7, 1), numpy.float32)
    y = F.max_pooling(x, (2, 2), (2, 2))
    assert y.shape == (1, 4, 4, 1)
    _, vjp = jax.vjp(lambda a: F.max_pooling(a, (2, 2), (2, 2)), x)
    (dx,) = vjp(numpy.ones((1, 4, 4, 1), numpy.float32))
    assert numpy.asarray(dx)[0, 6, 6, 0] != 0 or \
        numpy.asarray(dx)[0, 6, :, 0].sum() > 0


def test_max_pooling_backward_scatters_to_argmax():
    x = numpy.array([[[[1.0], [3.0]], [[2.0], [0.0]]]], numpy.float32)
    _, vjp = jax.vjp(lambda a: F.max_pooling(a, (2, 2), (2, 2)), x)
    (dx,) = vjp(numpy.ones((1, 1, 1, 1), numpy.float32))
    want = numpy.array([[[[0.0], [1.0]], [[0.0], [0.0]]]], numpy.float32)
    numpy.testing.assert_array_equal(numpy.asarray(dx), want)


def test_avg_pooling_backward_spreads_uniformly():
    x = numpy.ones((1, 2, 2, 1), numpy.float32)
    _, vjp = jax.vjp(lambda a: F.avg_pooling(a, (2, 2), (2, 2)), x)
    (dx,) = vjp(numpy.ones((1, 1, 1, 1), numpy.float32))
    numpy.testing.assert_allclose(numpy.asarray(dx),
                                  numpy.full((1, 2, 2, 1), 0.25))


def test_lrn_oracle():
    rng = numpy.random.RandomState(6)
    x = rng.randn(2, 4, 4, 8).astype(numpy.float32)
    alpha, beta, n, k = 1e-4, 0.75, 5, 2.0
    got = numpy.asarray(F.lrn_forward(x, alpha, beta, n, k))
    sq = x * x
    want = numpy.zeros_like(x)
    c = x.shape[-1]
    for j in range(c):
        lo, hi = max(0, j - n // 2), min(c, j + n // 2 + 1)
        denom = (k + alpha / n * sq[..., lo:hi].sum(-1)) ** beta
        want[..., j] = x[..., j] / denom
    numpy.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)


def test_dropout_semantics():
    rng = numpy.random.RandomState(7)
    x = rng.randn(64, 100).astype(numpy.float32) + 5.0
    key = jax.random.PRNGKey(0)
    # eval / rate 0: identity
    numpy.testing.assert_array_equal(
        numpy.asarray(F.dropout(x, key, 0.5, False)), x)
    numpy.testing.assert_array_equal(
        numpy.asarray(F.dropout(x, key, 0.0, True)), x)
    y = numpy.asarray(F.dropout(x, key, 0.5, True))
    kept = y != 0
    assert 0.35 < kept.mean() < 0.65          # ~half survive
    numpy.testing.assert_allclose(y[kept], (x * 2.0)[kept], rtol=1e-6)
    # same key -> identical mask (backward replay guarantee)
    y2 = numpy.asarray(F.dropout(x, key, 0.5, True))
    numpy.testing.assert_array_equal(y, y2)
    # vjp: gradient flows only through kept elements, scaled
    _, vjp = jax.vjp(lambda a: F.dropout(a, key, 0.5, True), x)
    (dx,) = vjp(numpy.ones_like(x))
    numpy.testing.assert_allclose(numpy.asarray(dx), kept * 2.0, rtol=1e-6)


def test_cutter_crop_and_backward_pad():
    from veles_tpu.ops.cutter import Cutter
    from veles_tpu.workflow import Workflow
    from veles_tpu.memory import Vector
    wf = Workflow(None, name="wf")
    cut = Cutter(wf, padding=(1, 2, 3, 1))   # left, top, right, bottom
    x = numpy.arange(2 * 8 * 9 * 1, dtype=numpy.float32).reshape(2, 8, 9, 1)
    cut.input = Vector(x)
    cut.initialize()
    cut.run()
    got = cut.output.mem
    numpy.testing.assert_array_equal(got, x[:, 2:7, 1:6, :])
    _, vjp = jax.vjp(cut.transform, x)
    (dx,) = vjp(numpy.ones_like(got))
    assert dx.sum() == got.size
    assert numpy.asarray(dx)[:, 0, :, :].sum() == 0   # cut rows got zeros
