"""Deconv / depooling op tests + MNIST-AE convergence (SURVEY §4 tiers 2-3).

Oracle pattern: numpy reference vs the jitted op (the role the reference's
numpy backend played — veles/znicz/tests/unit/ [M])."""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.ops import functional as F


def rng(seed=0):
    return numpy.random.RandomState(seed)


class TestDeconvFunctional:
    def test_upsamples_by_stride(self):
        x = rng().randn(2, 7, 7, 3).astype(numpy.float32)
        w = rng(1).randn(3, 3, 3, 5).astype(numpy.float32)
        y = F.deconv2d_forward(jnp.asarray(x), jnp.asarray(w), None,
                               stride=(2, 2), padding="SAME")
        assert y.shape == (2, 14, 14, 5)

    def test_adjoint_of_conv(self):
        """<conv(x), y> == <x, deconv(y)> — transposed conv IS the adjoint
        of conv with the same weights (stride 1, SAME)."""
        r = rng(2)
        x = r.randn(2, 8, 8, 3).astype(numpy.float32)
        w = r.randn(3, 3, 3, 4).astype(numpy.float32)
        y = r.randn(2, 8, 8, 4).astype(numpy.float32)
        conv_x = F.conv2d_forward(jnp.asarray(x), jnp.asarray(w), None,
                                  (1, 1), "SAME")
        # adjoint wrt x of conv is vjp; deconv with transposed kernel mirrors
        _, vjp = jax.vjp(
            lambda a: F.conv2d_forward(a, jnp.asarray(w), None, (1, 1),
                                       "SAME"), jnp.asarray(x))
        adj = vjp(jnp.asarray(y))[0]
        wt = jnp.flip(jnp.asarray(w), axis=(0, 1)).transpose(0, 1, 3, 2)
        dec = F.deconv2d_forward(jnp.asarray(y), wt, None, (1, 1), "SAME")
        lhs = float((conv_x * y).sum())
        rhs = float((jnp.asarray(x) * adj).sum())
        numpy.testing.assert_allclose(lhs, rhs, rtol=1e-4)
        numpy.testing.assert_allclose(numpy.asarray(adj), numpy.asarray(dec),
                                      rtol=1e-4, atol=1e-4)

    def test_int_padding_mirrors_conv(self):
        """deconv(k, s, p) must invert conv(k, s, p)'s spatial shape —
        the autoencoder mirror contract (explicit int padding)."""
        x = jnp.zeros((1, 28, 28, 3))
        w = jnp.zeros((5, 5, 3, 8))
        y = F.conv2d_forward(x, w, None, (2, 2), 2)
        assert y.shape == (1, 14, 14, 8)
        wt = jnp.zeros((5, 5, 8, 3))
        # (28 + 2*2 - 5) % 2 = 1 extra bottom/right pixel recovers 28 exactly
        back = F.deconv2d_forward(y, wt, None, (2, 2), 2, output_padding=1)
        assert back.shape == (1, 28, 28, 3)
        # without output_padding the transpose shape formula gives 27
        back = F.deconv2d_forward(y, wt, None, (2, 2), 2)
        assert back.shape == (1, 27, 27, 3)

    def test_numeric_gradient(self):
        r = rng(3)
        x = r.randn(1, 4, 4, 2).astype(numpy.float32)
        w = r.randn(3, 3, 2, 1).astype(numpy.float32)

        def loss(w_):
            y = F.deconv2d_forward(jnp.asarray(x), w_, None, (2, 2), "SAME")
            return (y * y).sum() * 0.5

        g = jax.grad(loss)(jnp.asarray(w))
        eps = 1e-3
        for idx in [(0, 0, 0, 0), (1, 2, 1, 0), (2, 2, 0, 0)]:
            wp, wm = w.copy(), w.copy()
            wp[idx] += eps
            wm[idx] -= eps
            num = (float(loss(jnp.asarray(wp))) -
                   float(loss(jnp.asarray(wm)))) / (2 * eps)
            numpy.testing.assert_allclose(float(g[idx]), num, rtol=2e-2,
                                          atol=1e-3)


class TestDepool:
    def test_nearest(self):
        x = numpy.arange(4, dtype=numpy.float32).reshape(1, 2, 2, 1)
        y = numpy.asarray(F.depool(jnp.asarray(x), (2, 2), "nearest"))
        expect = numpy.repeat(numpy.repeat(x, 2, 1), 2, 2)
        numpy.testing.assert_array_equal(y, expect)

    def test_zero(self):
        x = numpy.ones((1, 2, 2, 1), numpy.float32)
        y = numpy.asarray(F.depool(jnp.asarray(x), (2, 2), "zero"))
        assert y.shape == (1, 4, 4, 1)
        assert y.sum() == 4.0
        assert y[0, 0, 0, 0] == 1.0 and y[0, 1, 1, 0] == 0.0

    def test_nearest_vjp_is_window_sum(self):
        x = jnp.ones((1, 2, 2, 1))
        _, vjp = jax.vjp(lambda a: F.depool(a, (2, 2), "nearest"), x)
        g = vjp(jnp.ones((1, 4, 4, 1)))[0]
        numpy.testing.assert_array_equal(numpy.asarray(g),
                                         numpy.full((1, 2, 2, 1), 4.0))


class TestMnistAE:
    @pytest.mark.parametrize("fused", [True, False])
    def test_converges(self, fused):
        from veles_tpu import prng
        from veles_tpu.config import root
        prng.reset()
        prng.seed_all(1)
        root.mnist_ae.update({
            "loader": {"minibatch_size": 50, "n_train": 300, "n_valid": 100},
            "decision": {"max_epochs": 3, "fail_iterations": 10},
            "layers": [
                {"type": "conv_tanh", "n_kernels": 8, "kx": 5, "ky": 5,
                 "padding": "SAME", "learning_rate": 0.0005, "momentum": 0.9},
                {"type": "avg_pooling", "kx": 2, "ky": 2},
                {"type": "depooling", "kx": 2, "ky": 2},
                {"type": "deconv", "n_kernels": 1, "kx": 5, "ky": 5,
                 "padding": "SAME", "learning_rate": 0.0005, "momentum": 0.9},
            ],
        })
        from veles_tpu.samples import mnist_ae
        wf = mnist_ae.train(fused=fused)
        rmses = [m["validation"]["rmse"] for m in wf.decision.epoch_metrics
                 if "validation" in m]
        assert len(rmses) >= 3
        assert rmses[-1] < rmses[0], rmses
