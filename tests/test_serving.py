"""Serving subsystem (ISSUE 1): dynamic micro-batching, continuous LM
decode, admission control, metrics — the traffic layer over the jitted
forward/decode paths."""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

from load_gen import run_load  # noqa: E402


def _post(port, payload, timeout=30):
    req = urllib.request.Request(
        "http://127.0.0.1:%d/predict" % port,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


class TestMicroBatcher:
    def test_coalesces_and_preserves_rows(self):
        from veles_tpu.serving import MicroBatcher, ServingMetrics
        dispatched = []

        def forward(x):
            dispatched.append(len(x))
            time.sleep(0.004)      # a realistic dispatch the queue can
            return x * 2.0         # fill behind

        mb = MicroBatcher(forward, max_batch=8, batch_wait_s=0.01,
                          sample_shape=(4,),
                          metrics=ServingMetrics("mb_t1")).start()
        errors = []

        def client(ci):
            try:
                for j in range(5):
                    x = numpy.full((1, 4), ci * 10 + j, numpy.float32)
                    out = mb.submit(x)
                    assert out.shape == (1, 4)
                    numpy.testing.assert_array_equal(out, x * 2)
            except Exception as e:   # noqa: BLE001 — reported below
                errors.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        mb.stop()
        assert errors == []
        snap = mb.metrics.snapshot()
        assert snap["requests"] == 40
        # coalescing: measurably fewer dispatches than requests, mean
        # dispatch batch size above 1 (the acceptance criterion)
        assert snap["dispatches"] < snap["requests"]
        assert snap["batch_size"]["mean"] > 1
        # every dispatch was a power-of-two bucket (or max_batch)
        assert set(dispatched) <= {1, 2, 4, 8}

    def test_overload_rejects_instead_of_queueing(self):
        from veles_tpu.serving import MicroBatcher, Overloaded

        def slow_forward(x):
            time.sleep(0.05)
            return x

        mb = MicroBatcher(slow_forward, max_batch=2, queue_depth=2,
                          batch_wait_s=0.0, deadline_s=10.0,
                          sample_shape=(3,), name="mb_t2").start()
        outcomes = {"ok": 0, "over": 0}
        lock = threading.Lock()

        def client():
            try:
                mb.submit(numpy.zeros((1, 3), numpy.float32))
                with lock:
                    outcomes["ok"] += 1
            except Overloaded as e:
                assert e.retry_after > 0
                with lock:
                    outcomes["over"] += 1

        threads = [threading.Thread(target=client) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        mb.stop()
        assert outcomes["ok"] + outcomes["over"] == 16
        assert outcomes["over"] > 0                 # bounded, not hung
        assert mb.metrics.snapshot()["rejected"] == outcomes["over"]

    def test_deadline_sheds_stale_requests(self):
        from veles_tpu.serving import DeadlineExceeded, MicroBatcher

        def slow_forward(x):
            time.sleep(0.08)
            return x

        mb = MicroBatcher(slow_forward, max_batch=1, queue_depth=32,
                          batch_wait_s=0.0, deadline_s=0.02,
                          sample_shape=(2,), name="mb_t3").start()
        shed = []

        def client():
            try:
                mb.submit(numpy.zeros((1, 2), numpy.float32))
            except DeadlineExceeded:
                shed.append(1)

        threads = [threading.Thread(target=client) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        mb.stop()
        # the first request(s) dispatch; later ones aged out in queue
        assert shed
        assert mb.metrics.snapshot()["shed"] == len(shed)

    def test_oversized_request_chunks(self):
        from veles_tpu.serving import MicroBatcher
        mb = MicroBatcher(lambda x: x + 1, max_batch=4,
                          sample_shape=(2,), name="mb_t4").start()
        out = mb.submit(numpy.zeros((10, 2), numpy.float32))
        mb.stop()
        assert out.shape == (10, 2)
        assert (out == 1).all()

    def test_bucket_ladder(self):
        from veles_tpu.serving import batch_buckets, prompt_bucket
        assert batch_buckets(8) == [1, 2, 4, 8]
        assert batch_buckets(6) == [1, 2, 4, 6]
        assert batch_buckets(1) == [1]
        assert prompt_bucket(3, 64) == 16
        assert prompt_bucket(17, 64) == 32
        assert prompt_bucket(40, 48) == 48      # capped at the cache


class TestBatchedHTTP:
    def _api(self, forward, **knobs):
        from veles_tpu.restful_api import RESTfulAPI
        from veles_tpu.serving import ServingMetrics
        api = RESTfulAPI(None, forward=forward)
        api.enable_batching(metrics=ServingMetrics("http_t"), **knobs)
        return api.start(port=0)

    def test_threaded_load_correct_and_coalesced(self):
        """≥8 concurrent clients: every reply is row-correct, dispatches
        are measurably fewer than requests, mean batch size > 1 (the
        acceptance criterion), /metrics.json reports it all."""
        def forward(x):
            time.sleep(0.004)
            return x * 2.0

        api = self._api(forward, max_batch=8, batch_wait_s=0.01,
                        sample_shape=(4,))
        try:
            summary = run_load(
                "http://127.0.0.1:%d/predict" % api.port,
                payload=None, clients=8, requests_per_client=5,
                payload_fn=lambda ci, n: {
                    "input": [[float(ci * 10 + n)] * 4]})
            assert summary["ok"] == summary["sent"] == 40
            got = set()
            for r in summary["responses"]:
                # each reply is exactly 2× its own request's input row
                assert r["output"][0] == [r["output"][0][0]] * 4
                got.add(r["output"][0][0])
            assert got == {2.0 * (ci * 10 + n)
                           for ci in range(8) for n in range(5)}
            assert summary["latency_s"]["p99"] > 0
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/metrics.json" % api.port,
                    timeout=10) as resp:
                snap = json.loads(resp.read())
            assert snap["requests"] == 40
            assert snap["dispatches"] < snap["requests"]
            assert snap["batch_size"]["mean"] > 1
            assert snap["responses"] == 40
            assert snap["latency"]["p50"] > 0
        finally:
            api.stop()

    def test_overload_yields_429_with_retry_after(self):
        """A tiny queue under 16 concurrent clients sheds with HTTP 429
        (structured body, Retry-After) instead of hanging."""
        def slow_forward(x):
            time.sleep(0.05)
            return x

        api = self._api(slow_forward, max_batch=2, queue_depth=2,
                        batch_wait_s=0.0, deadline_s=10.0,
                        sample_shape=(3,))
        try:
            summary = run_load(
                "http://127.0.0.1:%d/predict" % api.port,
                payload={"input": [[0.0, 0.0, 0.0]]}, clients=16,
                requests_per_client=1, timeout=30)
            assert summary["sent"] == 16
            assert summary["by_status"].get("429", 0) > 0
            assert summary["ok"] + summary["by_status"]["429"] == 16
            rejected = [r for r in summary["responses"]
                        if r and "retry_after" in r]
            assert rejected and all(r["retry_after"] > 0
                                    for r in rejected)
        finally:
            api.stop()

    def test_malformed_request_fails_alone(self):
        """A wrong-shaped request gets its own 400 — it must never
        poison the coalesced batch it would have joined (other clients'
        replies stay correct)."""
        def forward(x):
            time.sleep(0.005)
            return x * 2.0

        api = self._api(forward, max_batch=8, batch_wait_s=0.02,
                        sample_shape=(4,))
        try:
            results = {"ok": [], "bad": []}
            lock = threading.Lock()

            def good(v):
                out = _post(api.port, {"input": [[v] * 4]})
                with lock:
                    results["ok"].append(out["output"][0][0] == 2 * v)

            def bad():
                try:
                    _post(api.port, {"input": [[1.0] * 5]})  # wrong width
                except urllib.error.HTTPError as e:
                    with lock:
                        results["bad"].append(
                            (e.code, json.loads(e.read())))

            threads = [threading.Thread(target=good, args=(float(i),))
                       for i in range(4)] + \
                      [threading.Thread(target=bad) for _ in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert results["ok"] == [True] * 4
            assert len(results["bad"]) == 2
            for code, body in results["bad"]:
                assert code == 400 and "sample shape" in body["error"]
        finally:
            api.stop()

    def test_retry_after_is_integer_seconds(self):
        """The Retry-After HEADER is RFC 9110 delta-seconds (integer);
        the exact float rides in the JSON body."""
        def slow_forward(x):
            time.sleep(0.05)
            return x

        api = self._api(slow_forward, max_batch=1, queue_depth=1,
                        batch_wait_s=0.0, sample_shape=(2,))
        try:
            headers = []

            def client():
                req = urllib.request.Request(
                    "http://127.0.0.1:%d/predict" % api.port,
                    data=json.dumps({"input": [[0.0, 0.0]]}).encode(),
                    headers={"Content-Type": "application/json"})
                try:
                    urllib.request.urlopen(req, timeout=30).read()
                except urllib.error.HTTPError as e:
                    if e.code == 429:
                        headers.append(e.headers.get("Retry-After"))
                    e.read()

            threads = [threading.Thread(target=client)
                       for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert headers                      # some 429s happened
            for h in headers:
                assert h is not None and h == str(int(h))   # integer
                assert int(h) >= 1
        finally:
            api.stop()

    def test_bad_first_request_does_not_poison_shape(self):
        """No-warmup server: the canonical sample shape is adopted only
        after a SUCCESSFUL dispatch, so a malformed first request fails
        alone (500 from the forward) and later valid traffic serves."""
        def forward(x):
            if x.shape[1] != 4:
                raise RuntimeError("bad width %d" % x.shape[1])
            return x * 2.0

        from veles_tpu.restful_api import RESTfulAPI
        from veles_tpu.serving import MicroBatcher, ServingMetrics
        api = RESTfulAPI(None, forward=forward)
        api.batcher = MicroBatcher(forward, max_batch=4,
                                   metrics=ServingMetrics("poison_t"))
        api.metrics = api.batcher.metrics
        api.start(port=0)
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(api.port, {"input": [[1.0] * 5]})     # bad FIRST
            assert err.value.code == 500
            out = _post(api.port, {"input": [[3.0] * 4]})   # still fine
            assert out["output"][0] == [6.0] * 4
            # shape adopted from the successful dispatch: mismatches
            # are now client errors, cheap and precise
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(api.port, {"input": [[1.0] * 5]})
            assert err.value.code == 400
            assert "sample shape" in json.loads(err.value.read())["error"]
        finally:
            api.stop()

    def test_malformed_content_length_is_400(self):
        import http.client
        api = self._api(lambda x: x, max_batch=2, sample_shape=(2,))
        try:
            conn = http.client.HTTPConnection("127.0.0.1", api.port,
                                              timeout=10)
            conn.putrequest("POST", "/predict")
            conn.putheader("Content-Length", "abc")
            conn.putheader("Content-Type", "application/json")
            conn.endheaders()
            resp = conn.getresponse()
            body = json.loads(resp.read())
            assert resp.status == 400 and "Content-Length" in body["error"]
            conn.close()
        finally:
            api.stop()

    def test_structured_errors(self):
        api = self._api(lambda x: x, max_batch=2, sample_shape=(2,))
        api.max_body = 200
        try:
            port = api.port

            def post_raw(body, path="/predict"):
                req = urllib.request.Request(
                    "http://127.0.0.1:%d%s" % (port, path), data=body,
                    headers={"Content-Type": "application/json"})
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(req, timeout=10)
                return err.value.code, json.loads(err.value.read())

            code, body = post_raw(b"this is not json")
            assert code == 400 and "error" in body
            code, body = post_raw(b"{}")                # no "input"
            assert code == 400 and "error" in body
            code, body = post_raw(b'{"input": [[0.0, 0.0]]}',
                                  path="/nope")
            assert code == 404 and "error" in body
            huge = json.dumps(
                {"input": [[0.0, 0.0]] * 100}).encode()
            assert len(huge) > api.max_body
            code, body = post_raw(huge)
            assert code == 413 and "error" in body
        finally:
            api.stop()


def _tiny_params(max_len=48, vocab=16, n_heads=2, n_layers=2):
    import jax
    import jax.numpy as jnp
    from veles_tpu import prng
    from veles_tpu.ops.transformer import init_transformer_params
    host = init_transformer_params(prng.get("init"), vocab, d_model=32,
                                   n_heads=n_heads, n_layers=n_layers,
                                   max_len=max_len)
    return jax.tree.map(jnp.asarray, host)


class TestLMEngine:
    def test_greedy_matches_generate(self):
        """Continuous batching is bit-identical to the sequential
        KV-cached ``generate`` for the same prompts (the acceptance
        criterion), including slot reuse when prompts outnumber
        slots."""
        import jax.numpy as jnp
        from veles_tpu.ops.transformer import generate
        from veles_tpu.serving import LMEngine
        params = _tiny_params()
        prompts = [[1, 2, 3], [2, 4, 6, 8, 10],
                   [5, 1, 5, 1, 5, 1, 5, 1, 5], [7, 7], [0, 3, 9, 12]]
        n_new = 6
        expected = [numpy.asarray(generate(
            params, jnp.asarray([p], jnp.int32), n_new, 2,
            temperature=0.0, max_len=48))[0] for p in prompts]
        engine = LMEngine(params, n_heads=2, max_len=48, slots=2,
                          name="lm_t1").start()
        try:
            # submitted together: 5 prompts share 2 slots mid-flight
            futures = [engine.submit(p, n_new) for p in prompts]
            for p, f, exp in zip(prompts, futures, expected):
                got = numpy.concatenate([p, f.result(timeout=60)])
                numpy.testing.assert_array_equal(got, exp)
            snap = engine.metrics.snapshot()
            assert snap["requests"] == 5
            assert snap["gauges"]["slots_total"] == 2
        finally:
            engine.stop()

    def test_batch_generate_and_occupancy(self):
        import jax.numpy as jnp
        from veles_tpu.ops.transformer import generate
        from veles_tpu.serving import LMEngine
        params = _tiny_params()
        prompts = numpy.asarray([[1, 2, 3, 4]] * 4, numpy.int32)
        expected = numpy.asarray(generate(
            params, jnp.asarray(prompts[:1], jnp.int32), 7, 2,
            temperature=0.0, max_len=48))[0]
        engine = LMEngine(params, n_heads=2, max_len=48, slots=4,
                          name="lm_t2").start()
        try:
            out = engine.generate(prompts, 7)
            assert out.shape == (4, 11)
            for row in out:
                numpy.testing.assert_array_equal(row, expected)
            # identical prompts decoding concurrently: the step
            # dispatches ran multiple lanes at once
            assert engine.metrics.snapshot()["batch_size"]["mean"] > 1
        finally:
            engine.stop()

    def test_batch_cancel_on_admission_failure(self):
        """generate() with more rows than the queue admits: rows already
        queued are withdrawn (no zombie decodes holding slots) and the
        caller sees the refusal."""
        from veles_tpu.serving import LMEngine, Overloaded
        params = _tiny_params()
        engine = LMEngine(params, n_heads=2, max_len=48, slots=1,
                          queue_depth=2, name="lm_t4").start()
        try:
            prompts = numpy.asarray([[1, 2, 3]] * 8, numpy.int32)
            with pytest.raises(Overloaded):
                engine.generate(prompts, 40)     # 8 rows >> 1 slot + 2 queue
            # the engine drains quickly: the withdrawn rows must not
            # decode their full 40 tokens each
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                snap = engine.metrics.snapshot()
                if snap["gauges"].get("slots_busy", 1) == 0 \
                        and snap["gauges"].get("queue_depth", 1) == 0:
                    break
                time.sleep(0.05)
            assert snap["gauges"]["queue_depth"] == 0
            # a fresh request still works after the cancelled batch
            out = engine.generate(prompts[:1], 4)
            assert out.shape == (1, 7)
        finally:
            engine.stop()

    def test_rejects_prompt_beyond_cache(self):
        from veles_tpu.serving import LMEngine
        params = _tiny_params(max_len=32)
        engine = LMEngine(params, n_heads=2, max_len=32, slots=1,
                          name="lm_t3").start()
        try:
            with pytest.raises(ValueError, match="exceeds the engine"):
                engine.submit(list(range(30)), 8)
        finally:
            engine.stop()

    def test_worker_survives_step_fault(self):
        """A decode-step fault fails the in-flight lanes to their
        clients and the engine keeps serving — it must never wedge
        with futures nobody will resolve."""
        import jax.numpy as jnp
        from veles_tpu.ops.transformer import generate
        from veles_tpu.serving import LMEngine
        params = _tiny_params()
        engine = LMEngine(params, n_heads=2, max_len=48, slots=2,
                          name="lm_t5").start()
        real_step = engine._step_jit
        calls = {"n": 0}

        def flaky_step(p, caches, last, pos):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected device fault")
            return real_step(p, caches, last, pos)

        engine._step_jit = flaky_step
        try:
            fut = engine.submit([1, 2, 3], 5)
            with pytest.raises(RuntimeError, match="injected"):
                fut.result(timeout=60)
            # the engine recovered: the next request decodes correctly
            out = engine.generate(numpy.asarray([[1, 2, 3]]), 5)
            expected = numpy.asarray(generate(
                params, jnp.asarray([[1, 2, 3]], jnp.int32), 5, 2,
                temperature=0.0, max_len=48))[0]
            numpy.testing.assert_array_equal(out[0], expected)
            assert engine.metrics.snapshot()["errors"] == 1
        finally:
            engine.stop()


class TestServeLMContinuous:
    def test_http_engine_matches_direct(self):
        """serve_lm(slots=2) over a (briefly) trained char_lm: engine
        replies are exactly the direct greedy continuation, n_new is
        honored exactly (no tier overshoot), and sampling requests
        still work (direct-path fallback)."""
        import jax.numpy as jnp
        from veles_tpu import prng
        from veles_tpu.config import root
        from veles_tpu.ops.transformer import generate
        from veles_tpu.restful_api import serve_lm
        prng.reset()
        prng.seed_all(5)
        root.__dict__.pop("char_lm", None)
        root.char_lm.update({
            "loader": {"minibatch_size": 32, "n_train": 64, "n_valid": 32,
                       "seq_len": 16, "vocab": 16},
            "trainer": {"vocab": 16, "d_model": 32, "n_heads": 2,
                        "n_layers": 1, "max_len": 32,
                        "learning_rate": 3e-3, "n_experts": 0,
                        "pipeline_stages": 0, "remat": False},
            "decision": {"max_epochs": 1, "fail_iterations": 10},
        })
        from veles_tpu.samples import char_lm
        wf = char_lm.train()
        trainer = wf.trainer
        params = trainer._to_portable(trainer.params)
        api = serve_lm(wf, port=0, max_new=8, slots=2)
        try:
            for p in ([1, 2, 3], [2, 4, 6, 8, 10]):
                out = _post(api.port, {"input": [p], "n_new": 5})
                row = out["tokens"][0]
                expected = numpy.asarray(generate(
                    params, jnp.asarray([p], jnp.int32), 5,
                    trainer.n_heads, temperature=0.0,
                    max_len=int(trainer.max_len)))[0]
                assert len(row) == len(p) + 5       # exact, no tier
                numpy.testing.assert_array_equal(row, expected)
            # sampling falls back to the direct path and still replies
            out = _post(api.port, {"input": [[1, 2, 3]], "n_new": 4,
                                   "temperature": 0.8, "seed": 3})
            row = out["tokens"][0]
            assert row[:3] == [1, 2, 3] and len(row) == 7
            assert all(0 <= t < 16 for t in row)
            # the engine's counters reached the serving port's metrics
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/metrics.json" % api.port,
                    timeout=10) as resp:
                snap = json.loads(resp.read())
            assert snap["requests"] >= 2
        finally:
            api.stop()


class TestMetrics:
    def test_snapshot_and_percentiles(self):
        from veles_tpu.serving import ServingMetrics
        m = ServingMetrics("snap_t")
        for i in range(100):
            m.record_enqueue()
            m.record_response(0.001 * (i + 1))
        m.record_dispatch(8, queue_waits=[0.002, 0.004])
        snap = m.snapshot()
        assert snap["requests"] == snap["responses"] == 100
        assert 0.045 < snap["latency"]["p50"] <= 0.06
        assert 0.09 < snap["latency"]["p99"] <= 0.1
        assert snap["batch_size"]["count"] == 1
        assert snap["queue_wait"]["count"] == 2

    def test_prometheus_rendering(self):
        from veles_tpu.serving import ServingMetrics
        m = ServingMetrics("prom_t")
        m.record_enqueue()
        m.record_dispatch(4, queue_waits=[0.003])
        m.set_gauge("slots_busy", 3)
        text = m.render_prometheus()
        assert 'veles_serving_requests_total{engine="prom_t"} 1' in text
        assert '# TYPE veles_serving_batch_size histogram' in text
        # cumulative buckets: a 4-row dispatch counts at le=4 and above
        assert 'veles_serving_batch_size_bucket{engine="prom_t",le="4"}'\
            ' 1' in text
        assert 'veles_serving_batch_size_bucket{engine="prom_t",le="2"}'\
            ' 0' in text
        assert 'veles_serving_batch_size_bucket{engine="prom_t",'\
            'le="+Inf"} 1' in text
        assert 'veles_serving_slots_busy{engine="prom_t"} 3' in text

    def test_multi_engine_render_single_type_line_per_family(self):
        """Two registered engines share ONE `# TYPE` line per family
        (strict Prometheus parsers reject duplicates)."""
        from veles_tpu.serving import metrics as metrics_mod
        a, b = metrics_mod.new("eng_a"), metrics_mod.new("eng_b")
        a.record_enqueue()
        b.record_enqueue()
        text = metrics_mod.render_prometheus()
        assert text.count(
            "# TYPE veles_serving_requests_total counter") == 1
        assert text.count("# TYPE veles_serving_batch_size histogram") \
            == 1
        assert 'veles_serving_requests_total{engine="eng_a"} 1' in text
        assert 'veles_serving_requests_total{engine="eng_b"} 1' in text

    def test_labeled_samples_share_family(self):
        """Satellite (ISSUE 8): the minimal {replica="i"} label path —
        labeled gauges/counters render into the SAME family as their
        unlabeled base name (one # TYPE line, strict-parser rule) and
        surface as name{...} keys in the snapshot."""
        from veles_tpu.serving import ServingMetrics
        m = ServingMetrics("lbl_t")
        m.set_gauge("queue_depth", 7)
        m.set_gauge("queue_depth", 3, labels={"replica": "0"})
        m.set_gauge("queue_depth", 4, labels={"replica": "1"})
        m.inc("routed_requests", 5, labels={"replica": "0"})
        text = m.render_prometheus()
        assert text.count("# TYPE veles_serving_queue_depth gauge") == 1
        assert 'veles_serving_queue_depth{engine="lbl_t"} 7' in text
        assert ('veles_serving_queue_depth{engine="lbl_t",'
                'replica="0"} 3') in text
        assert ('veles_serving_queue_depth{engine="lbl_t",'
                'replica="1"} 4') in text
        assert ('veles_serving_routed_requests_total{engine="lbl_t",'
                'replica="0"} 5') in text
        snap = m.snapshot()
        assert snap["gauges"]["queue_depth"] == 7
        assert snap["gauges"]['queue_depth{replica="0"}'] == 3
        assert snap["counters"]['routed_requests{replica="0"}'] == 5
        assert m.counter("routed_requests", labels={"replica": "0"}) \
            == 5

    def test_replica_instances_coexist_in_registry(self):
        """Replica engines share a family NAME and differ by instance
        labels: the registry keeps one row per (name, labels), and the
        merged render carries one # TYPE with one sample per
        replica."""
        from veles_tpu.serving import metrics as metrics_mod
        r0 = metrics_mod.new("repl_t", labels={"replica": "0"})
        r1 = metrics_mod.new("repl_t", labels={"replica": "1"})
        assert r0 is not r1
        r0.record_enqueue()
        r1.record_enqueue()
        r1.record_enqueue()
        text = metrics_mod.render_prometheus()
        assert text.count(
            "# TYPE veles_serving_requests_total counter") == 1
        assert ('veles_serving_requests_total{engine="repl_t",'
                'replica="0"} 1') in text
        assert ('veles_serving_requests_total{engine="repl_t",'
                'replica="1"} 2') in text
        # restart-with-same-labels still replaces its own row only
        r0b = metrics_mod.new("repl_t", labels={"replica": "0"})
        assert r0b is not r0
        text = metrics_mod.render_prometheus()
        assert ('veles_serving_requests_total{engine="repl_t",'
                'replica="0"} 0') in text
        assert ('veles_serving_requests_total{engine="repl_t",'
                'replica="1"} 2') in text

    def test_ewma_tracks_latency_facts(self):
        """The router's placement signal: TTFT / decode-step EWMAs
        update on record and read back cheaply."""
        from veles_tpu.serving import ServingMetrics
        m = ServingMetrics("ewma_t")
        assert m.ewma("decode_step") == 0.0
        m.record_decode_step(0.1)
        assert m.ewma("decode_step") == pytest.approx(0.1)
        for _ in range(40):
            m.record_decode_step(0.2)
        assert 0.19 < m.ewma("decode_step") <= 0.2
        m.record_ttft(0.05)
        assert m.snapshot()["ewma"]["ttft"] == pytest.approx(0.05)

    def test_new_replaces_registered_row(self):
        """Engine restarts begin at zero — `new` replaces the row."""
        from veles_tpu.serving import metrics as metrics_mod
        m1 = metrics_mod.new("fresh_t")
        m1.record_enqueue()
        m2 = metrics_mod.new("fresh_t")
        assert m2 is not m1
        assert metrics_mod.get("fresh_t") is m2
        assert m2.snapshot()["requests"] == 0

    def test_concurrent_writers_snapshot_and_render(self):
        """ISSUE 12 satellite: threads hammering inc/observe/set_gauge
        (labeled and not) while another thread snapshots and renders —
        no exceptions, counters monotone across successive snapshots,
        histogram _bucket/_sum/_count families intact with ONE # TYPE
        line each, and the final totals exact."""
        from veles_tpu.serving import ServingMetrics
        from veles_tpu.serving.metrics import render_instances
        m = ServingMetrics("conc_t")
        writers, per_writer = 4, 400
        errors = []

        def hammer(wid):
            try:
                for i in range(per_writer):
                    m.record_enqueue()
                    m.record_response(0.001 * (i % 7 + 1))
                    m.record_decode_step(0.002)
                    m.inc("tokens_out", 2)
                    m.inc("routed_requests",
                          labels={"replica": str(wid % 2)})
                    m.set_gauge("queue_depth", i)
                    m.set_gauge("queue_depth", i,
                                labels={"replica": str(wid % 2)})
                    m.set_gauge_max("queue_depth_peak", i)
            except Exception as e:   # noqa: BLE001 — the assertion
                errors.append(e)

        threads = [threading.Thread(target=hammer, args=(w,))
                   for w in range(writers)]
        for t in threads:
            t.start()
        prev_requests = prev_latency = -1
        try:
            while any(t.is_alive() for t in threads):
                snap = m.snapshot()
                text = render_instances([m])
                # counters never go backwards mid-storm
                assert snap["requests"] >= prev_requests
                assert snap["latency"]["count"] >= prev_latency
                prev_requests = snap["requests"]
                prev_latency = snap["latency"]["count"]
                # families are never torn: one # TYPE per family, and
                # the histogram triplet is complete in every render
                assert text.count(
                    "# TYPE veles_serving_latency histogram") == 1
                assert "veles_serving_latency_sum" in text
                assert "veles_serving_latency_count" in text
                assert 'le="+Inf"' in text
        finally:
            for t in threads:
                t.join(timeout=30)
        assert not errors, errors
        snap = m.snapshot()
        total = writers * per_writer
        assert snap["requests"] == total
        assert snap["latency"]["count"] == total
        assert snap["counters"]["tokens_out"] == 2 * total
        assert (snap["counters"]['routed_requests{replica="0"}']
                + snap["counters"]['routed_requests{replica="1"}']
                == total)
        assert snap["gauges"]["queue_depth_peak"] == per_writer - 1
        # the cumulative bucket counts sum to the observation count
        text = m.render_prometheus()
        inf_line = next(
            line for line in text.splitlines()
            if line.startswith("veles_serving_latency_bucket")
            and 'le="+Inf"' in line)
        assert inf_line.endswith(" %d" % total)

    def test_web_status_metrics_endpoint(self):
        """GET /metrics on the dashboard: registered serving engines +
        workflow rows as gauges, one scrape surface."""
        from veles_tpu.serving import metrics as metrics_mod
        from veles_tpu.web_status import WebStatus
        m = metrics_mod.get("ws_t")
        m.record_enqueue()
        m.record_dispatch(2, queue_waits=[0.001])
        status = WebStatus().start(port=0)
        try:
            status.update("wf1", workflow="wf1", process=0, epoch=3,
                          best=0.5, complete=True)
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/metrics" % status.port,
                    timeout=10) as resp:
                assert resp.headers["Content-Type"].startswith(
                    "text/plain")
                text = resp.read().decode()
            assert 'veles_serving_requests_total{engine="ws_t"} 1' \
                in text
            assert 'veles_serving_queue_wait_bucket{engine="ws_t"' \
                in text
            assert 'veles_workflow_epoch{workflow="wf1",process="0"} 3' \
                in text
            assert 'veles_workflow_best_metric{workflow="wf1"' in text
            assert 'veles_workflow_complete{workflow="wf1"' \
                ',process="0"} 1' in text
        finally:
            status.stop()


class TestTinyModelSmoke:
    def test_two_clients_against_trained_workflow(self):
        """Tier-1 smoke (satellite): a real (tiny) trained workflow
        behind the batched endpoint, 2 concurrent clients, replies
        match the direct path."""
        from veles_tpu import prng
        from veles_tpu.config import root
        from veles_tpu.restful_api import RESTfulAPI
        from veles_tpu.serving import ServingMetrics
        prng.reset()
        prng.seed_all(2)
        root.mnist.update({
            "loader": {"minibatch_size": 50, "n_train": 200,
                       "n_valid": 100},
            "decision": {"max_epochs": 1, "fail_iterations": 5},
            "layers": [
                {"type": "all2all_tanh", "output_sample_shape": 16,
                 "learning_rate": 0.03, "momentum": 0.9},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.03, "momentum": 0.9},
            ],
        })
        from veles_tpu.samples import mnist
        wf = mnist.train()
        api = RESTfulAPI(wf)
        direct = api.predict(numpy.zeros((1, 784), numpy.float32))
        api.enable_batching(max_batch=4, batch_wait_s=0.005,
                            metrics=ServingMetrics("mnist_t"))
        api.start(port=0)
        try:
            summary = run_load(
                "http://127.0.0.1:%d/predict" % api.port,
                payload={"input": numpy.zeros(
                    (1, 784), numpy.float32).tolist()},
                clients=2, requests_per_client=3)
            assert summary["ok"] == summary["sent"] == 6
            for r in summary["responses"]:
                numpy.testing.assert_allclose(r["output"],
                                              direct["output"],
                                              atol=1e-5)
        finally:
            api.stop()


class TestRouter:
    """ISSUE 8: data-parallel engine replicas behind the metrics-driven
    router — the degenerate single-replica path, balance, sick-replica
    draining, and unchanged admission semantics."""

    def _expected(self, params, prompts, n_new, max_len=48):
        import jax.numpy as jnp
        from veles_tpu.ops.transformer import generate
        return [numpy.asarray(generate(
            params, jnp.asarray([p], jnp.int32), n_new, 2,
            temperature=0.0, max_len=max_len))[0] for p in prompts]

    def _replicas(self, params, n, serving_mesh=None, **kw):
        import jax
        from veles_tpu.serving import LMEngine, ServingMetrics
        devs = jax.devices()
        return [LMEngine(params, n_heads=2, max_len=48,
                         devices=[devs[i % len(devs)]],
                         name="rt_r%d" % i,
                         metrics=ServingMetrics(
                             "rt", labels={"replica": str(i)}), **kw)
                for i in range(n)]

    def test_single_replica_degenerates_bit_identical(self):
        """Router([one engine]) IS today's path: same tokens, same
        Overloaded admission refusal — no behavioral tax for the
        degenerate fleet."""
        from veles_tpu.serving import LMEngine, Overloaded, Router
        params = _tiny_params()
        prompts = [[1, 2, 3], [2, 4, 6, 8, 10], [7, 7]]
        expected = self._expected(params, prompts, 6)
        engine = LMEngine(params, n_heads=2, max_len=48, slots=1,
                          queue_depth=4, name="rt_one")
        router = Router([engine]).start()
        try:
            futures = [router.submit(p, 6) for p in prompts]
            for p, f, exp in zip(prompts, futures, expected):
                got = numpy.concatenate([p, f.result(timeout=60)])
                numpy.testing.assert_array_equal(got, exp)
            # admission refusal surfaces exactly like the bare engine
            real_step = engine._step_jit

            def slow_step(*a):
                time.sleep(0.05)
                return real_step(*a)

            engine._step_jit = slow_step
            try:
                with pytest.raises(Overloaded):
                    for _ in range(12):
                        router.submit([1, 2, 3], 4)
            finally:
                engine._step_jit = real_step
        finally:
            router.stop()

    def test_idle_fleet_spreads_evenly(self, serving_mesh):
        """Cold traffic on an idle 2-replica fleet places by
        fewest-routed tiebreak: the split is even, not replica-0
        pile-up."""
        serving_mesh(2)
        from veles_tpu.serving import Router
        params = _tiny_params()
        replicas = self._replicas(params, 2, slots=2)
        router = Router(replicas).start()
        try:
            prompts = [[1 + i % 5, 2, 3] for i in range(8)]
            expected = self._expected(params, prompts, 4)
            futures = [router.submit(p, 4) for p in prompts]
            for p, f, exp in zip(prompts, futures, expected):
                got = numpy.concatenate([p, f.result(timeout=60)])
                numpy.testing.assert_array_equal(got, exp)
            counts = router.routed_counts()
            assert sum(counts) == 8
            assert max(counts) - min(counts) <= 2, counts
            snap = router.metrics.snapshot()
            assert snap["counters"]['routed_requests{replica="0"}'] \
                + snap["counters"]['routed_requests{replica="1"}'] == 8
        finally:
            router.stop()

    def test_sick_replica_drain_requeues_without_loss(self,
                                                     serving_mesh):
        """Hot-unregister mid-flight: everything pending on the sick
        replica re-places and completes whole and exactly greedy (no
        loss, no duplicate, no partial results), and the drained
        replica receives no new work."""
        serving_mesh(2)
        from veles_tpu.serving import Router
        params = _tiny_params()
        replicas = self._replicas(params, 2, slots=2)
        router = Router(replicas).start()
        real_step = replicas[0]._step_jit

        def slow_step(*a):
            time.sleep(0.05)
            return real_step(*a)

        replicas[0]._step_jit = slow_step
        try:
            prompts = [[1 + i % 7, 3, 5] for i in range(8)]
            expected = self._expected(params, prompts, 6)
            futures = [router.submit(p, 6) for p in prompts]
            time.sleep(0.12)          # replica 0 is mid-decode now
            moved = router.unregister(0, reason="test drain")
            for p, f, exp in zip(prompts, futures, expected):
                out = f.result(timeout=120)
                assert len(out) == 6          # whole, never partial
                numpy.testing.assert_array_equal(
                    numpy.concatenate([p, out]), exp)
            snap = router.metrics.snapshot()
            if moved:
                assert snap["counters"]["requeued_requests"] >= moved
            assert snap["gauges"]["replicas_live"] == 1
            # post-drain placement avoids the sick replica
            f = router.submit(prompts[0], 4)
            assert f.job.replica == 1
            assert len(f.result(timeout=60)) == 4
        finally:
            replicas[0]._step_jit = real_step
            router.stop()

    def test_admission_and_shed_semantics_unchanged(self, serving_mesh):
        """Behind the router, 429 (every live replica's queue full)
        and 503 (deadline shed inside an engine) look exactly like the
        single-engine contract."""
        serving_mesh(2)
        from veles_tpu.serving import (DeadlineExceeded, Overloaded,
                                       Router)
        params = _tiny_params()
        replicas = self._replicas(params, 2, slots=1, queue_depth=2,
                                  deadline_s=0.2)
        router = Router(replicas).start()
        reals = [e._step_jit for e in replicas]

        def make_slow(real):
            def slow_step(*a):
                time.sleep(0.1)
                return real(*a)
            return slow_step

        for e, real in zip(replicas, reals):
            e._step_jit = make_slow(real)
        try:
            futures, rejected = [], 0
            for k in range(12):
                try:
                    futures.append(router.submit([1, 2, 3], 12))
                except Overloaded:
                    rejected += 1
                if k == 3:
                    # let the workers pop the heads into their slots so
                    # the NEXT submits sit queued behind a busy lane
                    # (slots=1, 12 slow steps ≈ 1.2s >> the 0.2s
                    # deadline → those queued requests must shed)
                    time.sleep(0.05)
            assert rejected > 0            # 429 once the fleet is full
            shed = done = 0
            for f in futures:
                try:
                    f.result(timeout=120)
                    done += 1
                except DeadlineExceeded:   # 503 passes through
                    shed += 1
            assert done + shed == len(futures)
            assert shed > 0
        finally:
            for e, real in zip(replicas, reals):
                e._step_jit = real
            router.stop()

    def test_round_robin_policy(self, serving_mesh):
        serving_mesh(2)
        from veles_tpu.serving import Router
        params = _tiny_params()
        replicas = self._replicas(params, 2, slots=2)
        router = Router(replicas, policy="round_robin").start()
        try:
            futures = [router.submit([1, 2, 3], 3) for _ in range(6)]
            for f in futures:
                assert len(f.result(timeout=60)) == 3
            counts = router.routed_counts()
            assert counts == [3, 3], counts
        finally:
            router.stop()

    def test_router_validation(self):
        from veles_tpu.serving import Router
        with pytest.raises(ValueError, match="at least one"):
            Router([])
        from veles_tpu.serving import LMEngine
        params = _tiny_params()
        engine = LMEngine(params, n_heads=2, max_len=48, slots=1,
                          name="rt_v")
        with pytest.raises(ValueError, match="policy"):
            Router([engine], policy="fastest")


class TestFaultPlan:
    """ISSUE 10: the deterministic fault-injection layer — pure host
    logic, no engines."""

    def test_deterministic_call_sites(self):
        from veles_tpu.serving import FaultPlan, InjectedFault
        plan = FaultPlan().arm("engine.step", calls={2, 4})
        fired = []
        for _ in range(5):
            try:
                plan.fire("engine.step")
                fired.append(False)
            except InjectedFault:
                fired.append(True)
        assert fired == [False, True, False, True, False]
        assert plan.calls("engine.step") == 5
        assert plan.fired("engine.step") == 2

    def test_every_after_times_conditions(self):
        from veles_tpu.serving import FaultPlan, InjectedFault
        plan = FaultPlan().arm("s", every=3, after=3, times=2)
        hits = []
        for n in range(1, 13):
            try:
                plan.fire("s")
            except InjectedFault:
                hits.append(n)
        assert hits == [6, 9]          # every 3rd AND after 3, twice

    def test_seeded_prob_is_reproducible(self):
        from veles_tpu.serving import FaultPlan, InjectedFault

        def run(seed):
            plan = FaultPlan(seed=seed).arm("s", prob=0.5)
            out = []
            for _ in range(32):
                try:
                    plan.fire("s")
                    out.append(0)
                except InjectedFault:
                    out.append(1)
            return out

        assert run(7) == run(7)
        assert run(7) != run(8)        # astronomically unlikely equal

    def test_disarm_and_named_exceptions(self):
        from veles_tpu.serving import FaultPlan, Overloaded
        plan = FaultPlan().arm("s", exc="Overloaded")
        with pytest.raises(Overloaded):
            plan.fire("s")
        plan.disarm("s")
        plan.fire("s")                 # no-op again
        with pytest.raises(ValueError, match="unknown fault"):
            FaultPlan().arm("s", exc="NoSuchError")
        with pytest.raises(ValueError, match="kind"):
            FaultPlan().arm("s", kind="explode")

    def test_json_spec(self):
        from veles_tpu.serving import FaultPlan, InjectedHTTPError
        plan = FaultPlan.from_spec({"seed": 3, "sites": [
            {"site": "http.request", "kind": "error", "exc": "http_503",
             "calls": [1]}]})
        with pytest.raises(InjectedHTTPError) as err:
            plan.fire("http.request")
        assert err.value.code == 503
        plan.fire("http.request")      # call 2: unarmed

    def test_freeze_releases(self):
        from veles_tpu.serving import FaultPlan
        plan = FaultPlan().arm("s", kind="freeze", duration_s=60.0)
        t = threading.Thread(target=plan.fire, args=("s",))
        t.start()
        time.sleep(0.05)
        assert t.is_alive()            # frozen
        plan.release()
        t.join(timeout=10)
        assert not t.is_alive()
        plan.fire("s")                 # released plans never freeze

    def test_batcher_dispatch_site_wired_through_enable_batching(self):
        """The batcher.* sites arm through RESTfulAPI(faults=) →
        enable_batching: an injected dispatch fault fails its batch's
        clients (500) through the real fault-isolation path, and the
        worker keeps serving."""
        from veles_tpu.restful_api import RESTfulAPI
        from veles_tpu.serving import FaultPlan, ServingMetrics
        plan = FaultPlan().arm("batcher.dispatch", calls={1})
        api = RESTfulAPI(None, forward=lambda x: x * 2.0, faults=plan)
        api.enable_batching(max_batch=4, sample_shape=(2,),
                            metrics=ServingMetrics("bf_t"))
        api.start(port=0)
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(api.port, {"input": [[1.0, 2.0]]})
            assert err.value.code == 500
            assert "injected" in json.loads(err.value.read())["error"]
            out = _post(api.port, {"input": [[3.0, 4.0]]})
            assert out["output"][0] == [6.0, 8.0]   # worker survived
            assert plan.fired("batcher.dispatch") == 1
        finally:
            api.stop()


class TestResilience:
    """ISSUE 10: retry/backoff, hedging, health circuit breaker — the
    router-level resilience layer over injected faults."""

    def _expected(self, params, prompts, n_new, max_len=48):
        import jax.numpy as jnp
        from veles_tpu.ops.transformer import generate
        return [numpy.asarray(generate(
            params, jnp.asarray([p], jnp.int32), n_new, 2,
            temperature=0.0, max_len=max_len))[0] for p in prompts]

    def _replicas(self, params, plans, **kw):
        import jax
        from veles_tpu.serving import LMEngine, ServingMetrics
        devs = jax.devices()
        return [LMEngine(params, n_heads=2, max_len=48,
                         devices=[devs[i % len(devs)]],
                         name="rs_r%d" % i, faults=plan,
                         metrics=ServingMetrics(
                             "rs", labels={"replica": str(i)}), **kw)
                for i, plan in enumerate(plans)]

    def test_retry_replaces_faulted_request_on_other_replica(self):
        """An engine FAULT on a live replica re-places the request
        whole on the other replica (requests_retried metered), and
        the delivered tokens are exactly greedy — idempotent because
        replicas are bit-identical."""
        from veles_tpu.serving import FaultPlan, Router
        params = _tiny_params()
        plan = FaultPlan().arm("engine.step", times=20)
        replicas = self._replicas(params, [plan, None], slots=2)
        router = Router(replicas, retries=2,
                        retry_backoff_s=0.01).start()
        try:
            [exp] = self._expected(params, [[1, 2, 3]], 6)
            fut = router.submit([1, 2, 3], 6)
            out = fut.result(timeout=60)
            numpy.testing.assert_array_equal(
                numpy.concatenate([[1, 2, 3], out]), exp)
            assert fut.job.replica == 1          # served by the healthy one
            retried = router.metrics.counter("requests_retried")
            assert retried >= 1
            # budget exhaustion on the SAME fleet: with BOTH replicas
            # now faulting, retries run out and the client sees the
            # injected fault — bounded, never an infinite retry loop
            from veles_tpu.serving import InjectedFault
            replicas[1]._faults = FaultPlan().arm("engine.step",
                                                  times=100)
            fut = router.submit([1, 2, 3], 6)
            with pytest.raises(InjectedFault):
                fut.result(timeout=60)
            assert router.metrics.counter("requests_retried") \
                == retried + 2
        finally:
            router.stop()

    def test_hedge_wins_on_slow_replica(self):
        """A request stuck on the injected-latency replica hedges onto
        the fast one past the threshold; the hedge wins, output stays
        exactly greedy, and the loser is cancelled (not delivered)."""
        from veles_tpu.serving import FaultPlan, Router
        params = _tiny_params()
        plan = FaultPlan().arm("engine.step", kind="latency",
                               latency_s=0.2)
        replicas = self._replicas(params, [plan, None], slots=2)
        router = Router(replicas, hedge_after_s=0.15).start()
        try:
            prompts = [[1, 2, 3], [2, 4, 6]]
            expected = self._expected(params, prompts, 6)
            futures = [router.submit(p, 6) for p in prompts]
            for p, f, exp in zip(prompts, futures, expected):
                out = f.result(timeout=60)
                numpy.testing.assert_array_equal(
                    numpy.concatenate([p, out]), exp)
            m = router.metrics
            assert m.counter("requests_hedged") >= 1
            assert m.counter("hedge_wins") >= 1
        finally:
            router.stop()

    def test_health_checker_quarantines_and_recovers(self):
        """The full circuit-breaker cycle, driven synchronously: a
        frozen replica is quarantined through the drain path (its
        pending work completes on the survivor), and after the
        cooldown the half-open probe re-registers it."""
        from veles_tpu.serving import (FaultPlan, HealthChecker,
                                       Router)
        params = _tiny_params()
        plan = FaultPlan().arm("engine.tick", kind="freeze", after=2,
                               times=1, duration_s=60.0)
        replicas = self._replicas(params, [plan, None], slots=2)
        router = Router(replicas, drain_timeout_s=0.3).start()
        checker = HealthChecker(router, interval_s=0.05,
                                probe_timeout_s=2.0, fail_threshold=2,
                                cooldown_s=0.2, stall_s=0.25)
        try:
            futures = [router.submit([1 + i, 2, 3], 6)
                       for i in range(6)]
            deadline = time.monotonic() + 30
            while router._live[0] and time.monotonic() < deadline:
                checker.step()
                time.sleep(0.05)
            assert not router._live[0]            # quarantined
            assert checker.states()[0] == HealthChecker.OPEN
            assert router.metrics.counter("circuit_open_total") == 1
            for f in futures:                     # no loss, no wedge
                assert len(f.result(timeout=60)) == 6
            # thaw; after the cooldown the half-open probe re-admits
            plan.release()
            time.sleep(0.25)
            deadline = time.monotonic() + 30
            while not router._live[0] \
                    and time.monotonic() < deadline:
                checker.step()
                time.sleep(0.05)
            assert router._live[0]
            assert checker.states()[0] == HealthChecker.HEALTHY
            snap = router.metrics.snapshot()
            assert snap["gauges"][
                'replica_health_state{replica="0"}'] == 0
            # the recovered replica serves again
            out = router.submit([1, 2, 3], 4).result(timeout=60)
            assert len(out) == 4
        finally:
            plan.release()
            checker.stop()
            router.stop()

    def test_probe_warm_absorbs_first_compile(self):
        """Satellite (ISSUE 11): warm_probes() runs each replica's
        first synthetic probe with a generous budget BEFORE monitoring
        starts, so a slow first-compile of the probe's prompt bucket
        (the foot-gun the HealthChecker docstring warns about) can
        never count as a failed probe and walk an innocent replica
        toward quarantine."""
        from veles_tpu.serving import HealthChecker, LMEngine, Router
        params = _tiny_params()
        engine = LMEngine(params, n_heads=2, max_len=48, slots=1,
                          name="warm_r0").start()
        # emulate a slow first probe-bucket compile: the FIRST prefill
        # dispatch after start stalls well past the probe timeout
        real = engine._prefill_jit
        state = {"first": True}

        def slow_first(*a):
            if state["first"]:
                state["first"] = False
                time.sleep(0.6)
            return real(*a)

        engine._prefill_jit = slow_first
        router = Router([engine])
        checker = HealthChecker(router, interval_s=0.05,
                                probe_timeout_s=0.25,
                                fail_threshold=1, stall_s=5.0)
        try:
            checker.warm_probes()      # absorbs the 0.6s "compile"
            for _ in range(3):
                checker.step()
            assert checker.states() == [HealthChecker.HEALTHY]
            assert router.metrics.counter("health_probe_failures") == 0
            assert router._live[0]
        finally:
            router.stop()

    def test_429_retry_after_is_minimum_over_replicas(self):
        """Satellite: when every replica refuses, the surfaced
        Retry-After is the MINIMUM over the refusing replicas — the
        client may return as soon as the soonest one frees."""
        from veles_tpu.serving import LMEngine, Overloaded, Router
        params = _tiny_params()
        engines = [LMEngine(params, n_heads=2, max_len=48, slots=1,
                            name="ra_r%d" % i) for i in range(2)]

        def refuse(ra):
            def submit(prompt, n_new):
                raise Overloaded(retry_after=ra)
            return submit

        engines[0].submit = refuse(0.7)
        engines[1].submit = refuse(0.3)
        router = Router(engines)
        with pytest.raises(Overloaded) as err:
            router.submit([1, 2, 3], 4)
        assert err.value.retry_after == pytest.approx(0.3)

    def test_no_live_replicas_is_retryable_429(self):
        """A fully-quarantined fleet is a TRANSIENT condition: submit
        surfaces the Overloaded subclass NoLiveReplicas (429 +
        Retry-After upstream), never a bare 500-class error."""
        from veles_tpu.serving import (LMEngine, NoLiveReplicas,
                                       Overloaded, Router)
        params = _tiny_params()
        engine = LMEngine(params, n_heads=2, max_len=48, slots=1,
                          name="nl_r0")
        router = Router([engine])
        router.unregister(0, reason="test: full-fleet circuit open")
        with pytest.raises(NoLiveReplicas) as err:
            router.submit([1, 2, 3], 4)
        assert isinstance(err.value, Overloaded)
        assert err.value.retry_after > 0

    def test_checkpoint_restore_after_simulated_crash(self):
        """Kill-and-restore: a paged engine freezes mid-traffic, its
        checkpoint re-admits the journaled work on a FRESH engine
        (allocator invariants verified first), resumed outputs are
        bit-identical to greedy generate, the pool ends leak-free,
        and new traffic serves with unchanged parity."""
        import jax.numpy as jnp
        from veles_tpu.ops.transformer import generate
        from veles_tpu.serving import FaultPlan, LMEngine
        params = _tiny_params(max_len=64)
        prompts = [[1, 2, 3], [2, 4, 6, 8, 10], [5, 1, 5, 1, 5]]
        expected = [numpy.asarray(generate(
            params, jnp.asarray([p], jnp.int32), 5, 2,
            temperature=0.0, max_len=64))[0] for p in prompts]
        plan = FaultPlan().arm("engine.tick", kind="freeze", after=2,
                               duration_s=60.0)
        crashed = LMEngine(params, n_heads=2, max_len=64, slots=2,
                           paged_kv=8, prefill_chunk=8,
                           prefix_cache=8, name="crash",
                           faults=plan).start()
        try:
            for p in prompts:
                crashed.submit(p, 5)
            time.sleep(0.2)                  # wedged mid-flight
            state = crashed.checkpoint()
            assert len(state["requests"]) == 3
            json.dumps(state)                # JSON-safe by contract
            fresh = LMEngine(params, n_heads=2, max_len=64, slots=2,
                             paged_kv=8, prefill_chunk=8,
                             prefix_cache=8, name="fresh").start()
            try:
                restored = fresh.restore(state)
                assert len(restored) == 3
                outs = [restored[e["rid"]].result(timeout=60)
                        for e in state["requests"]]
                for p, out, exp in zip(prompts, outs, expected):
                    numpy.testing.assert_array_equal(
                        numpy.concatenate([p, out]), exp)
                # leak-free: drain the trie, the pool refills whole
                while fresh._trie.evict_one():
                    pass
                inv = fresh.verify_pool_invariants()
                assert inv["free_pages"] == fresh._pool.num_pages
                assert fresh._trie.live_pins() == 0
                # new traffic, unchanged parity
                out = fresh.generate(numpy.asarray([prompts[0]]), 5)
                numpy.testing.assert_array_equal(out[0], expected[0])
                assert fresh.metrics.counter("engine_restores") == 1
            finally:
                fresh.stop()
        finally:
            plan.release()
            crashed.stop()

    def test_restore_refuses_garbage_and_oversized(self):
        from veles_tpu.serving import LMEngine
        params = _tiny_params()
        engine = LMEngine(params, n_heads=2, max_len=48, slots=1,
                          name="rg")
        with pytest.raises(ValueError, match="format"):
            engine.restore({"format": 99})
        with pytest.raises(ValueError, match="max_len"):
            engine.restore({"format": 1, "config": {"max_len": 4096},
                            "requests": []})
        # all-or-nothing geometry check: a journaled request the
        # restoring pool can NEVER place refuses up front, before any
        # sibling entry is re-admitted
        paged = LMEngine(params, n_heads=2, max_len=48, slots=1,
                         paged_kv=2, prefill_chunk=8, name="rg_p")
        with pytest.raises(ValueError, match="KV pages"):
            paged.restore({"format": 1, "config": {"max_len": 48},
                           "requests": [
                               {"rid": 1, "prompt": [1, 2], "n_new": 2},
                               {"rid": 2, "prompt": list(range(30)),
                                "n_new": 10}]})


class TestInjectedHTTPFaults:
    """ISSUE 10: the http.request site serves structured transient
    errors, and load_gen's failure classes (satellite) split them from
    real errors."""

    def test_injected_503_is_structured_and_classified(self):
        from veles_tpu.restful_api import RESTfulAPI
        from veles_tpu.serving import FaultPlan, ServingMetrics
        plan = FaultPlan().arm("http.request", exc="http_503",
                               every=2)
        api = RESTfulAPI(None, forward=lambda x: x * 2.0, faults=plan)
        api.metrics = ServingMetrics("httpf_t")
        api.start(port=0)
        try:
            summary = run_load(
                "http://127.0.0.1:%d/predict" % api.port,
                payload={"input": [[1.0, 2.0]]}, clients=1,
                requests_per_client=6)
            assert summary["sent"] == 6
            # every 2nd request got the injected 503 (Retry-After set),
            # the rest served — and the failure CLASSES split them
            assert summary["failures"]["http_503"] == 3
            assert summary["failures"]["timeout"] == 0
            assert summary["failures"]["connection"] == 0
            assert summary["shed_not_errored"] is True
            assert summary["ok"] == 3
        finally:
            api.stop()

    def test_connection_failure_class(self):
        """A dead endpoint lands in the 'connection' class — chaos
        runs can tell a refused socket from a graceful shed."""
        import socket
        sock = socket.socket()
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]
        sock.close()                       # nothing listens here now
        summary = run_load("http://127.0.0.1:%d/predict" % port,
                           payload={"input": [[0.0]]}, clients=1,
                           requests_per_client=1, timeout=2)
        assert summary["failures"]["connection"] == 1
        assert summary["shed_not_errored"] is False


class TestWeightSwap:
    """ISSUE 11: zero-downtime weight updates — engine hot-swap (lanes
    finish on the old weights or drain onto the new), tp-mesh swap
    without recompiles, structural-mismatch refusal, canary rollback
    driven by the synchronous HealthChecker, and the publisher loop."""

    def _expected(self, params, prompts, n_new, max_len=48):
        import jax.numpy as jnp
        from veles_tpu.ops.transformer import generate
        return [numpy.asarray(generate(
            params, jnp.asarray([p], jnp.int32), n_new, 2,
            temperature=0.0, max_len=max_len))[0] for p in prompts]

    def _wait_busy(self, engine, n, timeout=10.0):
        deadline = time.monotonic() + timeout
        while engine.metrics.gauge("slots_busy") < n \
                and time.monotonic() < deadline:
            time.sleep(0.002)
        assert engine.metrics.gauge("slots_busy") >= n

    def test_swap_parity_straddling_lanes(self):
        """swap_weights mid-traffic: every request completes whole and
        exactly once, each delivered row is bit-identical to the
        weights version its future is stamped with (straddling lanes
        finish on the OLD weights — the default), and post-swap
        traffic serves the new weights."""
        from veles_tpu.serving import LMEngine
        pa = _tiny_params()
        pb = _tiny_params()       # fresh draws: same shapes, new weights
        prompts = [[1, 2, 3], [2, 4, 6, 8], [5, 1, 5], [7, 7, 1]]
        n_new = 12
        exp_a = self._expected(pa, prompts, n_new)
        exp_b = self._expected(pb, prompts, n_new)
        engine = LMEngine(pa, n_heads=2, max_len=48, slots=2,
                          name="sw_par").start()
        try:
            futures = [engine.submit(p, n_new) for p in prompts]
            self._wait_busy(engine, 2)
            v = engine.swap_weights(pb, version=7)
            assert v == 7 and engine.weights_version == 7
            seen = set()
            for p, f, ea, eb in zip(prompts, futures, exp_a, exp_b):
                out = f.result(timeout=60)
                assert len(out) == n_new      # whole, exactly once
                seen.add(f.version)
                numpy.testing.assert_array_equal(
                    numpy.concatenate([p, out]),
                    ea if f.version == 0 else eb)
            assert seen <= {0, 7}
            assert 0 in seen        # the confirmed-busy lanes finished
            #                         on the old weights
            fut = engine.submit(prompts[0], n_new)
            out = fut.result(timeout=60)
            assert fut.version == 7
            numpy.testing.assert_array_equal(
                numpy.concatenate([prompts[0], out]), exp_b[0])
            assert engine.metrics.counter("weight_swaps") == 1
            assert engine.metrics.gauge("weights_version") == 7
        finally:
            engine.stop()

    def test_swap_drain_requeues_on_new_weights_paged(self):
        """drain=True on a paged engine: in-flight lanes are withdrawn
        whole and re-decode from scratch on the NEW weights — futures
        resolve exactly once with the new stamp, and the page pool
        survives the requeue leak-free (allocator invariants)."""
        from veles_tpu.serving import FaultPlan, LMEngine
        pa = _tiny_params()
        pb = _tiny_params()
        prompts = [[1, 2, 3], [2, 4, 6, 8]]
        n_new = 16
        exp_b = self._expected(pb, prompts, n_new)
        # slow ticks so the swap provably lands mid-decode
        plan = FaultPlan().arm("engine.step", kind="latency",
                               latency_s=0.02)
        engine = LMEngine(pa, n_heads=2, max_len=48, slots=2,
                          paged_kv=True, prefill_chunk=8,
                          name="sw_drain", faults=plan).start()
        try:
            futures = [engine.submit(p, n_new) for p in prompts]
            self._wait_busy(engine, 2)
            engine.swap_weights(pb, version=3, drain=True)
            for p, f, eb in zip(prompts, futures, exp_b):
                out = f.result(timeout=60)
                assert len(out) == n_new and f.version == 3
                numpy.testing.assert_array_equal(
                    numpy.concatenate([p, out]), eb)
            assert engine.metrics.counter(
                "requests_requeued_for_swap") >= 1
            deadline = time.monotonic() + 15
            while engine.metrics.gauge("slots_busy") > 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            inv = engine.verify_pool_invariants()
            assert inv["free_pages"] == engine._pool.num_pages
        finally:
            plan.release()
            engine.stop()

    def test_swap_mismatch_refuses_loudly(self):
        """A shape- or structure-incompatible tree refuses with a loud
        ValueError and the OLD weights keep serving bit-exactly."""
        import jax
        import jax.numpy as jnp
        from veles_tpu import prng
        from veles_tpu.ops.transformer import init_transformer_params
        from veles_tpu.serving import LMEngine
        pa = _tiny_params()
        wrong = jax.tree.map(jnp.asarray, init_transformer_params(
            prng.get("init"), 16, d_model=16, n_heads=2, n_layers=2,
            max_len=48))
        [exp] = self._expected(pa, [[1, 2, 3]], 5)
        engine = LMEngine(pa, n_heads=2, max_len=48, slots=1,
                          name="sw_bad").start()
        try:
            with pytest.raises(ValueError, match="swap refused"):
                engine.swap_weights(wrong)
            broken = dict(pa)
            broken.pop("embed")            # different tree structure
            with pytest.raises(ValueError, match="swap refused"):
                engine.swap_weights(broken)
            assert engine.weights_version == 0
            assert engine.metrics.counter("weight_swaps") == 0
            out = engine.generate(numpy.asarray([[1, 2, 3]]), 5)
            numpy.testing.assert_array_equal(out[0], exp)
        finally:
            engine.stop()

    def test_tp_mesh_swap_no_recompile(self, serving_mesh):
        """A tp=2 engine swaps shard-by-shard under its existing mesh
        (lm_param_specs placement): output flips to the new weights
        bit-exactly, the swapped tree is REALLY sharded, and no
        program compiled a twin (same shapes + pinned shardings → the
        jit-guard bound holds across the swap)."""
        serving_mesh(2)
        from veles_tpu.serving import LMEngine
        pa = _tiny_params()
        pb = _tiny_params()
        prompts = [[1, 2, 3], [2, 4, 6, 8]]
        exp_a = self._expected(pa, prompts, 6)
        exp_b = self._expected(pb, prompts, 6)
        engine = LMEngine(pa, n_heads=2, max_len=48, slots=2, tp=2,
                          prefill_chunk=8, name="sw_tp").start()
        try:
            for p, ea in zip(prompts, exp_a):
                out = engine.submit(p, 6).result(timeout=60)
                numpy.testing.assert_array_equal(
                    numpy.concatenate([p, out]), ea)
            progs = {"step": engine._step_jit,
                     "chunk": engine._chunk_jit}
            sizes = {n: fn._cache_size() for n, fn in progs.items()}
            engine.swap_weights(pb, version=1)
            for p, eb in zip(prompts, exp_b):
                fut = engine.submit(p, 6)
                out = fut.result(timeout=60)
                assert fut.version == 1
                numpy.testing.assert_array_equal(
                    numpy.concatenate([p, out]), eb)
            for name, fn in progs.items():
                assert fn._cache_size() == sizes[name], (
                    "%s compiled a twin program across the swap"
                    % name)
            wq = engine.params["blocks"][0]["attn"]["wq"]
            assert len(wq.addressable_shards) == 2   # really sharded
        finally:
            engine.stop()

    def test_canary_rollback_driven_by_health_checker_step(self):
        """Router.deploy watches the health circuit during the canary
        window: a canary the synchronously-driven HealthChecker.step()
        quarantines mid-watch rolls the deploy back to the previous
        version, and the fleet keeps serving the old weights."""
        import jax
        from veles_tpu.serving import (FaultPlan, HealthChecker,
                                       LMEngine, Router)
        pa = _tiny_params()
        pb = _tiny_params()
        [exp_a] = self._expected(pa, [[1, 2, 3]], 4)
        plan = FaultPlan()
        devs = jax.devices()
        replicas = [LMEngine(pa, n_heads=2, max_len=48, slots=2,
                             devices=[devs[i % len(devs)]],
                             name="cb_r%d" % i,
                             faults=plan if i == 0 else None)
                    for i in range(2)]
        router = Router(replicas, drain_timeout_s=0.3).start()
        checker = HealthChecker(router, interval_s=0.05,
                                probe_timeout_s=2.0, fail_threshold=2,
                                cooldown_s=600.0, stall_s=0.3)
        checker.warm_probes()
        result = {}

        def run_deploy():
            result["rec"] = router.deploy(
                pb, version=1, canary=1, canary_fraction=0.5,
                watch_s=30.0, checker=checker, probe_n_new=1)

        t = threading.Thread(target=run_deploy, daemon=True)
        t.start()
        try:
            # the canary (replica 0) swaps, passes its parity probe and
            # rejoins — the deploy is now in its watch window
            deadline = time.monotonic() + 60
            while (replicas[0].weights_version != 1
                   or not router._live[0]) \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert replicas[0].weights_version == 1
            # NOW the canary goes bad: every prefill faults, so the
            # checker's synthetic 1-token probe dies — step()
            # (synchronous) walks it to quarantine, and the deploy's
            # watch sees the circuit
            plan.arm("engine.prefill", kind="error")
            deadline = time.monotonic() + 60
            while router._live[0] and time.monotonic() < deadline:
                checker.step()
                time.sleep(0.03)
            assert not router._live[0]
            t.join(timeout=60)
            assert not t.is_alive()
            rec = result["rec"]
            assert rec["rolled_back"] is True
            assert "canary 0" in rec["reason"]
            assert router.metrics.counter("rollbacks_total") == 1
            plan.disarm()
            # rolled all the way back: both replicas on v0, the
            # survivor serves the OLD weights bit-exactly
            assert replicas[0].weights_version == 0
            assert replicas[1].weights_version == 0
            fut = router.submit([1, 2, 3], 4)
            out = fut.result(timeout=60)
            assert fut.job.version == 0
            numpy.testing.assert_array_equal(
                numpy.concatenate([[1, 2, 3], out]), exp_a)
        finally:
            plan.disarm()
            router.stop()

    def _snapshot_payload(self, params):
        import jax
        host = jax.tree.map(numpy.asarray, params)
        return {"format": 1, "framework_version": "test",
                "workflow_class": "t", "workflow_name": "t",
                "epoch": 1, "best_metric": None, "time": time.time(),
                "state": {"units": {"TransformerTrainer": {
                    "params": host, "opt_state": None, "time": 0}},
                    "prng": {}},
                "config": {}}

    def test_model_manager_publishes_and_rejects(self, tmp_path):
        """The publisher loop end to end: a snapshot landing in the
        watched directory deploys across the fleet exactly once (the
        unchanged directory is a no-op next poll), replies flip to the
        new version, and a numerically-broken checkpoint is rejected
        OFF the hot path with the fleet untouched."""
        import gzip
        import pickle
        from veles_tpu.serving import LMEngine, ModelManager, Router
        pa = _tiny_params()
        pb = _tiny_params()
        [exp_b] = self._expected(pb, [[1, 2, 3]], 5)
        engine = LMEngine(pa, n_heads=2, max_len=48, slots=2,
                          name="mm_r0")
        router = Router([engine]).start()
        manager = ModelManager(router, str(tmp_path), interval_s=3600,
                               probe_n_new=2)

        def write(params, mtime):
            path = tmp_path / "wf_current.pickle.gz"
            with gzip.open(path, "wb") as f:
                pickle.dump(self._snapshot_payload(params), f)
            os.utime(path, (mtime, mtime))
            return path

        try:
            assert manager.poll_once() is None          # empty dir
            write(pb, time.time())
            rec = manager.poll_once()
            assert rec["deployed"] and not rec["rolled_back"]
            assert rec["version"] == 1 and rec["epoch"] == 1
            assert manager.poll_once() is None          # unchanged
            fut = router.submit([1, 2, 3], 5)
            out = fut.result(timeout=60)
            assert fut.job.version == 1
            numpy.testing.assert_array_equal(
                numpy.concatenate([[1, 2, 3], out]), exp_b)
            # a NaN checkpoint is rejected before any engine sees it
            bad_embed = numpy.array(pb["embed"], numpy.float32)
            bad_embed[0, 0] = numpy.nan
            write({**pb, "embed": bad_embed}, time.time() + 60)
            rec = manager.poll_once()
            assert rec["deployed"] is False
            assert "non-finite" in rec["rejected"]
            assert engine.weights_version == 1          # untouched
            assert router.metrics.counter("publish_rejected") == 1
            assert router.metrics.counter("publishes_total") == 1
        finally:
            router.stop()


class TestChaosSmoke:
    def test_chaos_smoke_kill_one_replica(self):
        """Satellite: the <60s chaos-smoke subset runs tier-1 so the
        fault-injection plumbing and the quarantine/drain/exactly-once
        contract cannot rot between TPU sessions."""
        from chaos_smoke import run_smoke
        record = run_smoke()
        assert record["completed_exactly_once"] == record["requests"]
        assert record["parity_vs_generate"] is True
        assert record["replica0_quarantined"] is True
        assert record["smoke_wall_s"] < 60

    def test_chaos_smoke_weight_swap(self):
        """Satellite (ISSUE 11): the <60s weight-swap-under-load
        subset rides tier-1 — requests straddling a canary deploy
        complete exactly once with per-stamped-version parity and
        zero 5xx, and an injected bad canary auto-rolls back with no
        client-visible errors."""
        from chaos_smoke import run_swap_smoke
        record = run_swap_smoke()
        assert record["completed_exactly_once"] == record["requests"]
        assert record["zero_5xx"] is True
        assert record["parity_per_stamped_version"] is True
        assert record["bad_canary_rolled_back"] is True
        assert record["rollbacks_total"] == 1
        assert record["smoke_wall_s"] < 60


@pytest.mark.slow
class TestSustainedLoad:
    def test_sustained_qps_with_histograms(self):
        """Closed-loop sustained load (the slow-marked evidence run):
        paced QPS for a fixed window, zero failures, coalescing and
        full latency histograms on the server side."""
        from veles_tpu.restful_api import RESTfulAPI
        from veles_tpu.serving import ServingMetrics

        def forward(x):
            time.sleep(0.002)
            return x * 3.0

        api = RESTfulAPI(None, forward=forward)
        api.enable_batching(max_batch=16, batch_wait_s=0.005,
                            sample_shape=(8,),
                            metrics=ServingMetrics("sustained_t"))
        api.start(port=0)
        try:
            summary = run_load(
                "http://127.0.0.1:%d/predict" % api.port,
                payload={"input": [[1.0] * 8]}, clients=16,
                qps=200, duration=5.0)
            assert summary["ok"] == summary["sent"] > 100
            assert summary["latency_s"]["p99"] < 5.0
            snap = api.metrics.snapshot()
            assert snap["dispatches"] < snap["requests"]
            assert snap["batch_size"]["mean"] > 1
            assert snap["latency"]["p99"] > 0
        finally:
            api.stop()
