"""End-to-end request tracing (ISSUE 12): the span tracer, the flight
recorder, the cost ledger, and the tracer threaded through engine /
router / HTTP — including the acceptance combo (prefix_cache +
prefill_chunk + spec_k + paged_kv + tp dryrun) exporting a valid
Chrome trace with complete span trees."""

import json
import time
import urllib.request

import numpy
import pytest

import jax.numpy as jnp

from veles_tpu import prng
from veles_tpu.ops.transformer import generate, init_transformer_params


def tiny_params(vocab=16, d_model=32, n_heads=2, n_layers=2,
                max_len=64, seed=7):
    import jax
    prng.reset()
    prng.seed_all(seed)
    host = init_transformer_params(prng.get("init"), vocab,
                                   d_model=d_model, n_heads=n_heads,
                                   n_layers=n_layers, max_len=max_len)
    return jax.tree.map(jnp.asarray, host)


def greedy_rows(params, prompts, n_new, n_heads=2, max_len=64):
    return [numpy.asarray(generate(
        params, jnp.asarray([p], jnp.int32), n_new, n_heads,
        temperature=0.0, max_len=max_len))[0] for p in prompts]


class TestSpanTracer:
    def test_span_tree_ring_and_waterfall(self):
        from veles_tpu.serving.tracing import (SpanTracer,
                                               format_waterfall,
                                               verify_integrity)
        tr = SpanTracer(mode="all", last=2)
        ctx = tr.start_request(rid="abc", name="http.request",
                               cat="http")
        h = tr.begin(ctx, "queue.wait", cat="queue")
        tr.end(h, attrs={"wait_s": 0.001})
        h2 = tr.begin(ctx, "attempt", cat="router",
                      attrs={"replica": 0})
        child = ctx.at(h2[1])
        t = time.monotonic()
        tr.add_many([child], "decode.step", "decode", t, t + 0.002,
                    attrs={"backend": "xla", "bucket": 4})
        tr.end(h2)
        rec = tr.finish_request(ctx)
        assert rec["rid"] == "abc" and rec["error"] is None
        assert verify_integrity([rec])["spans"] == 4
        # the decode span nests under the attempt, not the root
        step = next(s for s in rec["spans"]
                    if s["name"] == "decode.step")
        assert step["parent"] == h2[1]
        text = format_waterfall(rec)
        assert "http.request" in text and "decode.step" in text
        # ring bound: a third request evicts the first
        for i in range(2):
            c = tr.start_request(rid="r%d" % i)
            tr.finish_request(c)
        rids = [r["rid"] for r in tr.requests()]
        assert rids == ["r0", "r1"]
        assert tr.find("abc") is None and tr.find("r1") is not None

    def test_modes_errors_and_sampling(self):
        from veles_tpu.serving.tracing import SpanTracer
        tr = SpanTracer(mode="errors")
        ok = tr.start_request()
        tr.finish_request(ok)
        bad = tr.start_request()
        tr.finish_request(bad, error=RuntimeError("boom"))
        recs = tr.requests()
        assert len(recs) == 1 and "boom" in recs[0]["error"]
        # errored requests auto-dump their waterfall
        assert len(tr.dumps()) == 1 and tr.dumps()[0]["text"]
        # deadline-blown requests are retained and dumped too
        shed = tr.start_request()
        tr.finish_request(shed, deadline=True)
        assert tr.requests()[-1]["deadline_blown"]
        assert len(tr.dumps()) == 2
        # sample:0 traces nothing, sample:1 everything — seeded
        none = SpanTracer(mode="sample", sample=0.0)
        assert none.start_request() is None
        assert none.stats()["sampled_out"] == 1
        full = SpanTracer(mode="sample", sample=1.0)
        assert full.start_request() is not None

    def test_from_spec(self):
        from veles_tpu.serving.tracing import SpanTracer
        assert SpanTracer.from_spec(None) is None
        assert SpanTracer.from_spec("off") is None
        assert SpanTracer.from_spec(False) is None
        assert SpanTracer.from_spec("all").mode == "all"
        assert SpanTracer.from_spec(True).mode == "all"
        assert SpanTracer.from_spec("errors").mode == "errors"
        s = SpanTracer.from_spec("sample:0.25")
        assert s.mode == "sample" and s.sample == 0.25
        t = SpanTracer(mode="all")
        assert SpanTracer.from_spec(t) is t
        with pytest.raises(ValueError):
            SpanTracer.from_spec("sometimes")

    def test_unclosed_span_flagged_and_caught(self):
        from veles_tpu.serving.tracing import (SpanTracer,
                                               verify_integrity)
        tr = SpanTracer(mode="all")
        ctx = tr.start_request()
        tr.begin(ctx, "leaky")           # never ended
        rec = tr.finish_request(ctx)
        assert rec["unclosed"] == ["leaky"]
        with pytest.raises(AssertionError, match="unclosed"):
            verify_integrity([rec])
        # an orphan parent is caught too
        orphan = {"rid": "x", "error": None, "deadline_blown": False,
                  "unclosed": [],
                  "spans": [{"sid": 1, "parent": None, "name": "root",
                             "cat": "r", "t0": 0.0, "t1": 1.0,
                             "attrs": {}},
                            {"sid": 2, "parent": 99, "name": "lost",
                             "cat": "s", "t0": 0.0, "t1": 1.0,
                             "attrs": {}}]}
        with pytest.raises(AssertionError, match="ORPHAN"):
            verify_integrity([orphan])

    def test_ledger_dedups_batched_dispatches(self):
        from veles_tpu.serving.tracing import SpanTracer, cost_ledger
        tr = SpanTracer(mode="all")
        a, b = tr.start_request(), tr.start_request()
        t = time.monotonic()
        # one batched dispatch serving two requests...
        tr.add_many([a, b], "decode.step", "decode", t, t + 0.004,
                    attrs={"backend": "xla", "bucket": 2})
        # ...and one single-lane dispatch
        tr.add_many([a], "decode.step", "decode", t, t + 0.002,
                    attrs={"backend": "xla", "bucket": 2})
        recs = [tr.finish_request(a), tr.finish_request(b)]
        rows = cost_ledger(recs)
        assert len(rows) == 1
        row = rows[0]
        assert row["dispatches"] == 2 and row["lanes"] == 3
        # spans without a backend attr (non-device marks) stay out
        assert cost_ledger([{"rid": "x", "spans": [
            {"sid": 1, "parent": None, "name": "queue.wait",
             "cat": "queue", "t0": 0.0, "t1": 1.0, "attrs": {}}],
            "error": None, "deadline_blown": False,
            "unclosed": []}]) == []

    def test_max_spans_bounds_a_request(self):
        from veles_tpu.serving.tracing import SpanTracer
        tr = SpanTracer(mode="all", max_spans=4)
        ctx = tr.start_request()
        handles = [tr.begin(ctx, "s%d" % i) for i in range(6)]
        assert sum(1 for h in handles if h is not None) == 3  # + root
        for h in handles:
            tr.end(h)
        rec = tr.finish_request(ctx)
        assert len(rec["spans"]) == 4
        assert tr.stats()["dropped_spans"] == 3


class TestEngineTracing:
    N_NEW = 8

    def _run(self, tracer, prompts, expect, tp=0, **kw):
        from veles_tpu.serving import LMEngine, ServingMetrics
        params = tiny_params()
        engine = LMEngine(params, n_heads=2, max_len=64, slots=2,
                          metrics=ServingMetrics("trc_t"),
                          tracer=tracer, tp=tp, **kw).start()
        try:
            futures = [engine.submit(p, self.N_NEW) for p in prompts]
            outs = [f.result(timeout=120) for f in futures]
        finally:
            engine.stop()
        for p, out, exp in zip(prompts, outs, expect):
            numpy.testing.assert_array_equal(
                numpy.concatenate([p, out]), exp)
        return futures

    def test_full_fastpath_traced_chrome_export(self):
        """The acceptance combo minus tp: prefix_cache + prefill_chunk
        + spec_k + paged_kv, traced — parity unchanged, every span
        tree complete, the Chrome export strict-valid with root →
        queue/prefill/decode spans, and the cost ledger populated."""
        from veles_tpu.serving.tracing import (SpanTracer, cost_ledger,
                                               verify_integrity)
        params = tiny_params()
        prompts = [[1, 2, 3, 4, 5, 6, 7, 8, 9, 10], [2, 4, 6, 8],
                   [1, 2, 3, 4, 5, 6, 7, 8, 2, 1]]
        expect = greedy_rows(params, prompts, self.N_NEW)
        tracer = SpanTracer(mode="all", last=16)
        self._run(tracer, prompts, expect, prefill_chunk=8,
                  prefix_cache=32, spec_k=2, paged_kv=True)
        recs = tracer.requests()
        integ = verify_integrity(recs)
        assert integ["requests"] == len(prompts)
        names = {s["name"] for r in recs for s in r["spans"]}
        assert {"engine.request", "queue.wait", "prefill.chunk",
                "decode.verify"} <= names
        chrome = tracer.export_chrome()
        # strict JSON (what Perfetto/chrome://tracing require) with
        # X events carrying rid/sid/parent join keys
        parsed = json.loads(json.dumps(chrome, allow_nan=False))
        xs = [e for e in parsed["traceEvents"] if e["ph"] == "X"]
        assert xs and all("rid" in e["args"] and "ts" in e
                          and "dur" in e for e in xs)
        rows = cost_ledger(recs)
        assert rows and all(r["backend"] == "xla" for r in rows)
        ops = {r["op"] for r in rows}
        assert "decode.verify" in ops and "prefill.chunk" in ops
        # dispatch counts are deduped: total dispatches must not
        # exceed total lanes
        assert all(r["dispatches"] <= r["lanes"] for r in rows)

    def test_tp_traced_acceptance_combo(self, serving_mesh):
        """The FULL acceptance combo: prefix_cache + prefill_chunk +
        spec_k + paged_kv + tp=2 (CPU dryrun mesh), traced end to
        end — greedy parity, complete span trees, and the ledger's
        backend column names the tp path."""
        serving_mesh(2)
        from veles_tpu.serving.tracing import (SpanTracer, cost_ledger,
                                               verify_integrity)
        params = tiny_params()
        prompts = [[1, 2, 3, 4, 5, 6, 7, 8, 9, 10], [2, 4, 6, 8]]
        expect = greedy_rows(params, prompts, self.N_NEW)
        tracer = SpanTracer(mode="all", last=16)
        self._run(tracer, prompts, expect, tp=2, prefill_chunk=8,
                  prefix_cache=32, spec_k=2, paged_kv=True)
        recs = tracer.requests()
        assert verify_integrity(recs)["requests"] == len(prompts)
        rows = cost_ledger(recs)
        assert rows and all(r["backend"] == "xla-tp2" for r in rows)
        json.loads(json.dumps(tracer.export_chrome(), allow_nan=False))

    def test_flight_recorder_reconstructs_faulted_request(self):
        """Inject a chunk fault mid-prefill: the failed request's
        timeline — including the failed dispatch — reconstructs from
        the ring AFTER the fact, and was auto-dumped on failure."""
        from veles_tpu.serving import (FaultPlan, LMEngine,
                                       ServingMetrics)
        from veles_tpu.serving.tracing import (SpanTracer,
                                               format_waterfall,
                                               verify_integrity)
        params = tiny_params()
        prompts = [[1, 2, 3, 4, 5, 6, 7, 8, 9, 10],
                   [2, 4, 6, 8, 1, 3], [5, 5, 5, 5, 5, 5, 5, 5]]
        expect = greedy_rows(params, prompts, self.N_NEW)
        plan = FaultPlan(seed=0).arm("engine.chunk", kind="error",
                                     calls={2})
        tracer = SpanTracer(mode="all", last=16)
        engine = LMEngine(params, n_heads=2, max_len=64, slots=2,
                          prefill_chunk=8, faults=plan, tracer=tracer,
                          metrics=ServingMetrics("rec_t")).start()
        try:
            futures = [engine.submit(p, self.N_NEW) for p in prompts]
            failed, survived = [], 0
            for p, f, exp in zip(prompts, futures, expect):
                try:
                    out = f.result(timeout=120)
                except Exception:   # noqa: BLE001 — the injected fault
                    failed.append(f)
                    continue
                numpy.testing.assert_array_equal(
                    numpy.concatenate([p, out]), exp)
                survived += 1
        finally:
            engine.stop()
        assert len(failed) == 1 and survived == 2
        rid = failed[0].request.trace.rid
        rec = tracer.find(rid)
        assert rec is not None and "InjectedFault" in rec["error"]
        fault_span = [s for s in rec["spans"]
                      if s["name"] == "prefill.chunk"
                      and "error" in s["attrs"]]
        assert fault_span, "failed dispatch missing from the timeline"
        assert "InjectedFault" in format_waterfall(rec)
        assert rid in {d["rid"] for d in tracer.dumps()}
        verify_integrity(tracer.requests())

    def test_untraced_engine_unchanged(self):
        """tracer=None is the default: no trace fields set, no spans
        anywhere, parity as ever — the unarmed contract."""
        params = tiny_params()
        prompts = [[1, 2, 3, 4]]
        expect = greedy_rows(params, prompts, self.N_NEW)
        futures = self._run(None, prompts, expect, prefill_chunk=8)
        assert futures[0].request.trace is None


class TestRouterTracing:
    def test_retry_shows_both_attempts(self):
        """A request whose first attempt dies on a faulted replica
        completes on the second; its ONE trace shows the errored
        attempt, the retry marker, and the winning attempt with the
        engine spans nested under it."""
        from veles_tpu.serving import (FaultPlan, LMEngine, Router,
                                       ServingMetrics)
        from veles_tpu.serving.tracing import (SpanTracer,
                                               verify_integrity)
        params = tiny_params()
        prompts = [[1, 2, 3, 4, 5, 6, 7, 8, 9, 10], [2, 4, 6, 8]]
        expect = greedy_rows(params, prompts, 8)
        plan = FaultPlan(seed=0).arm("engine.chunk", kind="error",
                                     calls={1})
        tracer = SpanTracer(mode="all", last=16)
        replicas = [
            LMEngine(params, n_heads=2, max_len=64, slots=2,
                     prefill_chunk=8, name="rtr_t_r%d" % i,
                     metrics=ServingMetrics(
                         "rtr_t", labels={"replica": str(i)}),
                     faults=plan if i == 0 else None, tracer=tracer)
            for i in range(2)]
        router = Router(replicas, retries=2, tracer=tracer).start()
        try:
            futures = [router.submit(p, 8) for p in prompts]
            for p, f, exp in zip(prompts, futures, expect):
                numpy.testing.assert_array_equal(
                    numpy.concatenate([p, f.result(timeout=120)]), exp)
        finally:
            time.sleep(0.1)      # let hedge-loser/zombie spans settle
            router.stop()
        assert router.metrics.counter("requests_retried") >= 1
        recs = tracer.requests()
        verify_integrity(recs)
        retried = [r for r in recs
                   if sum(1 for s in r["spans"]
                          if s["name"] == "attempt") > 1]
        assert retried, "no trace shows a second attempt"
        rec = retried[0]
        attempts = [s for s in rec["spans"] if s["name"] == "attempt"]
        assert any("error" in s["attrs"] for s in attempts)
        winner = next(s for s in attempts
                      if s["attrs"].get("outcome") == "ok")
        # engine spans of the winning attempt nest under it
        nested = [s for s in rec["spans"]
                  if s["parent"] == winner["sid"]]
        assert any(s["name"] == "queue.wait" for s in nested)
        assert any(s["name"] == "retry.backoff"
                   for s in rec["spans"])


class TestHTTPTracing:
    def _api(self, tracer, params):
        """A serve_lm-shaped API (engine handler + tracer) without the
        char_lm training cost."""
        from veles_tpu.restful_api import RESTfulAPI
        from veles_tpu.serving import LMEngine, ServingMetrics

        engine = LMEngine(params, n_heads=2, max_len=64, slots=2,
                          prefill_chunk=8,
                          metrics=ServingMetrics("http_trc"),
                          tracer=tracer).start()

        def handler(request):
            prompt = numpy.asarray(request["input"], numpy.int32)
            toks = engine.generate(prompt,
                                   int(request.get("n_new", 4)))
            return {"tokens": toks.tolist()}

        api = RESTfulAPI(None, handler=handler, metrics=engine.metrics,
                         tracer=tracer)
        api.lm_engine = engine
        return api.start(port=0)

    def _post(self, port, payload, rid=None, path="/predict"):
        headers = {"Content-Type": "application/json"}
        if rid:
            headers["X-Request-Id"] = rid
        req = urllib.request.Request(
            "http://127.0.0.1:%d%s" % (port, path),
            data=json.dumps(payload).encode(), headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, json.loads(resp.read()), \
                    resp.headers
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read()), e.headers

    def test_request_id_echo_and_trace_json(self):
        """Satellite + tentpole surface: every reply (success AND
        structured error) carries request_id — echoed from
        X-Request-Id or generated — and GET /trace.json exports the
        flight recorder with the client's rid as the join key."""
        from veles_tpu.serving.tracing import SpanTracer
        params = tiny_params()
        tracer = SpanTracer(mode="all", last=32)
        api = self._api(tracer, params)
        try:
            code, out, hdrs = self._post(
                api.port, {"input": [[1, 2, 3]], "n_new": 4},
                rid="client-key-1")
            assert code == 200
            assert out["request_id"] == "client-key-1"
            assert hdrs["X-Request-Id"] == "client-key-1"
            # generated when absent — echoed in header and body alike
            code, out, hdrs = self._post(
                api.port, {"input": [[2, 4, 6]], "n_new": 4})
            assert code == 200
            assert out["request_id"] == hdrs["X-Request-Id"]
            assert len(out["request_id"]) == 16
            # structured errors carry it too
            code, out, _ = self._post(api.port, {"nope": 1},
                                      rid="bad-1")
            assert code == 400 and out["request_id"] == "bad-1"
            code, out, _ = self._post(api.port, {"input": [[1]]},
                                      rid="lost-1", path="/nowhere")
            assert code == 404 and out["request_id"] == "lost-1"
            # the exported trace joins on the same ids
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/trace.json?last=8" % api.port,
                    timeout=10) as resp:
                trace = json.loads(resp.read())
            xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
            rids = {e["args"].get("rid") for e in xs}
            assert "client-key-1" in rids and "bad-1" in rids
            names = {e["name"] for e in xs}
            assert "http.request" in names and "decode.step" in names
            # root spans carry the reply status
            statuses = {e["args"].get("status") for e in xs
                        if e["name"] == "http.request"}
            assert {200, 400, 404} <= statuses
        finally:
            api.stop()

    def test_request_id_stamped_without_tracer(self):
        """The request_id satellite holds with tracing off."""
        params = tiny_params()
        api = self._api(None, params)
        try:
            code, out, hdrs = self._post(
                api.port, {"input": [[1, 2, 3]], "n_new": 4},
                rid="no-trace-1")
            assert code == 200 and out["request_id"] == "no-trace-1"
            assert hdrs["X-Request-Id"] == "no-trace-1"
            # /trace.json is 404 when no tracer is armed
            try:
                urllib.request.urlopen(
                    "http://127.0.0.1:%d/trace.json" % api.port,
                    timeout=10)
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            api.stop()


class TestReviewHardening:
    """Pins for the review fixes: sampled-out propagation, hedge-loser
    span closure under an upstream-owned root, last=0 trim."""

    def test_sample_decision_made_once_across_layers(self):
        """sample:P rolls the coin ONCE at the outermost armed layer:
        a sampled-out request must not re-root partial trees at the
        router or engine (the 1-(1-P)^3 inflation bug)."""
        from veles_tpu.serving import (LMEngine, Router,
                                       ServingMetrics)
        from veles_tpu.serving.tracing import SpanTracer
        params = tiny_params()
        tracer = SpanTracer(mode="sample", sample=0.0)
        replicas = [
            LMEngine(params, n_heads=2, max_len=64, slots=2,
                     prefill_chunk=8, name="smp_r%d" % i,
                     metrics=ServingMetrics(
                         "smp", labels={"replica": str(i)}),
                     tracer=tracer)
            for i in range(2)]
        router = Router(replicas, tracer=tracer).start()
        try:
            futs = [router.submit([1, 2, 3, 4], 4) for _ in range(3)]
            for f in futs:
                f.result(timeout=60)
        finally:
            router.stop()
        stats = tracer.stats()
        # one roll per request — the engines never rolled again
        assert stats["started"] == 3
        assert stats["sampled_out"] == 3
        assert stats["retained"] == 0 and stats["live"] == 0

    def test_hedge_loser_spans_closed_under_upstream_root(self):
        """An upstream-owned (HTTP-shaped) root seals the trace the
        moment the handler returns — the hedge loser's attempt span
        must already be closed (outcome hedge-lost), never flagged
        unclosed."""
        from veles_tpu.serving import (FaultPlan, LMEngine, Router,
                                       ServingMetrics)
        from veles_tpu.serving import tracing
        from veles_tpu.serving.tracing import (SpanTracer,
                                               verify_integrity)
        params = tiny_params()
        prompts = [[1, 2, 3, 4, 5, 6], [2, 4, 6, 8]]
        expect = greedy_rows(params, prompts, 8)
        plan = FaultPlan(seed=0).arm("engine.step", kind="latency",
                                     latency_s=0.15)
        tracer = SpanTracer(mode="all", last=16)
        replicas = [
            LMEngine(params, n_heads=2, max_len=64, slots=2,
                     prefill_chunk=8, name="hdg_r%d" % i,
                     metrics=ServingMetrics(
                         "hdg", labels={"replica": str(i)}),
                     faults=plan if i == 0 else None, tracer=tracer)
            for i in range(2)]
        router = Router(replicas, hedge_after_s=0.25,
                        tracer=tracer).start()
        recs = []
        try:
            for p, exp in zip(prompts, expect):
                root = tracer.start_request(rid="up-%d" % len(recs),
                                            name="http.request",
                                            cat="http")
                with tracing.use(root):
                    fut = router.submit(p, 8)
                out = fut.result(timeout=120)
                # seal IMMEDIATELY, exactly like do_POST's finally —
                # the loser may still be decoding on the slow replica
                recs.append(tracer.finish_request(root))
                numpy.testing.assert_array_equal(
                    numpy.concatenate([p, out]), exp)
        finally:
            plan.release()
            router.stop()
        assert router.metrics.counter("requests_hedged") >= 1
        verify_integrity(recs)
        lost = [s for r in recs for s in r["spans"]
                if s["attrs"].get("outcome") == "hedge-lost"]
        assert lost, "no hedge-lost attempt recorded"

    def test_requests_last_zero_is_empty(self):
        from veles_tpu.serving.tracing import SpanTracer
        tr = SpanTracer(mode="all")
        for _ in range(3):
            tr.finish_request(tr.start_request())
        assert tr.requests(last=0) == []
        assert len(tr.requests(last=2)) == 2
        assert len(tr.export_chrome(last=0)["traceEvents"]) == 1  # meta

    def test_injected_503_not_flagged_deadline(self):
        """An injected transient HTTP 503 (the retryable-blip shape)
        is an error dump but NOT a deadline shed — only a real
        DeadlineExceeded sets deadline_blown."""
        from veles_tpu.restful_api import RESTfulAPI
        from veles_tpu.serving import FaultPlan
        from veles_tpu.serving.tracing import SpanTracer
        plan = FaultPlan(seed=0).arm("http.request", kind="error",
                                     exc="http_503", times=1)
        tracer = SpanTracer(mode="all", last=8)
        api = RESTfulAPI(None, handler=lambda req: {"ok": True},
                         faults=plan, tracer=tracer).start(port=0)
        try:
            req = urllib.request.Request(
                "http://127.0.0.1:%d/predict" % api.port,
                data=json.dumps({"input": [[1]]}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Request-Id": "blip-1"})
            try:
                urllib.request.urlopen(req, timeout=30)
                assert False, "expected 503"
            except urllib.error.HTTPError as e:
                assert e.code == 503
                assert json.loads(e.read())["request_id"] == "blip-1"
        finally:
            api.stop()
        rec = tracer.find("blip-1")
        assert rec is not None and rec["error"] == "http 503"
        assert rec["deadline_blown"] is False

    def test_batcher_injected_dispatch_fault_keeps_trees_sound(self):
        """A batcher.dispatch fault fails its clients with their
        queue-wait spans CLOSED — no unclosed spans in the finished
        trees (the fault fires after the spans close)."""
        from veles_tpu.serving import FaultPlan, MicroBatcher
        from veles_tpu.serving.tracing import (SpanTracer,
                                               verify_integrity)
        plan = FaultPlan(seed=0).arm("batcher.dispatch", kind="error",
                                     calls={1})
        tracer = SpanTracer(mode="all", last=8)
        mb = MicroBatcher(lambda x: x * 2, max_batch=4,
                          sample_shape=(2,), faults=plan,
                          tracer=tracer).start()
        try:
            with pytest.raises(Exception, match="injected"):
                mb.submit(numpy.ones((1, 2), numpy.float32))
            out = mb.submit(numpy.ones((1, 2), numpy.float32))
            numpy.testing.assert_array_equal(
                out, 2 * numpy.ones((1, 2), numpy.float32))
        finally:
            mb.stop()
        recs = tracer.requests()
        assert len(recs) == 2
        verify_integrity(recs)
        assert any(r["error"] and "injected" in r["error"]
                   for r in recs)
