"""Residual (skip) connections in the fused chain — beyond-parity DAG
support (veles_tpu/ops/residual.py; the reference's StandardWorkflow was
strictly linear)."""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu import prng
from veles_tpu.config import root


def _build_residual_mnist(skip=2, fused=True, seed=3):
    """784 -> 32 -> (dense 32 -> dense 32 -> +skip) -> softmax."""
    prng.reset()
    prng.seed_all(seed)
    root.__dict__.pop("mnist", None)
    root.mnist.update({
        "loader": {"minibatch_size": 50, "n_train": 200, "n_valid": 100},
        "decision": {"max_epochs": 3, "fail_iterations": 10},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "momentum": 0.9},
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "momentum": 0.9},
            {"type": "all2all", "output_sample_shape": 32,
             "learning_rate": 0.05, "momentum": 0.9},
            {"type": "residual", "skip": skip},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.05, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    return mnist.build(fused=fused)


class TestResidualForward:
    def test_forward_adds_skip_source(self):
        wf = _build_residual_mnist()
        wf.initialize()
        runner = wf._fused_runner
        x = jnp.asarray(numpy.random.RandomState(0)
                        .randn(4, 28, 28, 1), jnp.float32)
        acts = runner._forward_chain(runner.state, x)
        # layer 3 is the residual with skip=2: output = input + acts[1]
        numpy.testing.assert_allclose(
            numpy.asarray(acts[4]), numpy.asarray(acts[3] + acts[1]),
            rtol=1e-6)

    def test_shape_mismatch_raises(self):
        wf = _build_residual_mnist(skip=3)   # acts[0] is 28x28x1: mismatch
        wf.initialize()
        runner = wf._fused_runner
        x = jnp.zeros((4, 28, 28, 1), jnp.float32)
        with pytest.raises(ValueError, match="equal shapes"):
            runner._forward_chain(runner.state, x)

    def test_unit_mode_rejected(self):
        with pytest.raises(ValueError, match="fused"):
            _build_residual_mnist(fused=False)


class TestResidualBackward:
    def test_grads_match_autodiff_oracle(self):
        """The hand-derived backward with the pending-skip stash equals
        jax.grad of the summed loss through the same chain — the
        two-consumer fan-out is exact, not approximate."""
        wf = _build_residual_mnist()
        wf.initialize()
        runner = wf._fused_runner
        rs = numpy.random.RandomState(1)
        x = jnp.asarray(rs.randn(8, 28, 28, 1), jnp.float32)
        labels = jnp.asarray(rs.randint(0, 10, 8), jnp.int32)
        mask = jnp.ones(8, jnp.float32)

        got, _ = runner._grads_and_metrics(runner.state, x, labels, mask)

        def loss_of(state):
            acts = runner._forward_chain(state, x, rng=None, train=True)
            _, metrics = runner._loss(acts[-1], labels, mask)
            return metrics["loss_sum"]

        want = jax.grad(loss_of)(runner.state)
        checked = 0
        for i, (g, w) in enumerate(zip(got, want)):
            if g is None:
                continue
            grad_w, grad_b = g        # backward_fused's (gw, gb) pair
            numpy.testing.assert_allclose(
                numpy.asarray(grad_w), numpy.asarray(w["w"]),
                rtol=2e-4, atol=2e-5, err_msg="layer %d grad w" % i)
            numpy.testing.assert_allclose(
                numpy.asarray(grad_b), numpy.asarray(w["b"]),
                rtol=2e-4, atol=2e-5, err_msg="layer %d grad b" % i)
            checked += 1
        assert checked >= 4   # 4 parameterized layers

    def test_residual_net_trains(self):
        """End-to-end: the residual net runs the full fused loop through
        the launcher and improves on the synthetic set."""
        from veles_tpu.launcher import Launcher
        wf = _build_residual_mnist()
        Launcher(wf, stats=False).boot()
        assert wf.is_finished
        losses = [m["validation"]["loss"]
                  for m in wf.decision.epoch_metrics]
        assert losses[-1] < losses[0]
        assert wf.decision.epoch_metrics[-1]["validation"]["n_err"] <= 5

    def test_cifar_resnet_sample_trains(self):
        """The zoo sample (two identity blocks on the CIFAR loader)
        builds from config and improves on the synthetic set."""
        from veles_tpu.launcher import Launcher
        prng.reset()
        prng.seed_all(5)
        root.__dict__.pop("cifar_resnet", None)
        root.cifar_resnet.update({
            "loader": {"minibatch_size": 50, "n_train": 400,
                       "n_valid": 100},
            "decision": {"max_epochs": 3, "fail_iterations": 10},
        })
        from veles_tpu.samples import cifar_resnet
        wf = cifar_resnet.build(fused=True)
        # two identity blocks AND the projected downsampling block
        assert sum(getattr(f, "IS_RESIDUAL", False)
                   for f in wf.forwards) == 2
        assert sum(getattr(f, "IS_RESIDUAL_PROJ", False)
                   for f in wf.forwards) == 1
        Launcher(wf, stats=False).boot()
        losses = [m["validation"]["loss"]
                  for m in wf.decision.epoch_metrics]
        assert losses[-1] < losses[0]

    def test_projection_block_grads_match_autodiff(self):
        """Downsampling block: conv(s=2) -> conv -> residual_proj(s=2).
        The projection's weight grad AND the skip-source error both come
        from one vjp — pinned against the jax.grad oracle."""
        from veles_tpu.standard_workflow import StandardWorkflow
        from veles_tpu.samples.cifar import CifarLoader
        prng.reset()
        prng.seed_all(9)
        conv = {"type": "conv_str", "n_kernels": 16, "kx": 3, "ky": 3,
                "padding": "SAME", "learning_rate": 0.02, "momentum": 0.9}
        wf = StandardWorkflow(
            None, name="resproj", loader_factory=CifarLoader,
            loader_config={"minibatch_size": 25, "n_train": 100,
                           "n_valid": 50},
            layers=[
                dict(conv),
                dict(conv, sliding=2),          # main path downsamples
                dict(conv),
                {"type": "residual_proj", "skip": 2, "n_kernels": 16,
                 "sliding": 2, "learning_rate": 0.02, "momentum": 0.9},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.02, "momentum": 0.9},
            ],
            decision_config={"max_epochs": 2, "fail_iterations": 5},
            loss_function="softmax", fused=True)
        wf.initialize()
        runner = wf._fused_runner
        proj = wf.forwards[3]
        assert proj.IS_RESIDUAL_PROJ and proj.weights.shape == (1, 1, 16,
                                                                16)
        rs = numpy.random.RandomState(2)
        x = jnp.asarray(rs.randn(8, 32, 32, 3), jnp.float32)
        labels = jnp.asarray(rs.randint(0, 10, 8), jnp.int32)
        mask = jnp.ones(8, jnp.float32)
        got, _ = runner._grads_and_metrics(runner.state, x, labels, mask)

        def loss_of(state):
            acts = runner._forward_chain(state, x, rng=None, train=True)
            return runner._loss(acts[-1], labels, mask)[1]["loss_sum"]

        want = jax.grad(loss_of)(runner.state)
        checked = 0
        for i, (g, w) in enumerate(zip(got, want)):
            if g is None:
                continue
            grad_w = g[0]
            numpy.testing.assert_allclose(
                numpy.asarray(grad_w), numpy.asarray(w["w"]),
                rtol=5e-4, atol=5e-5, err_msg="layer %d grad w" % i)
            checked += 1
        assert checked == 5   # 4 convs (incl. projection) + softmax

        # and the block trains end to end
        from veles_tpu.launcher import Launcher
        Launcher(wf, stats=False).boot()
        losses = [m["validation"]["loss"]
                  for m in wf.decision.epoch_metrics]
        assert losses[-1] < losses[0]

    def test_double_initialize_still_trains(self):
        """initialize() followed by Launcher.boot() (which initializes
        again) must NOT install a duplicate FusedStep — the stale
        duplicate used to re-dispatch every minibatch with frozen
        weights and clobber the metrics, silently freezing training
        (dormant pre-round-5 bug, exposed by this file's oracle tests).
        The extra initialize legitimately advances PRNG streams, so the
        contract is "trains correctly", not bit-equality with a
        single-init run."""
        from veles_tpu.launcher import Launcher
        wf = _build_residual_mnist(seed=13)
        wf.initialize()              # the extra initialize
        step_a = wf.fused_step
        w0 = numpy.array(wf._fused_runner.state[0]["w"])
        Launcher(wf, stats=False).boot()
        assert wf.fused_step is step_a     # no duplicate install
        w1 = numpy.array(wf._fused_runner.state[0]["w"])
        assert numpy.abs(w1 - w0).max() > 0   # weights actually moved
        losses = [m["validation"]["loss"]
                  for m in wf.decision.epoch_metrics]
        assert losses[-1] < losses[0]

    def test_projection_rejects_fixed_keys(self):
        from veles_tpu.ops.residual import ResidualProjection
        with pytest.raises(ValueError, match="kx"):
            ResidualProjection(None, skip=2, n_kernels=8, kx=3)
        with pytest.raises(ValueError, match="bias-free"):
            ResidualProjection(None, skip=2, n_kernels=8,
                               include_bias=True)

    def test_projection_shape_mismatch_raises(self):
        from veles_tpu.standard_workflow import StandardWorkflow
        from veles_tpu.samples.cifar import CifarLoader
        prng.reset()
        prng.seed_all(9)
        wf = StandardWorkflow(
            None, name="resproj_bad", loader_factory=CifarLoader,
            loader_config={"minibatch_size": 25, "n_train": 100,
                           "n_valid": 50},
            layers=[
                {"type": "conv_str", "n_kernels": 16, "kx": 3, "ky": 3,
                 "padding": "SAME", "sliding": 2, "learning_rate": 0.02,
                 "momentum": 0.9},
                # stride-1 projection cannot match the downsampled path
                {"type": "residual_proj", "skip": 1, "n_kernels": 16,
                 "learning_rate": 0.02, "momentum": 0.9},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.02, "momentum": 0.9},
            ],
            decision_config={"max_epochs": 1, "fail_iterations": 5},
            loss_function="softmax", fused=True)
        with pytest.raises(ValueError, match="projected skip shape"):
            wf.initialize()

    def test_epoch_scan_matches_graph_loop(self):
        """The residual backward rides the epoch-scan path identically
        (same composed step functions)."""
        from veles_tpu.launcher import Launcher
        wf_a = _build_residual_mnist(seed=7)
        Launcher(wf_a, stats=False).boot()
        wf_b = _build_residual_mnist(seed=7)
        Launcher(wf_b, stats=False, epoch_scan=1).boot()
        for fa, fb in zip(wf_a.forwards, wf_b.forwards):
            if fa.has_params:
                numpy.testing.assert_allclose(
                    numpy.asarray(fa.weights.mem),
                    numpy.asarray(fb.weights.mem), rtol=2e-5, atol=2e-6)
