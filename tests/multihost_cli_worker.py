"""Worker for the CLI-level multi-host test (tests/test_multihost.py).

Unlike multihost_worker.py (which drives ShardedTrainer directly), this
goes through the PRODUCT path users get from ``--distributed``:
``Launcher.boot(distributed=True)`` — SPMD loader sharding from the
launcher-built mesh, FusedStep routing minibatches through
ShardedTrainer.train_step_pending, Decision/FusedCommit unchanged.
Prints the per-epoch decision metrics + final-weight checksum so the
parent test can assert both processes agree AND match a plain
single-process run (multi-host changes the wiring, not the math).
"""

import json
import os
import sys


def main(coordinator, num_processes, process_id, epoch_scan=0):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy
    from veles_tpu import prng
    from veles_tpu.config import root
    from veles_tpu.launcher import Launcher

    prng.reset()
    prng.seed_all(1)
    root.mnist.update({
        "loader": {"minibatch_size": 32, "n_train": 128, "n_valid": 32},
        "decision": {"max_epochs": 2, "fail_iterations": 5},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.05, "momentum": 0.9},
        ],
    })
    from veles_tpu.samples import mnist
    wf = mnist.build(fused=True)
    Launcher(wf, distributed=True, coordinator_address=coordinator,
             num_processes=num_processes, process_id=process_id,
             stats=False, epoch_scan=epoch_scan).boot()
    assert getattr(wf, "_sharded_trainer", None) is not None
    assert wf._sharded_trainer.multiprocess
    assert wf.loader.local_minibatch_size < 32   # really sharded rows
    epochs = [{s: {k: v for k, v in m.items()
                   if isinstance(v, (int, float))}
               for s, m in em.items()}
              for em in wf.decision.epoch_metrics]
    w0 = numpy.asarray(wf.forwards[0].weights.mem)
    print("METRICS " + json.dumps({
        "epochs": epochs,
        "best": wf.decision.best_metric,
        "wsum": float(numpy.abs(w0).sum()),
    }))


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
         int(sys.argv[4]) if len(sys.argv) > 4 else 0)
