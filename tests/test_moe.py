"""MoE FFN + expert parallelism (SURVEY §2.5 beyond-parity EP axis)."""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu import prng
from veles_tpu.ops.moe import (init_moe_params, moe_ffn, moe_ffn_ep,
                               router_probs)

D_MODEL, D_FF, N_EXPERTS = 16, 32, 4


def _setup(seed=11):
    prng.reset()
    prng.seed_all(seed)
    params = jax.tree.map(
        jnp.asarray,
        init_moe_params(prng.get("init"), D_MODEL, D_FF, N_EXPERTS))
    rng = numpy.random.RandomState(3)
    x = jnp.asarray(rng.randn(6, 9, D_MODEL).astype(numpy.float32))
    return params, x


def test_single_device_routing_semantics():
    """Each token's output is its top-1 expert's FFN scaled by the gate."""
    params, x = _setup()
    out = moe_ffn(params, x)
    assert out.shape == x.shape
    probs = router_probs(params, x)
    top = numpy.asarray(jnp.argmax(probs, axis=-1))
    flat = numpy.asarray(x.reshape(-1, D_MODEL))
    outf = numpy.asarray(out.reshape(-1, D_MODEL))
    # recompute token 0's expert by hand
    e = int(top[0])
    h = numpy.maximum(
        flat[0] @ numpy.asarray(params["w1"][e])
        + numpy.asarray(params["b1"][e]), 0.0)
    manual = (h @ numpy.asarray(params["w2"][e])
              + numpy.asarray(params["b2"][e]))
    gate = float(numpy.asarray(probs)[0, e])
    numpy.testing.assert_allclose(outf[0], manual * gate, rtol=2e-5,
                                  atol=1e-6)


@pytest.mark.parametrize("n_shards", [2, 4])
def test_expert_parallel_matches_single_device(n_shards):
    """EP over the 'expert' mesh axis == single-device MoE, values AND
    gradients."""
    from jax.sharding import Mesh
    params, x = _setup()
    mesh = Mesh(numpy.array(jax.devices()[:n_shards]), ("expert",))

    def loss_single(p):
        return (moe_ffn(p, x) ** 2).sum()

    def loss_ep(p):
        return (moe_ffn_ep(p, x, mesh) ** 2).sum()

    ref, ref_grads = jax.value_and_grad(loss_single)(params)
    out, out_grads = jax.value_and_grad(loss_ep)(params)
    numpy.testing.assert_allclose(float(out), float(ref), rtol=2e-5)
    jax.tree.map(
        lambda a, b: numpy.testing.assert_allclose(
            numpy.asarray(a), numpy.asarray(b), rtol=2e-4, atol=1e-5),
        out_grads, ref_grads)


def test_expert_count_guard():
    from jax.sharding import Mesh
    params, x = _setup()
    mesh = Mesh(numpy.array(jax.devices()[:3]), ("expert",))
    with pytest.raises(ValueError, match="n_experts"):
        moe_ffn_ep(params, x, mesh)
