"""MoE FFN + expert parallelism (SURVEY §2.5 beyond-parity EP axis)."""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu import prng
from veles_tpu.ops.moe import (init_moe_params, moe_ffn, moe_ffn_ep,
                               router_probs)

D_MODEL, D_FF, N_EXPERTS = 16, 32, 4


def _setup(seed=11):
    prng.reset()
    prng.seed_all(seed)
    params = jax.tree.map(
        jnp.asarray,
        init_moe_params(prng.get("init"), D_MODEL, D_FF, N_EXPERTS))
    rng = numpy.random.RandomState(3)
    x = jnp.asarray(rng.randn(6, 9, D_MODEL).astype(numpy.float32))
    return params, x


def test_single_device_routing_semantics():
    """Each token's output is its top-1 expert's FFN scaled by the gate."""
    params, x = _setup()
    out = moe_ffn(params, x)
    assert out.shape == x.shape
    probs = router_probs(params, x)
    top = numpy.asarray(jnp.argmax(probs, axis=-1))
    flat = numpy.asarray(x.reshape(-1, D_MODEL))
    outf = numpy.asarray(out.reshape(-1, D_MODEL))
    # recompute token 0's expert by hand
    e = int(top[0])
    h = numpy.maximum(
        flat[0] @ numpy.asarray(params["w1"][e])
        + numpy.asarray(params["b1"][e]), 0.0)
    manual = (h @ numpy.asarray(params["w2"][e])
              + numpy.asarray(params["b2"][e]))
    gate = float(numpy.asarray(probs)[0, e])
    numpy.testing.assert_allclose(outf[0], manual * gate, rtol=2e-5,
                                  atol=1e-6)


@pytest.mark.parametrize("n_shards", [2, 4])
def test_expert_parallel_matches_single_device(n_shards):
    """EP over the 'expert' mesh axis == single-device MoE, values AND
    gradients."""
    from jax.sharding import Mesh
    params, x = _setup()
    mesh = Mesh(numpy.array(jax.devices()[:n_shards]), ("expert",))

    def loss_single(p):
        return (moe_ffn(p, x) ** 2).sum()

    def loss_ep(p):
        return (moe_ffn_ep(p, x, mesh) ** 2).sum()

    ref, ref_grads = jax.value_and_grad(loss_single)(params)
    out, out_grads = jax.value_and_grad(loss_ep)(params)
    numpy.testing.assert_allclose(float(out), float(ref), rtol=2e-5)
    jax.tree.map(
        lambda a, b: numpy.testing.assert_allclose(
            numpy.asarray(a), numpy.asarray(b), rtol=2e-4, atol=1e-5),
        out_grads, ref_grads)


def test_load_balancing_loss_semantics():
    """1.0 at perfect balance; grows toward E as routing collapses."""
    from veles_tpu.ops.moe import load_balancing_loss
    e, t = 4, 400
    balanced_onehot = jnp.eye(e)[jnp.arange(t) % e]
    uniform_probs = jnp.full((t, e), 1.0 / e)
    numpy.testing.assert_allclose(
        float(load_balancing_loss(uniform_probs, balanced_onehot)), 1.0,
        rtol=1e-6)
    collapsed_probs = jnp.zeros((t, e)).at[:, 0].set(1.0)
    collapsed_onehot = jnp.zeros((t, e)).at[:, 0].set(1.0)
    numpy.testing.assert_allclose(
        float(load_balancing_loss(collapsed_probs, collapsed_onehot)),
        float(e), rtol=1e-6)


def test_aux_loss_spreads_experts():
    """Training WITH the aux loss routes tokens across more experts than
    training without it (the collapse the loss exists to prevent)."""
    from veles_tpu.ops.transformer import (init_transformer_params,
                                           lm_loss)
    rng = numpy.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 16, (16, 24)), jnp.int32)
    mask = jnp.ones(16, jnp.float32)

    def train(coef, steps=60):
        prng.reset()
        prng.seed_all(2)
        params = jax.tree.map(jnp.asarray, init_transformer_params(
            prng.get("init"), 16, d_model=16, n_heads=2, n_layers=1,
            max_len=32, n_experts=4))
        grad = jax.jit(jax.grad(
            lambda p: lm_loss(p, tokens, mask, 2, moe_aux_coef=coef)))
        for _ in range(steps):
            g = grad(params)
            params = jax.tree.map(lambda a, b: a - 0.05 * b, params, g)
        probs = router_probs(params["blocks"][0]["moe"],
                             jnp.take(params["embed"], tokens, axis=0))
        top = numpy.asarray(jnp.argmax(probs, axis=-1))
        return len(numpy.unique(top))

    assert train(coef=1e-2) >= train(coef=0.0)
    assert train(coef=1e-2) >= 2  # aux keeps multiple experts live


def test_expert_count_guard():
    from jax.sharding import Mesh
    params, x = _setup()
    mesh = Mesh(numpy.array(jax.devices()[:3]), ("expert",))
    with pytest.raises(ValueError, match="n_experts"):
        moe_ffn_ep(params, x, mesh)
