"""Tier-1 workflow scheduler tests: ordering, cycles, stop, stats, graph."""

from veles_tpu.units import Unit, TrivialUnit
from veles_tpu.workflow import Workflow, Repeater
from veles_tpu.mutable import Bool


class Recorder(Unit):
    def __init__(self, workflow, log, **kwargs):
        super().__init__(workflow, **kwargs)
        self.log = log

    def run(self):
        self.log.append(self.name)


def test_linear_chain_order():
    wf = Workflow(None, name="wf")
    log = []
    units = [Recorder(wf, log, name="u%d" % i) for i in range(4)]
    units[0].link_from(wf.start_point)
    for prev, nxt in zip(units, units[1:]):
        nxt.link_from(prev)
    wf.end_point.link_from(units[-1])
    wf.run()
    assert log == ["u0", "u1", "u2", "u3"]
    assert wf.is_finished


def test_diamond_join_runs_once():
    wf = Workflow(None, name="wf")
    log = []
    a = Recorder(wf, log, name="a")
    b = Recorder(wf, log, name="b")
    c = Recorder(wf, log, name="c")
    d = Recorder(wf, log, name="d")
    a.link_from(wf.start_point)
    b.link_from(a)
    c.link_from(a)
    d.link_from(b, c)            # AND join
    wf.end_point.link_from(d)
    wf.run()
    assert log.count("d") == 1
    assert set(log) == {"a", "b", "c", "d"}


def test_repeater_cycle_terminates_via_gate():
    """The canonical training-loop shape: repeater -> body -> repeater,
    end point gated on a completion Bool (SURVEY §1: the training loop is a
    cycle in the graph)."""
    wf = Workflow(None, name="wf")
    log = []
    complete = Bool(False)

    class Body(Recorder):
        def run(self):
            super().run()
            if len(self.log) >= 5:
                complete.set(True)

    rep = Repeater(wf, name="rep")
    body = Body(wf, log, name="body")
    rep.link_from(wf.start_point)
    body.link_from(rep)
    rep.link_from(body)          # closes the cycle
    wf.end_point.link_from(body)
    wf.end_point.gate_block = ~complete
    body.gate_block = complete
    wf.run()
    assert log == ["body"] * 5
    assert wf.is_finished


def test_stop_mid_run():
    wf = Workflow(None, name="wf")
    log = []

    class Stopper(Recorder):
        def run(self):
            super().run()
            self.workflow.stop()

    rep = Repeater(wf, name="rep")
    s = Stopper(wf, log, name="s")
    rep.link_from(wf.start_point)
    s.link_from(rep)
    rep.link_from(s)
    wf.run()
    assert log == ["s"]
    assert not wf.is_finished


def test_initialize_deferred_ordering():
    from veles_tpu.workflow import DeferredInitError

    wf = Workflow(None, name="wf")
    order = []

    class Producer(TrivialUnit):
        def initialize(self, **kwargs):
            self.ready = True
            order.append("producer")
            super().initialize(**kwargs)

    class Consumer(TrivialUnit):
        def initialize(self, **kwargs):
            if not getattr(producer, "ready", False):
                raise DeferredInitError()
            order.append("consumer")
            super().initialize(**kwargs)

    # Construction order is consumer-first to force the deferral path.
    consumer = Consumer(wf, name="consumer")
    producer = Producer(wf, name="producer")
    wf.initialize()
    assert order == ["producer", "consumer"]


def test_run_stats_accounting():
    wf = Workflow(None, name="wf")
    log = []
    a = Recorder(wf, log, name="a")
    a.link_from(wf.start_point)
    wf.end_point.link_from(a)
    wf.run()
    assert a.run_count == 1
    assert a.run_time >= 0.0
    wf.print_stats()


def test_generate_graph_dot():
    wf = Workflow(None, name="wf")
    a = TrivialUnit(wf, name="a")
    a.link_from(wf.start_point)
    wf.end_point.link_from(a)
    dot = wf.generate_graph()
    assert "digraph" in dot and "->" in dot and '"a"' in dot


def test_duplicate_unit_names_get_suffixed():
    wf = Workflow(None, name="wf")
    a = TrivialUnit(wf)
    b = TrivialUnit(wf)
    c = TrivialUnit(wf)
    names = {a.name, b.name, c.name}
    assert len(names) == 3            # snapshot state keys stay unique
