"""Repo tools: the trace analyzer (tools/trace_analyze.py) against a
synthetic Chrome trace, and the committed round-4 artifact."""

import gzip
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import trace_analyze  # noqa: E402


def _synthetic_trace(path, steps=4):
    """2 heavy ops x `steps` + one while wrapper, with metadata."""
    events = [
        {"ph": "M", "pid": 1, "tid": 7, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "X", "pid": 1, "tid": 7, "name": "while.1", "ts": 0,
         "dur": 4000 * steps,
         "args": {"hlo_category": "while"}},
    ]
    for i in range(steps):
        events.append({
            "ph": "X", "pid": 1, "tid": 7, "name": "fusion.1",
            "ts": 4000 * i, "dur": 3000,
            "args": {"hlo_category": "convolution fusion",
                     "model_flops": "6000000000",
                     "bytes_accessed": "1000000"}})
        events.append({
            "ph": "X", "pid": 1, "tid": 7, "name": "fusion.2",
            "ts": 4000 * i + 3000, "dur": 1000,
            "args": {"hlo_category": "loop fusion",
                     "model_flops": "0",
                     "bytes_accessed": "2000000"}})
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)
    return path


def test_analyze_synthetic(tmp_path):
    path = _synthetic_trace(str(tmp_path / "t.trace.json.gz"), steps=4)
    res = trace_analyze.analyze(path)
    assert res["steps"] == 4                   # inferred modal count
    assert res["total_ms_per_step"] == pytest.approx(4.0)
    rows = {r["op"]: r for r in res["rows"]}
    conv = rows["fusion.1"]
    assert conv["ms_per_step"] == pytest.approx(3.0)
    assert conv["category"] == "convolution fusion"
    # 6 GFLOP in 3ms => 2 TF/s; 1 MB in 3ms => ~0.33 GB/s
    assert conv["tflops"] == pytest.approx(2.0)
    assert rows["fusion.2"]["gbps"] == pytest.approx(2.0)
    # the while wrapper is excluded from rows
    assert "while.1" not in rows


def test_analyze_committed_round4_artifact():
    """The committed AlexNet trace stays parseable and the PERF.md
    headline numbers stay reproducible from it."""
    path = os.path.join(REPO, "docs", "traces",
                        "alexnet_r4_step60ms.trace.json.gz")
    res = trace_analyze.analyze(path)
    assert res["steps"] == 8
    assert 40.0 < res["total_ms_per_step"] < 43.0       # 41.3 ms/step
    top = res["rows"][0]
    assert top["category"] == "convolution fusion"
    assert 3.5 < top["ms_per_step"] < 4.5
