"""Repo tools: the trace analyzer (tools/trace_analyze.py) against a
synthetic Chrome trace, the committed round-4 artifact, the serving
trace report (tools/trace_report.py), and the streamed-summary-record
schema guard (tools/check_stream_records.py)."""

import gzip
import json
import os
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import trace_analyze  # noqa: E402


def _synthetic_trace(path, steps=4):
    """2 heavy ops x `steps` + one while wrapper, with metadata."""
    events = [
        {"ph": "M", "pid": 1, "tid": 7, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "X", "pid": 1, "tid": 7, "name": "while.1", "ts": 0,
         "dur": 4000 * steps,
         "args": {"hlo_category": "while"}},
    ]
    for i in range(steps):
        events.append({
            "ph": "X", "pid": 1, "tid": 7, "name": "fusion.1",
            "ts": 4000 * i, "dur": 3000,
            "args": {"hlo_category": "convolution fusion",
                     "model_flops": "6000000000",
                     "bytes_accessed": "1000000"}})
        events.append({
            "ph": "X", "pid": 1, "tid": 7, "name": "fusion.2",
            "ts": 4000 * i + 3000, "dur": 1000,
            "args": {"hlo_category": "loop fusion",
                     "model_flops": "0",
                     "bytes_accessed": "2000000"}})
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)
    return path


def test_analyze_synthetic(tmp_path):
    path = _synthetic_trace(str(tmp_path / "t.trace.json.gz"), steps=4)
    res = trace_analyze.analyze(path)
    assert res["steps"] == 4                   # inferred modal count
    assert res["total_ms_per_step"] == pytest.approx(4.0)
    rows = {r["op"]: r for r in res["rows"]}
    conv = rows["fusion.1"]
    assert conv["ms_per_step"] == pytest.approx(3.0)
    assert conv["category"] == "convolution fusion"
    # 6 GFLOP in 3ms => 2 TF/s; 1 MB in 3ms => ~0.33 GB/s
    assert conv["tflops"] == pytest.approx(2.0)
    assert rows["fusion.2"]["gbps"] == pytest.approx(2.0)
    # the while wrapper is excluded from rows
    assert "while.1" not in rows


def test_analyze_committed_round4_artifact():
    """The committed AlexNet trace stays parseable and the PERF.md
    headline numbers stay reproducible from it."""
    path = os.path.join(REPO, "docs", "traces",
                        "alexnet_r4_step60ms.trace.json.gz")
    res = trace_analyze.analyze(path)
    assert res["steps"] == 8
    assert 40.0 < res["total_ms_per_step"] < 43.0       # 41.3 ms/step
    top = res["rows"][0]
    assert top["category"] == "convolution fusion"
    assert 3.5 < top["ms_per_step"] < 4.5


def test_check_stream_records_builtin_contract():
    """ISSUE 12 satellite, tier-1 (<30s): every streaming tool's
    summary_record — bench.py, lm_bench, chaos_bench, profile_ops,
    trace_report — carries the shared required keys even for the
    empty-results worst case, so a schema drift fails HERE instead of
    silently breaking bench_report.py."""
    import check_stream_records
    assert check_stream_records.check_builtin() == []


def test_check_stream_records_flags_bad_lines():
    import check_stream_records
    good = json.dumps({"metric": "m", "value": 1, "unit": "x",
                       "vs_baseline": None, "configs": {}})
    assert check_stream_records.check_line(good) == []
    # missing keys, non-JSON, empty metric, NaN all flagged
    assert check_stream_records.check_line(json.dumps({"metric": "m"}))
    assert check_stream_records.check_line("{not json")
    assert check_stream_records.check_line(json.dumps(
        {"metric": "", "value": 1, "unit": "x", "vs_baseline": None,
         "configs": {}}))
    nan = ('{"metric": "m", "value": NaN, "unit": "x", '
           '"vs_baseline": null, "configs": {}}')
    assert check_stream_records.check_line(nan)
    # a stream with one bad line among good ones names its line number
    problems = check_stream_records.check_stream(
        good + "\n" + "{broken\n" + good, "s")
    assert len(problems) == 1 and "s:2" in problems[0]


def test_trace_report_roundtrip(tmp_path, capsys):
    """tools/trace_report.py rebuilds per-request records from an
    exported Chrome trace: waterfall renders, ledger dedups batched
    dispatches, integrity check passes, and the streamed summary
    lines honor the shared record schema."""
    import check_stream_records
    import trace_report
    from veles_tpu.serving.tracing import SpanTracer
    tr = SpanTracer(mode="all", last=8)
    a = tr.start_request(rid="req-a", name="http.request", cat="http")
    b = tr.start_request(rid="req-b", name="http.request", cat="http")
    t = time.monotonic()
    tr.add_many([a, b], "decode.step", "decode", t, t + 0.004,
                attrs={"backend": "xla", "bucket": 4})
    tr.add_many([a], "prefill.chunk", "prefill", t, t + 0.002,
                attrs={"backend": "xla", "bucket": 8})
    tr.finish_request(a)
    tr.finish_request(b, error=RuntimeError("boom"))
    path = str(tmp_path / "serve.trace.json")
    with open(path, "w") as f:
        json.dump(tr.export_chrome(), f)
    rc = trace_report.main([path, "--all", "--check",
                            "--ledger-json",
                            str(tmp_path / "ledger.json")])
    assert rc == 0
    out = capsys.readouterr()
    # stdout lines are all schema-conforming records, last-line-wins
    assert check_stream_records.check_stream(out.out) == []
    last = json.loads(out.out.strip().splitlines()[-1])
    assert last["metric"] == "trace_ledger_dispatches"
    # the 2-lane decode.step dedups to ONE dispatch + one prefill
    assert last["value"] == 2
    assert last["configs"]["requests"] == 2
    assert last["configs"]["errored"] == 1
    # waterfalls went to stderr for both requests (req-b also shows
    # up once more as the auto-dump log line from finish_request)
    assert "request req-a" in out.err and "request req-b" in out.err
    ledger = json.load(open(str(tmp_path / "ledger.json")))["ledger"]
    by_op = {r["op"]: r for r in ledger}
    assert by_op["decode.step"]["dispatches"] == 1
    assert by_op["decode.step"]["lanes"] == 2


def test_trace_report_unknown_request_errors(tmp_path, capsys):
    import trace_report
    from veles_tpu.serving.tracing import SpanTracer
    tr = SpanTracer(mode="all")
    tr.finish_request(tr.start_request(rid="only"))
    path = str(tmp_path / "t.json")
    with open(path, "w") as f:
        json.dump(tr.export_chrome(), f)
    assert trace_report.main([path, "--request", "nope"]) == 1
    capsys.readouterr()
