"""Trace report (ISSUE 12): per-request waterfalls + the per-op cost
ledger from an exported serving trace.

Input is the Chrome-trace JSON the serving stack exports — ``GET
/trace.json`` on a ``--serve-trace``-armed server, or
``SpanTracer.export_chrome()`` written to a file (``.json`` or
``.json.gz``).  Two views:

- WATERFALL — one request's span timeline as indented ASCII bars
  (``--request RID``; default: the slowest request, ``--all`` for every
  request).  The same rendering the flight recorder dumps on
  error/deadline.
- COST LEDGER — every device-dispatch span aggregated into (op family
  x bucket x backend) rows with dispatch count and p50/p95/mean
  duration: the measured per-op cost table the ROADMAP's
  cost-model-driven autotuning item needs.  Batched spans (one decode
  tick, many lanes) are deduplicated by dispatch id, so counts are
  device programs launched.

A bench.py-style summary JSON line (metric/value/unit/vs_baseline/
configs) streams to stdout after each completed stage, last-line-wins —
the ledger rides in ``configs["ledger"]`` and ``--ledger-json FILE``
writes it standalone for downstream consumers.

Standalone::

    python tools/trace_report.py trace.json [--request RID | --all]
        [--last N] [--ledger-json FILE] [--json FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from trace_analyze import load_events  # noqa: E402 — the ONE
#                        gzip-aware Chrome-trace loader in tools/
from veles_tpu.serving.tracing import (cost_ledger,  # noqa: E402
                                       format_waterfall,
                                       verify_integrity)


def load_trace(path):
    """Event list of a Chrome-trace JSON file (.json or .json.gz) —
    ``trace_analyze.load_events`` plus tolerance for the bare-list
    trace form (the JSON Array Format chrome://tracing also accepts)."""
    try:
        return load_events(path)
    except (KeyError, TypeError):
        import gzip
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt", encoding="utf-8") as f:
            return list(json.load(f))


def rebuild_requests(events):
    """Reconstruct per-request span records from exported events (the
    inverse of ``SpanTracer.export_chrome``): every complete (ph X)
    event whose args carry a ``rid`` joins that request, with
    sid/parent/attrs recovered from args.  Returns records in the
    tracing-module shape (rid/error/deadline_blown/unclosed/spans), so
    ``format_waterfall`` / ``cost_ledger`` / ``verify_integrity`` all
    apply unchanged."""
    recs = {}
    flags = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            args = ev.get("args") or {}
            name = args.get("name", "")
            if "rid" in args:
                # the structured form (rid-with-spaces safe; carries
                # the real error string)
                flags[str(args["rid"])] = {
                    "error": args.get("error") or None,
                    "deadline": bool(args.get("deadline_blown"))}
            elif name.startswith("req "):
                # label-only fallback for hand-built traces
                rid = name[4:].split(" ", 1)[0]
                flags[rid] = {"error": "[ERROR]" in name,
                              "deadline": "[DEADLINE]" in name}
            continue
        if ev.get("ph") != "X":
            continue
        args = dict(ev.get("args") or {})
        rid = args.pop("rid", None)
        if rid is None:
            continue
        sid = args.pop("sid", None)
        parent = args.pop("parent", None)
        t0 = ev.get("ts", 0.0) / 1e6
        rec = recs.setdefault(rid, {"rid": rid, "error": None,
                                    "deadline_blown": False,
                                    "unclosed": [], "spans": []})
        rec["spans"].append({
            "sid": sid, "parent": parent, "name": ev.get("name", "?"),
            "cat": ev.get("cat", "span"), "t0": t0,
            "t1": t0 + ev.get("dur", 0.0) / 1e6, "attrs": args})
    for rid, f in flags.items():
        if rid in recs:
            if f["error"]:
                recs[rid]["error"] = (f["error"] if f["error"] is not
                                      True else
                                      "errored (see flight recorder)")
            recs[rid]["deadline_blown"] = f["deadline"]
    out = list(recs.values())
    out.sort(key=lambda r: min((s["t0"] for s in r["spans"]),
                               default=0.0))
    return out


def request_wall(rec):
    if not rec["spans"]:
        return 0.0
    return (max(s["t1"] for s in rec["spans"])
            - min(s["t0"] for s in rec["spans"]))


def summary_record(results):
    """(record, exit_code) in the bench.py shape — one selection rule:
    traced dispatch count once the ledger exists, request count while
    only parsing finished."""
    ledger = results.get("ledger")
    if ledger is not None:
        return {
            "metric": "trace_ledger_dispatches",
            "value": int(sum(r["dispatches"] for r in ledger)),
            "unit": "dispatches",
            "vs_baseline": None,
            "configs": results,
        }, 0
    if results.get("requests") is not None:
        return {
            "metric": "trace_requests_parsed",
            "value": results["requests"],
            "unit": "requests",
            "vs_baseline": None,
            "configs": results,
        }, 0
    return {"metric": "trace_report_empty", "value": None,
            "unit": None, "vs_baseline": None, "configs": results}, 1


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("trace", help="Chrome-trace JSON exported by "
                        "GET /trace.json or SpanTracer.export_chrome "
                        "(.json or .json.gz)")
    parser.add_argument("--request", default=None, metavar="RID",
                        help="waterfall this request id (default: the "
                             "slowest request)")
    parser.add_argument("--all", action="store_true",
                        help="waterfall every request")
    parser.add_argument("--last", type=int, default=None, metavar="N",
                        help="only the newest N requests")
    parser.add_argument("--check", action="store_true",
                        help="also verify span-tree integrity (every "
                             "request one root, no orphans, no "
                             "unclosed spans) — non-zero exit on a "
                             "violation")
    parser.add_argument("--ledger-json", default=None, metavar="FILE",
                        help="write the cost ledger rows as JSON")
    parser.add_argument("--json", default=None, metavar="FILE",
                        help="also write the final summary record here")
    args = parser.parse_args(argv)

    results = {"trace": args.trace, "requests": None}
    records = rebuild_requests(load_trace(args.trace))
    if args.last:
        records = records[-args.last:]
    results["requests"] = len(records)
    results["errored"] = sum(1 for r in records if r["error"])
    results["deadline_blown"] = sum(1 for r in records
                                    if r["deadline_blown"])
    print(json.dumps(summary_record(results)[0]), flush=True)

    if args.check:
        integrity = verify_integrity(records)   # raises on violation
        results["integrity"] = integrity
        print("span-tree integrity: %d request(s), %d span(s), clean"
              % (integrity["requests"], integrity["spans"]),
              file=sys.stderr)

    # ---- waterfall(s)
    if records:
        if args.all:
            shown = records
        elif args.request is not None:
            shown = [r for r in records if r["rid"] == args.request]
            if not shown:
                print("request %r not in this trace (have: %s)"
                      % (args.request,
                         ", ".join(r["rid"] for r in records[:20])),
                      file=sys.stderr)
                return 1
        else:
            shown = [max(records, key=request_wall)]
        for rec in shown:
            print(format_waterfall(rec), file=sys.stderr)
            print(file=sys.stderr)
        results["waterfall_requests"] = [r["rid"] for r in shown]

    # ---- the per-op cost ledger
    ledger = cost_ledger(records)
    results["ledger"] = ledger
    if ledger:
        cols = ("op", "bucket", "backend", "dispatches", "lanes",
                "p50_ms", "p95_ms", "mean_ms", "total_ms")
        widths = [max(len(c), *(len(str(r[c])) for r in ledger))
                  for c in cols]
        line = "  ".join(c.ljust(w) for c, w in zip(cols, widths))
        print(line, file=sys.stderr)
        for r in ledger:
            print("  ".join(str(r[c]).ljust(w)
                            for c, w in zip(cols, widths)),
                  file=sys.stderr)
    if args.ledger_json:
        with open(args.ledger_json, "w", encoding="utf-8") as f:
            json.dump({"ledger": ledger, "requests": len(records)}, f)

    record, rc = summary_record(results)
    line = json.dumps(record)
    print(line)                  # final full record — last line wins
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            f.write(line + "\n")
    return rc


if __name__ == "__main__":
    sys.exit(main())
