"""veles_lint — project-specific static analysis for the serving tier
(ISSUE 15).

Three review-hardening rounds (PRs 11, 12, 14) each found real
concurrency violations by hand; this tool makes the rules they were
checking executable.  Two static passes (the runtime third — the
lock-order witness — lives in ``veles_tpu/serving/lockcheck.py``):

LOCK DISCIPLINE.  A class declares which attributes its lock guards::

    class Router:
        _guarded_by = {"_live": "_lock", "_jobs": "_lock"}

or per attribute, with a trailing comment on the assignment::

    self._queue = collections.deque()   # guarded-by: _cond

The pass walks every method and flags any read or write of a guarded
attribute that is not (a) inside a ``with self.<lock>:`` block, (b) in
a method marked ``# caller-holds: <lock>`` (placed on the ``def`` line
or directly under it, before the first real statement), or (c) in
``__init__`` (no concurrency before construction completes).  A call
``self.helper()`` where ``helper`` is marked ``# caller-holds: X``
and ``X`` is not held at the call site is flagged too — the broken
caller-holds CHAIN is exactly the bug class PR 12's review caught by
hand.  Module-level globals ride the same pass via a trailing
``# guarded-by: <lock>`` on the global's assignment (the metrics
registry, the default telemetry store).

Classes that are deliberately lock-free declare why::

    _synchronized_externally = "engine worker thread (single owner)"

TRACED PURITY.  Every function the engine jits or scans — discovered
from ``self._jit(...)`` / ``jax.jit(...)`` / ``lax.scan(...)`` call
sites plus the explicit ``TRACED_REGISTRY`` below — must be pure host-
side: the pass walks its call graph (same module, and one import hop
into project modules) and flags ``time.*``, ``random`` /
``numpy.random`` (``veles_tpu.prng`` is exempt — counter-based,
trace-safe by design), threading primitives, ``print``, and mutation
of closed-over containers.  A ``time.time()`` baked into a scanned
body is a constant at trace time — the class of bug that silently
costs a TPU window (PAPERS.md, the Julia-to-TPU compilation paper).

SUPPRESSIONS are per-site, named and greppable::

    x = self._queue  # lint: allow(lock-discipline): benign racy peek

Every suppression must carry a non-empty reason; a reasonless or
UNUSED suppression is itself a finding, so the exception list can
never rot.

Run standalone (``python tools/veles_lint.py --check``) — findings to
stderr, one bench.py-style summary record streamed to stdout — or via
tier-1 (``tests/test_lint.py`` runs the full-tree check), so a future
unguarded access fails the suite, not a review round.
"""

from __future__ import annotations

import argparse
import ast
import io
import json
import os
import re
import sys
import tokenize

TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS_DIR)

#: the serving modules the lock-discipline pass covers (ISSUE 15) —
#: every module that owns a lock or declares external synchronization
SERVING_MODULES = (
    "veles_tpu/serving/lm_engine.py",
    "veles_tpu/serving/router.py",
    "veles_tpu/serving/batcher.py",
    "veles_tpu/serving/kv_pool.py",
    "veles_tpu/serving/metrics.py",
    "veles_tpu/serving/tracing.py",
    "veles_tpu/serving/timeseries.py",
    "veles_tpu/serving/slo.py",
    "veles_tpu/serving/model_manager.py",
    "veles_tpu/serving/faults.py",
    "veles_tpu/serving/lockcheck.py",
)

#: traced-purity entry points beyond what call-site discovery finds:
#: (path suffix, bare function name) — functions RETURNED by builders
#: and jitted indirectly, or library functions every traced body runs
TRACED_REGISTRY = (
    ("veles_tpu/serving/lm_engine.py", "mega_plain"),
    ("veles_tpu/serving/lm_engine.py", "mega_spec"),
    ("veles_tpu/serving/lm_engine.py", "plain_iter"),
    ("veles_tpu/serving/lm_engine.py", "spec_iter"),
    ("veles_tpu/ops/transformer.py", "propose_draft_in_graph"),
)

#: modules the purity pass scans for jit/scan call sites
PURITY_MODULES = (
    "veles_tpu/serving/lm_engine.py",
    "veles_tpu/ops/transformer.py",
)

CHECKS = ("lock-discipline", "traced-purity", "suppression")

SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*allow\((?P<check>[\w-]+)\)\s*:?\s*(?P<reason>.*)")
GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(?P<lock>\w+)")
HOLDS_RE = re.compile(r"#\s*caller-holds:\s*(?P<locks>[\w\s,]+)")

#: mutating container methods (closed-over mutation detection)
MUTATORS = frozenset((
    "append", "extend", "insert", "remove", "pop", "popleft",
    "appendleft", "clear", "update", "setdefault", "add", "discard",
    "sort", "reverse",
))

#: dotted-call prefixes that are impure on a traced path
IMPURE_PREFIXES = (
    "time.", "random.", "numpy.random.", "np.random.", "threading.",
    "os.urandom", "secrets.",
)
IMPURE_BARE = frozenset(("print", "input", "open"))

#: prefixes exempt from the random rule — the project's counter-based
#: PRNG is trace-safe by design (veles_tpu/prng.py)
PURE_PREFIXES = ("prng.",)


class Finding:
    __slots__ = ("file", "line", "check", "message")

    def __init__(self, file, line, check, message):
        self.file = file
        self.line = int(line)
        self.check = check
        self.message = message

    def __repr__(self):
        return "%s:%d: [%s] %s" % (self.file, self.line, self.check,
                                   self.message)

    def to_dict(self):
        return {"file": self.file, "line": self.line,
                "check": self.check, "message": self.message}


class Suppression:
    __slots__ = ("file", "line", "check", "reason", "standalone",
                 "used")

    def __init__(self, file, line, check, reason, standalone):
        self.file = file
        self.line = int(line)
        self.check = check
        self.reason = reason.strip()
        #: a comment-only line (covers the statement BELOW it); a
        #: trailing comment covers its own line only
        self.standalone = bool(standalone)
        self.used = False


def _comments(src):
    """({lineno: comment text}, {standalone linenos}) over ``src`` —
    standalone marks comment-only lines (tokenize survives anything
    that parses as Python)."""
    out, standalone = {}, set()
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
                if not tok.line[:tok.start[1]].strip():
                    standalone.add(tok.start[0])
    except tokenize.TokenError:
        pass
    return out, standalone


def _suppressions(relpath, comments, standalone):
    """Every ``# lint: allow(check): reason`` site in the file, plus a
    finding for each malformed one (unknown check / missing reason)."""
    sups, findings = [], []
    for line, text in comments.items():
        m = SUPPRESS_RE.search(text)
        if not m:
            continue
        check, reason = m.group("check"), m.group("reason").strip()
        if check not in CHECKS:
            findings.append(Finding(
                relpath, line, "suppression",
                "unknown check %r in suppression (one of %r)"
                % (check, CHECKS)))
            continue
        if not reason:
            findings.append(Finding(
                relpath, line, "suppression",
                "suppression carries no reason string — every "
                "exception must say why"))
            continue
        sups.append(Suppression(relpath, line, check, reason,
                                line in standalone))
    return sups, findings


def _suppressed(sups, line, check):
    """A TRAILING suppression covers exactly its own line; a
    STANDALONE comment-line suppression covers exactly the statement
    directly below it — never both, so one comment can never swallow
    a second, unrelated finding on the next line."""
    for s in sups:
        if s.check == check \
                and line == (s.line + 1 if s.standalone else s.line):
            s.used = True
            return True
    return False


def _dotted(node):
    """'a.b.c' for an Attribute/Name chain, or None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# --------------------------------------------------------------- lock pass
def _caller_holds(fn, comments):
    """The locks a method declares its caller holds: a ``#
    caller-holds: X[, Y]`` comment on the ``def`` line or between it
    and the first real (non-docstring) statement."""
    if not fn.body:
        return frozenset()
    first = fn.body[0]
    end = first.lineno
    if (isinstance(first, ast.Expr)
            and isinstance(first.value, ast.Constant)
            and isinstance(first.value.value, str)):
        end = (fn.body[1].lineno if len(fn.body) > 1
               else first.end_lineno or first.lineno)
    locks = set()
    for line in range(fn.lineno, end + 1):
        m = HOLDS_RE.search(comments.get(line, ""))
        if m:
            locks.update(x.strip() for x in
                         m.group("locks").split(",") if x.strip())
    return frozenset(locks)


class _ClassLint:
    """Lock-discipline over one class: guard map, caller-holds chain,
    with-block tracking."""

    def __init__(self, relpath, cls, comments, sups, findings):
        self.relpath = relpath
        self.cls = cls
        self.comments = comments
        self.sups = sups
        self.findings = findings
        self.guard = {}          # attr -> lock
        self.external = None
        self.holds = {}          # method name -> frozenset(locks)
        self._collect()
        self.locks = frozenset(self.guard.values())

    def _collect(self):
        for node in self.cls.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if name == "_guarded_by" \
                        and isinstance(node.value, ast.Dict):
                    for k, v in zip(node.value.keys, node.value.values):
                        if isinstance(k, ast.Constant) \
                                and isinstance(v, ast.Constant):
                            self.guard[str(k.value)] = str(v.value)
                elif name == "_synchronized_externally" \
                        and isinstance(node.value, ast.Constant):
                    self.external = str(node.value.value)
                    if not self.external.strip():
                        self.findings.append(Finding(
                            self.relpath, node.lineno, "lock-discipline",
                            "_synchronized_externally must name the "
                            "owner (empty string)"))
        # trailing `# guarded-by:` comments on self.<attr> assignments
        for fn in self._methods():
            for node in ast.walk(fn):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                m = GUARDED_RE.search(
                    self.comments.get(node.lineno, ""))
                if not m:
                    continue
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        self.guard[t.attr] = m.group("lock")
        for fn in self._methods():
            self.holds[fn.name] = _caller_holds(fn, self.comments)

    def _methods(self):
        return [n for n in self.cls.body
                if isinstance(n, (ast.FunctionDef,
                                  ast.AsyncFunctionDef))]

    def run(self):
        if not self.guard:
            return
        for fn in self._methods():
            if fn.name == "__init__":
                continue
            self._walk_stmts(fn.body, self.holds.get(fn.name,
                                                     frozenset()))

    def _lock_of_with_item(self, item):
        expr = item.context_expr
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and expr.attr in self.locks:
            return expr.attr
        return None

    def _walk_stmts(self, stmts, held):
        for stmt in stmts:
            self._walk(stmt, held)

    def _walk(self, node, held):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newly = set()
            for item in node.items:
                lock = self._lock_of_with_item(item)
                if lock:
                    newly.add(lock)
                else:
                    self._walk(item.context_expr, held)
            self._walk_stmts(node.body, held | newly)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested function runs LATER, on whatever thread calls
            # it — it holds nothing unless it says so itself
            inner = _caller_holds(node, self.comments)
            self._walk_stmts(node.body, frozenset(inner))
            return
        if isinstance(node, ast.Lambda):
            self._walk(node.body, frozenset())
            return
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            attr = node.attr
            lock = self.guard.get(attr)
            if lock is not None and lock not in held \
                    and not _suppressed(self.sups, node.lineno,
                                        "lock-discipline"):
                kind = ("write" if isinstance(node.ctx, (ast.Store,
                                                         ast.Del))
                        else "read")
                self.findings.append(Finding(
                    self.relpath, node.lineno, "lock-discipline",
                    "%s of %s.%s (guarded by %s) outside `with "
                    "self.%s:` and no `# caller-holds: %s` marker"
                    % (kind, self.cls.name, attr, lock, lock, lock)))
            return      # leaf: Name('self') below needs no recursion
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            callee = node.func.attr
            missing = self.holds.get(callee, frozenset()) - held
            if missing and not _suppressed(self.sups, node.lineno,
                                           "lock-discipline"):
                self.findings.append(Finding(
                    self.relpath, node.lineno, "lock-discipline",
                    "call to %s.%s() (# caller-holds: %s) without "
                    "holding %s — caller-holds chain broken"
                    % (self.cls.name, callee,
                       ", ".join(sorted(self.holds[callee])),
                       ", ".join(sorted(missing)))))
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                self._walk(arg, held)
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child, held)


class _ModuleGlobalsLint:
    """Lock discipline over module-level globals: ``# guarded-by:``
    trailing a top-level assignment makes every module-level
    function's access of that global require ``with <lock>:``."""

    def __init__(self, relpath, tree, comments, sups, findings):
        self.relpath = relpath
        self.tree = tree
        self.comments = comments
        self.sups = sups
        self.findings = findings
        self.guard = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                m = GUARDED_RE.search(comments.get(node.lineno, ""))
                if m:
                    self.guard[node.targets[0].id] = m.group("lock")
        self.locks = frozenset(self.guard.values())

    def run(self):
        if not self.guard:
            return
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self._walk_stmts(node.body, frozenset())

    def _walk_stmts(self, stmts, held):
        for stmt in stmts:
            self._walk(stmt, held)

    def _walk(self, node, held):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newly = set()
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Name) and expr.id in self.locks:
                    newly.add(expr.id)
            self._walk_stmts(node.body, held | newly)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            body = node.body if isinstance(node.body, list) \
                else [node.body]
            self._walk_stmts(body, frozenset())
            return
        if isinstance(node, ast.Name) and node.id in self.guard:
            lock = self.guard[node.id]
            if lock not in held \
                    and not _suppressed(self.sups, node.lineno,
                                        "lock-discipline"):
                self.findings.append(Finding(
                    self.relpath, node.lineno, "lock-discipline",
                    "access of module global %s (guarded by %s) "
                    "outside `with %s:`" % (node.id, lock, lock)))
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child, held)


# ------------------------------------------------------------- purity pass
class _ModuleIndex:
    """Parsed-module cache for the purity pass: defs by bare name,
    project imports, comments."""

    def __init__(self, root, relpath):
        self.relpath = relpath
        path = os.path.join(root, relpath)
        with open(path, "r", encoding="utf-8") as f:
            self.src = f.read()
        self.tree = ast.parse(self.src, filename=relpath)
        self.comments, self.standalone = _comments(self.src)
        self.defs = {}           # bare name -> [FunctionDef]
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, []).append(node)
        #: imported name -> project-relative module path (one hop)
        self.imports = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.startswith("veles_tpu"):
                mod_rel = node.module.replace(".", "/") + ".py"
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = \
                        (mod_rel, alias.name)


class _PurityPass:
    """Traced-purity over discovered jit/scan targets + the registry;
    call graph followed same-module and one hop into project
    modules."""

    def __init__(self, root, sups_by_file, findings):
        self.root = root
        self.sups_by_file = sups_by_file
        self.findings = findings
        self._modules = {}
        self._analyzed = set()
        self.traced_functions = 0

    def module(self, relpath):
        if relpath not in self._modules:
            try:
                self._modules[relpath] = _ModuleIndex(self.root,
                                                      relpath)
            except (OSError, SyntaxError):
                self._modules[relpath] = None
        return self._modules[relpath]

    # ----------------------------------------------------------- discovery
    def discover(self, relpath):
        """Traced roots in ``relpath``: first args of self._jit /
        jax.jit / jit / (jax.)lax.scan calls, resolved through local
        ``name = vmap/partial/checkpoint(...)`` aliases."""
        mod = self.module(relpath)
        if mod is None:
            return []
        roots = []
        aliases = self._aliases(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = _dotted(node.func)
            if name is None:
                continue
            traced = (name in ("jax.jit", "jit")
                      or name.endswith("._jit")
                      or name in ("lax.scan", "jax.lax.scan"))
            if not traced:
                continue
            roots.extend(self._resolve(node.args[0], mod, aliases))
        return roots

    def _aliases(self, tree):
        """name -> value expr for simple ``name = <call>`` bindings
        anywhere in the module (function-local included) — how
        ``step_all = jax.vmap(step_one)`` resolves to ``step_one``."""
        out = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                out[node.targets[0].id] = node.value
        return out

    def _resolve(self, expr, mod, aliases, depth=0):
        """FunctionDef/Lambda nodes an expression can denote."""
        if depth > 6:
            return []
        if isinstance(expr, ast.Lambda):
            return [(mod, expr)]
        if isinstance(expr, ast.Name):
            name = expr.id
            if name in mod.defs:
                return [(mod, fn) for fn in mod.defs[name]]
            alias = aliases.get(name)
            if alias is not None:
                return self._resolve(alias, mod, aliases, depth + 1)
            return []
        if isinstance(expr, ast.Call):
            wrapper = _dotted(expr.func) or ""
            if wrapper.split(".")[-1] in ("vmap", "partial",
                                          "checkpoint", "remat",
                                          "named_call"):
                out = []
                for arg in expr.args:
                    out.extend(self._resolve(arg, mod, aliases,
                                             depth + 1))
                return out
        return []

    # ------------------------------------------------------------ analysis
    def analyze(self, mod, fn, depth=0):
        key = (mod.relpath, getattr(fn, "name", "<lambda>"),
               fn.lineno)
        if key in self._analyzed or depth > 8:
            return
        self._analyzed.add(key)
        self.traced_functions += 1
        local = self._local_names(fn)
        aliases = self._aliases(fn) if not isinstance(fn, ast.Lambda) \
            else {}
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                self._check_node(mod, fn, node, local, aliases, depth)

    @staticmethod
    def _local_names(fn):
        names = set()
        args = fn.args
        for a in (args.args + args.posonlyargs + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            names.add(a.arg)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Store):
                    names.add(node.id)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    names.add(node.name)
        return names

    def _flag(self, mod, node, message):
        sups = self.sups_by_file.get(mod.relpath, [])
        if _suppressed(sups, node.lineno, "traced-purity"):
            return
        self.findings.append(Finding(
            mod.relpath, node.lineno, "traced-purity", message))

    def _check_node(self, mod, fn, node, local, aliases, depth):
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name:
                if any(name.startswith(p) for p in PURE_PREFIXES):
                    return
                if name in IMPURE_BARE:
                    self._flag(mod, node,
                               "%s() in a traced/scanned body — a "
                               "host side effect baked in at trace "
                               "time" % name)
                    return
                for p in IMPURE_PREFIXES:
                    if name.startswith(p) or name == p.rstrip("."):
                        self._flag(mod, node,
                                   "%s in a traced/scanned body — "
                                   "host-side nondeterminism is a "
                                   "trace-time constant" % name)
                        return
                # closed-over container mutation: obj.append(...) on a
                # name not local to the traced function
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in MUTATORS \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id not in local:
                    self._flag(mod, node,
                               "%s.%s() mutates a closed-over/global "
                               "container inside a traced body"
                               % (node.func.value.id, node.func.attr))
                    return
                # call-graph follow
                self._follow(mod, name, aliases, depth)
        elif isinstance(node, ast.AugAssign) \
                and isinstance(node.target, ast.Name) \
                and node.target.id not in local:
            self._flag(mod, node,
                       "augmented assignment to closed-over/global "
                       "%r inside a traced body" % node.target.id)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id not in local:
                    self._flag(mod, node,
                               "subscript store into closed-over/"
                               "global %r inside a traced body"
                               % t.value.id)

    def _follow(self, mod, name, aliases, depth):
        if "." in name:
            return          # dotted calls: library (jnp/jax/numpy) —
        targets = []        # flagged above if impure, else trusted
        if name in mod.defs:
            targets = [(mod, f) for f in mod.defs[name]]
        elif name in aliases:
            targets = self._resolve(aliases[name], mod,
                                    self._aliases(mod.tree))
        elif name in mod.imports:
            rel, orig = mod.imports[name]
            other = self.module(rel)
            if other is not None and orig in other.defs:
                targets = [(other, f) for f in other.defs[orig]]
        for m, f in targets:
            self.analyze(m, f, depth + 1)

    # -------------------------------------------------------------- driver
    def run(self, purity_modules=PURITY_MODULES,
            registry=TRACED_REGISTRY):
        for relpath in purity_modules:
            for mod, fn in self.discover(relpath):
                self.analyze(mod, fn)
        for relpath, name in registry:
            mod = self.module(relpath)
            if mod is None or name not in mod.defs:
                self.findings.append(Finding(
                    relpath, 1, "traced-purity",
                    "TRACED_REGISTRY names %r but no such function "
                    "exists — registry drift" % name))
                continue
            for fn in mod.defs[name]:
                self.analyze(mod, fn)


# --------------------------------------------------------------- the lint
def lint_file(root, relpath, findings, suppressions):
    """Lock-discipline (classes + module globals) over one file.
    Returns per-file stats."""
    path = os.path.join(root, relpath)
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    tree = ast.parse(src, filename=relpath)
    comments, standalone = _comments(src)
    sups, sup_findings = _suppressions(relpath, comments, standalone)
    findings.extend(sup_findings)
    suppressions.extend(sups)
    classes = guarded = external = 0
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            cl = _ClassLint(relpath, node, comments, sups, findings)
            cl.run()
            classes += 1
            guarded += len(cl.guard)
            if cl.external:
                external += 1
    mg = _ModuleGlobalsLint(relpath, tree, comments, sups, findings)
    mg.run()
    return {"classes": classes, "guarded_attrs": guarded,
            "external": external,
            "module_globals": len(mg.guard)}


def run_check(root=REPO, modules=SERVING_MODULES,
              purity_modules=PURITY_MODULES, registry=TRACED_REGISTRY):
    """The full-tree check: every serving module through the lock
    pass, the purity pass over its discovery set + registry, unused/
    reasonless suppressions flagged.  Returns (findings,
    suppressions, stats)."""
    findings, suppressions = [], []
    stats = {"files": 0, "classes": 0, "guarded_attrs": 0,
             "module_globals": 0, "external": 0}
    sups_by_file = {}
    for relpath in modules:
        st = lint_file(root, relpath, findings, suppressions)
        stats["files"] += 1
        for k in ("classes", "guarded_attrs", "module_globals",
                  "external"):
            stats[k] += st[k]
    for s in suppressions:
        sups_by_file.setdefault(s.file, []).append(s)
    # purity files not already linted contribute their suppressions too
    for relpath in tuple(purity_modules) + tuple(
            r for r, _ in registry):
        if relpath in sups_by_file or relpath in modules:
            continue
        try:
            with open(os.path.join(root, relpath), "r",
                      encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        sups, sup_findings = _suppressions(relpath, *_comments(src))
        findings.extend(sup_findings)
        suppressions.extend(sups)
        sups_by_file[relpath] = sups
    purity = _PurityPass(root, sups_by_file, findings)
    purity.run(purity_modules, registry)
    stats["traced_functions"] = purity.traced_functions
    for s in suppressions:
        if not s.used:
            findings.append(Finding(
                s.file, s.line, "suppression",
                "suppression (%s) matched no finding — stale "
                "exception, delete it" % s.check))
    stats["suppressions"] = len(suppressions)
    findings.sort(key=lambda f: (f.file, f.line))
    return findings, suppressions, stats


# ------------------------------------------------------------- record/CLI
def summary_record(results):
    """The bench.py-shaped streamed summary record (validated by
    tools/check_stream_records.py builtin mode)."""
    stats = results.get("stats", {}) if isinstance(results, dict) else {}
    n = results.get("findings") if isinstance(results, dict) else None
    return [{
        "metric": "lint_findings",
        "value": int(n) if n is not None else 0,
        "unit": "count",
        "vs_baseline": "0 on a clean tree (ISSUE 15 acceptance)",
        "configs": {
            "files": stats.get("files", 0),
            "classes": stats.get("classes", 0),
            "guarded_attrs": stats.get("guarded_attrs", 0),
            "module_globals": stats.get("module_globals", 0),
            "traced_functions": stats.get("traced_functions", 0),
            "suppressions": stats.get("suppressions", 0),
        },
    }]


def main(argv=None):
    parser = argparse.ArgumentParser(
        description=__doc__.split("\n")[0])
    parser.add_argument("--check", action="store_true",
                        help="run the full-tree check (the default)")
    parser.add_argument("--root", default=REPO,
                        help="repository root (default: this repo)")
    parser.add_argument("--list-suppressions", action="store_true",
                        help="print every named suppression and exit")
    args = parser.parse_args(argv)
    findings, suppressions, stats = run_check(args.root)
    if args.list_suppressions:
        for s in suppressions:
            print("%s:%d: allow(%s): %s"
                  % (s.file, s.line, s.check, s.reason))
        return 0
    for f in findings:
        print("%s:%d: [%s] %s" % (f.file, f.line, f.check, f.message),
              file=sys.stderr)
    results = {"findings": len(findings), "stats": stats}
    print(json.dumps(summary_record(results)[0]))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
