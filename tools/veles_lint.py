"""veles_lint — project-specific static analysis for the serving tier
(ISSUE 15).

Three review-hardening rounds (PRs 11, 12, 14) each found real
concurrency violations by hand; this tool makes the rules they were
checking executable.  Two static passes (the runtime third — the
lock-order witness — lives in ``veles_tpu/serving/lockcheck.py``):

LOCK DISCIPLINE.  A class declares which attributes its lock guards::

    class Router:
        _guarded_by = {"_live": "_lock", "_jobs": "_lock"}

or per attribute, with a trailing comment on the assignment::

    self._queue = collections.deque()   # guarded-by: _cond

The pass walks every method and flags any read or write of a guarded
attribute that is not (a) inside a ``with self.<lock>:`` block, (b) in
a method marked ``# caller-holds: <lock>`` (placed on the ``def`` line
or directly under it, before the first real statement), or (c) in
``__init__`` (no concurrency before construction completes).  A call
``self.helper()`` where ``helper`` is marked ``# caller-holds: X``
and ``X`` is not held at the call site is flagged too — the broken
caller-holds CHAIN is exactly the bug class PR 12's review caught by
hand.  Module-level globals ride the same pass via a trailing
``# guarded-by: <lock>`` on the global's assignment (the metrics
registry, the default telemetry store).

Classes that are deliberately lock-free declare why::

    _synchronized_externally = "engine worker thread (single owner)"

TRACED PURITY.  Every function the engine jits or scans — discovered
from ``self._jit(...)`` / ``jax.jit(...)`` / ``lax.scan(...)`` call
sites plus the explicit ``TRACED_REGISTRY`` below — must be pure host-
side: the pass walks its call graph (same module, and one import hop
into project modules) and flags ``time.*``, ``random`` /
``numpy.random`` (``veles_tpu.prng`` is exempt — counter-based,
trace-safe by design), threading primitives, ``print``, and mutation
of closed-over containers.  A ``time.time()`` baked into a scanned
body is a constant at trace time — the class of bug that silently
costs a TPU window (PAPERS.md, the Julia-to-TPU compilation paper).

SUPPRESSIONS are per-site, named and greppable::

    x = self._queue  # lint: allow(lock-discipline): benign racy peek

Every suppression must carry a non-empty reason; a reasonless or
UNUSED suppression is itself a finding, so the exception list can
never rot.

Run standalone (``python tools/veles_lint.py --check``) — findings to
stderr, one bench.py-style summary record streamed to stdout — or via
tier-1 (``tests/test_lint.py`` runs the full-tree check), so a future
unguarded access fails the suite, not a review round.
"""

from __future__ import annotations

import argparse
import ast
import io
import json
import os
import re
import sys
import tokenize

TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS_DIR)

#: the serving modules the lock-discipline pass covers (ISSUE 15) —
#: every module that owns a lock or declares external synchronization
SERVING_MODULES = (
    "veles_tpu/serving/lm_engine.py",
    "veles_tpu/serving/router.py",
    "veles_tpu/serving/batcher.py",
    "veles_tpu/serving/kv_pool.py",
    "veles_tpu/serving/metrics.py",
    "veles_tpu/serving/tracing.py",
    "veles_tpu/serving/timeseries.py",
    "veles_tpu/serving/slo.py",
    "veles_tpu/serving/model_manager.py",
    "veles_tpu/serving/faults.py",
    "veles_tpu/serving/lockcheck.py",
)

#: traced-purity entry points beyond what call-site discovery finds:
#: (path suffix, bare function name) — functions RETURNED by builders
#: and jitted indirectly, or library functions every traced body runs
TRACED_REGISTRY = (
    ("veles_tpu/serving/lm_engine.py", "mega_plain"),
    ("veles_tpu/serving/lm_engine.py", "mega_spec"),
    ("veles_tpu/serving/lm_engine.py", "plain_iter"),
    ("veles_tpu/serving/lm_engine.py", "spec_iter"),
    ("veles_tpu/ops/transformer.py", "propose_draft_in_graph"),
)

#: modules the purity pass scans for jit/scan call sites
PURITY_MODULES = (
    "veles_tpu/serving/lm_engine.py",
    "veles_tpu/ops/transformer.py",
)

#: hot-path methods the host-sync pass covers (ISSUE 17): each entry
#: must EXIST and carry a trailing ``# hot-path`` marker on its def
#: line — the drift check that keeps a rename from silently shrinking
#: the analysis set (the TRACED_REGISTRY discipline, applied here)
HOT_PATH_REGISTRY = (
    ("veles_tpu/serving/lm_engine.py", "_admit"),
    ("veles_tpu/serving/lm_engine.py", "_admit_chunked"),
    ("veles_tpu/serving/lm_engine.py", "_admit_paged"),
    ("veles_tpu/serving/lm_engine.py", "_cow_guard"),
    ("veles_tpu/serving/lm_engine.py", "_advance_prefill"),
    ("veles_tpu/serving/lm_engine.py", "_advance_prefill_paged"),
    ("veles_tpu/serving/lm_engine.py", "_step_plain"),
    ("veles_tpu/serving/lm_engine.py", "_step_speculative"),
    ("veles_tpu/serving/lm_engine.py", "_step_megastep"),
    ("veles_tpu/serving/lm_engine.py", "_serve_loop"),
    ("veles_tpu/serving/batcher.py", "_take_batch"),
    ("veles_tpu/serving/batcher.py", "_dispatch"),
    ("veles_tpu/serving/batcher.py", "_serve_batches"),
    ("veles_tpu/serving/router.py", "_place"),
)

#: modules whose ``self._X_jit = self._jit(...)`` sites must each
#: carry a ``# programs: <family>`` census comment (ISSUE 17): the
#: declared program-family census the jit-guard fixtures are checked
#: against, so a silently-compiled twin (the PR 8 GSPMD bug class) is
#: a lint finding, not a _cache_size() audit
CENSUS_MODULES = ("veles_tpu/serving/lm_engine.py",)

#: jit-guard fixture files: every family the census declares must be
#: compile-count-asserted here, and vice versa
JIT_GUARD_FIXTURES = ("tests/test_lm_fastpath.py",)

CHECKS = ("lock-discipline", "traced-purity", "suppression",
          "recompile-hazard", "host-sync", "resource-lifecycle")

#: per-pass exit-code bits — ``main`` returns their OR, so CI can tell
#: WHICH pass failed from the exit status alone (pinned by
#: tests/test_lint.py so a pass dropping out of the default set fails
#: loudly)
PASS_BITS = {
    "lock-discipline": 1,
    "traced-purity": 2,
    "suppression": 4,
    "recompile-hazard": 8,
    "host-sync": 16,
    "resource-lifecycle": 32,
}

SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*allow\((?P<check>[\w-]+)\)\s*:?\s*(?P<reason>.*)")
GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(?P<lock>\w+)")
HOLDS_RE = re.compile(r"#\s*caller-holds:\s*(?P<locks>[\w\s,]+)")
PROGRAMS_RE = re.compile(r"#\s*programs:\s*(?P<family>\w+)")
HOT_PATH_RE = re.compile(r"#\s*hot-path\b")
#: family references a jit-guard fixture makes: ``engine._step_jit``
FIXTURE_FAMILY_RE = re.compile(r"\._(\w+)_jit\b")

#: mutating container methods (closed-over mutation detection)
MUTATORS = frozenset((
    "append", "extend", "insert", "remove", "pop", "popleft",
    "appendleft", "clear", "update", "setdefault", "add", "discard",
    "sort", "reverse",
))

#: dotted-call prefixes that are impure on a traced path
IMPURE_PREFIXES = (
    "time.", "random.", "numpy.random.", "np.random.", "threading.",
    "os.urandom", "secrets.",
)
IMPURE_BARE = frozenset(("print", "input", "open"))

#: prefixes exempt from the random rule — the project's counter-based
#: PRNG is trace-safe by design (veles_tpu/prng.py)
PURE_PREFIXES = ("prng.",)


class Finding:
    __slots__ = ("file", "line", "check", "message")

    def __init__(self, file, line, check, message):
        self.file = file
        self.line = int(line)
        self.check = check
        self.message = message

    def __repr__(self):
        return "%s:%d: [%s] %s" % (self.file, self.line, self.check,
                                   self.message)

    def to_dict(self):
        return {"file": self.file, "line": self.line,
                "check": self.check, "message": self.message}


class Suppression:
    __slots__ = ("file", "line", "check", "reason", "standalone",
                 "used")

    def __init__(self, file, line, check, reason, standalone):
        self.file = file
        self.line = int(line)
        self.check = check
        self.reason = reason.strip()
        #: a comment-only line (covers the statement BELOW it); a
        #: trailing comment covers its own line only
        self.standalone = bool(standalone)
        self.used = False


def _comments(src):
    """({lineno: comment text}, {standalone linenos}) over ``src`` —
    standalone marks comment-only lines (tokenize survives anything
    that parses as Python)."""
    out, standalone = {}, set()
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
                if not tok.line[:tok.start[1]].strip():
                    standalone.add(tok.start[0])
    except tokenize.TokenError:
        pass
    return out, standalone


def _suppressions(relpath, comments, standalone):
    """Every ``# lint: allow(check): reason`` site in the file, plus a
    finding for each malformed one (unknown check / missing reason)."""
    sups, findings = [], []
    for line, text in comments.items():
        m = SUPPRESS_RE.search(text)
        if not m:
            continue
        check, reason = m.group("check"), m.group("reason").strip()
        if check not in CHECKS:
            findings.append(Finding(
                relpath, line, "suppression",
                "unknown check %r in suppression (one of %r)"
                % (check, CHECKS)))
            continue
        if not reason:
            findings.append(Finding(
                relpath, line, "suppression",
                "suppression carries no reason string — every "
                "exception must say why"))
            continue
        sups.append(Suppression(relpath, line, check, reason,
                                line in standalone))
    return sups, findings


def _suppressed(sups, line, check):
    """A TRAILING suppression covers exactly its own line; a
    STANDALONE comment-line suppression covers exactly the statement
    directly below it — never both, so one comment can never swallow
    a second, unrelated finding on the next line."""
    for s in sups:
        if s.check == check \
                and line == (s.line + 1 if s.standalone else s.line):
            s.used = True
            return True
    return False


def _dotted(node):
    """'a.b.c' for an Attribute/Name chain, or None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# --------------------------------------------------------------- lock pass
def _caller_holds(fn, comments):
    """The locks a method declares its caller holds: a ``#
    caller-holds: X[, Y]`` comment on the ``def`` line or between it
    and the first real (non-docstring) statement."""
    if not fn.body:
        return frozenset()
    first = fn.body[0]
    end = first.lineno
    if (isinstance(first, ast.Expr)
            and isinstance(first.value, ast.Constant)
            and isinstance(first.value.value, str)):
        end = (fn.body[1].lineno if len(fn.body) > 1
               else first.end_lineno or first.lineno)
    locks = set()
    for line in range(fn.lineno, end + 1):
        m = HOLDS_RE.search(comments.get(line, ""))
        if m:
            locks.update(x.strip() for x in
                         m.group("locks").split(",") if x.strip())
    return frozenset(locks)


class _ClassLint:
    """Lock-discipline over one class: guard map, caller-holds chain,
    with-block tracking."""

    def __init__(self, relpath, cls, comments, sups, findings):
        self.relpath = relpath
        self.cls = cls
        self.comments = comments
        self.sups = sups
        self.findings = findings
        self.guard = {}          # attr -> lock
        self.external = None
        self.holds = {}          # method name -> frozenset(locks)
        self._collect()
        self.locks = frozenset(self.guard.values())

    def _collect(self):
        for node in self.cls.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if name == "_guarded_by" \
                        and isinstance(node.value, ast.Dict):
                    for k, v in zip(node.value.keys, node.value.values):
                        if isinstance(k, ast.Constant) \
                                and isinstance(v, ast.Constant):
                            self.guard[str(k.value)] = str(v.value)
                elif name == "_synchronized_externally" \
                        and isinstance(node.value, ast.Constant):
                    self.external = str(node.value.value)
                    if not self.external.strip():
                        self.findings.append(Finding(
                            self.relpath, node.lineno, "lock-discipline",
                            "_synchronized_externally must name the "
                            "owner (empty string)"))
        # trailing `# guarded-by:` comments on self.<attr> assignments
        for fn in self._methods():
            for node in ast.walk(fn):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                m = GUARDED_RE.search(
                    self.comments.get(node.lineno, ""))
                if not m:
                    continue
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        self.guard[t.attr] = m.group("lock")
        for fn in self._methods():
            self.holds[fn.name] = _caller_holds(fn, self.comments)

    def _methods(self):
        return [n for n in self.cls.body
                if isinstance(n, (ast.FunctionDef,
                                  ast.AsyncFunctionDef))]

    def run(self):
        if not self.guard:
            return
        for fn in self._methods():
            if fn.name == "__init__":
                continue
            self._walk_stmts(fn.body, self.holds.get(fn.name,
                                                     frozenset()))

    def _lock_of_with_item(self, item):
        expr = item.context_expr
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and expr.attr in self.locks:
            return expr.attr
        return None

    def _walk_stmts(self, stmts, held):
        for stmt in stmts:
            self._walk(stmt, held)

    def _walk(self, node, held):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newly = set()
            for item in node.items:
                lock = self._lock_of_with_item(item)
                if lock:
                    newly.add(lock)
                else:
                    self._walk(item.context_expr, held)
            self._walk_stmts(node.body, held | newly)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested function runs LATER, on whatever thread calls
            # it — it holds nothing unless it says so itself
            inner = _caller_holds(node, self.comments)
            self._walk_stmts(node.body, frozenset(inner))
            return
        if isinstance(node, ast.Lambda):
            self._walk(node.body, frozenset())
            return
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            attr = node.attr
            lock = self.guard.get(attr)
            if lock is not None and lock not in held \
                    and not _suppressed(self.sups, node.lineno,
                                        "lock-discipline"):
                kind = ("write" if isinstance(node.ctx, (ast.Store,
                                                         ast.Del))
                        else "read")
                self.findings.append(Finding(
                    self.relpath, node.lineno, "lock-discipline",
                    "%s of %s.%s (guarded by %s) outside `with "
                    "self.%s:` and no `# caller-holds: %s` marker"
                    % (kind, self.cls.name, attr, lock, lock, lock)))
            return      # leaf: Name('self') below needs no recursion
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            callee = node.func.attr
            missing = self.holds.get(callee, frozenset()) - held
            if missing and not _suppressed(self.sups, node.lineno,
                                           "lock-discipline"):
                self.findings.append(Finding(
                    self.relpath, node.lineno, "lock-discipline",
                    "call to %s.%s() (# caller-holds: %s) without "
                    "holding %s — caller-holds chain broken"
                    % (self.cls.name, callee,
                       ", ".join(sorted(self.holds[callee])),
                       ", ".join(sorted(missing)))))
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                self._walk(arg, held)
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child, held)


class _ModuleGlobalsLint:
    """Lock discipline over module-level globals: ``# guarded-by:``
    trailing a top-level assignment makes every module-level
    function's access of that global require ``with <lock>:``."""

    def __init__(self, relpath, tree, comments, sups, findings):
        self.relpath = relpath
        self.tree = tree
        self.comments = comments
        self.sups = sups
        self.findings = findings
        self.guard = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                m = GUARDED_RE.search(comments.get(node.lineno, ""))
                if m:
                    self.guard[node.targets[0].id] = m.group("lock")
        self.locks = frozenset(self.guard.values())

    def run(self):
        if not self.guard:
            return
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self._walk_stmts(node.body, frozenset())

    def _walk_stmts(self, stmts, held):
        for stmt in stmts:
            self._walk(stmt, held)

    def _walk(self, node, held):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newly = set()
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Name) and expr.id in self.locks:
                    newly.add(expr.id)
            self._walk_stmts(node.body, held | newly)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            body = node.body if isinstance(node.body, list) \
                else [node.body]
            self._walk_stmts(body, frozenset())
            return
        if isinstance(node, ast.Name) and node.id in self.guard:
            lock = self.guard[node.id]
            if lock not in held \
                    and not _suppressed(self.sups, node.lineno,
                                        "lock-discipline"):
                self.findings.append(Finding(
                    self.relpath, node.lineno, "lock-discipline",
                    "access of module global %s (guarded by %s) "
                    "outside `with %s:`" % (node.id, lock, lock)))
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child, held)


# ---------------------------------------------------------- shared parse
class _ModuleIndex:
    """ONE parse of one module, shared by every pass (ISSUE 17
    satellite: ``--check`` used to re-read and re-``ast.parse`` the
    tree once per pass): source, tree, comments, suppressions, defs by
    bare name, one-hop project imports."""

    def __init__(self, root, relpath):
        self.relpath = relpath
        path = os.path.join(root, relpath)
        with open(path, "r", encoding="utf-8") as f:
            self.src = f.read()
        self.tree = ast.parse(self.src, filename=relpath)
        self.comments, self.standalone = _comments(self.src)
        self.defs = {}           # bare name -> [FunctionDef]
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, []).append(node)
        #: imported name -> project-relative module path (one hop)
        self.imports = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.startswith("veles_tpu"):
                mod_rel = node.module.replace(".", "/") + ".py"
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = \
                        (mod_rel, alias.name)
        self.sups, self.sup_findings = _suppressions(
            relpath, self.comments, self.standalone)


class _ModuleSet:
    """The per-run parse cache: every pass resolves modules through
    here, so each file is read and ``ast.parse``d exactly once per
    ``run_check`` regardless of how many passes touch it."""

    def __init__(self, root):
        self.root = root
        self._cache = {}

    def get(self, relpath):
        if relpath not in self._cache:
            try:
                self._cache[relpath] = _ModuleIndex(self.root, relpath)
            except (OSError, SyntaxError):
                self._cache[relpath] = None
        return self._cache[relpath]

    def parses(self):
        return sum(1 for m in self._cache.values() if m is not None)


# ------------------------------------------------------------- purity pass
class _PurityPass:
    """Traced-purity over discovered jit/scan targets + the registry;
    call graph followed same-module and one hop into project
    modules.  Records every (module, fn) it analyzes so the
    recompile-hazard pass walks the SAME traced set without its own
    discovery."""

    def __init__(self, modules, sups_by_file, findings):
        self.modules = modules
        self.sups_by_file = sups_by_file
        self.findings = findings
        self._analyzed = set()
        self.traced_functions = 0
        #: [(mod, fn)] in analysis order — the recompile pass's input
        self.analyzed = []

    def module(self, relpath):
        return self.modules.get(relpath)

    # ----------------------------------------------------------- discovery
    def discover(self, relpath):
        """Traced roots in ``relpath``: first args of self._jit /
        jax.jit / jit / (jax.)lax.scan calls, resolved through local
        ``name = vmap/partial/checkpoint(...)`` aliases."""
        mod = self.module(relpath)
        if mod is None:
            return []
        roots = []
        aliases = self._aliases(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = _dotted(node.func)
            if name is None:
                continue
            traced = (name in ("jax.jit", "jit")
                      or name.endswith("._jit")
                      or name in ("lax.scan", "jax.lax.scan"))
            if not traced:
                continue
            roots.extend(self._resolve(node.args[0], mod, aliases))
        return roots

    def _aliases(self, tree):
        """name -> value expr for simple ``name = <call>`` bindings
        anywhere in the module (function-local included) — how
        ``step_all = jax.vmap(step_one)`` resolves to ``step_one``."""
        out = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                out[node.targets[0].id] = node.value
        return out

    def _resolve(self, expr, mod, aliases, depth=0):
        """FunctionDef/Lambda nodes an expression can denote."""
        if depth > 6:
            return []
        if isinstance(expr, ast.Lambda):
            return [(mod, expr)]
        if isinstance(expr, ast.Name):
            name = expr.id
            if name in mod.defs:
                return [(mod, fn) for fn in mod.defs[name]]
            alias = aliases.get(name)
            if alias is not None:
                return self._resolve(alias, mod, aliases, depth + 1)
            return []
        if isinstance(expr, ast.Call):
            wrapper = _dotted(expr.func) or ""
            if wrapper.split(".")[-1] in ("vmap", "partial",
                                          "checkpoint", "remat",
                                          "named_call"):
                out = []
                for arg in expr.args:
                    out.extend(self._resolve(arg, mod, aliases,
                                             depth + 1))
                return out
        return []

    # ------------------------------------------------------------ analysis
    def analyze(self, mod, fn, depth=0):
        key = (mod.relpath, getattr(fn, "name", "<lambda>"),
               fn.lineno)
        if key in self._analyzed or depth > 8:
            return
        self._analyzed.add(key)
        self.traced_functions += 1
        self.analyzed.append((mod, fn))
        local = self._local_names(fn)
        aliases = self._aliases(fn) if not isinstance(fn, ast.Lambda) \
            else {}
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                self._check_node(mod, fn, node, local, aliases, depth)

    @staticmethod
    def _local_names(fn):
        names = set()
        args = fn.args
        for a in (args.args + args.posonlyargs + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            names.add(a.arg)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Store):
                    names.add(node.id)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    names.add(node.name)
        return names

    def _flag(self, mod, node, message):
        sups = self.sups_by_file.get(mod.relpath, [])
        if _suppressed(sups, node.lineno, "traced-purity"):
            return
        self.findings.append(Finding(
            mod.relpath, node.lineno, "traced-purity", message))

    def _check_node(self, mod, fn, node, local, aliases, depth):
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name:
                if any(name.startswith(p) for p in PURE_PREFIXES):
                    return
                if name in IMPURE_BARE:
                    self._flag(mod, node,
                               "%s() in a traced/scanned body — a "
                               "host side effect baked in at trace "
                               "time" % name)
                    return
                for p in IMPURE_PREFIXES:
                    if name.startswith(p) or name == p.rstrip("."):
                        self._flag(mod, node,
                                   "%s in a traced/scanned body — "
                                   "host-side nondeterminism is a "
                                   "trace-time constant" % name)
                        return
                # closed-over container mutation: obj.append(...) on a
                # name not local to the traced function
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in MUTATORS \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id not in local:
                    self._flag(mod, node,
                               "%s.%s() mutates a closed-over/global "
                               "container inside a traced body"
                               % (node.func.value.id, node.func.attr))
                    return
                # call-graph follow
                self._follow(mod, name, aliases, depth)
        elif isinstance(node, ast.AugAssign) \
                and isinstance(node.target, ast.Name) \
                and node.target.id not in local:
            self._flag(mod, node,
                       "augmented assignment to closed-over/global "
                       "%r inside a traced body" % node.target.id)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id not in local:
                    self._flag(mod, node,
                               "subscript store into closed-over/"
                               "global %r inside a traced body"
                               % t.value.id)

    def _follow(self, mod, name, aliases, depth):
        if "." in name:
            return          # dotted calls: library (jnp/jax/numpy) —
        targets = []        # flagged above if impure, else trusted
        if name in mod.defs:
            targets = [(mod, f) for f in mod.defs[name]]
        elif name in aliases:
            targets = self._resolve(aliases[name], mod,
                                    self._aliases(mod.tree))
        elif name in mod.imports:
            rel, orig = mod.imports[name]
            other = self.module(rel)
            if other is not None and orig in other.defs:
                targets = [(other, f) for f in other.defs[orig]]
        for m, f in targets:
            self.analyze(m, f, depth + 1)

    # -------------------------------------------------------------- driver
    def run(self, purity_modules=PURITY_MODULES,
            registry=TRACED_REGISTRY):
        for relpath in purity_modules:
            for mod, fn in self.discover(relpath):
                self.analyze(mod, fn)
        for relpath, name in registry:
            mod = self.module(relpath)
            if mod is None or name not in mod.defs:
                self.findings.append(Finding(
                    relpath, 1, "traced-purity",
                    "TRACED_REGISTRY names %r but no such function "
                    "exists — registry drift" % name))
                continue
            for fn in mod.defs[name]:
                self.analyze(mod, fn)


# ------------------------------------------------- recompile-hazard pass
class _RecompilePass:
    """Recompile hazards over the traced set the purity pass walked
    (ISSUE 17): (a) closure over ``self`` — a traced body reading a
    mutable attribute bakes its trace-time value in (or retraces per
    identity) instead of threading it as an argument; (b)
    shape-dependent Python branching — an ``if``/``while`` on
    ``.shape`` / ``len()`` specializes the program per shape, silently
    multiplying the compiled-program family; (c) Python concretization
    — ``int()``/``float()``/``bool()`` of a traced value either dies
    at trace time or bakes a per-call scalar into the program.  Plus
    the CENSUS: every ``self._X_jit = self._jit(...)`` site declares
    its program family (``# programs: <family>``), and the declared
    set must agree bidirectionally with what the jit-guard fixtures
    compile-count-assert — a compiled family nobody bounds is exactly
    the PR 8 silently-compiled-twin bug class."""

    def __init__(self, modules, sups_by_file, findings):
        self.modules = modules
        self.sups_by_file = sups_by_file
        self.findings = findings
        self.census_sites = 0

    def _flag(self, relpath, node, message):
        sups = self.sups_by_file.get(relpath, [])
        if _suppressed(sups, node.lineno, "recompile-hazard"):
            return
        self.findings.append(Finding(
            relpath, node.lineno, "recompile-hazard", message))

    # ------------------------------------------------------ traced bodies
    def run_bodies(self, analyzed):
        for mod, fn in analyzed:
            args = fn.args
            params = {a.arg for a in (
                args.args + args.posonlyargs + args.kwonlyargs
                + ([args.vararg] if args.vararg else [])
                + ([args.kwarg] if args.kwarg else []))}
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    self._check_node(mod, node, params)

    @staticmethod
    def _names_outside_static(expr):
        """Load Names in ``expr`` NOT under a static accessor
        (``.shape``/``.ndim``/``.dtype``) — ``float(1.0 / dh)`` where
        ``dh = q.shape[-1]`` concretizes nothing traced."""
        out = set()

        def rec(n):
            if isinstance(n, ast.Attribute) \
                    and n.attr in ("shape", "ndim", "dtype"):
                return
            if isinstance(n, ast.Name):
                out.add(n.id)
            for c in ast.iter_child_nodes(n):
                rec(c)

        rec(expr)
        return out

    def _check_node(self, mod, node, params):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            self._flag(mod.relpath, node,
                       "traced body closes over self.%s — mutable "
                       "engine state baked in at trace time; thread "
                       "it as an argument" % node.attr)
        elif isinstance(node, (ast.If, ast.While, ast.IfExp)):
            for t in ast.walk(node.test):
                if isinstance(t, ast.Attribute) and t.attr == "shape":
                    self._flag(mod.relpath, node,
                               "Python branch on .shape inside a "
                               "traced body — one compiled program "
                               "per shape, a silent family multiplier")
                    return
                if isinstance(t, ast.Call) \
                        and isinstance(t.func, ast.Name) \
                        and t.func.id == "len":
                    self._flag(mod.relpath, node,
                               "Python branch on len() inside a "
                               "traced body — shape-dependent "
                               "control flow specializes per shape")
                    return
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Name) \
                and node.func.id in ("int", "float", "bool") \
                and node.args \
                and (self._names_outside_static(node.args[0])
                     & params):
            self._flag(mod.relpath, node,
                       "%s() of a traced argument inside a traced "
                       "body — concretizes a traced value (trace-"
                       "time error or a baked-in per-call constant)"
                       % node.func.id)

    # ------------------------------------------------------------- census
    def run_census(self, census_modules, jit_guard_fixtures):
        declared = {}        # family -> [(relpath, line)]
        for relpath in census_modules:
            mod = self.modules.get(relpath)
            if mod is None:
                continue
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                t = node.targets[0]
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and t.attr.endswith("_jit")):
                    continue
                call = node.value
                if not (isinstance(call, ast.Call)
                        and (_dotted(call.func) or "")
                        .endswith("._jit")):
                    continue       # e.g. `self._step_jit = None`
                self.census_sites += 1
                derived = t.attr[:-len("_jit")].lstrip("_")
                family = None
                for line in (node.lineno - 1, node.lineno):
                    m = PROGRAMS_RE.search(
                        mod.comments.get(line, ""))
                    if m:
                        family = m.group("family")
                if family is None:
                    self._flag(mod.relpath, node,
                               "jit site self.%s has no `# programs: "
                               "<family>` census entry — every "
                               "compiled family must be declared"
                               % t.attr)
                    continue
                if family != derived:
                    self._flag(mod.relpath, node,
                               "census declares family %r but the "
                               "site installs self.%s (family %r) — "
                               "the census lies" % (family, t.attr,
                                                    derived))
                    continue
                declared.setdefault(family, []).append(
                    (mod.relpath, node.lineno))
        # ISSUE 19: lax.while_loop-built resident programs — a while
        # loop is a whole program family behind ONE call, so every
        # call site in a census module must name its family, and that
        # family must be INSTALLED at a declared `self._<family>_jit`
        # site; a while program jitted under an undeclared attr is the
        # silently-compiled-twin bug class with in-graph control flow
        for relpath in census_modules:
            mod = self.modules.get(relpath)
            if mod is None:
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                if not (_dotted(node.func) or "").endswith(
                        "lax.while_loop"):
                    continue
                family = None
                for line in (node.lineno - 1, node.lineno):
                    m = PROGRAMS_RE.search(
                        mod.comments.get(line, ""))
                    if m:
                        family = m.group("family")
                if family is None:
                    self._flag(mod.relpath, node,
                               "lax.while_loop builds a resident loop "
                               "program with no `# programs: <family>` "
                               "census entry — a silently-compiled "
                               "while-twin would go unnoticed")
                    continue
                if family not in declared:
                    self._flag(mod.relpath, node,
                               "while-loop census names family %r but "
                               "no `self._%s_jit = self._jit(...)` "
                               "site installs it — the while-twin "
                               "would compile outside every jit-guard "
                               "bound" % (family, family))
        asserted = {}        # family -> (fixture relpath, line)
        for relpath in jit_guard_fixtures:
            mod = self.modules.get(relpath)
            if mod is None:
                continue
            for i, line in enumerate(mod.src.splitlines(), start=1):
                for m in FIXTURE_FAMILY_RE.finditer(line):
                    asserted.setdefault(m.group(1), (relpath, i))
        if not census_modules or not jit_guard_fixtures:
            return
        for family in sorted(set(declared) - set(asserted)):
            rel, line = declared[family][0]
            self.findings.append(Finding(
                rel, line, "recompile-hazard",
                "program family %r is compiled but no jit-guard "
                "fixture bounds its compile count — a silently-"
                "compiled twin would go unnoticed (add it to %s)"
                % (family, ", ".join(jit_guard_fixtures))))
        for family in sorted(set(asserted) - set(declared)):
            rel, line = asserted[family]
            self.findings.append(Finding(
                rel, line, "recompile-hazard",
                "jit-guard fixture asserts family %r but no census "
                "site declares it — fixture drift" % family))


# --------------------------------------------------------- host-sync pass
#: dispatch sites: a call through one of these produces DEVICE values
#: and counts as an un-fenced in-flight program until read back
_DISPATCH_SUFFIX = "_jit"
_DISPATCH_NAMES = frozenset(("self.forward",))
#: explicit device→host reads: their results are HOST values, and
#: reaching one fences the in-flight dispatch
_CLEANSERS = frozenset(("xfer.to_host", "jax.device_get",
                        "device_get"))
_TIMING_CALLS = frozenset(("time.monotonic", "time.perf_counter",
                           "time.time"))
_SYNC_BUILTINS = frozenset(("int", "float", "bool"))
_SYNC_ASARRAY = frozenset(("numpy.asarray", "np.asarray",
                           "numpy.array", "np.array"))
_SYNC_METHODS = frozenset(("item", "tolist", "__array__"))


class _HostSyncPass:
    """Implicit device→host syncs in ``# hot-path`` methods (ISSUE
    17): taint names bound from jit dispatches, then flag host
    coercions of tainted values (``int()``/``float()``/``bool()``/
    ``numpy.asarray``/``.item()``/``.tolist()``), ``jnp.*`` staging
    (implicit host→device), timing subtractions taken while a
    dispatch is un-fenced (they time the enqueue, not the device),
    and dispatches issued inside a ``with self.<lock>:`` block (the
    static face of lockcheck's lock-held-across-dispatch rule).
    ``xfer.to_host`` / ``jax.device_get`` are the sanctioned exits:
    they clear taint and fence timing."""

    def __init__(self, modules, sups_by_file, findings):
        self.modules = modules
        self.sups_by_file = sups_by_file
        self.findings = findings
        self.hot_path_methods = 0

    def _flag(self, relpath, node, message):
        sups = self.sups_by_file.get(relpath, [])
        if _suppressed(sups, node.lineno, "host-sync"):
            return
        self.findings.append(Finding(
            relpath, node.lineno, "host-sync", message))

    # ---------------------------------------------------------- discovery
    def run(self, hot_modules, registry):
        marked = {}          # (relpath, name) -> (mod, fn)
        for relpath in hot_modules:
            mod = self.modules.get(relpath)
            if mod is None:
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and HOT_PATH_RE.search(
                            mod.comments.get(node.lineno, "")):
                    marked[(relpath, node.name)] = (mod, node)
        for relpath, name in registry:
            if (relpath, name) not in marked:
                self.findings.append(Finding(
                    relpath, 1, "host-sync",
                    "HOT_PATH_REGISTRY names %s.%s but no such "
                    "`# hot-path`-marked method exists — registry "
                    "drift (renamed? marker dropped?)"
                    % (relpath, name)))
        for (relpath, _name), (mod, fn) in sorted(
                marked.items(), key=lambda kv: (kv[0][0],
                                                kv[1][1].lineno)):
            self.hot_path_methods += 1
            self._analyze(mod, fn)

    # ------------------------------------------------------------ analysis
    def _analyze(self, mod, fn):
        state = {"tainted": set(), "timers": set(), "pending": False}
        self._walk_stmts(mod, fn.body, state, locks_held=0)

    @staticmethod
    def _call_kind(call):
        """'dispatch' / 'cleanser' / 'fence' / 'timing' / None."""
        name = _dotted(call.func)
        if name is None:
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "block_until_ready":
                return "fence"
            return None
        if name.endswith(_DISPATCH_SUFFIX) or name in _DISPATCH_NAMES:
            return "dispatch"
        if name in _CLEANSERS or name.endswith(".block_until_ready"):
            return "cleanser"
        if name in _TIMING_CALLS:
            return "timing"
        return None

    def _roots(self, expr):
        """Load-context Names in an expression."""
        return {n.id for n in ast.walk(expr)
                if isinstance(n, ast.Name)
                and isinstance(n.ctx, ast.Load)}

    def _tainted_expr(self, expr, state):
        if isinstance(expr, ast.Call):
            kind = self._call_kind(expr)
            if kind == "dispatch":
                return True
            if kind == "cleanser":
                return False
        if isinstance(expr, (ast.Name, ast.Subscript, ast.Tuple,
                             ast.Starred)):
            return bool(self._roots(expr) & state["tainted"])
        return False

    def _walk_stmts(self, mod, stmts, state, locks_held):
        for stmt in stmts:
            self._stmt(mod, stmt, state, locks_held)

    def _stmt(self, mod, stmt, state, locks_held):
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            locky = locks_held
            for item in stmt.items:
                expr = item.context_expr
                if isinstance(expr, ast.Attribute) \
                        and isinstance(expr.value, ast.Name) \
                        and expr.value.id == "self" \
                        and ("lock" in expr.attr
                             or "cond" in expr.attr):
                    locky += 1
            self._scan_exprs(mod, [stmt.items], state, locks_held)
            self._walk_stmts(mod, stmt.body, state, locky)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_node(mod, stmt.test, state, locks_held)
            self._walk_stmts(mod, stmt.body, state, locks_held)
            self._walk_stmts(mod, stmt.orelse, state, locks_held)
            return
        if isinstance(stmt, ast.For):
            self._scan_node(mod, stmt.iter, state, locks_held)
            self._walk_stmts(mod, stmt.body, state, locks_held)
            self._walk_stmts(mod, stmt.orelse, state, locks_held)
            return
        if isinstance(stmt, ast.Try):
            self._walk_stmts(mod, stmt.body, state, locks_held)
            for h in stmt.handlers:
                self._walk_stmts(mod, h.body, state, locks_held)
            self._walk_stmts(mod, stmt.orelse, state, locks_held)
            self._walk_stmts(mod, stmt.finalbody, state, locks_held)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return        # runs later, on some other thread's budget
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = stmt.value
            if value is not None:
                self._scan_node(mod, value, state, locks_held)
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            names = set()
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name) \
                            and isinstance(n.ctx, ast.Store):
                        names.add(n.id)
            if value is not None and names:
                if self._tainted_expr(value, state):
                    state["tainted"] |= names
                else:
                    state["tainted"] -= names
                if isinstance(value, ast.Call) \
                        and self._call_kind(value) == "timing":
                    state["timers"] |= names
                else:
                    state["timers"] -= names
            return
        self._scan_node(mod, stmt, state, locks_held)

    def _scan_exprs(self, mod, groups, state, locks_held):
        for group in groups:
            for item in group:
                self._scan_node(mod, item.context_expr, state,
                                locks_held)

    def _scan_node(self, mod, node, state, locks_held):
        # a dispatch nested INSIDE a cleanser (`xfer.to_host(
        # self.forward(...))`) is born fenced — only bare dispatches
        # leave a program in flight
        fenced = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and self._call_kind(sub) in ("cleanser", "fence"):
                for inner in ast.walk(sub):
                    if inner is not sub and isinstance(inner, ast.Call) \
                            and self._call_kind(inner) == "dispatch":
                        fenced.add(id(inner))
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                self._check_method_sync(mod, sub, state)
                continue
            kind = self._call_kind(sub)
            name = _dotted(sub.func) or ""
            if kind == "dispatch":
                if id(sub) not in fenced:
                    state["pending"] = True
                if locks_held:
                    self._flag(mod.relpath, sub,
                               "device dispatch %s(...) inside a "
                               "`with self.<lock>:` block — a held "
                               "lock rides the device round-trip "
                               "(lockcheck's runtime rule, statically)"
                               % name)
            elif kind in ("cleanser", "fence"):
                state["pending"] = False
            elif kind == "timing":
                pass
            elif name.startswith("jnp.") or name.startswith(
                    "jax.numpy."):
                self._flag(mod.relpath, sub,
                           "%s(...) on the hot path — implicit "
                           "host→device staging; use xfer.to_device "
                           "for dispatch arguments" % name)
            else:
                self._check_call_sync(mod, sub, name, state)
        # un-fenced timing: `time.X() - t0` while a dispatch is in
        # flight times the ENQUEUE, not the device step
        for sub in ast.walk(node):
            if isinstance(sub, ast.BinOp) \
                    and isinstance(sub.op, ast.Sub) \
                    and state["pending"]:
                ops = (sub.left, sub.right)
                has_timing = any(
                    isinstance(o, ast.Call)
                    and self._call_kind(o) == "timing" for o in ops)
                has_timer_name = any(
                    isinstance(o, ast.Name) and o.id in state["timers"]
                    for o in ops)
                if has_timing and has_timer_name:
                    self._flag(mod.relpath, sub,
                               "timing read with a dispatch in "
                               "flight — measures enqueue latency, "
                               "not device time; fence via "
                               "xfer.to_host/block_until_ready first")

    def _check_call_sync(self, mod, call, name, state):
        arg = call.args[0] if call.args else None
        if arg is None:
            return
        if isinstance(arg, ast.Call):
            # int(xfer.to_host(x)) is the SANCTIONED shape; a nested
            # dispatch (int(self._step_jit(...))) is the violation
            arg_tainted = self._call_kind(arg) == "dispatch"
        else:
            arg_tainted = (self._tainted_expr(arg, state)
                           or bool(self._roots(arg)
                                   & state["tainted"]))
        if not arg_tainted:
            return
        if name in _SYNC_BUILTINS or name in _SYNC_ASARRAY:
            self._flag(mod.relpath, call,
                       "%s(...) of a device value on the hot path — "
                       "an implicit device→host sync; route it "
                       "through xfer.to_host" % name)

    def _check_method_sync(self, mod, node, state):
        if not (isinstance(node, ast.Attribute)
                and node.attr in _SYNC_METHODS
                and isinstance(node.value, ast.Name)
                and node.value.id in state["tainted"]):
            return
        self._flag(mod.relpath, node,
                   ".%s() on a device value on the hot path — an "
                   "implicit device→host sync; route it through "
                   "xfer.to_host" % node.attr)


# ------------------------------------------------- resource-lifecycle pass
#: creation calls the escape analysis tracks when bound to a local
#: name: (kind, dotted-suffix)
_CREATORS = (
    ("future", "Future"),
    ("pages", ".alloc"),
    ("span", ".begin"),
)
#: per-kind resolver method names (called ON the tracked name, or
#: with it as first argument)
_RESOLVERS = {
    "future": frozenset(("set_result", "set_exception", "cancel")),
    "pages": frozenset(("release", "free", "release_pages",
                        "_release_pages")),
    "span": frozenset(("end",)),
}


class _LifecyclePass:
    """AST escape analysis over Future / page-alloc / tracer-span
    creation sites (ISSUE 17): a resource bound to a local name must,
    before the function ends, either ESCAPE (stored on an object,
    passed to a call, returned — ownership handed off) or RESOLVE
    (set_result/set_exception/cancel, release, end).  A site with
    neither leaks on every path (the PR 6 COW-leak class); a site
    whose only resolvers sit in straight-line code after other
    raisable calls leaks on the exception path (the PR 12
    hedge-loser-span class) unless a try/finally/except owns the
    resolution."""

    def __init__(self, modules, sups_by_file, findings):
        self.modules = modules
        self.sups_by_file = sups_by_file
        self.findings = findings
        self.lifecycle_sites = 0

    def _flag(self, relpath, node, message):
        sups = self.sups_by_file.get(relpath, [])
        if _suppressed(sups, node.lineno, "resource-lifecycle"):
            return
        self.findings.append(Finding(
            relpath, node.lineno, "resource-lifecycle", message))

    def run(self, lifecycle_modules):
        for relpath in lifecycle_modules:
            mod = self.modules.get(relpath)
            if mod is None:
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    self._function(mod, node)

    @staticmethod
    def _creation_kind(call):
        name = _dotted(call.func)
        if name is None:
            return None
        for kind, suffix in _CREATORS:
            if name == suffix.lstrip(".") or name.endswith(suffix):
                return kind
        return None

    def _function(self, mod, fn):
        creations = []       # (name, kind, node)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                kind = self._creation_kind(node.value)
                if kind is not None:
                    creations.append((node.targets[0].id, kind, node))
        if not creations:
            return
        protected = self._protected_lines(fn)
        for name, kind, node in creations:
            self.lifecycle_sites += 1
            self._track(mod, fn, name, kind, node, protected)

    @staticmethod
    def _protected_lines(fn):
        """Lines inside an except handler or finally block — a
        resolver there covers the exception path."""
        lines = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Try):
                for h in node.handlers:
                    for s in h.body:
                        lines.update(range(s.lineno,
                                           (s.end_lineno or s.lineno)
                                           + 1))
                for s in node.finalbody:
                    lines.update(range(s.lineno,
                                       (s.end_lineno or s.lineno)
                                       + 1))
        return lines

    def _track(self, mod, fn, name, kind, creation, protected):
        resolvers = []       # linenos
        escapes = []         # linenos
        raisable = []        # linenos of calls that can raise
        resolver_names = _RESOLVERS[kind]
        created_at = creation.lineno
        for node in ast.walk(fn):
            line = getattr(node, "lineno", None)
            if line is None or line <= created_at or node is creation:
                continue
            if isinstance(node, ast.Call):
                func = node.func
                # resolver: name.set_result(...) / tracer.end(name)
                if isinstance(func, ast.Attribute) \
                        and func.attr in resolver_names:
                    recv = func.value
                    if isinstance(recv, ast.Name) and recv.id == name:
                        resolvers.append(line)
                        continue
                    if any(isinstance(a, ast.Name) and a.id == name
                           for a in node.args):
                        resolvers.append(line)
                        continue
                # escape: the resource handed to ANY other call
                if any(isinstance(a, ast.Name) and a.id == name
                       for sub in ast.walk(node)
                       if isinstance(sub, ast.Call)
                       for a in sub.args):
                    escapes.append(line)
                raisable.append(line)
            elif isinstance(node, ast.Assign):
                # escape: stored into an attribute/subscript/aliased
                if any(isinstance(n, ast.Name) and n.id == name
                       and isinstance(n.ctx, ast.Load)
                       for n in ast.walk(node.value)):
                    escapes.append(line)
            elif isinstance(node, (ast.Return, ast.Yield,
                                   ast.YieldFrom)):
                v = node.value
                if v is not None and any(
                        isinstance(n, ast.Name) and n.id == name
                        for n in ast.walk(v)):
                    escapes.append(line)
        if escapes:
            return           # ownership handed off — not ours to prove
        if not resolvers:
            self._flag(mod.relpath, creation,
                       "%s %r created here is never resolved "
                       "(%s) and never escapes — leaked on every "
                       "path" % (kind, name,
                                 "/".join(sorted(_RESOLVERS[kind]))))
            return
        if any(r in protected for r in resolvers):
            return           # a finally/except owns resolution
        first = min(resolvers)
        risky = [r for r in raisable
                 if created_at < r < first and r not in resolvers]
        if risky:
            self._flag(mod.relpath, creation,
                       "%s %r is resolved only in straight-line code "
                       "(first at line %d) with raisable calls in "
                       "between (line %d) — leaks on the exception "
                       "path; resolve in a finally/except"
                       % (kind, name, first, risky[0]))


# --------------------------------------------------------------- the lint
def lint_module(mod, findings):
    """Lock-discipline (classes + module globals) over one parsed
    module.  Returns per-file stats."""
    classes = guarded = external = 0
    for node in mod.tree.body:
        if isinstance(node, ast.ClassDef):
            cl = _ClassLint(mod.relpath, node, mod.comments, mod.sups,
                            findings)
            cl.run()
            classes += 1
            guarded += len(cl.guard)
            if cl.external:
                external += 1
    mg = _ModuleGlobalsLint(mod.relpath, mod.tree, mod.comments,
                            mod.sups, findings)
    mg.run()
    return {"classes": classes, "guarded_attrs": guarded,
            "external": external,
            "module_globals": len(mg.guard)}


def run_check(root=REPO, modules=SERVING_MODULES,
              purity_modules=PURITY_MODULES, registry=TRACED_REGISTRY,
              census_modules=CENSUS_MODULES,
              jit_guard_fixtures=JIT_GUARD_FIXTURES,
              hot_path_registry=HOT_PATH_REGISTRY,
              lifecycle_modules=None):
    """The full-tree check, all passes over ONE shared parse per
    module: lock discipline, traced purity, recompile hazards (+ the
    program-family census cross-check), host-sync taint over hot-path
    methods, resource-lifecycle escape analysis, and suppression
    hygiene.  ``lifecycle_modules`` defaults to ``modules``.  Returns
    (findings, suppressions, stats)."""
    import time as _time
    t0 = _time.perf_counter()
    findings, suppressions = [], []
    stats = {"files": 0, "classes": 0, "guarded_attrs": 0,
             "module_globals": 0, "external": 0}
    if lifecycle_modules is None:
        lifecycle_modules = modules
    mset = _ModuleSet(root)
    sups_by_file = {}

    def _adopt(relpath):
        """Register a module's suppressions (once per file)."""
        mod = mset.get(relpath)
        if mod is None or relpath in sups_by_file:
            return mod
        findings.extend(mod.sup_findings)
        suppressions.extend(mod.sups)
        sups_by_file[relpath] = mod.sups
        return mod

    for relpath in modules:
        mod = _adopt(relpath)
        if mod is None:
            continue
        st = lint_module(mod, findings)
        stats["files"] += 1
        for k in ("classes", "guarded_attrs", "module_globals",
                  "external"):
            stats[k] += st[k]
    # every file ANY pass reads contributes its suppressions, so an
    # allow() in a purity/census/fixture file is honored and audited
    for relpath in (tuple(purity_modules)
                    + tuple(r for r, _ in registry)
                    + tuple(census_modules)
                    + tuple(jit_guard_fixtures)
                    + tuple(lifecycle_modules)):
        _adopt(relpath)
    purity = _PurityPass(mset, sups_by_file, findings)
    purity.run(purity_modules, registry)
    stats["traced_functions"] = purity.traced_functions
    recompile = _RecompilePass(mset, sups_by_file, findings)
    recompile.run_bodies(purity.analyzed)
    recompile.run_census(census_modules, jit_guard_fixtures)
    stats["census_sites"] = recompile.census_sites
    hostsync = _HostSyncPass(mset, sups_by_file, findings)
    hostsync.run(modules, hot_path_registry)
    stats["hot_path_methods"] = hostsync.hot_path_methods
    lifecycle = _LifecyclePass(mset, sups_by_file, findings)
    lifecycle.run(lifecycle_modules)
    stats["lifecycle_sites"] = lifecycle.lifecycle_sites
    for s in suppressions:
        if not s.used:
            findings.append(Finding(
                s.file, s.line, "suppression",
                "suppression (%s) matched no finding — stale "
                "exception, delete it" % s.check))
    stats["suppressions"] = len(suppressions)
    stats["parses"] = mset.parses()
    stats["wall_s"] = round(_time.perf_counter() - t0, 3)
    findings.sort(key=lambda f: (f.file, f.line))
    return findings, suppressions, stats


# ------------------------------------------------------------- record/CLI
def summary_record(results):
    """The bench.py-shaped streamed summary record (validated by
    tools/check_stream_records.py builtin mode)."""
    stats = results.get("stats", {}) if isinstance(results, dict) else {}
    n = results.get("findings") if isinstance(results, dict) else None
    return [{
        "metric": "lint_findings",
        "value": int(n) if n is not None else 0,
        "unit": "count",
        "vs_baseline": "0 on a clean tree (ISSUE 15/17 acceptance)",
        "configs": {
            "files": stats.get("files", 0),
            "classes": stats.get("classes", 0),
            "guarded_attrs": stats.get("guarded_attrs", 0),
            "module_globals": stats.get("module_globals", 0),
            "traced_functions": stats.get("traced_functions", 0),
            "census_sites": stats.get("census_sites", 0),
            "hot_path_methods": stats.get("hot_path_methods", 0),
            "lifecycle_sites": stats.get("lifecycle_sites", 0),
            "suppressions": stats.get("suppressions", 0),
            "parses": stats.get("parses", 0),
            "wall_s": stats.get("wall_s", 0.0),
        },
    }]


def clean_record(findings, stats):
    """The bench-leg ``lint_clean`` assertion record (ISSUE 17
    satellite): lm_bench/chaos_bench run the full check as one leg
    and stream this — 1 means the shipped tree is lint-clean.  Takes
    a findings count or list."""
    n = findings if isinstance(findings, int) else len(findings)
    stats = stats or {}
    return [{
        "metric": "lint_clean",
        "value": 0 if n else 1,
        "unit": "bool",
        "vs_baseline": "1 (zero findings) on a shipped tree",
        "configs": {
            "findings": int(n),
            "files": stats.get("files", 0),
            "traced_functions": stats.get("traced_functions", 0),
            "hot_path_methods": stats.get("hot_path_methods", 0),
            "census_sites": stats.get("census_sites", 0),
            "lifecycle_sites": stats.get("lifecycle_sites", 0),
            "suppressions": stats.get("suppressions", 0),
            "wall_s": stats.get("wall_s", 0.0),
        },
    }]


def exit_code(findings):
    """OR of PASS_BITS for every pass with >= 1 finding — CI reads
    WHICH passes failed from the status alone (0 = clean)."""
    code = 0
    for f in findings:
        code |= PASS_BITS.get(f.check, 64)
    return code


def main(argv=None):
    parser = argparse.ArgumentParser(
        description=__doc__.split("\n")[0])
    parser.add_argument("--check", action="store_true",
                        help="run the full-tree check (the default)")
    parser.add_argument("--all", action="store_true",
                        help="alias for --check: every pass — lock "
                             "discipline, traced purity, recompile "
                             "hazard, host sync, resource lifecycle, "
                             "suppression hygiene")
    parser.add_argument("--root", default=REPO,
                        help="repository root (default: this repo)")
    parser.add_argument("--list-suppressions", action="store_true",
                        help="print every named suppression and exit")
    args = parser.parse_args(argv)
    findings, suppressions, stats = run_check(args.root)
    if args.list_suppressions:
        for s in suppressions:
            print("%s:%d: allow(%s): %s"
                  % (s.file, s.line, s.check, s.reason))
        return 0
    for f in findings:
        print("%s:%d: [%s] %s" % (f.file, f.line, f.check, f.message),
              file=sys.stderr)
    results = {"findings": len(findings), "stats": stats}
    print(json.dumps(summary_record(results)[0]))
    return exit_code(findings)


if __name__ == "__main__":
    sys.exit(main())
