"""Standalone smoke target for the serving attention kernels (ISSUE 7).

Runs the interpret-mode Pallas-vs-XLA parity suite — every test marked
``kernel_parity`` in tests/test_pallas.py — as ONE fast pytest
invocation on CPU, and refuses (exit 1) if the suite exceeds the
60-second budget the CI wiring promises.  The marker set is tier-1
(``-m 'not slow'`` runs it too); this entry point exists so a kernel
change can be validated in seconds without the whole tier-1 ladder,
and so an external CI lane has one command to call::

    python tools/check_kernel_parity.py [--budget-s 60] [--list]

Exit code: pytest's (0 = parity holds), or 1 on budget overrun.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--budget-s", type=float, default=60.0,
                        help="wall-clock budget; overrun fails even if "
                             "every test passed (the <60s smoke "
                             "contract)")
    parser.add_argument("--list", action="store_true",
                        help="collect-only: show the parity tests "
                             "without running them")
    args = parser.parse_args(argv)
    cmd = [sys.executable, "-m", "pytest",
           os.path.join(REPO, "tests", "test_pallas.py"),
           "-m", "kernel_parity", "-q",
           "-p", "no:cacheprovider", "-p", "no:randomly"]
    if args.list:
        cmd.append("--collect-only")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    t0 = time.monotonic()
    rc = subprocess.call(cmd, cwd=REPO, env=env)
    wall = time.monotonic() - t0
    print("kernel-parity suite: rc=%d in %.1fs (budget %.0fs)"
          % (rc, wall, args.budget_s), flush=True)
    if rc == 0 and not args.list and wall > args.budget_s:
        print("FAIL: parity suite exceeded its smoke budget — trim it "
              "or move cases to the slow suite", file=sys.stderr)
        return 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
