"""Microbenchmark AlexNet train-step components on the real chip.

The tunnel adds O(100ms) per dispatch, so per-op cost is measured by
repeating the op K times INSIDE one jit (fori_loop with a scalar data
dependency that defeats CSE), then differencing K vs 1 repetitions.
Timing windows end in a VALUE FETCH (block_until_ready does not block
through the tunnel — see bench.py).

The round-3 patch-materializing pooling / cumsum LRN are kept here as
local copies so the current native implementations can always be
re-compared against them (the r3→r4 rewrite rationale: docs/PERF.md).
"""
import time

import numpy
import jax
import jax.numpy as jnp

from veles_tpu.ops import functional as F

K = 20


def _sync(x):
    return numpy.asarray(jax.tree.leaves(x)[0]).ravel()[0]


def bench_op(name, op, x, n_timed=3):
    """op: x -> y (any shape).  Reports per-application device time."""
    def chain(x, k):
        def body(i, carry):
            y = op(carry)
            s = jnp.asarray(jax.tree.leaves(y)[0], jnp.float32).ravel()[0]
            return carry + (s * 1e-30).astype(carry.dtype)
        return jax.lax.fori_loop(0, k, body, x)

    f0 = jax.jit(lambda x: chain(x, 1))
    fk = jax.jit(lambda x: chain(x, 1 + K))
    _sync(f0(x)); _sync(fk(x))  # compile both
    ts = []
    for variant in (f0, fk):
        best = float("inf")
        for _ in range(n_timed):
            begin = time.perf_counter()
            out = variant(x)
            _sync(out)
            best = min(best, time.perf_counter() - begin)
        ts.append(best)
    per_op = (ts[1] - ts[0]) / K
    print("%-44s %10.3f ms" % (name, per_op * 1e3), flush=True)
    return per_op


# ---- round-3 implementations, kept for A/B comparison -----------------
def _r3_patch_maxpool(x, window=(3, 3), stride=(2, 2)):
    """The replaced patch-materializing max pooling (kh*kw HBM blowup)."""
    lowest = float(jnp.finfo(x.dtype).min) / 2
    patches, _, _ = F._pool_patches(x, window, stride, lowest)
    idx = jnp.argmax(patches, axis=3, keepdims=True)
    return jnp.take_along_axis(patches, idx, axis=3)[:, :, :, 0, :]


def _r3_cumsum_lrn(x, alpha=1e-4, beta=0.75, n=5, k=2.0):
    """The replaced cumsum-based LRN (prefix-scan lowering)."""
    c = x.shape[-1]
    sq = x * x
    half = n // 2
    padded = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, half)])
    csum = jnp.cumsum(padded, axis=-1)
    csum = jnp.pad(csum, [(0, 0)] * (x.ndim - 1) + [(1, 0)])
    window_sums = jax.lax.slice_in_dim(csum, n, n + c, axis=-1) - \
        jax.lax.slice_in_dim(csum, 0, c, axis=-1)
    return x / (k + (alpha / n) * window_sums) ** beta


def main():
    key = jax.random.PRNGKey(0)
    B = 128

    # ---- crop at alexnet shape
    x_raw = jax.random.normal(key, (B, 256, 256, 3), jnp.float32)
    bench_op("crop 256->227 train (pad back to 256)",
             lambda x: jnp.pad(F.random_crop_flip(
                 x, jax.random.PRNGKey(1), (227, 227), True, True),
                 [(0, 0), (14, 15), (14, 15), (0, 0)]), x_raw)

    # ---- conv1 11x11 s4 fwd
    x227 = jax.random.normal(key, (B, 227, 227, 3), jnp.float32)
    w1 = jax.random.normal(key, (11, 11, 3, 96), jnp.float32) * 0.01
    b1 = jnp.zeros((96,))
    bench_op("conv1 fwd (current precision mode)",
             lambda x: F.conv2d_forward(x, w1, b1, (4, 4), "VALID",
                                        "strict_relu"), x227)

    # ---- LRN at conv1 output shape: current slice-sum vs r3 cumsum
    y1 = jax.random.normal(key, (B, 55, 55, 96), jnp.float32)
    bench_op("lrn fwd (current slice-sum)", F.lrn_forward, y1)
    bench_op("lrn fwd (r3 cumsum)", _r3_cumsum_lrn, y1)

    def lrn_vjp(x):
        _, vjp = jax.vjp(F.lrn_forward, x)
        return vjp(x)[0]
    bench_op("lrn fwd+vjp (current)", lrn_vjp, y1)

    # ---- max pooling 3x3 s2: current reduce_window vs r3 patches
    bench_op("maxpool fwd (current reduce_window)",
             lambda x: F.max_pooling(x, (3, 3), (2, 2)), y1)
    bench_op("maxpool fwd (r3 patches)", _r3_patch_maxpool, y1)

    def pool_vjp(x):
        y, vjp = jax.vjp(lambda a: F.max_pooling(a, (3, 3), (2, 2)), x)
        return vjp(y)[0]
    bench_op("maxpool fwd+vjp (current)", pool_vjp, y1)

    # ---- conv2 5x5 pad2 96->256 under both precision modes
    x2 = jax.random.normal(key, (B, 27, 27, 96), jnp.float32)
    w2 = jax.random.normal(key, (5, 5, 96, 256), jnp.float32) * 0.01
    b2 = jnp.zeros((256,))
    for mode in ("float32", "bfloat16"):
        with F.matmul_precision(mode):
            bench_op("conv2 fwd (%s)" % mode,
                     lambda x: F.conv2d_forward(x, w2, b2, (1, 1), 2,
                                                "strict_relu"), x2)

    # ---- FC trunk 9216->4096->4096->1000
    xf = jax.random.normal(key, (B, 9216), jnp.float32)
    wf1 = jax.random.normal(key, (9216, 4096), jnp.float32) * 0.01
    wf2 = jax.random.normal(key, (4096, 4096), jnp.float32) * 0.01
    wf3 = jax.random.normal(key, (4096, 1000), jnp.float32) * 0.01

    def fc_fwd(x):
        h = jnp.maximum(F.matmul(x, wf1), 0.0)
        h = jnp.maximum(F.matmul(h, wf2), 0.0)
        return F.matmul(h, wf3)
    bench_op("fc trunk fwd", fc_fwd, xf)

    def fc_vjp(x):
        y, vjp = jax.vjp(fc_fwd, x)
        return vjp(y)[0]
    bench_op("fc trunk fwd+vjp", fc_vjp, xf)

    # ---- roofline sanity
    xm = jax.random.normal(key, (4096, 4096), jnp.float32)
    t = bench_op("matmul 4096^3 HIGHEST", lambda x: F.matmul(x, x), xm)
    print("   -> %.1f TF/s fp32-HIGHEST" % (2 * 4096**3 / t / 1e12))

    def mm_bf16(x):
        return jnp.matmul(x.astype(jnp.bfloat16),
                          x.astype(jnp.bfloat16)).astype(jnp.float32)
    t = bench_op("matmul 4096^3 bf16-cast", mm_bf16, xm)
    print("   -> %.1f TF/s bf16" % (2 * 4096**3 / t / 1e12))


if __name__ == "__main__":
    main()
