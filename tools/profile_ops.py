"""Microbenchmark train-step AND serving-attention components on the
real chip — the per-op cost table.

The tunnel adds O(100ms) per dispatch, so per-op cost is measured by
repeating the op K times INSIDE one jit (fori_loop with a scalar data
dependency that defeats CSE), then differencing K vs 1 repetitions.
Timing windows end in a VALUE FETCH (block_until_ready does not block
through the tunnel — see bench.py).

The round-3 patch-materializing pooling / cumsum LRN are kept here as
local copies so the current native implementations can always be
re-compared against them (the r3→r4 rewrite rationale: docs/PERF.md).

ISSUE 7 adds the serving attention rows (decode step / chunked
prefill, contiguous / paged, Pallas kernel vs XLA — the inputs the
ROADMAP autotuning item will select between) and the bench.py
streaming discipline: after EVERY completed row one summary_record
JSON line goes to stdout (metric/value/unit/vs_baseline/configs,
last-line-wins), so an outer watchdog kill still leaves a parseable
record of everything measured so far.
"""
import argparse
import json
import os
import sys
import time

import numpy
import jax
import jax.numpy as jnp

# run as a script, tools/ is on sys.path but the repo root (veles_tpu/)
# is not
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from veles_tpu.ops import functional as F  # noqa: E402

K = 20

#: accumulated {row name: per-op ms} — the ``configs`` payload of every
#: streamed summary line
RESULTS = {}


def stream_summary():
    """Bank everything measured so far as ONE stdout JSON line in the
    bench.py summary_record shape — a watchdog kill keeps the last."""
    last = next(reversed(RESULTS)) if RESULTS else None
    print(json.dumps({
        "metric": "profile_ops_row_ms",
        "value": RESULTS.get(last),
        "unit": "ms/op",
        "vs_baseline": None,
        "configs": {"last_row": last, "rows_ms": dict(RESULTS)},
    }), flush=True)


def _sync(x):
    return numpy.asarray(jax.tree.leaves(x)[0]).ravel()[0]


def bench_op(name, op, x, n_timed=3, reps=K):
    """op: x -> y (any shape).  Reports per-application device time."""
    def chain(x, k):
        def body(i, carry):
            y = op(carry)
            s = jnp.asarray(jax.tree.leaves(y)[0], jnp.float32).ravel()[0]
            return carry + (s * 1e-30).astype(carry.dtype)
        return jax.lax.fori_loop(0, k, body, x)

    f0 = jax.jit(lambda x: chain(x, 1))
    fk = jax.jit(lambda x: chain(x, 1 + reps))
    _sync(f0(x)); _sync(fk(x))  # compile both
    ts = []
    for variant in (f0, fk):
        best = float("inf")
        for _ in range(n_timed):
            begin = time.perf_counter()
            out = variant(x)
            _sync(out)
            best = min(best, time.perf_counter() - begin)
        ts.append(best)
    per_op = (ts[1] - ts[0]) / reps
    print("%-44s %10.3f ms" % (name, per_op * 1e3), flush=True,
          file=sys.stderr)
    RESULTS[name] = round(per_op * 1e3, 4)
    stream_summary()
    return per_op


# ---- round-3 implementations, kept for A/B comparison -----------------
def _r3_patch_maxpool(x, window=(3, 3), stride=(2, 2)):
    """The replaced patch-materializing max pooling (kh*kw HBM blowup)."""
    lowest = float(jnp.finfo(x.dtype).min) / 2
    patches, _, _ = F._pool_patches(x, window, stride, lowest)
    idx = jnp.argmax(patches, axis=3, keepdims=True)
    return jnp.take_along_axis(patches, idx, axis=3)[:, :, :, 0, :]


def _r3_cumsum_lrn(x, alpha=1e-4, beta=0.75, n=5, k=2.0):
    """The replaced cumsum-based LRN (prefix-scan lowering)."""
    c = x.shape[-1]
    sq = x * x
    half = n // 2
    padded = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, half)])
    csum = jnp.cumsum(padded, axis=-1)
    csum = jnp.pad(csum, [(0, 0)] * (x.ndim - 1) + [(1, 0)])
    window_sums = jax.lax.slice_in_dim(csum, n, n + c, axis=-1) - \
        jax.lax.slice_in_dim(csum, 0, c, axis=-1)
    return x / (k + (alpha / n) * window_sums) ** beta


# ---- serving attention rows (ISSUE 7) --------------------------------
def attention_rows(kernels="auto"):
    """Per-op cost of the serving hot loop's attention programs at the
    lm-bench geometry: decode step (c=1) and chunked prefill (c=page),
    contiguous vs paged storage, XLA vs the Pallas serving kernels —
    the same pairs tools/lm_bench.py reads end-to-end, isolated here
    per dispatch (autotuning seed data).

    ``kernels``: 'auto' rows the Pallas kernels only on real TPU
    hardware (off-TPU they would run in interpret mode — minutes per
    timing rep, useless numbers); 'force' insists (parity spelunking);
    'off' skips them."""
    from veles_tpu import prng
    from veles_tpu.ops import attention as A
    from veles_tpu.ops.pallas_kernels import on_tpu

    d_model, n_heads, max_len, page, b = 64, 4, 256, 16, 4
    params = jax.tree.map(jnp.asarray, A.init_mha_params(
        prng.get("profile_attn"), d_model, n_heads))
    rng = numpy.random.RandomState(11)
    kv = A.kv_heads_of(params, n_heads, d_model)
    dh = d_model // n_heads
    m = max_len // page                       # pages per lane
    n_pages = b * m + 1                       # + reserved scratch page
    kc = jnp.asarray(rng.randn(b, kv, max_len, dh), jnp.float32)
    vc = jnp.asarray(rng.randn(b, kv, max_len, dh), jnp.float32)
    kp = jnp.asarray(rng.randn(n_pages, kv, page, dh), jnp.float32)
    vp = jnp.asarray(rng.randn(n_pages, kv, page, dh), jnp.float32)
    ptab = jnp.asarray(
        1 + numpy.arange(b * m).reshape(b, m), jnp.int32)
    pos_mid = jnp.full((b,), max_len // 2, jnp.int32)  # page-aligned
    pos_scalar = jnp.asarray(max_len // 2, jnp.int32)  # contiguous path

    x1 = jnp.asarray(rng.randn(b, 1, d_model), jnp.float32)
    xc = jnp.asarray(rng.randn(b, page, d_model), jnp.float32)

    def contig(a):
        return A.mha_chunk_step(
            params, a, kc, vc, pos_scalar, n_heads, rope=True)[0]

    def paged(kern=None):
        return lambda a: A.mha_paged_chunk_step(
            params, a, kp, vp, ptab, pos_mid, n_heads, rope=True,
            attn_kernel=kern)[0]

    bench_op("attn decode step c=1 (contiguous)", contig, x1)
    bench_op("attn chunk prefill c=%d (contiguous)" % page, contig, xc)
    bench_op("attn decode step c=1 (paged, xla)", paged(), x1)
    bench_op("attn chunk prefill c=%d (paged, xla)" % page, paged(),
             xc)
    run_kernels = (kernels == "force"
                   or (kernels == "auto" and on_tpu()))
    if run_kernels:
        bench_op("attn decode step c=1 (paged, pallas kernel)",
                 paged("decode"), x1, reps=5)
        bench_op("attn chunk prefill c=%d (paged, pallas kernel)"
                 % page, paged("prefill"), xc, reps=5)
    elif kernels == "auto":
        print("(pallas kernel rows skipped off-TPU — interpret mode "
              "measures the interpreter, not the kernel; pass "
              "--attn-kernels force to insist)", file=sys.stderr)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--only", default="all",
                    choices=("all", "alexnet", "attention"),
                    help="which section of the cost table to run")
    ap.add_argument("--attn-kernels", default="auto",
                    choices=("auto", "force", "off"),
                    help="Pallas serving-kernel rows: auto = only on "
                         "real TPU hardware; force = interpret mode "
                         "off-TPU (slow, parity gear); off = skip")
    args = ap.parse_args(argv)
    if args.only in ("all", "attention"):
        attention_rows(kernels=args.attn_kernels)
    if args.only in ("all", "alexnet"):
        alexnet_rows()
    stream_summary()


def alexnet_rows():
    key = jax.random.PRNGKey(0)
    B = 128

    # ---- crop at alexnet shape
    x_raw = jax.random.normal(key, (B, 256, 256, 3), jnp.float32)
    bench_op("crop 256->227 train (pad back to 256)",
             lambda x: jnp.pad(F.random_crop_flip(
                 x, jax.random.PRNGKey(1), (227, 227), True, True),
                 [(0, 0), (14, 15), (14, 15), (0, 0)]), x_raw)

    # ---- conv1 11x11 s4 fwd
    x227 = jax.random.normal(key, (B, 227, 227, 3), jnp.float32)
    w1 = jax.random.normal(key, (11, 11, 3, 96), jnp.float32) * 0.01
    b1 = jnp.zeros((96,))
    bench_op("conv1 fwd (current precision mode)",
             lambda x: F.conv2d_forward(x, w1, b1, (4, 4), "VALID",
                                        "strict_relu"), x227)

    # ---- LRN at conv1 output shape: current slice-sum vs r3 cumsum
    y1 = jax.random.normal(key, (B, 55, 55, 96), jnp.float32)
    bench_op("lrn fwd (current slice-sum)", F.lrn_forward, y1)
    bench_op("lrn fwd (r3 cumsum)", _r3_cumsum_lrn, y1)

    def lrn_vjp(x):
        _, vjp = jax.vjp(F.lrn_forward, x)
        return vjp(x)[0]
    bench_op("lrn fwd+vjp (current)", lrn_vjp, y1)

    # ---- max pooling 3x3 s2: current reduce_window vs r3 patches
    bench_op("maxpool fwd (current reduce_window)",
             lambda x: F.max_pooling(x, (3, 3), (2, 2)), y1)
    bench_op("maxpool fwd (r3 patches)", _r3_patch_maxpool, y1)

    def pool_vjp(x):
        y, vjp = jax.vjp(lambda a: F.max_pooling(a, (3, 3), (2, 2)), x)
        return vjp(y)[0]
    bench_op("maxpool fwd+vjp (current)", pool_vjp, y1)

    # ---- conv2 5x5 pad2 96->256 under both precision modes
    x2 = jax.random.normal(key, (B, 27, 27, 96), jnp.float32)
    w2 = jax.random.normal(key, (5, 5, 96, 256), jnp.float32) * 0.01
    b2 = jnp.zeros((256,))
    for mode in ("float32", "bfloat16"):
        with F.matmul_precision(mode):
            bench_op("conv2 fwd (%s)" % mode,
                     lambda x: F.conv2d_forward(x, w2, b2, (1, 1), 2,
                                                "strict_relu"), x2)

    # ---- FC trunk 9216->4096->4096->1000
    xf = jax.random.normal(key, (B, 9216), jnp.float32)
    wf1 = jax.random.normal(key, (9216, 4096), jnp.float32) * 0.01
    wf2 = jax.random.normal(key, (4096, 4096), jnp.float32) * 0.01
    wf3 = jax.random.normal(key, (4096, 1000), jnp.float32) * 0.01

    def fc_fwd(x):
        h = jnp.maximum(F.matmul(x, wf1), 0.0)
        h = jnp.maximum(F.matmul(h, wf2), 0.0)
        return F.matmul(h, wf3)
    bench_op("fc trunk fwd", fc_fwd, xf)

    def fc_vjp(x):
        y, vjp = jax.vjp(fc_fwd, x)
        return vjp(y)[0]
    bench_op("fc trunk fwd+vjp", fc_vjp, xf)

    # ---- roofline sanity
    xm = jax.random.normal(key, (4096, 4096), jnp.float32)
    t = bench_op("matmul 4096^3 HIGHEST", lambda x: F.matmul(x, x), xm)
    print("   -> %.1f TF/s fp32-HIGHEST" % (2 * 4096**3 / t / 1e12),
          file=sys.stderr)

    def mm_bf16(x):
        return jnp.matmul(x.astype(jnp.bfloat16),
                          x.astype(jnp.bfloat16)).astype(jnp.float32)
    t = bench_op("matmul 4096^3 bf16-cast", mm_bf16, xm)
    print("   -> %.1f TF/s bf16" % (2 * 4096**3 / t / 1e12),
          file=sys.stderr)


if __name__ == "__main__":
    main()
