"""Render the round-over-round benchmark story as one markdown table.

Reads the driver-recorded ``BENCH_r*.json`` files at the repo root (shape:
``{"n": round, "rc": exit, "parsed": {"configs": {...}}}``) plus any
session-captured raw records under ``docs/bench_sessions/*.json`` (shape:
the bench's own one-line JSON, ``{"configs": {...}}``), and prints per
config × round: samples/sec (or the config's native headline metric) with
step time, so progress and regressions are visible at a glance.

Usage: python tools/bench_report.py [--metric samples_per_sec]
"""
import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_records():
    """[(label, configs dict)]: driver rounds in order, then every
    session capture (alphabetical) appended as extra columns — a session
    column is labeled with its filename, not merged into a round."""
    out = []
    for path in sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json"))):
        try:
            rec = json.load(open(path))
        except Exception:
            continue
        parsed = rec.get("parsed") or {}
        label = "r%02d" % rec.get("n", 0)
        if rec.get("rc"):
            label += "(rc=%s)" % rec["rc"]
        out.append((label, parsed.get("configs") or {}))
    for path in sorted(glob.glob(
            os.path.join(REPO, "docs", "bench_sessions", "*.json"))):
        try:
            rec = json.load(open(path))
        except Exception:
            continue
        out.append((os.path.basename(path).replace(".json", ""),
                    rec.get("configs") or {}))
    return out


def cell(cfg, metric):
    """One table cell for a config record: headline value + step time."""
    if not isinstance(cfg, dict):
        return ""
    if metric in cfg:
        value = "{:,.0f}".format(cfg[metric])
        if cfg.get("step_time_us") is not None:
            value += " ({:,.0f} us)".format(cfg["step_time_us"])
        return value
    # aux configs carry their own headline fields
    for key in ("tokens_per_sec", "xla_us", "read_mb_per_sec",
                "best_val_err_pct", "best_val_mse", "best_qe",
                "selfcheck"):
        if key in cfg:
            return "%s=%s" % (key, cfg[key])
    return "ok"


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--metric", default="samples_per_sec")
    args = parser.parse_args()
    records = load_records()
    if not records:
        print("no BENCH_r*.json records found", file=sys.stderr)
        return 1
    sys.path.insert(0, REPO)
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    worker_of = bench.RECORD_WORKERS   # bench.py owns the vocabulary
    names = []
    for _, cfgs in records:
        for name in cfgs:
            if name.endswith("_error"):
                continue
            if name not in names:
                names.append(name)
    # configs that NEVER succeeded would otherwise vanish from the table
    # — surface them as a row named after the failing worker config
    covered = {worker_of.get(n, n) for n in names}
    for _, cfgs in records:
        for key in cfgs:
            if key.endswith("_error"):
                base = key[:-len("_error")]
                if base not in covered and base not in names:
                    names.append(base)
                    covered.add(base)
    labels = [label for label, _ in records]
    print("| config | " + " | ".join(labels) + " |")
    print("|---" * (len(labels) + 1) + "|")
    for name in names:
        row = []
        for _, cfgs in records:
            if name in cfgs:
                row.append(cell(cfgs[name], args.metric))
            elif (name + "_error" in cfgs
                  or worker_of.get(name, name) + "_error" in cfgs):
                row.append("failed")
            else:
                row.append("")
        print("| %s | %s |" % (name, " | ".join(row)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
