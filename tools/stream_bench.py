"""Micro-harness: graph-loop vs streaming windowed epoch-scan.

Trains the SAME tiny model over the SAME synthetic records dataset two
ways — the per-minibatch graph loop (one device dispatch per minibatch)
and the streaming windowed epoch-scan driver (``--stream-window``: one
dispatch per window, next window staged concurrently) — and prints one
JSON line with the evidence the streaming path (ISSUE 3) claims:

- ``dispatches_per_epoch`` drops from ~minibatches to ~windows,
- ``staging_stall_pct`` (time the device waited on the host) stays low
  when staging overlaps compute,
- ``windows_per_sec`` / ``samples_per_sec`` for throughput comparison.

Standalone::

    python tools/stream_bench.py [--samples 4096] [--minibatch 64] \
        [--window 8] [--stage-ahead 1] [--epochs 3]

Importable: :func:`run_stream_bench` is used by the slow-marked test in
``tests/test_streaming_bench.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

# run as a script, tools/ is on sys.path but the repo root (veles_tpu/)
# is not — the convergence.py convention
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _build_workflow(rec_path, minibatch, max_epochs, seed=17):
    from veles_tpu import prng
    from veles_tpu.loader.records import RecordsLoader
    from veles_tpu.standard_workflow import StandardWorkflow
    prng.reset()
    prng.seed_all(seed)
    return StandardWorkflow(
        None, name="stream_bench",
        loader_factory=RecordsLoader,
        loader_config={"path": rec_path, "minibatch_size": minibatch,
                       "scale_uint8": False},
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.02, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.02, "momentum": 0.9},
        ],
        decision_config={"max_epochs": max_epochs,
                         "fail_iterations": max_epochs + 1},
        loss_function="softmax")


def make_dataset(path, samples=4096, features=64):
    """Synthetic records file: ``samples`` rows of ``features`` floats,
    10 classes, 1/8 of the rows as the validation split."""
    import numpy
    from veles_tpu.loader.records import write_records
    rng = numpy.random.RandomState(5)
    data = rng.normal(0, 1, (samples, features)).astype(numpy.float32)
    labels = (numpy.arange(samples) % 10).astype(numpy.int32)
    n_valid = samples // 8
    return write_records(path, data, labels,
                         [0, n_valid, samples - n_valid])


def run_stream_bench(samples=4096, minibatch=64, window=8, stage_ahead=1,
                     epochs=3, rec_path=None):
    """Returns the comparison record (also the one JSON line printed by
    the CLI): graph-loop vs streaming timings over identical work."""
    tmp = None
    if rec_path is None:
        tmp = tempfile.mkdtemp(prefix="stream_bench_")
        rec_path = make_dataset(os.path.join(tmp, "bench.rec"),
                                samples=samples)
    try:
        return _run_stream_bench(samples, minibatch, window, stage_ahead,
                                 epochs, rec_path)
    finally:
        if tmp is not None:
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)


def _run_stream_bench(samples, minibatch, window, stage_ahead, epochs,
                      rec_path):
    from veles_tpu.launcher import Launcher
    n_valid = samples // 8
    train_minibatches = -(-(samples - n_valid) // minibatch)

    # graph loop: one fused dispatch per minibatch (train + eval sets)
    wf_graph = _build_workflow(rec_path, minibatch, epochs)
    begin = time.perf_counter()
    Launcher(wf_graph, stats=False).boot()
    graph_seconds = time.perf_counter() - begin
    graph_epochs = len(wf_graph.decision.epoch_metrics)
    graph_dispatches = wf_graph.fused_step.run_count

    # streaming windowed epoch-scan: one dispatch per window
    wf_stream = _build_workflow(rec_path, minibatch, epochs)
    begin = time.perf_counter()
    Launcher(wf_stream, stats=False, stream_window=window,
             stage_ahead=stage_ahead).boot()
    stream_seconds = time.perf_counter() - begin
    stats = wf_stream._stream_stats

    record = {
        "samples": samples,
        "minibatch": minibatch,
        "window_minibatches": window,
        "stage_ahead": stage_ahead,
        "epochs": stats["epochs"],
        "train_minibatches_per_epoch": train_minibatches,
        "graph_loop": {
            "seconds": round(graph_seconds, 4),
            "dispatches_per_epoch": (graph_dispatches
                                     / max(graph_epochs, 1)),
            "samples_per_sec": ((samples - n_valid) * graph_epochs
                                / graph_seconds),
        },
        "streaming": {
            "seconds": round(stream_seconds, 4),
            "dispatches_per_epoch": (stats["dispatches"]
                                     / max(stats["epochs"], 1)),
            "windows_per_epoch": (stats["windows"]
                                  / max(stats["epochs"], 1)),
            "windows_per_sec": (stats["windows"]
                                / max(stats["compute_s"]
                                      + stats["staging_stall_s"], 1e-9)),
            "samples_per_sec": stats["samples_per_sec"],
            "staging_stall_pct": round(
                100.0 * stats["staging_stall_fraction"], 2),
        },
        "dispatch_reduction": (graph_dispatches / max(graph_epochs, 1))
        / max(stats["dispatches"] / max(stats["epochs"], 1), 1e-9),
    }
    # identical work check: both trained the same number of epochs
    record["parity"] = {
        "epochs_equal": graph_epochs == stats["epochs"],
        "final_train_loss_graph": float(
            wf_graph.decision.epoch_metrics[-1]["train"]["loss"]),
        "final_train_loss_stream": float(
            wf_stream.decision.epoch_metrics[-1]["train"]["loss"]),
    }
    return record


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--samples", type=int, default=4096)
    parser.add_argument("--minibatch", type=int, default=64)
    parser.add_argument("--window", type=int, default=8)
    parser.add_argument("--stage-ahead", type=int, default=1)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--records", default=None,
                        help="reuse an existing records file instead of "
                             "synthesizing one")
    args = parser.parse_args(argv)
    record = run_stream_bench(
        samples=args.samples, minibatch=args.minibatch,
        window=args.window, stage_ahead=args.stage_ahead,
        epochs=args.epochs, rec_path=args.records)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
