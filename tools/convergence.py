"""Convergence runs for BASELINE.md rows 0-1: train MNIST-FC and CIFAR
at FULL dataset size with pinned seeds, record final val-acc + wall.

Usage: python tools/convergence.py [mnist] [cifar] [cifar_bf16]
Prints one summary line per config:
  <config>: best val_err <n>/<N> (<pct>%), ..., @<git-sha>

Protocol (BASELINE.md): fixed seed; train until no val improvement for
``patience`` epochs (the sample Decision's criterion); wall time covers
the whole run.  Runs the SAME pure step functions the Decision-driven
unit graph runs, via bench.bench_convergence's epoch-scan path — through
the TPU tunnel an execute RPC costs ~0.1-1 s, so the per-minibatch graph
path (600 RPCs/epoch) would take hours where epoch-scan takes minutes;
numerics are identical by construction (compiled.py composes one set of
step fns for both paths, pinned by tests/test_parallel.py).
"""
import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def git_sha():
    try:
        return subprocess.check_output(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=REPO).decode().strip()
    except Exception:
        return "unknown"


def run_config(name, seed=1, max_epochs=25, patience=8):
    import bench
    bench.enable_compile_cache()

    # the builders thread the seed through to prng.seed_all, so the
    # printed ``seed=%d`` is the seed that actually governed init and
    # shuffle order (it was silently dead before)
    if name == "mnist":
        build = lambda: bench.build_mnist(60000, 10000, 100,  # noqa: E731
                                          seed=seed)
    elif name == "cifar":
        build = lambda: bench.build_cifar(50000, 10000, 100,  # noqa: E731
                                          seed=seed)
    elif name == "cifar_bf16":
        def build():
            from veles_tpu.ops import functional as F
            F.set_matmul_precision("bfloat16")
            return bench.build_cifar(50000, 10000, 100, seed=seed)
    else:
        raise SystemExit("unknown config %r" % name)

    begin = time.perf_counter()
    try:
        rec = bench.bench_convergence(build, max_epochs=max_epochs,
                                      patience=patience)
    finally:
        if name.endswith("_bf16"):
            from veles_tpu.ops import functional as F
            F.set_matmul_precision("float32")
    wall = time.perf_counter() - begin
    import jax
    print("%s: best val_err %s/%d (%.2f%%), best@%d of %d epochs, "
          "%.1fs wall, device=%s, seed=%d, @%s"
          % (name, rec.get("best_val_err"), rec["val_count"],
             rec.get("best_val_err_pct", float("nan")),
             rec["best_epoch"], rec["epochs_run"], wall,
             jax.devices()[0].device_kind, seed, git_sha()), flush=True)
    return rec


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("configs", nargs="*",
                        default=["mnist", "cifar", "cifar_bf16"])
    parser.add_argument("--max-epochs", type=int, default=25)
    parser.add_argument("--patience", type=int, default=8)
    parser.add_argument("--seed", type=int, default=1)
    parser.add_argument("--worker", default=None, metavar="CONFIG",
                        help=argparse.SUPPRESS)   # internal: one config
    parser.add_argument("--in-process", action="store_true",
                        help="no per-config watchdog subprocesses")
    args = parser.parse_args()
    configs = args.configs or ["mnist", "cifar", "cifar_bf16"]
    if args.worker is not None:
        run_config(args.worker, seed=args.seed,
                   max_epochs=args.max_epochs, patience=args.patience)
        return
    if args.in_process:
        for name in configs:
            run_config(name, seed=args.seed, max_epochs=args.max_epochs,
                       patience=args.patience)
        return
    # per-config watchdog subprocesses, like bench.py's orchestrator: a
    # TPU-tunnel wedge mid-config costs that config, not the ones behind
    # it (each summary line prints from the worker the moment it lands)
    per_config = float(os.environ.get("VELES_CONV_CONFIG_TIMEOUT_S",
                                      3600))
    failed = 0
    for name in configs:
        cmd = [sys.executable, os.path.abspath(__file__), "--worker",
               name, "--seed", str(args.seed),
               "--max-epochs", str(args.max_epochs),
               "--patience", str(args.patience)]
        try:
            rc = subprocess.call(cmd, timeout=per_config)
            if rc:
                failed += 1
                print("%s: worker failed (rc=%d)" % (name, rc),
                      flush=True)
        except subprocess.TimeoutExpired:
            failed += 1
            print("%s: killed after %.0fs (hung device dispatch/compile)"
                  % (name, per_config), flush=True)
    # a failed/hung leg must surface in the exit code — the watcher log's
    # "convergence rc=" is how automation judges whether the rows landed
    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
