"""Convergence runs for BASELINE.md rows 0-2: train MNIST-FC and CIFAR
to Decision-complete with pinned seeds, record final val-acc + samples/s.

Usage: python tools/convergence.py [mnist] [cifar]
Prints one summary line per config:
  <config>: best val_err <n>/<N> (<pct>%), ..., @<git-sha>

Protocol (BASELINE.md): fixed seed; train to the sample's stopping
criterion (Decision-complete); wall time covers the whole run.
"""
import argparse
import os
import subprocess
import time


def git_sha():
    try:
        return subprocess.check_output(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))).decode().strip()
    except Exception:
        return "unknown"


def run_config(name, seed=1):
    from veles_tpu import prng
    from veles_tpu.config import root
    prng.reset()
    prng.seed_all(seed)
    if name == "mnist":
        root.__dict__.pop("mnist", None)
        root.mnist.update({
            "loader": {"minibatch_size": 100, "n_train": 60000,
                       "n_valid": 10000},
            "decision": {"max_epochs": 25, "fail_iterations": 10},
        })
        from veles_tpu.samples import mnist as sample
    elif name == "cifar":
        root.__dict__.pop("cifar", None)
        root.cifar.update({
            "loader": {"minibatch_size": 100, "n_train": 50000,
                       "n_valid": 10000},
            "decision": {"max_epochs": 25, "fail_iterations": 10},
        })
        from veles_tpu.samples import cifar as sample
    else:
        raise SystemExit("unknown config %r" % name)

    begin = time.perf_counter()
    wf = sample.train(fused=True)
    wall = time.perf_counter() - begin
    hist = [m["validation"] for m in wf.decision.epoch_metrics
            if "validation" in m]
    best = wf.decision.best_metric
    count = hist[-1]["count"]
    epochs = int(wf.loader.epoch_number)
    n_train = wf.loader.class_lengths[2]
    sps = epochs * n_train / wall   # incl. eval epochs: LOWER bound
    import jax
    print("%s: best val_err %d/%d (%.2f%%), %d epochs, "
          "%.0f samples/s overall, %.1fs wall, device=%s, seed=%d, @%s"
          % (name, best, count, 100.0 * best / count, epochs, sps, wall,
             jax.devices()[0].device_kind, seed, git_sha()), flush=True)
    return wf


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("configs", nargs="*", default=["mnist", "cifar"])
    args = parser.parse_args()
    for name in (args.configs or ["mnist", "cifar"]):
        run_config(name)


if __name__ == "__main__":
    main()
