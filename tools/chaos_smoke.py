"""Chaos smoke (ISSUE 10/11 satellite): the <60s, tier-1-safe subsets
of ``tools/chaos_bench.py`` — kill-one-replica-under-load and
weight-swap-under-load on a tiny model, CPU, deterministic — wired
into ``tests/test_serving.py`` so the fault-injection plumbing, the
health checker's quarantine path, the router's drain/retry
exactly-once contract, and the hot-swap/canary-rollback path cannot
rot between TPU sessions.

Standalone::

    python tools/chaos_smoke.py        # prints one summary JSON line
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from chaos_bench import (build_params, expected_rows,  # noqa: E402
                         mixed_length_prompts, scenario_kill_replica,
                         scenario_weight_swap)

#: the smoke's wall budget — asserted, so a slow drift fails loudly
#: instead of silently eating the tier-1 watchdog's headroom
BUDGET_S = 60.0


def run_smoke(n_new=6, requests=6):
    """Run the kill-one-replica scenario at smoke size; returns the
    scenario record (raises on any violated invariant)."""
    vocab, max_len, n_heads = 16, 48, 2
    params = build_params(vocab=vocab, d_model=32, n_heads=n_heads,
                          n_layers=2, max_len=max_len, seed=7)
    prompts = mixed_length_prompts(requests, vocab, 3,
                                   max_len - n_new - 4, seed=5)
    expect = expected_rows(params, prompts, n_new, n_heads, max_len)
    t0 = time.monotonic()
    record = scenario_kill_replica(params, n_heads, max_len, prompts,
                                   n_new, expect, slots=2,
                                   freeze_after_ticks=4,
                                   drain_timeout_s=0.4)
    record["smoke_wall_s"] = round(time.monotonic() - t0, 2)
    if record["smoke_wall_s"] >= BUDGET_S:
        raise AssertionError("chaos smoke took %.1fs (budget %.0fs)"
                             % (record["smoke_wall_s"], BUDGET_S))
    return record


def run_swap_smoke(n_new=6, requests=4):
    """Run the weight-swap-under-load scenario at smoke size (ISSUE
    11): requests straddle a canary deploy, the injected bad canary
    rolls back.  Returns the scenario record (raises on any violated
    invariant)."""
    vocab, max_len, n_heads = 16, 48, 2
    params = build_params(vocab=vocab, d_model=32, n_heads=n_heads,
                          n_layers=2, max_len=max_len, seed=7)
    params_new = build_params(vocab=vocab, d_model=32, n_heads=n_heads,
                              n_layers=2, max_len=max_len, seed=11)
    prompts = mixed_length_prompts(requests, vocab, 3,
                                   max_len - n_new - 4, seed=5)
    expect_old = expected_rows(params, prompts, n_new, n_heads,
                               max_len)
    expect_new = expected_rows(params_new, prompts, n_new, n_heads,
                               max_len)
    t0 = time.monotonic()
    record = scenario_weight_swap(params, params_new, n_heads, max_len,
                                  prompts, n_new, expect_old,
                                  expect_new, slots=2)
    record["smoke_wall_s"] = round(time.monotonic() - t0, 2)
    if record["smoke_wall_s"] >= BUDGET_S:
        raise AssertionError("swap smoke took %.1fs (budget %.0fs)"
                             % (record["smoke_wall_s"], BUDGET_S))
    return record


def main(argv=None):
    record = run_smoke()
    swap = run_swap_smoke()
    print(json.dumps({"metric": "chaos_smoke_kill_one_replica",
                      "value": record["completed_exactly_once"],
                      "unit": "requests_completed_exactly_once",
                      "vs_baseline": record["requests"],
                      "configs": {"kill": record, "swap": swap}}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
